"""Operability tail: structured log formatters (runtime-switchable) and
MQTT reason-code tables (emqx_logger_jsonfmt / emqx_reason_codes parity).
"""

import json
import logging

import pytest

from emqx_tpu.mqtt import reason_codes as RC
from emqx_tpu.observe import logfmt


def test_text_and_json_formatters(capsys):
    h = logfmt.setup_logging("info", "text")
    log = logging.getLogger("emqx_tpu.test")
    log.info("hello %s", "world")
    err = capsys.readouterr().err
    assert "[info] emqx_tpu.test: hello world" in err

    logfmt.set_formatter("json")
    log.warning("boom", extra={"ctx_clientid": "c1"})
    err = capsys.readouterr().err
    obj = json.loads(err.strip().splitlines()[-1])
    assert obj["level"] == "warning"
    assert obj["msg"] == "boom"
    assert obj["clientid"] == "c1"
    assert "time" in obj

    logfmt.set_formatter("text")


def test_log_level_and_validation():
    logfmt.setup_logging("info", "text")
    logfmt.set_level("debug")
    assert logging.getLogger("emqx_tpu").level == logging.DEBUG
    logfmt.set_level("warning")
    with pytest.raises(ValueError):
        logfmt.set_level("verbose")
    with pytest.raises(ValueError):
        logfmt.set_formatter("yaml")


def test_log_to_file(tmp_path):
    f = tmp_path / "broker.log"
    logfmt.setup_logging("info", "json", str(f))
    logging.getLogger("emqx_tpu.filetest").error("to-file")
    logfmt.setup_logging("info", "text")  # restore + close the file
    obj = json.loads(f.read_text().strip())
    assert obj["msg"] == "to-file" and obj["level"] == "error"


def test_runtime_config_switches_formatter():
    import asyncio

    from emqx_tpu.app import BrokerApp
    from emqx_tpu.config.schema import load_config

    async def run():
        app = BrokerApp(load_config({
            "listeners": [{"port": 0, "bind": "127.0.0.1"}],
            "dashboard": {"enable": False},
            "router": {"enable_tpu": False},
        }))
        await app.start()
        try:
            app.config_handler.update("log", {"formatter": "json"})
            h = logfmt._handler
            assert isinstance(h.formatter, logfmt.JsonFormatter)
            app.config_handler.update("log", {"formatter": "text"})
            assert isinstance(h.formatter, logfmt.TextFormatter)
            with pytest.raises(Exception):
                app.config_handler.update("log", {"formatter": "bogus"})
        finally:
            await app.stop()

    asyncio.run(asyncio.wait_for(run(), 60))


def test_reason_code_tables():
    assert RC.name(0x00) == "success"
    assert RC.text(0x87) == "Not authorized"
    assert RC.name(0x8E) == "session_taken_over"
    assert RC.name(0x9B) == "qos_not_supported"
    assert RC.name(0xFF).startswith("unknown_")
    # v3 CONNACK names
    assert RC.name(5, version=4) == "unauthorized_client"
    assert "not authorized" in RC.text(5, version=4)


def test_reason_code_compat_mapping():
    # v5 -> v3.1.1 CONNACK compatibility (emqx_reason_codes:compat/1)
    assert RC.compat_connack(0x00) == 0
    assert RC.compat_connack(0x84) == 1  # unsupported protocol version
    assert RC.compat_connack(0x85) == 2  # clientid not valid
    assert RC.compat_connack(0x86) == 4  # bad username or password
    assert RC.compat_connack(0x87) == 5  # not authorized
    assert RC.compat_connack(0x8A) == 5  # banned
    assert RC.compat_connack(0x89) == 3  # server busy -> unavailable
