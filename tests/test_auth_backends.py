"""Auth backend tests: HTTP authn/authz, JWKS RS256, SCRAM, PSK, file ACL.

Parity targets: apps/emqx_authn (http/jwt-jwks/scram providers),
apps/emqx_authz (http/file sources), apps/emqx_psk.
"""

import asyncio
import base64
import functools
import hashlib
import json
import secrets

import pytest

from emqx_tpu.auth.file_acl import parse_rules
from emqx_tpu.auth.http import HttpAuthProvider, HttpAuthzSource
from emqx_tpu.auth.jwks import JwksAuthProvider, rsa_verify_pkcs1_sha256
from emqx_tpu.auth.psk import PskStore
from emqx_tpu.auth.scram import ScramAuthenticator, ScramClient
from emqx_tpu.broker.auth import DENY, IGNORE, OK, AuthChain
from emqx_tpu.broker.authz import Authorizer
from emqx_tpu.mqtt import packet as pkt


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*a, **kw):
        asyncio.run(asyncio.wait_for(fn(*a, **kw), timeout=30))

    return wrapper


# -- stub HTTP auth service --------------------------------------------------


async def _stub_server(handler):
    from aiohttp import web

    app = web.Application()
    app.router.add_post("/auth", handler)
    app.router.add_post("/authz", handler)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    return runner, site._server.sockets[0].getsockname()[1]


@async_test
async def test_http_authn_provider():
    from aiohttp import web

    async def handler(request):
        body = await request.json()
        if body["username"] == "root":
            return web.json_response({"result": "allow", "is_superuser": True})
        if body["username"] == "evil":
            return web.json_response({"result": "deny"})
        if body["username"] == "boom":
            return web.Response(status=500)
        return web.json_response({"result": "ignore"})

    runner, port = await _stub_server(handler)
    p = HttpAuthProvider(f"http://127.0.0.1:{port}/auth")
    try:
        ci = {"client_id": "c1", "username": "root"}
        assert await p.authenticate_async(ci, {"password": b"x"}) == (OK, None)
        assert ci["is_superuser"] is True
        r, rc = await p.authenticate_async(
            {"client_id": "c", "username": "evil"}, {"password": b"x"}
        )
        assert r == DENY
        r, _ = await p.authenticate_async(
            {"client_id": "c", "username": "boom"}, {"password": b"x"}
        )
        assert r == IGNORE  # 5xx falls through the chain
        r, _ = await p.authenticate_async(
            {"client_id": "c", "username": "meh"}, {"password": b"x"}
        )
        assert r == IGNORE

        # through the chain: deny stops, allow_anonymous=False denies unknowns
        chain = AuthChain([p], allow_anonymous=False)
        out = await chain.aauthenticate(
            {"client_id": "c", "username": "meh"}, {"password": b"x"}
        )
        assert out[1]["result"] == "deny"
    finally:
        await p.close()
        await runner.cleanup()


@async_test
async def test_http_authz_source_and_cache():
    from aiohttp import web

    calls = []

    async def handler(request):
        body = await request.json()
        calls.append(body)
        if body["topic"].startswith("secret/"):
            return web.json_response({"result": "deny"})
        if body["topic"].startswith("open/"):
            return web.json_response({"result": "allow"})
        return web.json_response({"result": "ignore"})

    runner, port = await _stub_server(handler)
    src = HttpAuthzSource(f"http://127.0.0.1:{port}/authz")
    az = Authorizer(no_match="deny", sources=[src])
    try:
        ci = {"client_id": "c1", "username": "u"}
        assert await az.acheck(ci, "publish", "secret/a") == "deny"
        assert await az.acheck(ci, "publish", "open/a") == "allow"
        # ignore -> built-in rules (none) -> no_match
        assert await az.acheck(ci, "publish", "other/a") == "deny"
        n = len(calls)
        # cached: no extra HTTP call
        assert await az.acheck(ci, "publish", "open/a") == "allow"
        assert len(calls) == n
        # superuser bypasses sources entirely
        assert await az.acheck({"is_superuser": True}, "publish", "secret/a") == "allow"
        assert len(calls) == n
    finally:
        await src.close()
        await runner.cleanup()


# -- JWKS / RS256 ------------------------------------------------------------


def _miller_rabin(n, k=24):
    if n % 2 == 0:
        return n == 2
    r, d = 0, n - 1
    while d % 2 == 0:
        r += 1
        d //= 2
    for _ in range(k):
        a = secrets.randbelow(n - 3) + 2
        x = pow(a, d, n)
        if x in (1, n - 1):
            continue
        for _ in range(r - 1):
            x = pow(x, 2, n)
            if x == n - 1:
                break
        else:
            return False
    return True


def _gen_prime(bits):
    while True:
        p = secrets.randbits(bits) | (1 << (bits - 1)) | 1
        if _miller_rabin(p):
            return p


def _gen_rsa(bits=1024):
    e = 65537
    while True:
        p, q = _gen_prime(bits // 2), _gen_prime(bits // 2)
        if p == q:
            continue
        n = p * q
        phi = (p - 1) * (q - 1)
        if phi % e:
            d = pow(e, -1, phi)
            return n, e, d


def _b64u(b: bytes) -> str:
    return base64.urlsafe_b64encode(b).rstrip(b"=").decode()


def _sign_rs256(n, d, header: dict, claims: dict) -> str:
    h = _b64u(json.dumps(header).encode())
    p = _b64u(json.dumps(claims).encode())
    msg = f"{h}.{p}".encode()
    prefix = bytes.fromhex("3031300d060960864801650304020105000420")
    t = prefix + hashlib.sha256(msg).digest()
    k = (n.bit_length() + 7) // 8
    em = b"\x00\x01" + b"\xff" * (k - len(t) - 3) + b"\x00" + t
    sig = pow(int.from_bytes(em, "big"), d, n).to_bytes(k, "big")
    return f"{h}.{p}.{_b64u(sig)}"


def test_jwks_rs256_verify():
    n, e, d = _gen_rsa(1024)
    jwks = {
        "keys": [
            {
                "kty": "RSA",
                "kid": "k1",
                "use": "sig",
                "n": _b64u(n.to_bytes((n.bit_length() + 7) // 8, "big")),
                "e": _b64u(e.to_bytes(3, "big")),
            }
        ]
    }
    prov = JwksAuthProvider("http://unused.example/jwks")
    prov.load_keys(jwks)

    good = _sign_rs256(
        n, d, {"alg": "RS256", "kid": "k1"}, {"sub": "dev1", "aud": "mqtt"}
    )
    ci = {"client_id": "dev1"}
    r, _ = prov.authenticate(ci, {"password": good.encode()})
    assert r == OK
    assert ci["jwt_claims"]["sub"] == "dev1"

    # claim pinning
    prov2 = JwksAuthProvider("http://u/", verify_claims={"sub": "${clientid}"})
    prov2.load_keys(jwks)
    assert prov2.authenticate({"client_id": "dev1"}, {"password": good.encode()})[0] == OK
    assert prov2.authenticate({"client_id": "other"}, {"password": good.encode()})[0] == DENY

    # tampered signature
    bad = good[:-6] + ("AAAAAA" if not good.endswith("AAAAAA") else "BBBBBB")
    assert prov.authenticate(ci, {"password": bad.encode()})[0] == DENY
    # HS256 token is not ours -> ignore
    assert prov.authenticate(ci, {"password": b"x.y"})[0] == IGNORE

    # raw primitive sanity
    assert rsa_verify_pkcs1_sha256(n, e, b"msg", pow(
        int.from_bytes(
            b"\x00\x01" + b"\xff" * ((n.bit_length() + 7) // 8 - 3 - 51) + b"\x00"
            + bytes.fromhex("3031300d060960864801650304020105000420")
            + hashlib.sha256(b"msg").digest(), "big"), d, n).to_bytes((n.bit_length() + 7) // 8, "big"))


# -- SCRAM -------------------------------------------------------------------


def test_scram_roundtrip_unit():
    server = ScramAuthenticator(iterations=1024)
    server.add_user("alice", "wonder", is_superuser=True)

    client = ScramClient("alice", "wonder")
    status, server_first, st = server.start(client.client_first())
    assert status == "continue"
    final = client.client_final(server_first)
    status, server_final, attrs = server.finish(st, final)
    assert status == "ok"
    assert attrs == {"username": "alice", "is_superuser": True}
    assert client.verify_server(server_final)

    # wrong password -> deny
    bad = ScramClient("alice", "nope")
    status, sf, st = server.start(bad.client_first())
    assert server.finish(st, bad.client_final(sf))[0] == "deny"
    # unknown user
    unk = ScramClient("bob", "x")
    assert server.start(unk.client_first())[0] == "deny"


@async_test
async def test_scram_enhanced_auth_over_mqtt5():
    """Full MQTT5 AUTH exchange against a live listener, raw frames."""
    from emqx_tpu.broker.broker import Broker
    from emqx_tpu.broker.channel import ChannelConfig
    from emqx_tpu.broker.cm import ChannelManager
    from emqx_tpu.broker.hooks import Hooks
    from emqx_tpu.mqtt.frame import Parser, serialize
    from emqx_tpu.transport.listener import ListenerConfig, Listeners

    scram = ScramAuthenticator(iterations=512)
    scram.add_user("alice", "wonder")

    broker = Broker(hooks=Hooks())
    cm = ChannelManager(broker)
    listeners = Listeners(broker, cm)
    cfg = ChannelConfig(enhanced_auth={scram.METHOD: scram})
    l = await listeners.start_listener(ListenerConfig(port=0), cfg)

    async def exchange(username, password, expect_rc):
        reader, writer = await asyncio.open_connection("127.0.0.1", l.port)
        parser = Parser(version=pkt.MQTT_V5)
        client = ScramClient(username, password)

        async def recv():
            while True:
                data = await asyncio.wait_for(reader.read(4096), 5)
                assert data, "connection closed"
                pkts = parser.feed(data)
                if pkts:
                    return pkts[0]

        writer.write(
            serialize(
                pkt.Connect(
                    client_id=f"scram-{username}",
                    proto_ver=pkt.MQTT_V5,
                    properties={
                        "Authentication-Method": scram.METHOD,
                        "Authentication-Data": client.client_first(),
                    },
                ),
                pkt.MQTT_V5,
            )
        )
        p = await recv()
        if expect_rc != pkt.RC_SUCCESS and p.type == pkt.CONNACK:
            assert p.reason_code == expect_rc
            writer.close()
            return None
        assert p.type == pkt.AUTH
        assert p.reason_code == pkt.RC_CONTINUE_AUTHENTICATION
        server_first = p.properties["Authentication-Data"]
        writer.write(
            serialize(
                pkt.Auth(
                    reason_code=pkt.RC_CONTINUE_AUTHENTICATION,
                    properties={
                        "Authentication-Method": scram.METHOD,
                        "Authentication-Data": client.client_final(
                            server_first
                        ),
                    },
                ),
                pkt.MQTT_V5,
            )
        )
        p = await recv()
        assert p.type == pkt.CONNACK
        assert p.reason_code == expect_rc
        if expect_rc == pkt.RC_SUCCESS:
            # mutual auth: CONNACK carries the server signature
            assert client.verify_server(
                p.properties["Authentication-Data"]
            )
        writer.close()
        return p

    await exchange("alice", "wonder", pkt.RC_SUCCESS)
    await exchange("alice", "wrong", pkt.RC_NOT_AUTHORIZED)

    # re-authentication while connected (MQTT5 4.12.1)
    reader, writer = await asyncio.open_connection("127.0.0.1", l.port)
    parser = Parser(version=pkt.MQTT_V5)
    client = ScramClient("alice", "wonder")

    async def recv2():
        while True:
            data = await asyncio.wait_for(reader.read(4096), 5)
            assert data, "connection closed"
            pkts = parser.feed(data)
            if pkts:
                return pkts[0]

    writer.write(
        serialize(
            pkt.Connect(
                client_id="re-auth",
                proto_ver=pkt.MQTT_V5,
                properties={
                    "Authentication-Method": scram.METHOD,
                    "Authentication-Data": client.client_first(),
                },
            ),
            pkt.MQTT_V5,
        )
    )
    p = await recv2()
    writer.write(
        serialize(
            pkt.Auth(
                reason_code=pkt.RC_CONTINUE_AUTHENTICATION,
                properties={
                    "Authentication-Method": scram.METHOD,
                    "Authentication-Data": client.client_final(
                        p.properties["Authentication-Data"]
                    ),
                },
            ),
            pkt.MQTT_V5,
        )
    )
    p = await recv2()
    assert p.type == pkt.CONNACK and p.reason_code == pkt.RC_SUCCESS
    # now re-authenticate on the live connection
    re_client = ScramClient("alice", "wonder")
    writer.write(
        serialize(
            pkt.Auth(
                reason_code=pkt.RC_REAUTHENTICATE,
                properties={
                    "Authentication-Method": scram.METHOD,
                    "Authentication-Data": re_client.client_first(),
                },
            ),
            pkt.MQTT_V5,
        )
    )
    p = await recv2()
    assert p.type == pkt.AUTH
    assert p.reason_code == pkt.RC_CONTINUE_AUTHENTICATION
    writer.write(
        serialize(
            pkt.Auth(
                reason_code=pkt.RC_CONTINUE_AUTHENTICATION,
                properties={
                    "Authentication-Method": scram.METHOD,
                    "Authentication-Data": re_client.client_final(
                        p.properties["Authentication-Data"]
                    ),
                },
            ),
            pkt.MQTT_V5,
        )
    )
    p = await recv2()
    assert p.type == pkt.AUTH and p.reason_code == pkt.RC_SUCCESS
    assert re_client.verify_server(p.properties["Authentication-Data"])
    writer.close()

    # unknown method -> CONNACK bad authentication method
    reader, writer = await asyncio.open_connection("127.0.0.1", l.port)
    parser = Parser(version=pkt.MQTT_V5)
    writer.write(
        serialize(
            pkt.Connect(
                client_id="x",
                proto_ver=pkt.MQTT_V5,
                properties={"Authentication-Method": "GS2-KRB5"},
            ),
            pkt.MQTT_V5,
        )
    )
    data = await asyncio.wait_for(reader.read(4096), 5)
    p = parser.feed(data)[0]
    assert p.type == pkt.CONNACK
    assert p.reason_code == pkt.RC_BAD_AUTHENTICATION_METHOD
    writer.close()
    await listeners.stop_all()


@async_test
async def test_scram_does_not_bypass_ban_gate():
    """Enhanced auth must still hit the banned gate on the authenticate
    hookpoint (regression: skip_chain once bypassed Banned/Flapping)."""
    from emqx_tpu.broker.banned import Banned, BanEntry
    from emqx_tpu.broker.broker import Broker
    from emqx_tpu.broker.channel import ChannelConfig
    from emqx_tpu.broker.cm import ChannelManager
    from emqx_tpu.broker.hooks import Hooks
    from emqx_tpu.mqtt.frame import Parser, serialize
    from emqx_tpu.transport.listener import ListenerConfig, Listeners

    scram = ScramAuthenticator(iterations=512)
    scram.add_user("alice", "wonder")
    hooks = Hooks()
    broker = Broker(hooks=hooks)
    banned = Banned()
    banned.add(BanEntry(kind="clientid", value="outlaw", by="test"))
    banned.attach(hooks)
    cm = ChannelManager(broker)
    listeners = Listeners(broker, cm)
    l = await listeners.start_listener(
        ListenerConfig(port=0),
        ChannelConfig(enhanced_auth={scram.METHOD: scram}),
    )
    reader, writer = await asyncio.open_connection("127.0.0.1", l.port)
    parser = Parser(version=pkt.MQTT_V5)
    client = ScramClient("alice", "wonder")
    writer.write(
        serialize(
            pkt.Connect(
                client_id="outlaw",
                proto_ver=pkt.MQTT_V5,
                properties={
                    "Authentication-Method": scram.METHOD,
                    "Authentication-Data": client.client_first(),
                },
            ),
            pkt.MQTT_V5,
        )
    )

    async def recv():
        while True:
            data = await asyncio.wait_for(reader.read(4096), 5)
            assert data
            pkts = parser.feed(data)
            if pkts:
                return pkts[0]

    p = await recv()
    assert p.type == pkt.AUTH  # SCRAM exchange proceeds...
    writer.write(
        serialize(
            pkt.Auth(
                reason_code=pkt.RC_CONTINUE_AUTHENTICATION,
                properties={
                    "Authentication-Method": scram.METHOD,
                    "Authentication-Data": client.client_final(
                        p.properties["Authentication-Data"]
                    ),
                },
            ),
            pkt.MQTT_V5,
        )
    )
    p = await recv()
    # ...but the ban gate still rejects at CONNACK
    assert p.type == pkt.CONNACK
    assert p.reason_code == pkt.RC_BANNED
    writer.close()
    await listeners.stop_all()


# -- PSK / file ACL ----------------------------------------------------------


def test_psk_store(tmp_path):
    store = PskStore()
    store.insert("dev1", "deadbeef")
    assert store.lookup("dev1") == bytes.fromhex("deadbeef")
    assert store.lookup("devX") is None

    f = tmp_path / "psk.txt"
    f.write_text("# comment\nclient1:aabbcc\nbadline\nclient2:00ff\n")
    assert store.import_file(str(f)) == 2
    assert sorted(store.identities()) == ["client1", "client2", "dev1"]
    assert store.delete("dev1") is True

    import ssl

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    # on interpreters without PSK support this reports False and leaves
    # the context usable; with support it must return True
    ok = store.wire_into(ctx)
    assert ok == hasattr(ssl.SSLContext, "set_psk_server_callback")


def test_file_acl_rules():
    text = """
# comments are fine
{"permit": "deny", "who": {"username": "mallory"}, "action": "all", "topics": ["#"]}
{"permit": "allow", "who": "all", "action": "publish", "topics": ["pub/${clientid}/#"]}
"""
    rules = parse_rules(text)
    az = Authorizer(rules=rules, no_match="deny")
    assert az.check({"client_id": "c1", "username": "mallory"}, "publish", "a") == "deny"
    assert az.check({"client_id": "c1", "username": "u"}, "publish", "pub/c1/x") == "allow"
    assert az.check({"client_id": "c1", "username": "u"}, "publish", "pub/c2/x") == "deny"
    with pytest.raises(ValueError):
        parse_rules('{"who": "all"}')  # missing permit


@async_test
async def test_license_verification_and_gate():
    """lib-ee/emqx_license parity: signed license, expiry alarm,
    connection gate."""
    import time as _time

    from emqx_tpu import license as lic
    from emqx_tpu.app import BrokerApp
    from emqx_tpu.config.schema import load_config
    from tests.minimqtt import MiniClient

    n, e, d = _gen_rsa(1024)
    key = lic.sign(
        (n, d),
        {"customer": "acme", "edition": "enterprise",
         "max_connections": 2, "expiry_at": _time.time() + 3600},
    )
    # standalone parse/verify semantics
    parsed = lic.parse(key, (n, e))
    assert parsed.customer == "acme" and parsed.max_connections == 2
    with pytest.raises(lic.LicenseError):
        lic.parse(key[:-8] + "AAAAAAAA", (n, e))
    expired = lic.sign((n, d), {"customer": "x", "expiry_at": 1.0})
    assert lic.parse(expired, (n, e)).expired()

    app = BrokerApp(
        load_config(
            {
                "listeners": [{"port": 0, "bind": "127.0.0.1"}],
                "dashboard": {"enable": False},
                "router": {"enable_tpu": False},
                "license": {"key": key, "pubkey_n": hex(n)[2:]},
            }
        )
    )
    await app.start()
    try:
        port = list(app.listeners.list().values())[0].port
        c1 = MiniClient("lic-1")
        assert (await c1.connect("127.0.0.1", port))["rc"] == 0
        c2 = MiniClient("lic-2")
        assert (await c2.connect("127.0.0.1", port))["rc"] == 0
        c3 = MiniClient("lic-3")  # over max_connections=2
        ack = await c3.connect("127.0.0.1", port)
        assert ack["rc"] != 0
        await c1.disconnect()
        await asyncio.sleep(0.1)
        c4 = MiniClient("lic-4")  # slot freed
        assert (await c4.connect("127.0.0.1", port))["rc"] == 0
        assert app.license.license.info()["customer"] == "acme"
    finally:
        await app.stop()
