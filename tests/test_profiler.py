"""Performance provenance & device profiling plane (PR 16).

Pins the observability contracts docs/observability.md ("Profiling &
provenance") names:

- the launch waterfall: every stage of the serving path (prepare ->
  queue-wait -> launch -> device-execute -> readback -> host-dispatch)
  records into its own histogram on the REAL BatchIngest path, and the
  stage means tile the measured enqueue->settle latency;
- per-kernel cost attribution: `device.kernel.<name>.*` series are
  keyed to @device_contract REGISTRY names — the route, session-ride,
  and semantic kernels each show up when their path runs;
- the disarmed profiler is structurally zero (racetrack discipline):
  no capture object, no trace directory, no series, no tick work;
- the REST arm/capture/disarm lifecycle with a REAL on-disk byte
  budget (an over-budget capture is deleted, not kept);
- the static cost harvest covers the ENTIRE contract registry via the
  audit's own config-matrix recipes;
- hardware fingerprints are stable within a process, proxy-tagged off
  TPU, and stamped into bench emitters;
- tools/bench_trend.py flags same-fingerprint regressions and REFUSES
  cross-fingerprint comparisons.
"""

import asyncio
import functools
import json
import os

import numpy as np
import pytest

from emqx_tpu.broker.broker import Broker
from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.broker.ingest import BatchIngest
from emqx_tpu.broker.message import Message
from emqx_tpu.broker.metrics import Metrics
from emqx_tpu.broker.router import Router
from emqx_tpu.broker.session import Session, SessionConfig
from emqx_tpu.broker.session_store import SessionStore
from emqx_tpu.mqtt import packet as pkt
from emqx_tpu.observe import provenance
from emqx_tpu.observe.profiler import (
    STAGES,
    Profiler,
    harvest_cost,
    kernel_summary,
    record_kernel_launch,
    roofline_summary,
    waterfall,
)
from emqx_tpu.ops.contract import REGISTRY


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*a, **kw):
        asyncio.run(asyncio.wait_for(fn(*a, **kw), timeout=120))

    return wrapper


def _mk_broker(min_batch=1):
    return Broker(router=Router(min_tpu_batch=min_batch), hooks=Hooks())


def _sub_n(b, n, sink=None):
    for i in range(n):
        b.subscribe(
            f"s{i}", f"c{i}", f"t/{i}/+", pkt.SubOpts(),
            (lambda m, o: sink.append(m.topic)) if sink is not None
            else (lambda m, o: None),
        )


def _msgs(n, qos=0):
    return [
        Message(topic=f"t/{i % 8}/x", payload=b"p", qos=qos,
                from_client=f"pub{i}")
        for i in range(n)
    ]


# -- launch waterfall on the real ingest path --------------------------------


class TestWaterfall:
    @async_test
    async def test_stage_sums_tile_the_settle_latency(self):
        """Every waterfall stage records on the real enqueue->settle
        path, and the per-message stage means reconstruct the measured
        `ingest.settle.seconds` mean to within tolerance: the waterfall
        is an attribution of the SLO latency, not a parallel universe
        of timers."""
        b = _mk_broker(min_batch=8)
        _sub_n(b, 8)
        ing = BatchIngest(b, max_batch=64, window_us=500)
        b.ingest = ing
        ing.start()
        # warm batch: the jit compile lands outside the measured window
        await b.apublish_enqueue(Message(topic="t/0/w", payload=b"w"))
        await asyncio.sleep(0.2)
        rs = [await b.apublish_enqueue(m) for m in _msgs(256)]
        await asyncio.gather(*[r for r in rs if not isinstance(r, int)])
        await ing.stop()
        m = b.metrics
        wf = waterfall(m)
        assert set(wf) == set(STAGES)
        for stage in STAGES:
            assert wf[stage] is not None, f"stage {stage} never observed"
            assert wf[stage]["count"] > 0
            assert wf[stage]["p99"] >= wf[stage]["p50"] >= 0.0
        settle = m.histogram("ingest.settle.seconds")
        assert settle is not None and settle.count > 0
        settle_mean = settle.sum / settle.count
        # queue_wait is per-message; the remaining stages are per-batch
        # and shared by every message that rode the batch — their means
        # add directly onto the per-message queue wait
        stage_sum = sum(wf[s]["mean"] for s in STAGES)
        # tolerant tiling: executor hops / loop scheduling live in the
        # gaps, and histogram means are bucket-interpolated
        assert stage_sum <= settle_mean * 2.0 + 0.05, (
            stage_sum, settle_mean)
        assert stage_sum >= settle_mean * 0.2, (stage_sum, settle_mean)


# -- per-kernel attribution keyed to contract names --------------------------


class TestKernelAttribution:
    def test_route_kernels_attributed_under_registry_names(self):
        b = _mk_broker()
        _sub_n(b, 8)
        dr = b._device_router()
        res = dr.route_prepared(dr.prepare(),
                                [m.topic for m in _msgs(16)])
        assert res.kernels, "RouteResult.kernels must name the program"
        for name in res.kernels:
            assert name in REGISTRY, name
        ks = kernel_summary(b.metrics)
        hit = [k for k in res.kernels if k in ks]
        assert hit, (res.kernels, sorted(ks))
        for k in hit:
            assert ks[k]["launches"] >= 1
            assert ks[k]["mean_ms"] > 0.0
        # the route program itself rode the launch
        assert any(
            k in ks for k in ("shape_route_step",
                              "sparse_shape_route_step")
        ), sorted(ks)

    @async_test
    async def test_session_ride_attributes_session_ack_step(self):
        b = _mk_broker()
        store = SessionStore(metrics=b.metrics, capacity=256,
                             sweep_slots=64, retry_interval=30.0)
        b.session_store = store
        sess = Session("c0", SessionConfig(), store=store)
        sent = []

        def deliver(m, o):
            sent.extend(sess.deliver(m, o))

        b.subscribe("c0", "c0", "t/#", pkt.SubOpts(qos=1), deliver)
        await b.adispatch_batch_folded(_msgs(8, qos=1))
        for p in sent[:4]:
            sess.puback(p.packet_id)
        await b.adispatch_batch_folded(_msgs(8, qos=1))  # rider batch
        ks = kernel_summary(b.metrics)
        assert "session_ack_step" in ks, sorted(ks)
        assert ks["session_ack_step"]["launches"] >= 1

    def test_semantic_match_attributed(self):
        from emqx_tpu.broker.semantic import SemanticRouting

        rng = np.random.default_rng(7)
        dim = 16

        def unit():
            v = rng.normal(size=dim).astype(np.float32)
            return v / np.linalg.norm(v)

        b = _mk_broker()
        b.semantic = SemanticRouting(dim=dim, topk=4, threshold=0.3,
                                     metrics=b.metrics)
        opts = pkt.SubOpts(qos=0)
        b.subscribe("p1", "p1", "a/#", opts, lambda m, o: None)
        for i in range(4):
            b.subscribe(f"m{i}", f"m{i}", "a/#", opts,
                        lambda m, o: None,
                        embedding=unit(), sem_threshold=0.3)
        msgs = []
        for i in range(8):
            m = Message(topic=f"a/{i}", payload=b"{}",
                        from_client="pub")
            m.headers["semantic_embedding"] = unit()
            msgs.append(m)
        b.dispatch_batch_folded(msgs)
        ks = kernel_summary(b.metrics)
        assert "semantic_match_step" in ks, sorted(ks)
        assert ks["semantic_match_step"]["launches"] >= 1

    def test_record_kernel_launch_is_metrics_optional(self):
        # bare-library semantics: no metrics registry, no crash
        record_kernel_launch(None, ("shape_route_step",), 0.001, 64)


# -- disarmed profiler: structurally zero ------------------------------------


class TestDisarmedStructuralZero:
    def test_disarmed_is_inert(self, tmp_path):
        """Racetrack discipline: DISARMED means no capture object, no
        trace directory on disk, a no-op tick, and a None disarm —
        there is nothing for the hot path to even check."""
        m = Metrics()
        trace_dir = str(tmp_path / "captures")
        p = Profiler(metrics=m, trace_dir=trace_dir)
        assert p.capture is None
        assert p.armed is False
        assert not os.path.exists(trace_dir)  # nothing made eagerly
        p.tick()  # no-op while disarmed
        assert p.disarm() is None
        assert not os.path.exists(trace_dir)
        assert m.get("profile.captures") == 0
        snap = p.snapshot()
        assert snap["armed"] is False
        assert snap["capture"] is None
        assert snap["history"] == []
        assert snap["cost_harvested"] is False
        assert p.cost_cached() is None


# -- capture lifecycle + file budget -----------------------------------------


class TestCaptureLifecycle:
    def test_arm_capture_disarm_with_budget_kept(self, tmp_path):
        import jax
        import jax.numpy as jnp

        m = Metrics()
        p = Profiler(metrics=m, trace_dir=str(tmp_path))
        try:
            info = p.arm(duration_s=20.0)
            assert p.armed and os.path.isdir(info["dir"])
            with pytest.raises(RuntimeError):
                p.arm()  # one capture at a time (process-global trace)
            jax.block_until_ready(
                jnp.ones((64, 64)) @ jnp.ones((64, 64))
            )
        finally:
            entry = p.disarm("test")
        assert entry is not None
        assert entry["bytes"] > 0, "capture files must be non-empty"
        assert entry["deleted"] is False
        assert os.path.isdir(entry["dir"])
        assert p.capture is None
        assert m.get("profile.captures") == 1
        assert p.snapshot()["history"][-1]["reason"] == "test"

    def test_over_budget_capture_is_deleted(self, tmp_path):
        import jax
        import jax.numpy as jnp

        p = Profiler(metrics=Metrics(), trace_dir=str(tmp_path))
        try:
            info = p.arm(duration_s=20.0, max_bytes=1)  # clamps to 64 KiB
            assert info["max_bytes"] == 1 << 16
            jax.block_until_ready(
                jnp.ones((128, 128)) @ jnp.ones((128, 128))
            )
        finally:
            entry = p.disarm("budget-test")
        assert entry is not None and entry["bytes"] > 1 << 16
        assert entry["over_budget"] is True and entry["deleted"] is True
        assert not os.path.exists(entry["dir"]), (
            "over-budget captures must be removed from disk"
        )

    def test_tick_auto_disarms_past_deadline(self, tmp_path):
        import time as _time

        p = Profiler(metrics=Metrics(), trace_dir=str(tmp_path))
        p.arm(duration_s=0.1)
        p.tick(now=_time.time() + 5.0)  # housekeeping past the deadline
        assert p.capture is None
        hist = p.snapshot()["history"]
        assert hist and hist[-1]["reason"] == "deadline"

    @async_test
    async def test_rest_arm_capture_disarm_lifecycle(self, tmp_path):
        import aiohttp

        from emqx_tpu.app import BrokerApp
        from emqx_tpu.config.schema import load_config

        app = BrokerApp(load_config({
            "listeners": [{"port": 0, "bind": "127.0.0.1"}],
            "dashboard": {"port": 0, "bind": "127.0.0.1"},
            "observe": {"profile_trace_dir": str(tmp_path)},
        }))
        await app.start()
        try:
            api = f"http://127.0.0.1:{app.mgmt_server.port}/api/v5"
            async with aiohttp.ClientSession() as s:
                async with s.get(f"{api}/profile") as r:
                    assert r.status == 200
                    snap = await r.json()
                    assert snap["armed"] is False
                    assert snap["fingerprint"]["proxy"] is True
                    assert set(snap["waterfall"]) == set(STAGES)
                async with s.post(
                    f"{api}/profile", json={"duration_s": 20.0}
                ) as r:
                    assert r.status == 201
                    info = await r.json()
                    assert info["dir"].startswith(str(tmp_path))
                async with s.post(f"{api}/profile", json={}) as r:
                    assert r.status == 400  # already armed
                # the armed state is visible in the hotpath block too
                async with s.get(f"{api}/metrics/hotpath") as r:
                    hp = await r.json()
                    assert hp["profile"]["capture_armed"] is True
                    assert hp["profile"]["proxy"] is True
                    assert hp["profile"]["fingerprint"]
                async with s.delete(f"{api}/profile") as r:
                    assert r.status == 200
                    entry = await r.json()
                    assert entry["reason"] == "rest"
                async with s.delete(f"{api}/profile") as r:
                    assert r.status == 204  # idempotent when disarmed
                async with s.get(f"{api}/profile") as r:
                    snap = await r.json()
                    assert snap["armed"] is False
                    assert len(snap["history"]) == 1
        finally:
            await app.stop()


# -- static cost harvest over the contract matrix ----------------------------


class TestCostHarvest:
    def test_harvest_covers_entire_contract_registry(self):
        """Every @device_contract kernel compiles through the audit's
        own harness recipes and yields a roofline row — a kernel the
        harvest cannot reach lands in `skipped`, never silently."""
        # populate the registry exactly as the audit does
        import emqx_tpu.models.router_model  # noqa: F401
        import emqx_tpu.ops.session_table  # noqa: F401
        import emqx_tpu.parallel.mesh  # noqa: F401

        assert len(REGISTRY) >= 14
        out = harvest_cost(max_configs_per_kernel=1)
        names = {r["kernel"] for r in out["rows"]}
        assert names == set(REGISTRY), (
            sorted(set(REGISTRY) - names), out["skipped"])
        for r in out["rows"]:
            assert r["flops"] >= 0.0
            assert r["bytes_accessed"] >= 0.0
            assert r["config"]
            if r["arithmetic_intensity"] is not None:
                assert r["bound"] in ("compute", "memory")
                assert r["attainable_flops"] > 0.0
        assert out["proxy"] is True  # CPU run: peaks are placeholders
        roof = roofline_summary(out)
        assert set(roof["kernels"]) == names
        assert roofline_summary(None) is None

    def test_profiler_caches_harvest(self):
        p = Profiler(metrics=Metrics())
        first = p.cost_harvest(max_configs_per_kernel=1)
        assert p.cost_cached() is first
        assert p.cost_harvest(max_configs_per_kernel=1) is first
        assert p.metrics.gauge("profile.cost.kernels") >= 14


# -- provenance fingerprints -------------------------------------------------


class TestProvenance:
    def test_fingerprint_is_stable_and_proxy_tagged(self):
        fp1 = provenance.fingerprint()
        fp2 = provenance.fingerprint()
        assert fp1 == fp2
        assert fp1 is not fp2  # callers get copies, not the cache
        for key in provenance.KEY_FIELDS:
            assert key in fp1, key
        # the tier-1 environment is never a TPU: proxy MUST be true
        assert fp1["platform"] != "tpu"
        assert fp1["proxy"] is True
        assert provenance.is_proxy() is True
        assert provenance.fingerprint_key(fp1) == \
            provenance.fingerprint_key(fp2)
        assert str(fp1["platform"]) in provenance.fingerprint_key(fp1)

    def test_stamp_and_resource_attrs(self):
        doc = {"metric": "x", "value": 1.0}
        out = provenance.stamp(doc)
        assert out is doc
        assert doc["proxy"] is True
        assert doc["fingerprint"]["platform"] == \
            provenance.fingerprint()["platform"]
        attrs = provenance.resource_attrs()
        assert attrs["hw.proxy"] is True
        assert attrs["hw.platform"] == doc["fingerprint"]["platform"]

    def test_span_exporter_carries_hw_resource_attrs(self, tmp_path):
        from emqx_tpu.observe.spans import OtlpFileExporter, Span

        path = str(tmp_path / "spans.jsonl")
        exp = OtlpFileExporter(path, flush_every=1)
        exp.export([Span(trace_id="t" * 32, span_id="s" * 16,
                         name="probe", start_ns=1, end_ns=2)])
        exp.flush()
        with open(path) as f:
            env = json.loads(f.readline())
        attrs = {
            a["key"]: a["value"]
            for a in env["resourceSpans"][0]["resource"]["attributes"]
        }
        assert attrs["service.name"] == {"stringValue": "emqx_tpu"}
        assert attrs["hw.proxy"] == {"boolValue": True}
        assert "hw.platform" in attrs and "hw.git_sha" in attrs


# -- bench trend: fingerprint-grouped regression gate ------------------------


def _fp(**over):
    fp = {
        "platform": "cpu", "device_kind": "cpu", "device_count": 1,
        "host_cores": 1, "jax": "0.0", "jaxlib": "0.0",
        "git_sha": "abc", "clock_source": "tsc", "proxy": True,
    }
    fp.update(over)
    return fp


def _bench_wrapper(n, value, fp, metric="e2e_serving_msgs_per_s",
                   detail=None):
    doc = {"metric": metric, "value": value, "unit": "msgs/s",
           "detail": detail or {}, "fingerprint": fp,
           "proxy": fp["proxy"] if fp else True}
    if fp is None:
        doc.pop("fingerprint")
        doc.pop("proxy")
    return {"n": n, "cmd": "bench", "rc": 0, "parsed": None,
            "tail": "noise line\n" + json.dumps(doc)}


class TestBenchTrend:
    def _write(self, tmp_path, runs):
        for n, run in enumerate(runs, start=1):
            (tmp_path / f"BENCH_r{n:02d}.json").write_text(
                json.dumps(run)
            )

    def test_same_fingerprint_regression_fails_check(self, tmp_path):
        from tools import bench_trend

        fp = _fp()
        self._write(tmp_path, [
            _bench_wrapper(1, 100_000.0, fp),
            _bench_wrapper(2, 40_000.0, fp),  # -60% past any threshold
        ])
        rc = bench_trend.main(["--dir", str(tmp_path), "--check",
                               "--out", str(tmp_path / "trend.md")])
        assert rc == 1
        report = (tmp_path / "trend.md").read_text()
        assert "REGRESSIONS" in report
        assert "e2e_serving_msgs_per_s" in report

    def test_improvement_and_within_threshold_pass(self, tmp_path):
        from tools import bench_trend

        fp = _fp()
        self._write(tmp_path, [
            _bench_wrapper(1, 100_000.0, fp),
            _bench_wrapper(2, 95_000.0, fp),   # -5%: inside threshold
            _bench_wrapper(3, 200_000.0, fp),  # improvement
        ])
        rc = bench_trend.main(["--dir", str(tmp_path), "--check",
                               "--out", str(tmp_path / "trend.md")])
        assert rc == 0

    def test_cross_fingerprint_comparison_rejected(self, tmp_path):
        from tools import bench_trend

        self._write(tmp_path, [
            _bench_wrapper(1, 1_000_000.0, _fp(device_kind="tpu-v5p",
                                               platform="tpu",
                                               proxy=False)),
            # same metric, 100x lower on different hardware: NOT a
            # regression — the comparison itself must be refused
            _bench_wrapper(2, 10_000.0, _fp()),
        ])
        runs = bench_trend.load_trajectory(str(tmp_path))
        cmp = bench_trend.compare(runs, 0.25)
        assert cmp["regressions"] == []
        assert cmp["rejected"] >= 1
        rc = bench_trend.main(["--dir", str(tmp_path), "--check",
                               "--out", str(tmp_path / "trend.md")])
        assert rc == 0

    def test_legacy_runs_backfilled_and_never_compared(self, tmp_path):
        from tools import bench_trend

        self._write(tmp_path, [
            _bench_wrapper(1, 100_000.0, None),  # pre-provenance
            _bench_wrapper(2, 1_000.0, None),
        ])
        runs = bench_trend.load_trajectory(str(tmp_path))
        assert all(r["fingerprint"] is None for r in runs)
        assert all(r["proxy"] is True for r in runs)
        assert all(r["key"] == bench_trend.LEGACY_KEY for r in runs)
        cmp = bench_trend.compare(runs, 0.25)
        assert cmp["regressions"] == []  # unattributable: no baseline
        assert cmp["rejected"] >= 1

    def test_lower_is_better_direction(self, tmp_path):
        from tools import bench_trend

        fp = _fp()
        self._write(tmp_path, [
            _bench_wrapper(1, 100_000.0, fp,
                           detail={"e2e_paced_p99_ms": 1.0}),
            _bench_wrapper(2, 100_000.0, fp,
                           detail={"e2e_paced_p99_ms": 5.0}),
        ])
        rc = bench_trend.main(["--dir", str(tmp_path), "--check",
                               "--out", str(tmp_path / "trend.md")])
        assert rc == 1  # 5x the p99 latency IS a regression
        assert not bench_trend.lower_is_better("e2e_serving_msgs_per_s")
        assert bench_trend.lower_is_better("e2e_paced_p99_ms")

    def test_committed_trajectory_passes_check(self):
        from tools import bench_trend

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        rc = bench_trend.main(["--dir", root, "--check",
                               "--out", os.devnull])
        assert rc == 0
