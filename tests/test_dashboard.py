"""Dashboard backend: JWT admin login, protected API, monitor stream.

Parity: apps/emqx_dashboard (emqx_dashboard_admin JWT tokens,
emqx_dashboard_monitor sampling + WebSocket stream).
"""

import asyncio
import functools

import pytest

from emqx_tpu.app import BrokerApp
from emqx_tpu.config.schema import load_config


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*a, **kw):
        asyncio.run(asyncio.wait_for(fn(*a, **kw), timeout=30))

    return wrapper


def _cfg(**dash):
    return load_config(
        {
            "listeners": [{"port": 0, "bind": "127.0.0.1"}],
            "dashboard": {"port": 0, "bind": "127.0.0.1", **dash},
            "router": {"enable_tpu": False},
        }
    )


@async_test
async def test_admin_jwt_login_protects_api():
    import aiohttp

    app = BrokerApp(
        _cfg(admins={"root": "hunter2"}, monitor_interval=0.1)
    )
    await app.start()
    try:
        base = f"http://127.0.0.1:{app.mgmt_server.port}"
        async with aiohttp.ClientSession() as s:
            # protected without a token
            async with s.get(f"{base}/api/v5/status") as r:
                assert r.status == 401
            # the status page and login stay public
            async with s.get(f"{base}/") as r:
                assert r.status == 200
                assert "emqx_tpu" in await r.text()
            async with s.post(
                f"{base}/api/v5/login",
                json={"username": "root", "password": "wrong"},
            ) as r:
                assert r.status == 401
            async with s.post(
                f"{base}/api/v5/login",
                json={"username": "root", "password": "hunter2"},
            ) as r:
                assert r.status == 200
                token = (await r.json())["token"]
            hdrs = {"Authorization": f"Bearer {token}"}
            async with s.get(f"{base}/api/v5/status", headers=hdrs) as r:
                assert r.status == 200
            # garbage token rejected
            async with s.get(
                f"{base}/api/v5/status",
                headers={"Authorization": "Bearer junk.t.x"},
            ) as r:
                assert r.status == 401

            # monitor: current sample + history + websocket stream
            async with s.get(
                f"{base}/api/v5/monitor_current", headers=hdrs
            ) as r:
                cur = await r.json()
                assert {"connections", "subscriptions", "received"} <= set(cur)
            await asyncio.sleep(0.35)
            async with s.get(
                f"{base}/api/v5/monitor_history", headers=hdrs
            ) as r:
                hist = (await r.json())["data"]
                assert len(hist) >= 2
            async with s.ws_connect(
                f"{base}/api/v5/monitor", headers=hdrs
            ) as ws:
                first = await asyncio.wait_for(ws.receive_json(), 5)
                assert "connections" in first
                second = await asyncio.wait_for(ws.receive_json(), 5)
                assert second["at"] >= first["at"]
    finally:
        await app.stop()


@async_test
async def test_dev_mode_stays_open():
    import aiohttp

    app = BrokerApp(_cfg())
    await app.start()
    try:
        base = f"http://127.0.0.1:{app.mgmt_server.port}"
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{base}/api/v5/status") as r:
                assert r.status == 200
    finally:
        await app.stop()
