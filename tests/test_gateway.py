"""Gateway framework tests: STOMP, MQTT-SN, exproto clients driving the broker.

Each protocol is exercised by a raw-socket client implemented in the test
(independent of the gateway's codec where practical), bridging into the
same core Broker an MQTT client uses — the parity target is the
reference's per-gateway CT suites (apps/emqx_gateway/test/).
"""

import asyncio
import functools
import struct

import pytest

from emqx_tpu.broker.broker import Broker
from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.gateway.mqttsn import (
    CONNACK,
    CONNECT,
    DISCONNECT,
    PINGREQ,
    PINGRESP,
    PUBACK,
    PUBLISH,
    REGACK,
    REGISTER,
    SUBACK,
    SUBSCRIBE,
    UNSUBACK,
    UNSUBSCRIBE,
    SnGateway,
    decode,
    encode,
    flags_from,
    TOPIC_PREDEF,
)
from emqx_tpu.gateway.registry import GatewayRegistry
from emqx_tpu.gateway.stomp import StompCodec, StompFrame, StompGateway
from emqx_tpu.mqtt import packet as pkt


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*a, **kw):
        asyncio.run(asyncio.wait_for(fn(*a, **kw), timeout=30))

    return wrapper


class GwBed:
    """Broker + gateway registry, no MQTT listener needed."""

    __test__ = False

    def __init__(self):
        self.hooks = Hooks()
        self.broker = Broker(hooks=self.hooks)
        self.registry = GatewayRegistry(self.broker, self.hooks)
        self.registry.register_type("stomp", StompGateway)
        self.registry.register_type("mqttsn", SnGateway)

    def collect(self, filter_, bucket):
        """Subscribe an in-process MQTT-side observer."""
        self.broker.subscribe(
            "obs",
            "obs",
            filter_,
            pkt.SubOpts(qos=0),
            lambda msg, opts: bucket.append(msg),
        )


class StompClient:
    """Minimal independent STOMP client for tests."""

    def __init__(self):
        self.codec = StompCodec()
        self.frames = asyncio.Queue()

    async def connect(self, port, headers=None):
        self.reader, self.writer = await asyncio.open_connection(
            "127.0.0.1", port
        )
        self._task = asyncio.get_running_loop().create_task(self._read_loop())
        h = {"accept-version": "1.2", "host": "/"}
        h.update(headers or {})
        self.send("CONNECT", h)
        f = await self.recv()
        assert f.command == "CONNECTED", f
        return f

    async def _read_loop(self):
        try:
            while True:
                data = await self.reader.read(4096)
                if not data:
                    return
                for f in self.codec.parse(data):
                    self.frames.put_nowait(f)
        except (ConnectionError, asyncio.CancelledError):
            pass

    def send(self, command, headers=None, body=b""):
        self.writer.write(
            self.codec.serialize(StompFrame(command, headers or {}, body))
        )

    async def recv(self, timeout=5.0):
        return await asyncio.wait_for(self.frames.get(), timeout)

    async def close(self):
        self._task.cancel()
        self.writer.close()


@async_test
async def test_stomp_connect_send_subscribe():
    bed = GwBed()
    gw = await bed.registry.load("stomp", {"bind": "127.0.0.1", "port": 0})
    seen = []
    bed.collect("t/#", seen)

    c = StompClient()
    await c.connect(gw.port, {"client-id": "sc1", "login": "u1"})
    # SEND -> broker
    c.send("SEND", {"destination": "t/x", "receipt": "r1"}, b"hello")
    r = await c.recv()
    assert r.command == "RECEIPT" and r.headers["receipt-id"] == "r1"
    await asyncio.sleep(0.05)
    assert len(seen) == 1 and seen[0].payload == b"hello"

    # SUBSCRIBE; deliver broker -> stomp MESSAGE
    c.send("SUBSCRIBE", {"id": "s1", "destination": "evt/+"})
    await asyncio.sleep(0.05)
    bed.broker.publish(
        __import__(
            "emqx_tpu.broker.message", fromlist=["Message"]
        ).Message(topic="evt/a", payload=b"m1")
    )
    m = await c.recv()
    assert m.command == "MESSAGE"
    assert m.headers["destination"] == "evt/a"
    assert m.headers["subscription"] == "s1"
    assert m.body == b"m1"

    # UNSUBSCRIBE stops delivery
    c.send("UNSUBSCRIBE", {"id": "s1", "receipt": "r2"})
    assert (await c.recv()).command == "RECEIPT"
    bed.broker.publish(
        __import__(
            "emqx_tpu.broker.message", fromlist=["Message"]
        ).Message(topic="evt/b", payload=b"m2")
    )
    await asyncio.sleep(0.05)
    assert c.frames.empty()
    await c.close()
    await bed.registry.unload_all()


@async_test
async def test_stomp_transactions_and_errors():
    bed = GwBed()
    gw = await bed.registry.load("stomp", {"bind": "127.0.0.1", "port": 0})
    seen = []
    bed.collect("tx/#", seen)
    c = StompClient()
    await c.connect(gw.port)
    c.send("BEGIN", {"transaction": "t1"})
    c.send("SEND", {"destination": "tx/a", "transaction": "t1"}, b"1")
    c.send("SEND", {"destination": "tx/b", "transaction": "t1"}, b"2")
    await asyncio.sleep(0.05)
    assert seen == []  # buffered until COMMIT
    c.send("COMMIT", {"transaction": "t1", "receipt": "rc"})
    assert (await c.recv()).command == "RECEIPT"
    await asyncio.sleep(0.05)
    assert sorted(m.topic for m in seen) == ["tx/a", "tx/b"]
    # ABORT drops
    c.send("BEGIN", {"transaction": "t2"})
    c.send("SEND", {"destination": "tx/c", "transaction": "t2"}, b"3")
    c.send("ABORT", {"transaction": "t2"})
    await asyncio.sleep(0.05)
    assert len(seen) == 2
    # unknown transaction -> ERROR
    c.send("COMMIT", {"transaction": "nope"})
    assert (await c.recv()).command == "ERROR"
    await c.close()
    await bed.registry.unload_all()


@async_test
async def test_stomp_duplicate_clientid_discards_old():
    bed = GwBed()
    gw = await bed.registry.load("stomp", {"bind": "127.0.0.1", "port": 0})
    c1 = StompClient()
    await c1.connect(gw.port, {"client-id": "dup"})
    c2 = StompClient()
    await c2.connect(gw.port, {"client-id": "dup"})
    await asyncio.sleep(0.05)
    assert gw.cm.count() == 1
    await c2.close()
    await bed.registry.unload_all()


class SnClient:
    """Minimal MQTT-SN UDP client."""

    def __init__(self):
        self.frames = asyncio.Queue()

    async def connect(self, port, client_id="snc", duration=60):
        loop = asyncio.get_running_loop()
        inbox = self.frames

        class P(asyncio.DatagramProtocol):
            def connection_made(self, transport):
                pass

            def datagram_received(self, data, addr):
                f = decode(data)
                if f is not None:
                    inbox.put_nowait(f)

        self.transport, _ = await loop.create_datagram_endpoint(
            P, remote_addr=("127.0.0.1", port)
        )
        self.send(
            CONNECT,
            bytes([flags_from(clean=True), 0x01])
            + struct.pack("!H", duration)
            + client_id.encode(),
        )
        f = await self.recv()
        assert f.type == CONNACK and f.fields["rc"] == 0

    def send(self, type_, body):
        self.transport.sendto(encode(type_, body))

    async def recv(self, timeout=5.0):
        return await asyncio.wait_for(self.frames.get(), timeout)

    def close(self):
        self.transport.close()


@async_test
async def test_mqttsn_register_publish_subscribe():
    bed = GwBed()
    gw = await bed.registry.load("mqttsn", {"bind": "127.0.0.1", "port": 0})
    seen = []
    bed.collect("sn/#", seen)

    c = SnClient()
    await c.connect(gw.port, "snc1")

    # REGISTER topic -> topic id
    c.send(REGISTER, struct.pack("!HH", 0, 1) + b"sn/data")
    f = await c.recv()
    assert f.type == REGACK and f.fields["rc"] == 0
    tid = f.fields["topic_id"]

    # QoS1 PUBLISH via registered id
    c.send(
        PUBLISH,
        bytes([flags_from(qos=1)])
        + struct.pack("!H", tid)
        + struct.pack("!H", 7)
        + b"snpayload",
    )
    f = await c.recv()
    assert f.type == PUBACK and f.fields["rc"] == 0 and f.fields["msg_id"] == 7
    await asyncio.sleep(0.05)
    assert len(seen) == 1 and seen[0].payload == b"snpayload"
    assert seen[0].topic == "sn/data"

    # SUBSCRIBE by name: SUBACK assigns the topic id, delivery uses it
    c.send(SUBSCRIBE, bytes([flags_from(qos=1)]) + struct.pack("!H", 9) + b"mq/evt")
    f = await c.recv()
    assert f.type == SUBACK and f.fields["rc"] == 0
    sub_tid = f.fields["topic_id"]
    assert sub_tid != 0
    from emqx_tpu.broker.message import Message

    bed.broker.publish(Message(topic="mq/evt", payload=b"down", qos=1))
    f = await c.recv()
    assert f.type == PUBLISH and f.fields["payload"] == b"down"
    assert f.fields["topic_id"] == sub_tid

    # WILDCARD subscribe: no id at SUBACK; server REGISTERs on first deliver
    c.send(SUBSCRIBE, bytes([flags_from(qos=0)]) + struct.pack("!H", 10) + b"wild/+")
    f = await c.recv()
    assert f.type == SUBACK and f.fields["topic_id"] == 0
    bed.broker.publish(Message(topic="wild/one", payload=b"w1"))
    f = await c.recv()
    assert f.type == REGISTER and f.fields["topic"] == "wild/one"
    f = await c.recv()
    assert f.type == PUBLISH and f.fields["payload"] == b"w1"

    # PINGREQ keepalive
    c.send(PINGREQ, b"")
    assert (await c.recv()).type == PINGRESP
    c.close()
    await bed.registry.unload_all()


@async_test
async def test_mqttsn_predefined_and_sleep():
    bed = GwBed()
    gw = await bed.registry.load(
        "mqttsn",
        {"bind": "127.0.0.1", "port": 0, "predefined": {5: "pre/t"}},
    )
    seen = []
    bed.collect("pre/#", seen)
    c = SnClient()
    await c.connect(gw.port, "snc2")
    # publish to predefined id 5
    c.send(
        PUBLISH,
        bytes([flags_from(qos=0, topic_type=TOPIC_PREDEF)])
        + struct.pack("!H", 5)
        + struct.pack("!H", 0)
        + b"pd",
    )
    await asyncio.sleep(0.05)
    assert len(seen) == 1 and seen[0].topic == "pre/t"

    # subscribe then sleep; messages buffer; PINGREQ flushes
    c.send(SUBSCRIBE, bytes([flags_from(qos=0)]) + struct.pack("!H", 2) + b"pre/t")
    f = await c.recv()
    assert f.type == SUBACK
    c.send(DISCONNECT, struct.pack("!H", 30))  # sleep 30s
    f = await c.recv()
    assert f.type == DISCONNECT
    from emqx_tpu.broker.message import Message

    bed.broker.publish(Message(topic="pre/t", payload=b"while-asleep"))
    await asyncio.sleep(0.05)
    assert c.frames.empty()  # buffered, not delivered
    c.send(PINGREQ, b"snc2")
    got = [await c.recv(), await c.recv()]
    types = {g.type for g in got}
    assert PINGRESP in types and PUBLISH in types
    c.close()
    await bed.registry.unload_all()


@async_test
async def test_mqttsn_unsubscribe():
    bed = GwBed()
    gw = await bed.registry.load("mqttsn", {"bind": "127.0.0.1", "port": 0})
    c = SnClient()
    await c.connect(gw.port, "snc3")
    c.send(SUBSCRIBE, bytes([flags_from(qos=0)]) + struct.pack("!H", 3) + b"u/t")
    assert (await c.recv()).type == SUBACK
    c.send(UNSUBSCRIBE, bytes([flags_from()]) + struct.pack("!H", 4) + b"u/t")
    assert (await c.recv()).type == UNSUBACK
    from emqx_tpu.broker.message import Message

    bed.broker.publish(Message(topic="u/t", payload=b"x"))
    await asyncio.sleep(0.05)
    assert c.frames.empty()
    c.close()
    await bed.registry.unload_all()


@async_test
async def test_registry_lifecycle():
    bed = GwBed()
    gw = await bed.registry.load("stomp", {"bind": "127.0.0.1", "port": 0})
    assert bed.registry.get("stomp") is gw
    assert [s["name"] for s in bed.registry.list()] == ["stomp"]
    with pytest.raises(ValueError):
        await bed.registry.load("stomp", {})  # duplicate name
    with pytest.raises(ValueError):
        await bed.registry.load("nope", {})  # unknown type
    assert await bed.registry.unload("stomp") is True
    assert await bed.registry.unload("stomp") is False
    assert bed.registry.list() == []


@async_test
async def test_gateway_rest_api():
    """REST load/list/unload of gateways (emqx_mgmt_api_gateway analog)."""
    import aiohttp

    from emqx_tpu.app import BrokerApp
    from emqx_tpu.config.schema import load_config

    app = BrokerApp(
        load_config(
            {
                "listeners": [{"port": 0, "bind": "127.0.0.1"}],
                "dashboard": {"port": 0, "bind": "127.0.0.1"},
                "router": {"enable_tpu": False},
            }
        )
    )
    await app.start()
    try:
        api = f"http://127.0.0.1:{app.mgmt_server.port}/api/v5"
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{api}/gateways") as r:
                assert (await r.json())["data"] == []
            async with s.post(
                f"{api}/gateways",
                json={"type": "stomp", "opts": {"bind": "127.0.0.1", "port": 0}},
            ) as r:
                assert r.status == 201
                st = await r.json()
                assert st["name"] == "stomp" and st["running"]
            async with s.get(f"{api}/gateways/stomp") as r:
                assert r.status == 200
            # the loaded gateway accepts a real client
            c = StompClient()
            await c.connect(app.gateways.get("stomp").port)
            await c.close()
            async with s.post(f"{api}/gateways", json={"type": "bogus"}) as r:
                assert r.status == 400
            async with s.delete(f"{api}/gateways/stomp") as r:
                assert r.status == 204
            async with s.get(f"{api}/gateways/stomp") as r:
                assert r.status == 404
    finally:
        await app.stop()
