"""End-to-end broker tests over real TCP sockets with the in-repo client.

Parity targets: the client-visible behaviors of the reference's
emqx_mqtt_SUITE / emqx_mqtt_protocol_v5_SUITE (driven there with the real
emqtt client; SURVEY.md §4).
"""

import asyncio
import functools

import pytest

from emqx_tpu.broker.broker import Broker
from emqx_tpu.broker.channel import ChannelConfig
from emqx_tpu.broker.cm import ChannelManager
from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.broker.session import SessionConfig
from emqx_tpu.mqtt import packet as pkt
from emqx_tpu.mqtt.client import Client, MqttError
from emqx_tpu.transport.listener import ListenerConfig, Listeners


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*a, **kw):
        asyncio.run(asyncio.wait_for(fn(*a, **kw), timeout=30))

    return wrapper


class TestBed:
    """One broker + listener on an ephemeral port."""

    __test__ = False  # not a pytest test class

    def __init__(self, channel_config=None):
        self.broker = Broker(hooks=Hooks())
        self.cm = ChannelManager(self.broker)
        self.listeners = Listeners(self.broker, self.cm)
        self.channel_config = channel_config or ChannelConfig(
            session=SessionConfig(retry_interval=0.5)
        )
        self.port = None

    async def __aenter__(self):
        l = await self.listeners.start_listener(
            ListenerConfig(port=0), self.channel_config
        )
        self.port = l.port
        return self

    async def __aexit__(self, *exc):
        await self.listeners.stop_all()

    async def client(self, client_id="", **kw) -> Client:
        c = Client(client_id=client_id, **kw)
        await c.connect("127.0.0.1", self.port)
        return c


@async_test
async def test_connect_ping_disconnect():
    async with TestBed() as tb:
        c = await tb.client("c1")
        assert c.connack.reason_code == 0
        assert c.connack.session_present is False
        await c.ping()
        await c.disconnect()


@async_test
async def test_qos0_pubsub():
    async with TestBed() as tb:
        sub = await tb.client("sub1")
        await sub.subscribe("t/0")
        publ = await tb.client("pub1")
        await publ.publish("t/0", b"hello")
        m = await sub.recv()
        assert (m.topic, m.payload, m.qos) == ("t/0", b"hello", 0)
        await sub.disconnect()
        await publ.disconnect()


@async_test
async def test_qos1_pubsub_and_ack():
    async with TestBed() as tb:
        sub = await tb.client("s1")
        sa = await sub.subscribe("t/1", qos=1)
        assert sa.reason_codes == [1]
        publ = await tb.client("p1")
        ack = await publ.publish("t/1", b"m1", qos=1)
        assert ack.type == pkt.PUBACK
        m = await sub.recv()
        assert (m.topic, m.payload, m.qos) == ("t/1", b"m1", 1)
        await sub.disconnect()
        await publ.disconnect()


@async_test
async def test_qos2_full_handshake():
    async with TestBed() as tb:
        sub = await tb.client("s2")
        await sub.subscribe("t/2", qos=2)
        publ = await tb.client("p2")
        comp = await publ.publish("t/2", b"m2", qos=2)
        assert comp.type == pkt.PUBCOMP
        m = await sub.recv()
        assert (m.payload, m.qos) == (b"m2", 2)
        await sub.disconnect()
        await publ.disconnect()


@async_test
async def test_qos_downgrade_to_subscription_qos():
    async with TestBed() as tb:
        sub = await tb.client("sd")
        await sub.subscribe("t/down", qos=0)
        publ = await tb.client("pd")
        await publ.publish("t/down", b"x", qos=2)
        m = await sub.recv()
        assert m.qos == 0
        await sub.disconnect()
        await publ.disconnect()


@async_test
async def test_wildcard_and_unsubscribe():
    async with TestBed() as tb:
        sub = await tb.client("w1")
        await sub.subscribe([("a/+/c", pkt.SubOpts(qos=0)), ("a/#", pkt.SubOpts(qos=0))])
        publ = await tb.client("w2")
        await publ.publish("a/b/c", b"1")
        got = {(await sub.recv()).topic for _ in range(2)}
        assert got == {"a/b/c"}  # delivered twice, once per matching filter
        ua = await sub.unsubscribe("a/#")
        assert ua.packet_id is not None
        await publ.publish("a/b/c", b"2")
        m = await sub.recv()
        assert m.payload == b"2"
        assert sub.messages.empty()
        await sub.disconnect()
        await publ.disconnect()


@async_test
async def test_no_local_v5():
    async with TestBed() as tb:
        c = await tb.client("nl", version=pkt.MQTT_V5)
        await c.subscribe([("self/t", pkt.SubOpts(qos=0, no_local=True))])
        await c.publish("self/t", b"own")
        other = await tb.client("nl2", version=pkt.MQTT_V5)
        await other.publish("self/t", b"theirs")
        m = await c.recv()
        assert m.payload == b"theirs"
        assert c.messages.empty()
        await c.disconnect()
        await other.disconnect()


@async_test
async def test_will_message_on_abnormal_close():
    async with TestBed() as tb:
        watcher = await tb.client("watcher")
        await watcher.subscribe("will/t")
        dying = await tb.client(
            "dying", will=pkt.Will(topic="will/t", payload=b"gone", qos=0)
        )
        # abrupt socket close (no DISCONNECT) => will must fire
        dying._writer.close()
        m = await watcher.recv()
        assert (m.topic, m.payload) == ("will/t", b"gone")
        await watcher.disconnect()


@async_test
async def test_no_will_on_normal_disconnect():
    async with TestBed() as tb:
        watcher = await tb.client("watcher2")
        await watcher.subscribe("will/t2")
        polite = await tb.client(
            "polite", will=pkt.Will(topic="will/t2", payload=b"bye", qos=0)
        )
        await polite.disconnect()
        await watcher.publish("will/t2", b"marker")
        m = await watcher.recv()
        assert m.payload == b"marker"  # only the marker, no will
        await watcher.disconnect()


@async_test
async def test_session_takeover_and_offline_queue():
    async with TestBed() as tb:
        c1 = await tb.client("take1", clean_start=False)
        await c1.subscribe("q/t", qos=1)
        # abrupt drop: session (expiry 2h default for v4 non-clean) detaches
        c1._writer.close()
        await c1.closed.wait()
        await asyncio.sleep(0.05)
        publ = await tb.client("qpub")
        for i in range(3):
            await publ.publish("q/t", b"m%d" % i, qos=1)
        c2 = await tb.client("take1", clean_start=False)
        assert c2.connack.session_present is True
        got = sorted([(await c2.recv()).payload for _ in range(3)])
        assert got == [b"m0", b"m1", b"m2"]
        await c2.disconnect()
        await publ.disconnect()


@async_test
async def test_clean_start_discards_session():
    async with TestBed() as tb:
        c1 = await tb.client("cs1", clean_start=False)
        await c1.subscribe("cs/t", qos=1)
        c1._writer.close()
        await c1.closed.wait()
        await asyncio.sleep(0.05)
        c2 = await tb.client("cs1", clean_start=True)
        assert c2.connack.session_present is False
        publ = await tb.client("cspub")
        await publ.publish("cs/t", b"x", qos=1)
        await asyncio.sleep(0.1)
        assert c2.messages.empty()  # old subscription gone
        await c2.disconnect()
        await publ.disconnect()


@async_test
async def test_takeover_kicks_live_connection():
    async with TestBed() as tb:
        c1 = await tb.client("dup", version=pkt.MQTT_V5, clean_start=False)
        await c1.subscribe("dup/t", qos=1)
        c2 = await tb.client("dup", version=pkt.MQTT_V5, clean_start=False)
        assert c2.connack.session_present is True
        await c1.closed.wait()  # old connection must be closed by broker
        assert c1.disconnect_packet is not None
        assert c1.disconnect_packet.reason_code == pkt.RC_SESSION_TAKEN_OVER
        publ = await tb.client("duppub")
        await publ.publish("dup/t", b"after", qos=1)
        m = await c2.recv()
        assert m.payload == b"after"
        await c2.disconnect()
        await publ.disconnect()


@async_test
async def test_shared_subscription_round_robin():
    async with TestBed() as tb:
        a = await tb.client("sha")
        b = await tb.client("shb")
        await a.subscribe("$share/g1/sh/t", qos=0)
        await b.subscribe("$share/g1/sh/t", qos=0)
        publ = await tb.client("shpub")
        for i in range(6):
            await publ.publish("sh/t", b"%d" % i)
        await asyncio.sleep(0.2)
        na, nb = a.messages.qsize(), b.messages.qsize()
        assert na + nb == 6
        assert na == 3 and nb == 3  # round_robin default
        await a.disconnect()
        await b.disconnect()
        await publ.disconnect()


@async_test
async def test_bad_connack_on_wildcard_publish():
    async with TestBed() as tb:
        c = await tb.client("badpub")
        # publishing to a wildcard topic is a protocol violation: the frame
        # parser rejects it and the connection drops
        c._send(pkt.Publish(topic="a/#", payload=b"x"))
        import emqx_tpu.mqtt.frame as frame

        wire = frame.serialize(pkt.Publish(topic="a/+", payload=b"x"), c.version)
        c._writer.write(wire)
        await c.closed.wait()


@async_test
async def test_keepalive_timeout_closes():
    async with TestBed() as tb:
        c = await tb.client("ka", keepalive=1)
        # send nothing; server must close after ~1.5s grace
        await asyncio.wait_for(c.closed.wait(), timeout=5)


@async_test
async def test_connect_must_be_first():
    async with TestBed() as tb:
        reader, writer = await asyncio.open_connection("127.0.0.1", tb.port)
        from emqx_tpu.mqtt.frame import serialize

        writer.write(serialize(pkt.PingReq(), 4))
        data = await reader.read(100)
        assert data == b""  # closed without response


@async_test
async def test_second_connect_is_protocol_error():
    async with TestBed() as tb:
        c = await tb.client("twice")
        c._send(
            pkt.Connect(proto_ver=pkt.MQTT_V4, client_id="twice")
        )
        await c.closed.wait()


@async_test
async def test_v5_assigned_client_id():
    async with TestBed() as tb:
        c = await tb.client("", version=pkt.MQTT_V5)
        assert "Assigned-Client-Identifier" in c.connack.properties
        await c.disconnect()


@async_test
async def test_qos1_retry_on_missing_ack():
    """Broker retransmits with DUP when PUBACK never arrives."""
    async with TestBed() as tb:
        sub = await tb.client("retry1")
        await sub.subscribe("r/t", qos=1)
        # monkey-patch client to swallow its PUBACK (_handle is sync)
        orig = sub._handle

        seen = []

        def no_ack(p):
            if p.type == pkt.PUBLISH and p.qos == 1:
                seen.append(p)
                return  # no ack sent
            orig(p)

        sub._handle = no_ack
        publ = await tb.client("retry2")
        await publ.publish("r/t", b"again", qos=1)
        await asyncio.sleep(1.2)  # > retry_interval (0.5s)
        assert len(seen) >= 2
        assert seen[1].dup is True
        await sub.close()
        await publ.disconnect()
