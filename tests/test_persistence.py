"""Persistent-session + durable-state tests.

Parity targets: emqx_persistent_session_SUITE (messages persisted while the
client is away survive a broker restart and replay on resume), the session
router's detached-delivery role, and the mnesia disc_copies analog for
retained/delayed/banned (SURVEY.md §5.4).
"""

import asyncio
import tempfile
import time
from pathlib import Path

import pytest

from emqx_tpu.app import BrokerApp
from emqx_tpu.broker.message import Message
from emqx_tpu.broker.session import Session, SessionConfig
from emqx_tpu.config.schema import load_config
from emqx_tpu.mqtt import packet as pkt
from emqx_tpu.mqtt.client import Client
from emqx_tpu.storage.codec import (
    msg_from_json,
    msg_to_json,
    session_from_json,
    session_to_json,
)
from emqx_tpu.storage.kv import FileKv
from tests.test_broker_e2e import async_test


# -- storage layer ---------------------------------------------------------

def test_filekv_roundtrip_and_atomicity():
    with tempfile.TemporaryDirectory() as d:
        kv = FileKv(d)
        assert kv.read("x") is None
        kv.write("x", {"a": 1, "b": [1, 2]})
        assert kv.read("x") == {"a": 1, "b": [1, 2]}
        kv.write("x", {"a": 2})
        assert kv.read("x") == {"a": 2}
        assert kv.delete("x")
        assert kv.read("x") is None
        # corrupt file degrades to cold start, not crash
        p = Path(d) / "y.json"
        p.write_text("{not json")
        assert kv.read("y") is None


def test_message_codec_roundtrip():
    m = Message(
        topic="a/b",
        payload=b"\x00\xffbin",
        qos=2,
        retain=True,
        from_client="c1",
        headers={"retained": True, "raw": b"\x01"},
        properties={"Message-Expiry-Interval": 60},
    )
    m2 = msg_from_json(msg_to_json(m))
    assert m2.topic == m.topic and m2.payload == m.payload
    assert m2.qos == 2 and m2.retain and m2.from_client == "c1"
    assert m2.headers["retained"] is True and m2.headers["raw"] == b"\x01"
    assert m2.properties["Message-Expiry-Interval"] == 60


def test_message_codec_list_properties_roundtrip():
    """MQTT5 list-valued properties (User-Property pairs) survive the
    snapshot and still serialize on the wire after restore."""
    from emqx_tpu.mqtt.frame import serialize

    m = Message(
        topic="a/b",
        payload=b"x",
        qos=1,
        properties={
            "User-Property": [("k1", "v1"), ("k2", "v2")],
            "Subscription-Identifier": 5,
        },
    )
    m2 = msg_from_json(msg_to_json(m))
    assert m2.properties["User-Property"] == [["k1", "v1"], ["k2", "v2"]]
    # the restored message must still encode to a valid v5 PUBLISH frame
    p = pkt.Publish(
        topic=m2.topic, payload=m2.payload, qos=1, packet_id=1,
        properties=m2.properties,
    )
    assert serialize(p, pkt.MQTT_V5)


def test_session_codec_roundtrip():
    cfg = SessionConfig(max_inflight=4)
    s = Session("cid-1", cfg)
    s.subscriptions["t/#"] = pkt.SubOpts(qos=1, no_local=True)
    s.mqueue.in_(Message(topic="t/q", payload=b"queued", qos=1))
    s.inflight.insert(7, Message(topic="t/i", payload=b"inflight", qos=1))
    s.awaiting_rel[3] = time.time()
    s2 = session_from_json(session_to_json(s), cfg)
    assert s2.client_id == "cid-1"
    assert s2.subscriptions["t/#"].qos == 1
    assert s2.subscriptions["t/#"].no_local
    assert len(s2.mqueue) == 1
    assert s2.inflight.contains(7)
    assert 3 in s2.awaiting_rel


# -- full restart cycle ----------------------------------------------------

def _cfg(data_dir, port=0):
    return load_config(
        {
            "listeners": [{"port": port, "bind": "127.0.0.1"}],
            "dashboard": {"enable": False},
            "router": {"enable_tpu": False},
            "durability": {
                "enable": True,
                "data_dir": str(data_dir),
                "flush_interval": 0.5,
            },
            "session": {"expiry_interval": 3600},
        }
    )


@async_test
async def test_session_survives_broker_restart():
    """Subscribe -> disconnect -> offline publish -> broker restart ->
    resume -> replay (the reference's persistent-session core loop)."""
    with tempfile.TemporaryDirectory() as d:
        app1 = BrokerApp(_cfg(d))
        await app1.start()
        port = list(app1.listeners.list().values())[0].port
        c = Client("psc", version=pkt.MQTT_V5, clean_start=False,
                   properties={"Session-Expiry-Interval": 3600})
        await c.connect("127.0.0.1", port)
        await c.subscribe("ps/t", qos=1)
        await c.disconnect()
        await asyncio.sleep(0.05)
        # messages arrive while the client is away
        app1.broker.publish(Message(topic="ps/t", payload=b"m1", qos=1))
        app1.broker.publish(Message(topic="ps/t", payload=b"m2", qos=1))
        await app1.stop()  # final flush happens here

        app2 = BrokerApp(_cfg(d))
        await app2.start()
        try:
            assert app2.broker.metrics.gauge("sessions.restored") == 1
            port2 = list(app2.listeners.list().values())[0].port
            # a publish BEFORE the client resumes also lands in the queue
            app2.broker.publish(Message(topic="ps/t", payload=b"m3", qos=1))
            c2 = Client("psc", version=pkt.MQTT_V5, clean_start=False,
                        properties={"Session-Expiry-Interval": 3600})
            await c2.connect("127.0.0.1", port2)
            assert c2.connack.session_present
            got = sorted([(await c2.recv(5)).payload for _ in range(3)])
            assert got == [b"m1", b"m2", b"m3"]
            await c2.disconnect()
        finally:
            await app2.stop()


@async_test
async def test_clean_start_discards_persisted_session():
    with tempfile.TemporaryDirectory() as d:
        app1 = BrokerApp(_cfg(d))
        await app1.start()
        port = list(app1.listeners.list().values())[0].port
        c = Client("cs", version=pkt.MQTT_V5, clean_start=False,
                   properties={"Session-Expiry-Interval": 3600})
        await c.connect("127.0.0.1", port)
        await c.subscribe("cs/t", qos=1)
        await c.disconnect()
        await app1.stop()

        app2 = BrokerApp(_cfg(d))
        await app2.start()
        try:
            port2 = list(app2.listeners.list().values())[0].port
            c2 = Client("cs", version=pkt.MQTT_V5, clean_start=True)
            await c2.connect("127.0.0.1", port2)
            assert not c2.connack.session_present
            # old subscription is gone
            app2.broker.publish(Message(topic="cs/t", payload=b"x", qos=1))
            with pytest.raises(asyncio.TimeoutError):
                await c2.recv(0.3)
            await c2.disconnect()
        finally:
            await app2.stop()


@async_test
async def test_expired_session_not_restored():
    with tempfile.TemporaryDirectory() as d:
        cfg = _cfg(d)
        cfg.session.expiry_interval = 0.2
        app1 = BrokerApp(cfg)
        await app1.start()
        port = list(app1.listeners.list().values())[0].port
        c = Client("exp", version=pkt.MQTT_V4, clean_start=False)
        await c.connect("127.0.0.1", port)
        await c.subscribe("e/t", qos=1)
        await c.disconnect()
        await asyncio.sleep(0.05)
        await app1.stop()
        await asyncio.sleep(0.3)  # session expires while broker is down

        app2 = BrokerApp(_cfg(d))
        await app2.start()
        try:
            assert app2.broker.metrics.gauge("sessions.restored") == 0
            assert len(app2.cm._detached) == 0
        finally:
            await app2.stop()


@async_test
async def test_retained_delayed_banned_survive_restart():
    with tempfile.TemporaryDirectory() as d:
        from emqx_tpu.broker.banned import BanEntry

        app1 = BrokerApp(_cfg(d))
        await app1.start()
        port = list(app1.listeners.list().values())[0].port
        c = Client("dur", version=pkt.MQTT_V5)
        await c.connect("127.0.0.1", port)
        await c.publish("ret/t", b"keepme", qos=1, retain=True)
        await c.publish("$delayed/3600/del/t", b"later", qos=1)
        await c.disconnect()
        app1.banned.add(
            BanEntry(kind="clientid", value="evil",
                     until=time.time() + 3600)
        )
        await app1.stop()

        app2 = BrokerApp(_cfg(d))
        await app2.start()
        try:
            assert app2.retainer.get("ret/t").payload == b"keepme"
            assert len(app2.delayed) == 1
            assert app2.delayed.pending()[0][1].topic == "del/t"
            assert any(
                e.value == "evil" for e in app2.banned.entries()
            )
            # retained message actually delivered to a new subscriber
            port2 = list(app2.listeners.list().values())[0].port
            c2 = Client("dur2", version=pkt.MQTT_V5)
            await c2.connect("127.0.0.1", port2)
            await c2.subscribe("ret/#", qos=1)
            m = await c2.recv(5)
            assert m.payload == b"keepme" and m.retain
            await c2.disconnect()
        finally:
            await app2.stop()


@async_test
async def test_periodic_flush_captures_offline_messages():
    """Crash-consistency: messages banked while detached are on disk after
    the flush interval, without a clean shutdown."""
    with tempfile.TemporaryDirectory() as d:
        app1 = BrokerApp(_cfg(d))
        await app1.start()
        port = list(app1.listeners.list().values())[0].port
        c = Client("pf", version=pkt.MQTT_V5, clean_start=False,
                   properties={"Session-Expiry-Interval": 3600})
        await c.connect("127.0.0.1", port)
        await c.subscribe("pf/t", qos=1)
        await c.disconnect()
        await asyncio.sleep(0.05)
        app1.broker.publish(Message(topic="pf/t", payload=b"banked", qos=1))
        await asyncio.sleep(1.2)  # > flush_interval (0.5)
        kv = FileKv(d)
        snap = kv.read("persistent_sessions")
        # simulate crash: no app1.stop() flush — read what the periodic
        # flush wrote
        sessions = snap["sessions"]
        assert "pf" in sessions
        assert any(
            m["payload"] for m in sessions["pf"]["mqueue"]
        )
        await app1.stop()
