"""Topic algebra tests (parity targets: emqx_topic_SUITE behaviors)."""

import pytest

from emqx_tpu.ops import topics as T


def test_words():
    assert T.words("a/b/c") == ["a", "b", "c"]
    assert T.words("a//b") == ["a", "", "b"]
    assert T.words("/a") == ["", "a"]
    assert T.words("a/") == ["a", ""]
    assert T.words("/") == ["", ""]


def test_wildcard():
    assert not T.wildcard("a/b/c")
    assert T.wildcard("a/+/c")
    assert T.wildcard("a/#")
    assert T.wildcard("#")
    assert not T.wildcard("a/b+c")  # '+' must be a whole level to be a wildcard op


@pytest.mark.parametrize(
    "name,filt,expect",
    [
        ("a/b/c", "a/b/c", True),
        ("a/b/c", "a/+/c", True),
        ("a/b/c", "a/#", True),
        ("a/b/c", "#", True),
        ("a", "a/#", True),  # '#' matches the parent level itself
        ("a/b", "a/+", True),
        ("a/b/c", "a/+", False),
        ("a", "a/+", False),
        ("a/b", "a", False),
        ("a", "a/b", False),
        ("a/b/c", "a/b/d", False),
        ("a//c", "a/+/c", True),  # empty level matches '+'
        ("a//c", "a//c", True),
        ("$SYS/broker", "#", False),  # $ topics excluded from root wildcards
        ("$SYS/broker", "+/broker", False),
        ("$SYS/broker", "$SYS/#", True),
        ("$SYS/broker", "$SYS/+", True),
        ("$SYS", "$SYS", True),
        ("a/$b/c", "a/+/c", True),  # '$' only special at the first level
        ("a/b/c/d", "a/b/#", True),
        ("a/b", "a/b/#", True),
        ("a/b", "a/b/+", False),
        ("ab/cd", "+/+", True),
        ("ab/cd", "+", False),
    ],
)
def test_match(name, filt, expect):
    assert T.match(name, filt) is expect


def test_validate():
    T.validate("a/b/c")
    T.validate("+/#")
    T.validate("a/+/b")
    T.validate("#")
    T.validate("a//b")
    with pytest.raises(T.TopicValidationError):
        T.validate("")
    with pytest.raises(T.TopicValidationError):
        T.validate("a/#/b")
    with pytest.raises(T.TopicValidationError):
        T.validate("a/b#")
    with pytest.raises(T.TopicValidationError):
        T.validate("a/b+")
    with pytest.raises(T.TopicValidationError):
        T.validate("a/+b/c")
    with pytest.raises(T.TopicValidationError):
        T.validate("a/+/c", kind="name")
    with pytest.raises(T.TopicValidationError):
        T.validate("x" * 70000)


def test_parse_share():
    assert T.parse_share("t/1") == (None, "t/1")
    assert T.parse_share("$share/g1/t/1") == ("g1", "t/1")
    with pytest.raises(T.TopicValidationError):
        T.parse_share("$share/g1")
    with pytest.raises(T.TopicValidationError):
        T.parse_share("$share/+/t")


def test_feed_var_and_join():
    assert T.join(["a", "b"]) == "a/b"
    assert T.feed_var("%c", "client1", "a/%c/b") == "a/client1/b"
