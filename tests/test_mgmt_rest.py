"""REST completion suites: listeners CRUD, authn/authz CRUD, API keys.

Parity targets: emqx_mgmt_api_listeners SUITE, emqx_authn_api /
emqx_authz_api_sources SUITEs, emqx_mgmt_auth (API keys) SUITE.
"""

import asyncio
import base64
import hashlib

import aiohttp
import pytest

from emqx_tpu.app import BrokerApp
from emqx_tpu.config.schema import load_config
from emqx_tpu.mqtt import packet as pkt
from emqx_tpu.mqtt.client import Client
from tests.test_broker_e2e import async_test
from tests.test_sql_backends import StubMysql, StubPg


def _app_config(**over):
    data = {
        "listeners": [{"port": 0, "bind": "127.0.0.1"}],
        "dashboard": {"port": 0, "bind": "127.0.0.1"},
        "router": {"enable_tpu": False},
        **over,
    }
    return load_config(data)


@async_test
async def test_listeners_crud_lifecycle():
    app = BrokerApp(_app_config())
    await app.start()
    try:
        api = f"http://127.0.0.1:{app.mgmt_server.port}/api/v5"
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{api}/listeners") as r:
                rows = (await r.json())["data"]
                assert len(rows) == 1 and rows[0]["running"] is True
                assert rows[0]["id"] == "tcp:default"
            # create a second listener
            async with s.post(
                f"{api}/listeners",
                json={"type": "tcp", "name": "extra", "port": 0},
            ) as r:
                assert r.status == 201
                extra_port = (await r.json())["port"]
            # a client can connect to it
            c = Client("l-test")
            await c.connect("127.0.0.1", extra_port)
            await c.disconnect()
            # stop it -> connections refused
            async with s.post(f"{api}/listeners/tcp:extra/stop") as r:
                assert r.status == 200
            async with s.get(f"{api}/listeners") as r:
                rows = {x["id"]: x for x in (await r.json())["data"]}
                assert rows["tcp:extra"]["running"] is False
            with pytest.raises(OSError):
                c2 = Client("l-test2")
                await c2.connect("127.0.0.1", extra_port)
            # start it again from the saved spec
            async with s.post(f"{api}/listeners/tcp:extra/start") as r:
                assert r.status == 200
            async with s.get(f"{api}/listeners") as r:
                rows = {x["id"]: x for x in (await r.json())["data"]}
                assert rows["tcp:extra"]["running"] is True
                restarted_port = rows["tcp:extra"]["port"]
            c3 = Client("l-test3")
            await c3.connect("127.0.0.1", restarted_port)
            await c3.disconnect()
            # restart the default listener
            async with s.post(f"{api}/listeners/tcp:default/restart") as r:
                assert r.status == 200
            # delete the extra listener entirely
            async with s.delete(f"{api}/listeners/tcp:extra") as r:
                assert r.status == 204
            async with s.get(f"{api}/listeners") as r:
                ids = [x["id"] for x in (await r.json())["data"]]
                assert "tcp:extra" not in ids
            # unknown id -> 404
            async with s.post(f"{api}/listeners/tcp:nope/stop") as r:
                assert r.status == 404
    finally:
        await app.stop()


@async_test
async def test_authn_chain_crud_and_builtin_users():
    app = BrokerApp(_app_config())
    await app.start()
    try:
        api = f"http://127.0.0.1:{app.mgmt_server.port}/api/v5"
        mqtt_port = list(app.listeners.list().values())[0].port
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{api}/authentication") as r:
                assert (await r.json())["data"] == []
            # create a builtin-database provider
            async with s.post(
                f"{api}/authentication",
                json={
                    "mechanism": "password_based",
                    "backend": "built_in_database",
                    "user_id_type": "username",
                    "password_hash_algorithm": "sha256",
                },
            ) as r:
                assert r.status == 201
                pid = (await r.json())["id"]
                assert pid == "password_based:built_in_database"
            # duplicate -> 409
            async with s.post(
                f"{api}/authentication",
                json={"mechanism": "password_based",
                      "backend": "built_in_database"},
            ) as r:
                assert r.status == 409
            # add a user, then a good/bad login pair
            async with s.post(
                f"{api}/authentication/{pid}/users",
                json={"user_id": "u1", "password": "pw1"},
            ) as r:
                assert r.status == 201
            async with s.get(f"{api}/authentication/{pid}/users") as r:
                assert (await r.json())["data"] == ["u1"]
            ok = Client("good", username="u1", password=b"pw1")
            await ok.connect("127.0.0.1", mqtt_port)
            await ok.disconnect()
            bad = Client("bad", username="u1", password=b"nope")
            with pytest.raises(Exception):
                await bad.connect("127.0.0.1", mqtt_port)
            # delete user then provider
            async with s.delete(f"{api}/authentication/{pid}/users/u1") as r:
                assert r.status == 204
            async with s.delete(f"{api}/authentication/{pid}") as r:
                assert r.status == 204
            async with s.get(f"{api}/authentication") as r:
                assert (await r.json())["data"] == []
    finally:
        await app.stop()


@async_test
async def test_authn_mysql_provider_via_rest():
    phash = hashlib.sha256(b"s9mypw").hexdigest()
    stub = await StubMysql(
        tables={"FROM mqtt_user": (
            ["password_hash", "salt", "is_superuser"],
            [[phash, "s9", "0"]],
        )}
    ).start()
    app = BrokerApp(_app_config())
    await app.start()
    try:
        api = f"http://127.0.0.1:{app.mgmt_server.port}/api/v5"
        mqtt_port = list(app.listeners.list().values())[0].port
        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"{api}/authentication",
                json={
                    "mechanism": "password_based",
                    "backend": "mysql",
                    "server": f"127.0.0.1:{stub.port}",
                    "username": "app",
                    "password": "pw",
                    "password_hash_algorithm": "sha256",
                },
            ) as r:
                assert r.status == 201, await r.text()
        ok = Client("mysql-ok", username="u1", password=b"mypw")
        await ok.connect("127.0.0.1", mqtt_port)
        await ok.disconnect()
        bad = Client("mysql-bad", username="u1", password=b"wrong")
        with pytest.raises(Exception):
            await bad.connect("127.0.0.1", mqtt_port)
    finally:
        await app.stop()
        await stub.stop()


@async_test
async def test_authz_sources_crud_and_enforcement():
    stub = await StubPg(
        auth="trust",
        tables={"FROM mqtt_acl": (
            ["permission", "action", "topic"],
            [["deny", "publish", "forbidden/#"]],
        )},
    ).start()
    app = BrokerApp(_app_config())
    await app.start()
    try:
        api = f"http://127.0.0.1:{app.mgmt_server.port}/api/v5"
        mqtt_port = list(app.listeners.list().values())[0].port
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{api}/authorization/sources") as r:
                assert (await r.json())["data"] == []
            async with s.post(
                f"{api}/authorization/sources",
                json={
                    "type": "postgresql",
                    "server": f"127.0.0.1:{stub.port}",
                    "username": "app",
                },
            ) as r:
                assert r.status == 201, await r.text()
            async with s.get(f"{api}/authorization/sources") as r:
                assert [x["type"] for x in (await r.json())["data"]] == [
                    "postgresql"
                ]
            # publish to a denied topic is dropped; allowed passes
            sub_ok = Client("authz-sub")
            await sub_ok.connect("127.0.0.1", mqtt_port)
            await sub_ok.subscribe("#", qos=0)
            pub = Client("authz-pub", username="u")
            await pub.connect("127.0.0.1", mqtt_port)
            await pub.publish("forbidden/x", b"no", qos=0)
            await pub.publish("fine/x", b"yes", qos=0)
            m = await sub_ok.recv(timeout=5)
            assert m.topic == "fine/x"  # denied one never delivered
            await pub.disconnect()
            await sub_ok.disconnect()
            # move + delete round-trip
            async with s.post(
                f"{api}/authorization/sources/postgresql/move",
                json={"position": "front"},
            ) as r:
                assert r.status == 200
            async with s.delete(
                f"{api}/authorization/sources/postgresql"
            ) as r:
                assert r.status == 204
            async with s.get(f"{api}/authorization/sources") as r:
                assert (await r.json())["data"] == []
    finally:
        await app.stop()
        await stub.stop()


@async_test
async def test_api_key_machine_auth():
    app = BrokerApp(_app_config())
    await app.start()
    try:
        api = f"http://127.0.0.1:{app.mgmt_server.port}/api/v5"
        async with aiohttp.ClientSession() as s:
            # open surface (no admins/keys yet): create the first key
            async with s.post(
                f"{api}/api_key",
                json={"name": "ci", "description": "ci bot"},
            ) as r:
                assert r.status == 201
                rec = await r.json()
                key, secret = rec["api_key"], rec["api_secret"]
            # now the surface requires auth
            async with s.get(f"{api}/metrics") as r:
                assert r.status == 401
            basic = base64.b64encode(f"{key}:{secret}".encode()).decode()
            hdr = {"Authorization": f"Basic {basic}"}
            async with s.get(f"{api}/metrics", headers=hdr) as r:
                assert r.status == 200
            # secret never shown again
            async with s.get(f"{api}/api_key/ci", headers=hdr) as r:
                rec2 = await r.json()
                assert "api_secret" not in rec2
            # disable the key (this request still carries valid auth)
            async with s.put(
                f"{api}/api_key/ci", json={"enable": False}, headers=hdr
            ) as r:
                assert r.status == 200
        # disabled key is rejected afterwards
        async with aiohttp.ClientSession() as s2:
            async with s2.get(f"{api}/metrics", headers=hdr) as r:
                assert r.status == 401
    finally:
        await app.stop()


@async_test
async def test_api_key_expiry_and_delete():
    import time as _time

    app = BrokerApp(_app_config())
    await app.start()
    try:
        api = f"http://127.0.0.1:{app.mgmt_server.port}/api/v5"
        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"{api}/api_key",
                json={"name": "old", "expired_at": _time.time() - 1},
            ) as r:
                rec = await r.json()
            basic = base64.b64encode(
                f"{rec['api_key']}:{rec['api_secret']}".encode()
            ).decode()
            hdr = {"Authorization": f"Basic {basic}"}
            async with s.get(f"{api}/metrics", headers=hdr) as r:
                assert r.status == 401  # expired
            # a live key can delete the stale one
            mapi = app.mgmt_server
            live = mapi.api_keys.create("live")
            basic2 = base64.b64encode(
                f"{live['api_key']}:{live['api_secret']}".encode()
            ).decode()
            hdr2 = {"Authorization": f"Basic {basic2}"}
            async with s.delete(f"{api}/api_key/old", headers=hdr2) as r:
                assert r.status == 204
            async with s.get(f"{api}/api_key/old", headers=hdr2) as r:
                assert r.status == 404
    finally:
        await app.stop()


@async_test
async def test_new_endpoints_in_openapi():
    app = BrokerApp(_app_config())
    await app.start()
    try:
        api = f"http://127.0.0.1:{app.mgmt_server.port}"
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{api}/api-docs") as r:
                doc = await r.json()
        paths = doc["paths"]
        for p in (
            "/api/v5/listeners",
            "/api/v5/authentication",
            "/api/v5/authorization/sources",
            "/api/v5/api_key",
        ):
            assert p in paths, p
    finally:
        await app.stop()


@async_test
async def test_cluster_info_and_drain_endpoints():
    """GET /cluster reflects membership state; POST /nodes/drain runs the
    rolling-upgrade orchestration (r3 verdict item 7's control surface)."""
    app = BrokerApp(_app_config(session={"expiry_interval": 3600}))
    await app.start()
    try:
        api = f"http://127.0.0.1:{app.mgmt_server.port}/api/v5"
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{api}/cluster") as r:
                body = await r.json()
                assert r.status == 200 and body["enabled"] is False

            # persistent session to be parked by the drain
            port = list(app.listeners.list().values())[0].port
            c = Client("drainee", version=pkt.MQTT_V5, clean_start=False,
                       properties={"Session-Expiry-Interval": 3600})
            await c.connect("127.0.0.1", port)
            await c.subscribe("d/#", qos=1)
            await c.disconnect()
            await asyncio.sleep(0.05)

            async with s.post(f"{api}/nodes/drain", json={}) as r:
                body = await r.json()
                assert r.status == 200
                assert body["detached_sessions"] == 1
            # drained: the MQTT listener no longer accepts
            with pytest.raises(OSError):
                await asyncio.open_connection("127.0.0.1", port)
    finally:
        await app.stop()


@async_test
async def test_retained_rest_cursor_pagination():
    """GET /retainer/messages pages with cursor+limit (paged-read parity
    with emqx_retainer_mnesia — a huge store must not dump in one
    response)."""
    from emqx_tpu.broker.message import Message

    app = BrokerApp(_app_config())
    await app.start()
    try:
        for i in range(250):
            app.retainer.on_publish(
                Message(topic=f"rp/{i:03d}", payload=b"v", retain=True)
            )
        api = f"http://127.0.0.1:{app.mgmt_server.port}/api/v5"
        got, cursor, pages = [], None, 0
        async with aiohttp.ClientSession() as s:
            while True:
                url = f"{api}/retainer/messages?limit=100"
                if cursor:
                    url += f"&cursor={cursor}"
                async with s.get(url) as r:
                    assert r.status == 200
                    body = await r.json()
                got.extend(body["data"])
                pages += 1
                assert len(body["data"]) <= 100
                assert body["meta"]["count"] == 250
                cursor = body["meta"]["cursor"]
                if not body["meta"]["hasnext"]:
                    break
        assert pages >= 3
        assert sorted(got) == [f"rp/{i:03d}" for i in range(250)]
        assert len(set(got)) == 250  # no dupes across pages
    finally:
        await app.stop()
