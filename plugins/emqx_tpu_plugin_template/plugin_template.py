"""Template plugin for emqx_tpu (reference analog: emqx_plugin_template,
shipped out-of-tree for EMQX; apps/emqx_plugins/src/emqx_plugins.erl:72-91
is the install/start/stop flow that loads this).

Demonstrates the full extension surface a plugin gets:
- hook registration on the SAME hookpoints as built-in extensions
  (message.publish fold, client.connected notification),
- broker publish access (a periodic stats topic),
- clean symmetric teardown (every hook removed, the task cancelled).

Install/start/stop/uninstall via the REST API:
    POST /api/v5/plugins/install          (multipart: the .tar.gz)
    PUT  /api/v5/plugins/{ref}/start
    PUT  /api/v5/plugins/{ref}/stop
    DELETE /api/v5/plugins/{ref}
"""

import asyncio
import json
import time

TAG = "plugin_template"
STATS_TOPIC = "$plugins/template/stats"
_state = {}


def _on_publish(msg):
    """message.publish fold: count and annotate (never block the path)."""
    if msg is None or msg.topic.startswith("$"):
        return None
    _state["published"] = _state.get("published", 0) + 1
    msg.headers["seen_by_template"] = True
    return None


def _on_connected(client_info, _channel):
    _state["connected"] = _state.get("connected", 0) + 1


async def _stats_loop(app):
    from emqx_tpu.broker.message import Message

    while True:
        await asyncio.sleep(5.0)
        app.broker.publish(
            Message(
                topic=STATS_TOPIC,
                payload=json.dumps(
                    {
                        "published": _state.get("published", 0),
                        "connected": _state.get("connected", 0),
                        "ts": int(time.time() * 1000),
                    }
                ).encode(),
            )
        )


def plugin_start(app):
    _state.clear()
    _state["started_at"] = time.time()
    app.hooks.add("message.publish", _on_publish, priority=50, tag=TAG)
    app.hooks.add("client.connected", _on_connected, tag=TAG)
    try:
        _state["task"] = asyncio.get_running_loop().create_task(
            _stats_loop(app)
        )
    except RuntimeError:
        _state["task"] = None  # library mode: no loop, hooks still work


def plugin_stop(app):
    app.hooks.delete("message.publish", TAG)
    app.hooks.delete("client.connected", TAG)
    task = _state.get("task")
    if task is not None:
        task.cancel()
    _state.clear()
