"""Profile the single-process serving hot path (no TPU: host plane only)."""
import asyncio
import cProfile
import pstats
import socket
import sys
import time


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


async def main():
    from emqx_tpu.app import BrokerApp
    from emqx_tpu.config.schema import load_config
    from emqx_tpu.mqtt.client import Client

    workers = int(sys.argv[1]) if len(sys.argv) > 1 else 0
    port = _free_port()
    app = BrokerApp(load_config({
        "listeners": [{"port": port, "bind": "127.0.0.1",
                       "workers": workers}],
        "dashboard": {"enable": False},
        "router": {"enable_tpu": False},
    }))
    await app.start()
    if workers:
        await app.worker_pools[0].wait_ready()

    N_SUB, N_PUB, PER = 8, 8, 1500
    subs = []
    for i in range(N_SUB):
        c = Client(client_id=f"s{i}", keepalive=0)
        await c.connect("127.0.0.1", port)
        await c.subscribe("bench/+/t", qos=0)
        subs.append(c)
    pubs = []
    for i in range(N_PUB):
        c = Client(client_id=f"p{i}", keepalive=0)
        await c.connect("127.0.0.1", port)
        pubs.append(c)
    await asyncio.sleep(0.5)

    total = N_PUB * PER

    async def pump(p, i):
        for j in range(PER):
            await p.publish(f"bench/{i}/t", b"x" * 64, qos=0)
            if j % 200 == 0:
                await asyncio.sleep(0)

    async def drain(c):
        got = 0
        while got < total:
            await c.recv(120)
            got += 1
        return got

    import os
    prof = os.environ.get("PROF", "1") == "1"
    pr = cProfile.Profile()
    t0 = time.perf_counter()
    if prof:
        pr.enable()
    await asyncio.wait_for(
        asyncio.gather(*[pump(p, i) for i, p in enumerate(pubs)],
                       *[drain(c) for c in subs]), 600)
    if prof:
        pr.disable()
    wall = time.perf_counter() - t0
    print(f"workers={workers} msgs/s={total / wall:.0f} "
          f"dlv/s={total * N_SUB / wall:.0f} wall={wall:.1f}s")
    if prof:
        st = pstats.Stats(pr)
        st.sort_stats("cumulative").print_stats(35)
    for c in subs + pubs:
        await c.disconnect()
    await app.stop()


asyncio.run(main())
