#!/usr/bin/env bash
# ci_gate.sh — THE single pre-merge command (docs/concurrency.md,
# docs/static_analysis.md). Five gates, in the order that fails fastest:
#
#   1. tpu_lint + the consolidated tier-B audit in ONE invocation
#      (`--audit`): all 16 AST checkers, the device-contract audit
#      (jaxpr tracing on CPU), the replication replay audit
#      (shadow-replica convergence + seeded incomplete-log control),
#      and the wire-compatibility audit (golden-corpus replay through
#      current decoders + seeded drift control + live layout
#      cross-check — docs/static_analysis.md "Tier B")
#   2. tier-1 pytest                      (`-m "not slow"`; the race-marked
#      racetrack suite is part of tier-1 and runs with the detector armed)
#   3. the race suite alone, verbose      (`-m race`) — redundant with (2)
#      but isolates the concurrency rig's verdict in its own section of
#      the log, so a race report is never buried in a 500-test dot wall
#   4. the bench-trend gate               (tools/bench_trend.py --check:
#      the committed BENCH trajectory, grouped by hardware fingerprint —
#      fails when a same-fingerprint metric regressed past threshold;
#      run it again after any bench recipe below refreshes a capture)
#
# Fast mode for the inner loop (pre-push, not pre-merge):
#
#   tools/ci_gate.sh --fast     # lint scoped to git-touched files
#                               # (--changed-only --jobs 8) + the
#                               # bounded tier-B smoke (`--audit
#                               # --smoke`: replay capped at 8 rounds,
#                               # full corpus replay, contracts
#                               # skipped) + race suite
#
# Bench recipes (slow — NOT part of tier-1 or this gate; run when a PR
# touches the paths they measure):
#
#   python bench.py --configs chaos_soak    # degradation ladder gate
#                                           # (incl. the overload wave:
#                                           # QoS0 firehose + open
#                                           # breaker vs the control
#                                           # lane, SLO ladder asserts)
#   python bench.py --configs latency_frontier # SLO-adaptive batching:
#                                           # measured latency-vs-
#                                           # throughput frontier 10%->
#                                           # 100% load; gates p99@10%
#                                           # < 5ms, monotone frontier,
#                                           # bounded control-lane p99
#                                           # under a storm (~25s CPU —
#                                           # docs/robustness.md)
#   python bench.py churn_storm             # segmented update path at
#                                           # 10M subs (~3-4 min): gates
#                                           # >1M inserts/s and <10ms
#                                           # subscribe visibility
#                                           # (docs/update_path.md)
#   python bench.py --configs session_storm # device-resident session
#                                           # state: 1M-session resume
#                                           # via segment replay + QoS1
#                                           # redelivery flood (~30s —
#                                           # docs/sessions.md)
#   python bench.py --configs conn_scaling  # slab protocol plane:
#                                           # 10k->1M simulated-client
#                                           # scaling curve with the
#                                           # distinct-topic axis
#                                           # (4096->100k->1M topics;
#                                           # CSR sub_table_bytes
#                                           # measured per point,
#                                           # deliveries drained to
#                                           # quiescence) + codec
#                                           # microbench
#                                           # (docs/protocol_plane.md,
#                                           # serving_pipeline.md)
#   python bench.py --configs agentic_fabric # semantic routing plane:
#                                           # mixed topic+semantic
#                                           # fan-in/fan-out scenarios,
#                                           # device-fused similarity +
#                                           # rule WHERE masks vs the
#                                           # post-dispatch host filter
#                                           # (~40s CPU —
#                                           # docs/semantic_routing.md)
#   python bench.py --configs mesh_serving  # scale-out sharded serving:
#                                           # the four-scenario broker
#                                           # matrix through the mesh
#                                           # entry (100M subs on TPU;
#                                           # 2-shard CPU proxy, ~90s —
#                                           # docs/scale_out.md)
#   python bench.py                         # full sweep (BENCH json)
#
# Exit non-zero on the first failing gate.
set -euo pipefail

cd "$(dirname "$0")/.."
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

FAST=0
for arg in "$@"; do
    case "$arg" in
        --fast) FAST=1 ;;
        *) echo "usage: tools/ci_gate.sh [--fast]" >&2; exit 2 ;;
    esac
done

banner() { printf '\n== %s ==\n' "$*"; }

profile_smoke() {
    # arm -> one real batch through ingest -> disarm -> assert the
    # jax.profiler capture landed non-empty and under budget
    python - <<'PY'
import asyncio, tempfile

from emqx_tpu.broker.broker import Broker
from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.broker.ingest import BatchIngest
from emqx_tpu.broker.message import Message
from emqx_tpu.broker.router import Router
from emqx_tpu.mqtt import packet as pkt
from emqx_tpu.observe.profiler import Profiler


async def main():
    broker = Broker(router=Router(min_tpu_batch=8), hooks=Hooks())
    prof = Profiler(metrics=broker.metrics, trace_dir=tempfile.mkdtemp())
    sink = []
    for i in range(8):
        broker.subscribe(f"s{i}", f"c{i}", f"p/{i}", pkt.SubOpts(),
                         lambda m, o: sink.append(m.topic))
    ing = BatchIngest(broker, max_batch=64, window_us=500)
    broker.ingest = ing
    ing.start()
    prof.arm(duration_s=20.0)
    rs = [await broker.apublish_enqueue(
        Message(topic=f"p/{i % 8}", payload=b"x", from_client=f"b{i}"))
        for i in range(64)]
    await asyncio.gather(*[r for r in rs if not isinstance(r, int)])
    entry = prof.disarm("smoke")
    await ing.stop()
    assert entry is not None and entry["bytes"] > 0 \
        and not entry["deleted"], entry
    print(f"profile smoke ok: {entry['bytes']} bytes -> {entry['dir']}")


asyncio.run(main())
PY
}

if [ "$FAST" = 1 ]; then
    banner "tpu_lint (changed files)"
    python -m tools.analysis --changed-only --jobs 8
    banner "profile smoke (arm -> batch -> disarm)"
    profile_smoke
    banner "tier-B smoke (bounded replay + full wirecompat corpus)"
    python -m tools.analysis --audit --smoke --checks oplog
    banner "bench trend gate (fingerprint-grouped)"
    python -m tools.bench_trend --check > /dev/null
    banner "race suite (racetrack armed)"
    python -m pytest tests/ -q -m race -p no:cacheprovider
    exit 0
fi

banner "tpu_lint + tier-B audit (contracts, replay, wirecompat)"
python -m tools.analysis --jobs 8 --audit

banner "tier-1 tests"
python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider

banner "race suite (racetrack armed)"
python -m pytest tests/ -m race -p no:cacheprovider

banner "bench trend gate (fingerprint-grouped)"
python -m tools.bench_trend --check > /dev/null

banner "ci_gate: all gates green"
