"""Fingerprint-grouped benchmark trend report + regression gate.

Loads the committed BENCH_r*/BENCH_FULL/MULTICHIP_r* trajectory, groups
every run by its hardware fingerprint (observe/provenance.py), and
compares each metric ONLY against the most recent earlier run with the
SAME fingerprint. Cross-fingerprint comparison is rejected outright: a
throughput delta between a TPU v5p run and a 1-core CPU proxy run is
not a regression, it is a hardware swap, and the honest answer is "not
comparable" — not a percentage.

Legacy captures (BENCH_r01..r05 and the pre-provenance BENCH_FULL)
carry no fingerprint; the loader backfills `fingerprint: null,
proxy: true` and files them under the `legacy` group, which is never
comparable to anything (including itself — an unattributed number has
no provenance to match on).

Regression rule: a metric regresses when it moves in its BAD direction
(lower for throughput/speedup series, higher for latency/footprint
series) by more than its threshold fraction vs the last same-
fingerprint value. Thresholds are deliberately loose by default (25%):
this gate catches cliffs, not noise — the SLO lanes own the fine
percentiles.

Usage:
    python -m tools.bench_trend               # markdown report, exit 0
    python -m tools.bench_trend --check       # exit 1 on any regression
    python -m tools.bench_trend --dir PATH    # trajectory directory
    python -m tools.bench_trend --threshold 0.4
    python -m tools.bench_trend --out trend.md

`tools/ci_gate.sh` runs `--check` after the bench recipes: a sweep that
silently halved a headline fails the gate even when every test passes.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

# metric-name heuristics for the BAD direction. Default is higher-is-
# better (throughput trajectory); these mark lower-is-better. The `_ms`
# arm must NOT match `_msgs_per_s` — hence the lookahead.
_LOWER_RE = re.compile(
    r"_ms(?:_|$)|latency|_seconds|_bytes|overhead_pct"
)

# never gated: bookkeeping, wall budgets, identifiers, curve blobs
_SKIP_KEYS = {
    "n",
    "rc",
    "wall_s",
    "e2e_timeout",
    "e2e_best_workers",
    "skipped_configs",
    "note",
    "device",
    "batch",
    "baseline",
    "configs",
    "fingerprint",
    "proxy",
    "fingerprint_key",
}

DEFAULT_THRESHOLD = 0.25
# per-metric overrides where the default is wrong for the series' noise
THRESHOLDS: Dict[str, float] = {
    # e2e serving rides a subprocess socket harness — noisier than the
    # kernel series, so give it extra headroom before flagging
    "e2e_serving_msgs_per_s": 0.35,
}

LEGACY_KEY = "legacy"


def lower_is_better(name: str) -> bool:
    return _LOWER_RE.search(name) is not None


def threshold_for(name: str, default: float) -> float:
    return THRESHOLDS.get(name, default)


def _last_json_line(text: str) -> Optional[Dict]:
    """Extract the last parseable one-line JSON object from a tail
    capture (the driver wrappers store stdout tails, where the final
    line is bench.py's compact summary — when the run survived)."""
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if isinstance(doc, dict):
            return doc
    return None


def _numeric_items(d: Dict, prefix: str = "") -> List[Tuple[str, float]]:
    out: List[Tuple[str, float]] = []
    for k, v in d.items():
        if k in _SKIP_KEYS:
            continue
        if isinstance(v, bool):
            continue
        if isinstance(v, (int, float)):
            out.append((prefix + k, float(v)))
    return out


def _harvest_metrics(doc: Dict) -> Dict[str, float]:
    """Flatten one bench summary doc to {metric_name: value}."""
    out: Dict[str, float] = {}
    metric = doc.get("metric")
    value = doc.get("value")
    if isinstance(metric, str) and isinstance(value, (int, float)) \
            and not isinstance(value, bool):
        out[metric] = float(value)
    detail = doc.get("detail")
    if isinstance(detail, dict):
        for name, v in _numeric_items(detail):
            out[name] = v
    return out


def _fingerprint_key(fp: Optional[Dict]) -> str:
    if not isinstance(fp, dict):
        return LEGACY_KEY
    from emqx_tpu.observe.provenance import fingerprint_key

    try:
        return fingerprint_key(fp)
    except Exception:  # noqa: BLE001 — malformed stamp => legacy
        return LEGACY_KEY


def load_run(path: str) -> Optional[Dict[str, Any]]:
    """One trajectory file -> a run record, or None when unreadable.

    Handles all three committed shapes: the driver wrapper
    (`{n, cmd, rc, tail, parsed}`), bench.py's own full document
    (`{metric, value, detail, ...}`), and the multichip wrapper
    (`{n_devices, rc, ok, skipped, tail}`)."""
    try:
        with open(path, encoding="utf-8") as f:
            raw = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(raw, dict):
        return None
    base = os.path.basename(path)
    m = re.search(r"_r(\d+)", base)
    rnd = int(m.group(1)) if m else raw.get("n")
    run: Dict[str, Any] = {
        "source": base,
        "round": rnd,
        "kind": "multichip" if base.startswith("MULTICHIP") else "bench",
        "ok": True,
        "metrics": {},
    }
    doc: Optional[Dict] = None
    if "tail" in raw:  # driver / multichip wrapper
        run["ok"] = (raw.get("rc") == 0) and not raw.get("skipped")
        doc = _last_json_line(raw.get("tail") or "")
        # provenance stamped on the wrapper itself wins over the tail's
        if isinstance(raw.get("fingerprint"), dict):
            doc = dict(doc or {})
            doc["fingerprint"] = raw["fingerprint"]
            doc["proxy"] = raw.get("proxy", True)
    elif "metric" in raw or "detail" in raw:  # BENCH_FULL shape
        doc = raw
    if doc is not None:
        run["metrics"] = _harvest_metrics(doc)
        fp = doc.get("fingerprint")
    else:
        fp = raw.get("fingerprint")
    if not isinstance(fp, dict):
        # legacy backfill: pre-provenance captures have no fingerprint;
        # they are kept in the report but are never comparable
        fp = None
    run["fingerprint"] = fp
    run["proxy"] = bool(doc.get("proxy", True)) if doc else True
    if fp is not None:
        run["proxy"] = bool(fp.get("proxy", run["proxy"]))
    run["key"] = _fingerprint_key(fp)
    return run


def load_trajectory(root: str) -> List[Dict[str, Any]]:
    paths = sorted(
        glob.glob(os.path.join(root, "BENCH_r*.json"))
        + glob.glob(os.path.join(root, "MULTICHIP_r*.json"))
    )
    full = os.path.join(root, "BENCH_FULL.json")
    if os.path.exists(full):
        paths.append(full)
    runs = [load_run(p) for p in paths]
    runs = [r for r in runs if r is not None]

    def order(r):
        return (r["round"] if r["round"] is not None else 10**6,
                r["source"])

    runs.sort(key=order)
    return runs


def compare(runs: List[Dict[str, Any]], default_threshold: float
            ) -> Dict[str, Any]:
    """Walk the trajectory; for every bench run, diff each metric
    against the last SAME-fingerprint run that carried it. Returns
    {regressions, improvements, deltas, rejected} where `rejected`
    counts would-be comparisons refused for provenance reasons."""
    last_by_key: Dict[str, Dict[str, Tuple[float, str]]] = {}
    regressions: List[Dict[str, Any]] = []
    improvements: List[Dict[str, Any]] = []
    deltas: List[Dict[str, Any]] = []
    rejected = 0
    for run in runs:
        if run["kind"] != "bench" or not run["metrics"]:
            continue
        key = run["key"]
        if key == LEGACY_KEY:
            # no provenance => nothing to anchor a comparison to; the
            # run still seeds nothing (legacy never baselines legacy)
            rejected += 1
            continue
        prev = last_by_key.setdefault(key, {})
        other_keys = [k for k in last_by_key if k != key and k !=
                      LEGACY_KEY]
        if other_keys and not prev:
            # a fingerprint flip mid-trajectory: every metric of this
            # run WOULD have compared against the other group
            rejected += 1
        for name, value in run["metrics"].items():
            if name in prev:
                base, base_src = prev[name]
                entry = {
                    "metric": name,
                    "value": value,
                    "baseline": base,
                    "baseline_source": base_src,
                    "source": run["source"],
                    "fingerprint_key": key,
                }
                if base != 0:
                    worse = (
                        (base - value) / abs(base)
                        if not lower_is_better(name)
                        else (value - base) / abs(base)
                    )
                    entry["delta_pct"] = round(
                        100.0 * (value - base) / abs(base), 2
                    )
                    thr = threshold_for(name, default_threshold)
                    if worse > thr:
                        entry["threshold_pct"] = round(100.0 * thr, 1)
                        regressions.append(entry)
                    elif worse < -thr:
                        improvements.append(entry)
                deltas.append(entry)
            prev[name] = (value, run["source"])
    return {
        "regressions": regressions,
        "improvements": improvements,
        "deltas": deltas,
        "rejected": rejected,
    }


def render_markdown(runs: List[Dict[str, Any]], cmp: Dict[str, Any]
                    ) -> str:
    lines = ["# Benchmark trend (fingerprint-grouped)", ""]
    groups: Dict[str, List[Dict]] = {}
    for r in runs:
        groups.setdefault(r["key"], []).append(r)
    for key in sorted(groups):
        rs = groups[key]
        proxy = any(r["proxy"] for r in rs)
        label = "legacy (no fingerprint — never comparable)" \
            if key == LEGACY_KEY else f"`{key}`"
        lines.append(f"## Fingerprint {label}"
                     + (" — PROXY (non-TPU)" if proxy else ""))
        lines.append("")
        lines.append("| round | source | kind | ok | metrics |")
        lines.append("|---|---|---|---|---|")
        for r in rs:
            head = ", ".join(
                f"{k}={v:.4g}" for k, v in sorted(r["metrics"].items())
                [:4]
            ) or "—"
            lines.append(
                f"| {r['round']} | {r['source']} | {r['kind']} | "
                f"{'yes' if r['ok'] else 'NO'} | {head} |"
            )
        lines.append("")
    lines.append(f"Cross-fingerprint / unattributable comparisons "
                 f"rejected: {cmp['rejected']}")
    lines.append("")
    if cmp["regressions"]:
        lines.append("## REGRESSIONS")
        lines.append("")
        for e in cmp["regressions"]:
            lines.append(
                f"- **{e['metric']}**: {e['value']:.4g} vs "
                f"{e['baseline']:.4g} ({e['delta_pct']:+.1f}%, "
                f"threshold {e['threshold_pct']}%) — {e['source']} vs "
                f"{e['baseline_source']}"
            )
        lines.append("")
    else:
        lines.append("No regressions against same-fingerprint "
                     "baselines.")
        lines.append("")
    if cmp["improvements"]:
        lines.append("## Improvements")
        lines.append("")
        for e in cmp["improvements"]:
            lines.append(
                f"- {e['metric']}: {e['value']:.4g} vs "
                f"{e['baseline']:.4g} ({e['delta_pct']:+.1f}%) — "
                f"{e['source']} vs {e['baseline_source']}"
            )
        lines.append("")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=None,
                    help="trajectory directory (default: repo root)")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 when any same-fingerprint regression "
                         "is flagged")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                    help="default fractional regression threshold")
    ap.add_argument("--out", default=None,
                    help="write the markdown report here (default: "
                         "stdout)")
    args = ap.parse_args(argv)
    root = args.dir or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    runs = load_trajectory(root)
    if not runs:
        print(f"bench_trend: no trajectory files under {root}",
              file=sys.stderr)
        return 0 if not args.check else 0
    cmp = compare(runs, args.threshold)
    report = render_markdown(runs, cmp)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(report + "\n")
    else:
        print(report)
    if args.check and cmp["regressions"]:
        print(
            f"bench_trend: {len(cmp['regressions'])} regression(s) vs "
            "same-fingerprint baselines (see report)",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
