"""tpu_lint: project-specific AST static analysis for emqx_tpu.

`python -m tools.analysis` runs five checkers over `emqx_tpu/` and fails
(exit 1) on any finding not recorded in the checked-in baseline:

- lock discipline (LK*): attributes annotated `# guarded-by: <lock>` (or
  listed in a class-level `GUARDED_BY` dict) may only be touched inside
  `with self.<lock>:` blocks — the PR 1 gauge-bypass bug class;
- async blocking calls (AB*): `time.sleep`, sync socket/file I/O,
  `requests.*`, bare `Future.result()`, subprocess, sync DB clients inside
  `async def` bodies — anything that stalls the broker's event loop;
- jit purity (JP*): functions reachable from `jax.jit` / `shard_map` call
  sites must not sync to host (`.item()`), cast tracers to Python
  scalars, mutate globals, read wall-clock/RNG, or branch on tracer
  truthiness — trace-impurity breaks TrieJax-style kernel caching;
- config-key drift (CK*): attribute paths on typed `AppConfig` dataclass
  trees must exist in `config/schema.py`; gateway `config.get("key")`
  reads must name a declared gateway opt key; schema keys nothing reads
  are reported as dead;
- metric names (MN*): every static `metrics.inc/observe/gauge_set` series
  name must be `declare()`d in the metric-kind registry (the former
  standalone metric-name script, now a checker here).

See docs/static_analysis.md for codes, suppression, and extension.
"""

from tools.analysis.core import (  # noqa: F401
    Baseline,
    Finding,
    Report,
    run_analysis,
)
