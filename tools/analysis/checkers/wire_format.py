"""WF: wire-format registration + digest-pin discipline.

The BPAPI rule for bytes (reference: apps/emqx/src/bpapi/ — every
externalized layout is a frozen, versioned module). Here the registry is
emqx_tpu/proto/registry.py and this checker closes the loop statically:

- WF001 — a wire literal (module-level `struct.Struct`/`np.dtype`
  constant, or a `T_*`/`NS_*` tag-constant group) in a module with a
  serialize boundary (send/pack/pickle calls, pack_*/unpack_* defs)
  that no registration's `source` covers. Unregistered layouts are
  invisible to the version discipline and the corpus gate.
- WF002 — a registered structure literal that drifted from the DEFINING
  code (registry says one layout, the `np.dtype(...)` at the source
  pointer says another), or a source pointer that rotted. This is what
  catches a field reorder in `PUB_HDR_DT` without running any broker
  code: the registry mirror no longer digests to the same string.
- WF003 — a registered digest that drifted from the golden pin
  (tests/fixtures/analysis/wire/digests.json) while the version stayed
  put: a layout change shipping without a version bump.
- WF004 — a registration with no pin, or a pin left stale after a
  version bump: regenerate via
  `python -m tools.analysis --wirecompat --update-corpus`.

All structure comparison is digest-string equality, so messages show
the actual field-level diff, not just "mismatch".
"""

from __future__ import annotations

import ast
import struct as _struct
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from emqx_tpu.proto.digest import dtype_digest, struct_digest, tag_digest
from tools.analysis.core import Checker, Finding, ParsedModule
from tools.analysis.checkers.wire_common import (
    Registration,
    extract_registrations,
    load_pins,
    module_index,
    prefix_constants,
    toplevel_assigns,
)

# call names that mark a module as a serialize boundary: its bytes
# leave the process, so its layout constants must be registered
BOUNDARY_CALLS = frozenset({
    "send", "sendall", "sendto", "send_frame", "_send_frame",
    "enqueue", "cast", "dumps", "dump", "pack", "pack_into",
    "pack_frame", "tobytes",
})

# tag-constant group prefixes the registry covers with ":T_*" sources
TAG_PREFIXES = ("T_", "NS_")


def _call_name(node: ast.Call) -> Optional[str]:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _has_serialize_boundary(mod: ParsedModule) -> bool:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and _call_name(node) in BOUNDARY_CALLS:
            return True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
            node.name.startswith("pack_") or node.name.startswith("unpack_")
        ):
            return True
    return False


def _wire_literal_kind(value: ast.AST) -> Optional[str]:
    """'struct' for `struct.Struct(...)`, 'dtype' for `np.dtype(...)`."""
    if not isinstance(value, ast.Call):
        return None
    name = _call_name(value)
    if name == "Struct":
        return "struct"
    if name == "dtype":
        return "dtype"
    return None


def _literal_digest(kind: str, value: ast.Call) -> Optional[str]:
    """Digest of a defining-code wire literal, from its AST node."""
    if not value.args:
        return None
    try:
        arg = ast.literal_eval(value.args[0])
    except (ValueError, SyntaxError):
        return None
    try:
        if kind == "struct" and isinstance(arg, str):
            return struct_digest(arg)
        if kind == "dtype" and isinstance(arg, (list, tuple)):
            return dtype_digest(list(arg))
    except (ValueError, _struct.error):
        return None
    return None


class WireFormatChecker(Checker):
    name = "wire"
    codes = {
        "WF001": "wire literal at a serialize boundary is not registered",
        "WF002": "registered structure drifted from the defining code",
        "WF003": "registered digest drifted from pin without version bump",
        "WF004": "registration has no golden pin / pin is stale",
    }

    def __init__(self, pins_path: Optional[Path] = None):
        self._pins_path = pins_path
        self._regs: List[Registration] = []
        self._pins: Dict[str, Tuple[int, str]] = {}
        self._by_rel: Dict[str, ParsedModule] = {}
        # (module rel, symbol-or-prefix) pairs covered by a registration
        self._covered: set = set()

    def begin(self, modules: Sequence[ParsedModule]) -> None:
        self._regs = extract_registrations(modules)
        self._pins = load_pins(self._pins_path)
        self._by_rel = module_index(modules)
        self._covered = set()
        for reg in self._regs:
            path, symbol, _frag = reg.source_parts()
            if symbol:
                self._covered.add((path, symbol))

    def check(self, mod: ParsedModule) -> Iterable[Finding]:
        # WF001: unregistered boundary literals
        if not _has_serialize_boundary(mod):
            return
        seen_prefixes = set()
        for name, value in toplevel_assigns(mod).items():
            kind = _wire_literal_kind(value)
            if kind is not None:
                if (mod.rel, name) not in self._covered:
                    yield Finding(
                        code="WF001",
                        path=mod.rel,
                        line=value.lineno,
                        symbol="<module>",
                        detail=name,
                        message=(
                            f"module-level {kind} literal {name} reaches a "
                            "serialize boundary but has no "
                            "proto.registry registration"
                        ),
                    )
                continue
            for prefix in TAG_PREFIXES:
                if name.startswith(prefix) and prefix not in seen_prefixes:
                    group = prefix_constants(mod, prefix)
                    if len(group) < 2:
                        continue  # one stray constant is not a tag table
                    seen_prefixes.add(prefix)
                    if (mod.rel, prefix + "*") not in self._covered:
                        yield Finding(
                            code="WF001",
                            path=mod.rel,
                            line=value.lineno,
                            symbol="<module>",
                            detail=prefix + "*",
                            message=(
                                f"tag-constant group {prefix}* "
                                f"({len(group)} values) reaches a "
                                "serialize boundary but has no "
                                "proto.registry registration"
                            ),
                        )

    def finalize(self) -> Iterable[Finding]:
        for reg in self._regs:
            yield from self._check_source(reg)
            yield from self._check_pin(reg)

    # -- WF002: registry literal vs defining code ------------------------
    def _check_source(self, reg: Registration) -> Iterable[Finding]:
        if reg.kind not in ("dtype", "struct", "tags"):
            return  # schema/class_state are SS's, proto is BP's
        path, symbol, _frag = reg.source_parts()
        if not symbol:
            return  # module-scope tag family: no single defining literal
        src_mod = self._by_rel.get(path)
        if src_mod is None:
            yield Finding(
                code="WF002",
                path=reg.mod.rel,
                line=reg.lineno,
                symbol="<module>",
                detail=f"{reg.name}:source",
                message=(
                    f"wire format {reg.name!r} points at missing source "
                    f"module {path}"
                ),
            )
            return
        code_digest: Optional[str] = None
        if symbol.endswith("*"):
            group = prefix_constants(src_mod, symbol[:-1])
            code_digest = tag_digest(group) if group else None
        else:
            value = toplevel_assigns(src_mod).get(symbol)
            if value is not None:
                kind = _wire_literal_kind(value)
                if kind == reg.kind:
                    code_digest = _literal_digest(kind, value)
        if code_digest is None:
            yield Finding(
                code="WF002",
                path=reg.mod.rel,
                line=reg.lineno,
                symbol="<module>",
                detail=f"{reg.name}:source",
                message=(
                    f"wire format {reg.name!r}: source symbol "
                    f"{path}:{symbol} not found or not a {reg.kind} literal"
                ),
            )
            return
        if reg.digest is not None and code_digest != reg.digest:
            yield Finding(
                code="WF002",
                path=reg.mod.rel,
                line=reg.lineno,
                symbol="<module>",
                detail=reg.name,
                message=(
                    f"wire format {reg.name!r} drifted from its defining "
                    f"code: registry={reg.digest} code={code_digest} "
                    f"({path}:{symbol})"
                ),
            )

    # -- WF003/WF004: registry digest vs golden pin -----------------------
    def _check_pin(self, reg: Registration) -> Iterable[Finding]:
        if reg.digest is None:
            return  # unresolvable structure; source check already fails
        pin = self._pins.get(reg.name)
        if pin is None:
            yield Finding(
                code="WF004",
                path=reg.mod.rel,
                line=reg.lineno,
                symbol="<module>",
                detail=f"{reg.name}:unpinned",
                message=(
                    f"wire format {reg.name!r} has no golden digest pin — "
                    "run `python -m tools.analysis --wirecompat "
                    "--update-corpus`"
                ),
            )
            return
        pin_version, pin_digest = pin
        if reg.version == pin_version and reg.digest != pin_digest:
            yield Finding(
                code="WF003",
                path=reg.mod.rel,
                line=reg.lineno,
                symbol="<module>",
                detail=reg.name,
                message=(
                    f"wire format {reg.name!r} digest drifted without a "
                    f"version bump (v{reg.version}): pin={pin_digest} "
                    f"now={reg.digest} — bump the version and regenerate "
                    "the pins + corpus"
                ),
            )
        elif reg.version != pin_version:
            yield Finding(
                code="WF004",
                path=reg.mod.rel,
                line=reg.lineno,
                symbol="<module>",
                detail=f"{reg.name}:stale-pin",
                message=(
                    f"wire format {reg.name!r} is v{reg.version} but the "
                    f"pin is v{pin_version} — regenerate via "
                    "`python -m tools.analysis --wirecompat "
                    "--update-corpus`"
                ),
            )
