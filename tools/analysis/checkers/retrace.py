"""RT: retrace hazards — non-static jit arguments in shape positions.

A jitted kernel whose *traced* argument reaches a shape position
(`jnp.zeros(n)`, `x.reshape(n, -1)`, `jnp.arange(n)`) either raises at
trace time or — when the value arrives as a Python int — silently
recompiles per distinct value. On the serving path one such leak turns
the steady-state "launch + readback" cost into a compile per batch.
The fix is always the same: cover the argument with `static_argnums`/
`static_argnames` (or derive the size from `.shape`, which is static
under the trace).

  RT001  non-static jit argument flows into a shape position

Roots are jit-wrapped functions (decorated `@jax.jit` /
`@partial(jax.jit, ...)`, or wrapped by a module-level assignment like
`route_step = partial(jax.jit, static_argnames=...)(route_step_impl)`).
Hazard = the root's parameters minus its static names. Hazards follow
simple assignment and propagate through calls into callee parameters
(`route_step_impl` hands `kslot` to `compact_fanout_slots` — dropping
`kslot` from the static tuple is flagged *inside the callee*). Deriving
from `.shape`/`.ndim`/`.size`/`len()` clears the hazard: those are
static at trace time. Closure variables are static by construction and
never hazardous.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.analysis.callgraph import (
    FnInfo,
    FuncKey,
    ProjectGraph,
    module_dotted,
    shared_graph,
)
from tools.analysis.core import Checker, Finding, ParsedModule

JIT_NAMES = {"jax.jit", "jit"}
PARTIAL_NAMES = {"functools.partial", "partial"}
STATIC_ATTRS = {"shape", "ndim", "size", "dtype", "nbytes"}

# callable -> indices of its shape-position arguments
SHAPE_ARG0 = {"zeros", "ones", "full", "empty", "arange", "eye",
              "linspace", "iota"}
SHAPE_ARG1 = {"broadcast_to", "tile", "reshape", "full_like"}
SHAPE_METHODS = {"reshape", "broadcast_to", "resize"}

_MESSAGES = {
    "RT001": "non-static jit argument in a shape position (retrace per "
             "value, or a trace-time error on array args) — cover it "
             "with static_argnums/static_argnames or derive the size "
             "from .shape",
}


def _jnp_tail(name: str) -> str:
    """'jax.numpy.zeros' / 'jnp.zeros' / 'numpy.zeros' -> 'zeros'."""
    head, _, tail = name.rpartition(".")
    if head in ("jax.numpy", "jnp", "numpy", "np", "jax.lax", "lax"):
        return tail
    return ""


def _static_names(call: ast.Call, fn_node) -> Set[str]:
    """static_argnames/static_argnums literals -> parameter-name set."""
    out: Set[str] = set()
    params = [a.arg for a in fn_node.args.args + fn_node.args.kwonlyargs]
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                out.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                for e in v.elts:
                    if isinstance(e, ast.Constant) and isinstance(
                        e.value, str
                    ):
                        out.add(e.value)
        elif kw.arg == "static_argnums":
            v = kw.value
            nums: List[int] = []
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                nums = [v.value]
            elif isinstance(v, (ast.Tuple, ast.List)):
                nums = [
                    e.value for e in v.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, int)
                ]
            for n in nums:
                if 0 <= n < len(params):
                    out.add(params[n])
    return out


class RetraceChecker(Checker):
    name = "retrace"
    codes = dict(_MESSAGES)

    def begin(self, modules: Sequence[ParsedModule]) -> None:
        g = self._graph = shared_graph(modules)
        # (func key) -> hazardous parameter names, grown to a fixpoint
        self._hazard: Dict[FuncKey, Set[str]] = {}
        self._roots: List[Tuple[FnInfo, Set[str]]] = []
        for info in g.infos:
            statics = self._root_statics(info)
            if statics is not None:
                self._roots.append((info, statics))
        for mod in modules:
            dn = module_dotted(mod.rel)
            for stmt in mod.tree.body:
                if not isinstance(stmt, ast.Assign):
                    continue
                hit = self._wrapped_impl(dn, stmt.value)
                if hit is None:
                    continue
                impl_key, jit_call = hit
                for impl in g.funcs.get(impl_key, []):
                    self._roots.append(
                        (impl, _static_names(jit_call, impl.node))
                    )
        for info, statics in self._roots:
            params = [
                a.arg
                for a in info.node.args.args + info.node.args.kwonlyargs
            ]
            hazard = {
                p for p in params
                if p not in statics and p not in ("self", "cls")
            }
            if hazard:
                self._hazard.setdefault(info.key, set()).update(hazard)
        # fixpoint: hazards flow through call sites into callees
        for _ in range(12):
            grew = False
            for key in list(self._hazard):
                for info in g.funcs.get(key, []):
                    if self._propagate(info):
                        grew = True
            if not grew:
                break

    def _root_statics(self, info: FnInfo) -> Optional[Set[str]]:
        """Static names when `info` is jit-decorated, else None."""
        g = self._graph
        for dec in info.node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            name = g.call_name(info.dn, target)
            if name in JIT_NAMES:
                call = dec if isinstance(dec, ast.Call) else ast.Call(
                    func=dec, args=[], keywords=[]
                )
                return _static_names(call, info.node)
            if (
                isinstance(dec, ast.Call)
                and name in PARTIAL_NAMES
                and dec.args
                and g.call_name(info.dn, dec.args[0]) in JIT_NAMES
            ):
                return _static_names(dec, info.node)
        return None

    def _wrapped_impl(
        self, dn: str, value: ast.AST
    ) -> Optional[Tuple[FuncKey, ast.Call]]:
        """`[wrap(...)](partial(jax.jit, ...)(impl))` / `jax.jit(impl)`
        anywhere in an assignment RHS -> (impl key, the jit call)."""
        g = self._graph
        for node in ast.walk(value):
            if not isinstance(node, ast.Call):
                continue
            name = g.call_name(dn, node.func)
            if name in JIT_NAMES and node.args:
                targets = g.ref_targets(dn, node.args[0])
                for t in targets:
                    if t in g.funcs:
                        return t, node
            if isinstance(node.func, ast.Call):
                inner = g.call_name(dn, node.func.func)
                if (
                    inner in PARTIAL_NAMES
                    and node.func.args
                    and g.call_name(dn, node.func.args[0]) in JIT_NAMES
                    and node.args
                ):
                    for t in g.ref_targets(dn, node.args[0]):
                        if t in g.funcs:
                            return t, node.func
        return None

    # -- hazard propagation / screening ------------------------------------
    def _hazard_names(self, info: FnInfo) -> Set[str]:
        return self._hazard.get(info.key, set())

    def _local_hazards(self, info: FnInfo) -> Dict[ast.Call, List[str]]:
        """Walk one function: returns shape-position violations, and as a
        side effect records hazard propagation into callees."""
        g = self._graph
        dn = info.dn
        hazard = set(self._hazard_names(info))
        cleared: Set[str] = set()
        violations: Dict[ast.Call, List[str]] = {}

        def expr_hazards(e: ast.AST) -> List[str]:
            out = []
            for sub in ast.walk(e):
                if isinstance(sub, ast.Attribute) or (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Name)
                    and sub.func.id == "len"
                ):
                    # `.shape[0]` / `len(x)` subtrees are static
                    return []
            for sub in ast.walk(e):
                if isinstance(sub, ast.Name) and sub.id in hazard \
                        and sub.id not in cleared:
                    out.append(sub.id)
            return out

        def check_call(node: ast.Call) -> None:
            name = g.call_name(dn, node.func)
            tail = _jnp_tail(name)
            shape_args: List[ast.AST] = []
            if tail in SHAPE_ARG0 and node.args:
                shape_args.append(node.args[0])
                if tail == "arange" and len(node.args) > 1:
                    shape_args.extend(node.args[1:3])
            elif tail in SHAPE_ARG1 and len(node.args) > 1:
                shape_args.extend(node.args[1:])
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in SHAPE_METHODS
                and not _jnp_tail(name)  # method call, not jnp.reshape
            ):
                shape_args.extend(node.args)
            for kw in node.keywords:
                if kw.arg == "shape":
                    shape_args.append(kw.value)
            hits: List[str] = []
            for a in shape_args:
                hits.extend(expr_hazards(a))
            if hits:
                violations[node] = sorted(set(hits))
            # propagate hazards into callee params
            targets = [t for t in g.ref_targets(dn, node.func)
                       if t in g.funcs]
            for t in targets:
                for callee in g.funcs.get(t, []):
                    cparams = [
                        a.arg
                        for a in callee.node.args.args
                        + callee.node.args.kwonlyargs
                    ]
                    is_method = bool(cparams) and cparams[0] in (
                        "self", "cls"
                    )
                    shift = 1 if (
                        is_method and isinstance(node.func, ast.Attribute)
                    ) else 0
                    names: List[str] = []
                    for i, arg in enumerate(node.args):
                        if expr_hazards(arg) and i + shift < len(cparams):
                            names.append(cparams[i + shift])
                    for kw in node.keywords:
                        if kw.arg and kw.arg in cparams \
                                and expr_hazards(kw.value):
                            names.append(kw.arg)
                    if names:
                        cur = self._hazard.setdefault(t, set())
                        self._grew |= not set(names) <= cur
                        cur.update(names)

        def track_assign(s: ast.Assign) -> None:
            hz = expr_hazards(s.value)
            names: List[ast.Name] = []
            for t in s.targets:
                if isinstance(t, ast.Name):
                    names.append(t)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    names.extend(
                        e for e in t.elts if isinstance(e, ast.Name)
                    )
            for n in names:
                if hz:
                    hazard.add(n.id)
                    cleared.discard(n.id)
                else:
                    cleared.add(n.id)

        def walk(stmts) -> None:
            for s in stmts:
                if isinstance(
                    s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                ):
                    continue
                if isinstance(s, ast.Assign):
                    # order matters: screen the RHS calls against the
                    # PRE-assignment hazard set, then update it
                    for sub in ast.walk(s.value):
                        if isinstance(sub, ast.Call):
                            check_call(sub)
                    track_assign(s)
                    continue
                for sub in ast.walk(s):
                    if isinstance(sub, ast.Call):
                        check_call(sub)
                for attr in ("body", "orelse", "finalbody"):
                    nested = getattr(s, attr, None)
                    if nested:
                        # hazard/cleared tracking for nested assigns;
                        # calls were already screened by the ast.walk
                        for sub in nested:
                            if isinstance(sub, ast.Assign):
                                track_assign(sub)
        walk(info.node.body)
        return violations

    def finalize(self) -> Iterable[Finding]:
        self._grew = False
        findings: List[Finding] = []
        seen: Set[Tuple[str, int, str]] = set()
        done: Set[int] = set()
        for key in list(self._hazard):
            for info in self._graph.funcs.get(key, []):
                if id(info.node) in done:
                    continue
                done.add(id(info.node))
                for call, names in self._local_hazards(info).items():
                    k = (info.mod.rel, call.lineno, ",".join(names))
                    if k in seen:
                        continue
                    seen.add(k)
                    detail = ",".join(names)
                    findings.append(Finding(
                        code="RT001", path=info.mod.rel, line=call.lineno,
                        symbol=info.symbol, detail=detail,
                        message=f"{detail}: {_MESSAGES['RT001']}",
                    ))
        return findings

    def _propagate(self, info: FnInfo) -> bool:
        self._grew = False
        self._local_hazards(info)
        return self._grew
