"""SD: sharding discipline for mesh collectives and PartitionSpecs.

PR 3 fixed the serving hot path's collective set over the ('dp', 'tp')
mesh; nothing kept it fixed. A `psum` over an axis the mesh does not
bind deadlocks (or mis-reduces) a multi-chip deployment, and a
collective introduced in code the `shard_map` bodies never reach is
either dead or — worse — a latent crash when someone wires it in. The
axis-name registry is *sourced from the code*: every
`Mesh(..., axis_names=(...))` literal in the scanned tree contributes
(for `emqx_tpu/` that is `parallel/mesh.py`'s ('dp', 'tp') mesh — the
single place the topology is declared).

  SD001  collective names an axis the mesh registry does not bind
  SD002  collective call outside any shard_map-reachable body
  SD003  PartitionSpec names an axis the mesh registry does not bind

Reachability follows the shared project call graph from every function
passed to `shard_map(...)` — a collective in a helper *called from* a
shard_map body (`_reduce_stats`, `share_pick_device`) is inside the
mesh context and legal. Non-literal axis arguments (e.g. a `dp_axis`
parameter threaded from a static arg) are not judged: the checker only
validates what it can read.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Sequence, Set

from tools.analysis.callgraph import (
    FuncKey,
    ProjectGraph,
    is_literal_axes,
    module_dotted,
    shared_graph,
    str_constants,
)
from tools.analysis.core import Checker, Finding, ParsedModule

# canonical dotted names after import-alias resolution
SHARD_MAP_NAMES = {
    "jax.shard_map",
    "shard_map",
    "jax.experimental.shard_map.shard_map",
}
COLLECTIVES = {
    "jax.lax.psum",
    "jax.lax.pmean",
    "jax.lax.pmax",
    "jax.lax.pmin",
    "jax.lax.all_gather",
    "jax.lax.all_to_all",
    "jax.lax.ppermute",
    "jax.lax.pshuffle",
    "jax.lax.psum_scatter",
    "jax.lax.axis_index",
}
# axis argument: position for the common collectives (after the operand),
# axis_index takes it first
_AXIS_ARG_POS = {name: (0 if name.endswith("axis_index") else 1)
                 for name in COLLECTIVES}
_AXIS_KWARGS = ("axis_name", "axis")

PARTITION_SPEC_NAMES = {"jax.sharding.PartitionSpec", "PartitionSpec"}

_MESSAGES = {
    "SD001": "collective names an axis the mesh does not bind",
    "SD002": "collective call outside any shard_map body (unreachable "
             "from every shard_map-ped function)",
    "SD003": "PartitionSpec names an axis the mesh does not bind",
}


def _short(name: str) -> str:
    return name.rpartition(".")[2]


class ShardingChecker(Checker):
    name = "shard"
    codes = dict(_MESSAGES)

    def begin(self, modules: Sequence[ParsedModule]) -> None:
        self._graph = shared_graph(modules)
        self._axes: Set[str] = set()
        roots: List[FuncKey] = []
        for mod in modules:
            dn = module_dotted(mod.rel)
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = self._graph.call_name(dn, node.func)
                if _short(name) == "Mesh":
                    self._axes.update(self._mesh_axes(node))
                if name in SHARD_MAP_NAMES:
                    body = self._shard_map_body(node)
                    if body is not None:
                        roots.extend(self._graph.ref_targets(dn, body))
        self._reachable = self._graph.reachable_from(roots)

    @staticmethod
    def _mesh_axes(call: ast.Call) -> List[str]:
        for kw in call.keywords:
            if kw.arg == "axis_names":
                return str_constants(kw.value)
        if len(call.args) >= 2:
            return str_constants(call.args[1])
        return []

    @staticmethod
    def _shard_map_body(call: ast.Call) -> Optional[ast.AST]:
        for kw in call.keywords:
            if kw.arg == "f":
                return kw.value
        if call.args:
            return call.args[0]
        return None

    def check(self, mod: ParsedModule) -> Iterable[Finding]:
        dn = module_dotted(mod.rel)
        findings: List[Finding] = []
        # symbol + enclosing-function lookup for reachability
        enclosing: List[tuple] = []  # (node, key, symbol)

        def collect(node, prefix):
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    sym = f"{prefix}.{child.name}" if prefix else child.name
                    enclosing.append((child, (dn, child.name), sym))
                    collect(child, sym)
                elif isinstance(child, ast.ClassDef):
                    collect(
                        child,
                        f"{prefix}.{child.name}" if prefix else child.name,
                    )
                else:
                    collect(child, prefix)

        collect(mod.tree, "")

        def owner(call: ast.Call):
            """Innermost enclosing function of a call node."""
            best = None
            for fn, key, sym in enclosing:
                if fn.lineno <= call.lineno <= (fn.end_lineno or fn.lineno):
                    if best is None or fn.lineno >= best[0].lineno:
                        best = (fn, key, sym)
            return best

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = self._graph.call_name(dn, node.func)
            if name in COLLECTIVES:
                enc = owner(node)
                symbol = enc[2] if enc else "<module>"
                if enc is None or enc[1] not in self._reachable:
                    findings.append(Finding(
                        code="SD002", path=mod.rel, line=node.lineno,
                        symbol=symbol, detail=_short(name),
                        message=f"{_short(name)}: {_MESSAGES['SD002']}",
                    ))
                for axis in self._collective_axes(node, name):
                    if self._axes and axis not in self._axes:
                        findings.append(Finding(
                            code="SD001", path=mod.rel, line=node.lineno,
                            symbol=symbol,
                            detail=f"{_short(name)}:{axis}",
                            message=(
                                f"{_short(name)} over axis {axis!r}: "
                                f"{_MESSAGES['SD001']} (bound: "
                                f"{sorted(self._axes)})"
                            ),
                        ))
            elif name in PARTITION_SPEC_NAMES and self._axes:
                enc = owner(node)
                symbol = enc[2] if enc else "<module>"
                for arg in node.args:
                    for axis in str_constants(arg):
                        if axis not in self._axes:
                            findings.append(Finding(
                                code="SD003", path=mod.rel,
                                line=node.lineno, symbol=symbol,
                                detail=f"P:{axis}",
                                message=(
                                    f"PartitionSpec axis {axis!r}: "
                                    f"{_MESSAGES['SD003']} (bound: "
                                    f"{sorted(self._axes)})"
                                ),
                            ))
        return findings

    @staticmethod
    def _collective_axes(call: ast.Call, name: str) -> List[str]:
        pos = _AXIS_ARG_POS.get(name, 1)
        cand = None
        if len(call.args) > pos:
            cand = call.args[pos]
        else:
            for kw in call.keywords:
                if kw.arg in _AXIS_KWARGS:
                    cand = kw.value
                    break
        if cand is None or not is_literal_axes(cand):
            return []
        return str_constants(cand)
