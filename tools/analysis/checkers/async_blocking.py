"""AB: blocking calls inside `async def` bodies.

One stalled coroutine stalls the whole broker — ingest batching, PINGREQ
deadlines, and the device dispatch pipeline all share the loop. The
checker walks every async function body (there are ~350 across broker/,
transport/, gateway/, mgmt/) and flags calls that are known to block the
thread. Nested *sync* defs and lambdas are skipped: they are usually
`run_in_executor` / `to_thread` thunks, which is exactly where blocking
calls belong.

Codes:
  AB001  time.sleep                      -> use `await asyncio.sleep`
  AB002  sync network I/O (requests/urllib/socket/http.client/smtplib)
  AB003  sync file I/O (builtin open, os.fsync)
  AB004  subprocess / os.system
  AB005  bare Future.result() (blocks; asyncio results want `await`)
  AB006  sync DB clients (sqlite3/psycopg2/pymongo/mysql.connector)
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from tools.analysis.core import (
    Checker,
    Finding,
    ParsedModule,
    enclosing_symbols,
    import_aliases,
    resolve_call_name,
)

# canonical dotted name (exact) -> code
EXACT = {
    "time.sleep": "AB001",
    "socket.create_connection": "AB002",
    "socket.getaddrinfo": "AB002",
    "socket.gethostbyname": "AB002",
    "urllib.request.urlopen": "AB002",
    "open": "AB003",
    "io.open": "AB003",
    "os.fsync": "AB003",
    "os.system": "AB004",
    "subprocess.run": "AB004",
    "subprocess.call": "AB004",
    "subprocess.check_call": "AB004",
    "subprocess.check_output": "AB004",
    "sqlite3.connect": "AB006",
}

# canonical dotted prefix -> code
PREFIXES = {
    "requests.": "AB002",
    "http.client.": "AB002",
    "smtplib.": "AB002",
    "ftplib.": "AB002",
    "telnetlib.": "AB002",
    "psycopg2.": "AB006",
    "pymongo.": "AB006",
    "mysql.connector.": "AB006",
}

_MESSAGES = {
    "AB001": "blocking time.sleep in async code (use asyncio.sleep)",
    "AB002": "synchronous network I/O on the event loop",
    "AB003": "synchronous file I/O on the event loop",
    "AB004": "subprocess/system call blocks the event loop",
    "AB005": "bare Future.result() blocks (await it, or it is a sync "
             "future that belongs in an executor)",
    "AB006": "synchronous DB client call on the event loop",
}


class AsyncBlockingChecker(Checker):
    name = "async"
    codes = {
        code: msg for code, msg in _MESSAGES.items()
    }

    def check(self, mod: ParsedModule) -> Iterable[Finding]:
        findings: List[Finding] = []
        aliases = import_aliases(mod.tree)
        symbols = enclosing_symbols(mod.tree)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.AsyncFunctionDef):
                self._scan_async_body(
                    mod, node, aliases,
                    symbols.get(node, node.name), findings,
                )
        return findings

    def _scan_async_body(self, mod, fn, aliases, symbol, findings) -> None:
        for stmt in fn.body:
            self._walk(mod, stmt, aliases, symbol, findings)

    def _walk(self, mod, node, aliases, symbol, findings) -> None:
        # nested defs/lambdas run elsewhere (executor thunks, callbacks):
        # they are not awaited in this body, so skip them — nested async
        # defs get their own top-level visit
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        if isinstance(node, ast.Call):
            code, name = self._classify(node, aliases)
            if code is not None:
                findings.append(Finding(
                    code=code,
                    path=mod.rel,
                    line=node.lineno,
                    symbol=symbol,
                    detail=name,
                    message=f"{name}: {_MESSAGES[code]}",
                ))
        for child in ast.iter_child_nodes(node):
            self._walk(mod, child, aliases, symbol, findings)

    def _classify(self, call: ast.Call, aliases) -> tuple:
        name = resolve_call_name(call.func, aliases)
        if name is not None:
            if name in EXACT:
                return EXACT[name], name
            for prefix, code in PREFIXES.items():
                if name.startswith(prefix):
                    return code, name
        # <expr>.result() with no args: concurrent.futures blocking read
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr == "result"
            and not call.args
            and not call.keywords
        ):
            return "AB005", self._recv_name(call.func) or "result"
        return None, None

    @staticmethod
    def _recv_name(func: ast.Attribute) -> Optional[str]:
        base = func.value
        if isinstance(base, ast.Name):
            return f"{base.id}.result"
        if isinstance(base, ast.Attribute):
            return f"{base.attr}.result"
        return "result"
