"""MN: static metric-name lint (former tools/check_metric_names.py).

Every static series name passed to `metrics.inc/observe/observe_many/
gauge_set` must be `declare()`d in the metric-kind registry
(emqx_tpu/broker/metrics.py) — an undeclared series silently renders no
`# TYPE` line and is invisible to every dashboard, exporter, and alarm.

Unlike the old script this collects the declared set *statically* (every
`declare("name", ...)` call in the scanned tree), so the analyzer never
imports broker code. Dynamic names (f-strings, variables) are skipped —
they must be composed from declared prefixes, e.g. the
`matcher.fallback.rows.<cause>` family, each declared explicitly.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Sequence, Set, Tuple

from tools.analysis.core import (
    Checker,
    Finding,
    ParsedModule,
    enclosing_symbols,
)

METHODS = ("inc", "observe", "observe_many", "gauge_set")


def declared_names(modules: Sequence[ParsedModule]) -> Set[str]:
    """Every `declare("<name>", ...)` first-arg string in the tree."""
    out: Set[str] = set()
    for mod in modules:
        for node in ast.walk(mod.tree):
            if (
                isinstance(node, ast.Call)
                and (
                    (isinstance(node.func, ast.Name)
                     and node.func.id == "declare")
                    or (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "declare")
                )
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                out.add(node.args[0].value)
    return out


def call_sites(mod: ParsedModule) -> List[Tuple[int, str]]:
    """[(lineno, name)] for every static-name metric call in a module."""
    sites = []
    for node in ast.walk(mod.tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in METHODS
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            sites.append((node.lineno, node.args[0].value))
    return sites


class MetricNameChecker(Checker):
    name = "metrics"
    codes = {
        "MN001": "metric series name not declared in the metric-kind "
                 "registry",
    }

    def begin(self, modules: Sequence[ParsedModule]) -> None:
        self._declared = declared_names(modules)

    def check(self, mod: ParsedModule) -> Iterable[Finding]:
        findings: List[Finding] = []
        syms = enclosing_symbols(mod.tree)

        def nearest_symbol(lineno, end):
            best = "<module>"
            for n, s in syms.items():
                if n.lineno <= lineno and \
                        getattr(n, "end_lineno", 1 << 30) >= end:
                    best = s
            return best

        for node in ast.walk(mod.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in METHODS
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and node.args[0].value not in self._declared
            ):
                name = node.args[0].value
                findings.append(Finding(
                    code="MN001",
                    path=mod.rel,
                    line=node.lineno,
                    symbol=nearest_symbol(
                        node.lineno, node.end_lineno or node.lineno
                    ),
                    detail=name,
                    message=(
                        f"undeclared metric name {name!r}; declare() it "
                        "in emqx_tpu/broker/metrics.py"
                    ),
                ))
        return findings
