"""SS: snapshot/capture schema discipline.

Durable snapshots and pickled captures are decoded by a DIFFERENT
process version than the one that wrote them (restart, rolling upgrade,
warm standby). The registry (emqx_tpu/proto/registry.py) pins each
snapshot root's statically visible shape; this checker re-derives the
shape from the defining code and flags drift — the static twin of the
tier-B corpus replay, and the static catch for the PR 10 bug class
(a live device handle reaching `pickle` because `__getstate__` stopped
nulling it).

- SS001 — the shape the root actually emits (the string-keyed dict
  literals in a `schema` source, or the instance-field surface of a
  `class_state` source) no longer digests to the registered structure.
- SS002 — a registered source root that no longer exists (module or
  symbol rot): the registry points at nothing, so nothing is guarded.
- SS003 — a field the registration declares DROPPED (nulled/removed in
  `__getstate__` — meshes, device buffers) is no longer dropped. This
  is the unpicklable-mesh class caught without constructing a mesh.

Shape extraction is deliberately syntactic: every non-empty dict
literal whose keys are all string constants inside the source function
is one key group (comprehensions and computed keys are invisible and
intentionally excluded — the registry pins what can be pinned
statically; the corpus replay covers the rest).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Sequence

from emqx_tpu.proto.digest import class_state_digest, schema_digest
from tools.analysis.core import Checker, Finding, ParsedModule
from tools.analysis.checkers.wire_common import (
    Registration,
    class_fields,
    dict_key_groups,
    extract_registrations,
    find_def,
    getstate_drops,
    module_index,
)


class SnapshotSchemaChecker(Checker):
    name = "snapshot"
    codes = {
        "SS001": "snapshot root shape drifted from its registered schema",
        "SS002": "registered snapshot root no longer exists",
        "SS003": "declared-dropped field no longer dropped in __getstate__",
    }

    def __init__(self):
        self._regs: List[Registration] = []
        self._by_rel: Dict[str, ParsedModule] = {}

    def begin(self, modules: Sequence[ParsedModule]) -> None:
        self._regs = extract_registrations(modules)
        self._by_rel = module_index(modules)

    def finalize(self) -> Iterable[Finding]:
        for reg in self._regs:
            if reg.kind == "schema":
                yield from self._check_schema(reg)
            elif reg.kind == "class_state":
                yield from self._check_class_state(reg)

    def _rot(self, reg: Registration, what: str) -> Finding:
        return Finding(
            code="SS002",
            path=reg.mod.rel,
            line=reg.lineno,
            symbol="<module>",
            detail=reg.name,
            message=(
                f"snapshot format {reg.name!r}: registered root "
                f"{reg.source} {what}"
            ),
        )

    def _check_schema(self, reg: Registration) -> Iterable[Finding]:
        path, symbol, _frag = reg.source_parts()
        src_mod = self._by_rel.get(path)
        if src_mod is None:
            yield self._rot(reg, "points at a missing module")
            return
        func = find_def(src_mod, symbol)
        if func is None or not isinstance(
            func, (ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            yield self._rot(reg, "is not a function in the scanned tree")
            return
        groups = dict_key_groups(func)
        if not groups:
            yield self._rot(reg, "emits no statically visible dict shape")
            return
        code_digest = schema_digest(groups)
        if reg.digest is not None and code_digest != reg.digest:
            yield Finding(
                code="SS001",
                path=path,
                line=func.lineno,
                symbol=symbol,
                detail=reg.name,
                message=(
                    f"snapshot shape of {symbol} drifted from registered "
                    f"{reg.name!r}: registry={reg.digest} "
                    f"code={code_digest} — bump the version and "
                    "regenerate pins + corpus if intentional"
                ),
            )

    def _check_class_state(self, reg: Registration) -> Iterable[Finding]:
        path, symbol, _frag = reg.source_parts()
        src_mod = self._by_rel.get(path)
        if src_mod is None:
            yield self._rot(reg, "points at a missing module")
            return
        cls = find_def(src_mod, symbol)
        if not isinstance(cls, ast.ClassDef):
            yield self._rot(reg, "is not a class in the scanned tree")
            return
        declared_drops: tuple = ()
        if isinstance(reg.structure, (list, tuple)) and len(reg.structure) == 2:
            declared_drops = tuple(reg.structure[1])
        fields = class_fields(cls)
        code_digest = class_state_digest(fields, declared_drops)
        if reg.digest is not None and code_digest != reg.digest:
            yield Finding(
                code="SS001",
                path=path,
                line=cls.lineno,
                symbol=symbol,
                detail=reg.name,
                message=(
                    f"pickled surface of class {symbol} drifted from "
                    f"registered {reg.name!r}: registry={reg.digest} "
                    f"code={code_digest}"
                ),
            )
        actual_drops = set(getstate_drops(cls))
        for field in declared_drops:
            if field not in actual_drops:
                yield Finding(
                    code="SS003",
                    path=path,
                    line=cls.lineno,
                    symbol=symbol,
                    detail=f"{reg.name}:{field}",
                    message=(
                        f"{reg.name!r} declares field {field!r} dropped "
                        f"from pickles, but {symbol}.__getstate__ no "
                        "longer nulls/removes it (live-handle leak — the "
                        "unpicklable-mesh bug class)"
                    ),
                )
