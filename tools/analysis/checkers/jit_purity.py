"""JP: trace purity for code reachable from `jax.jit` / `shard_map`.

TrieJax-style kernel acceleration only pays off when the route-step
kernels stay trace-pure: an `.item()` forces a device sync inside the
step, wall-clock/RNG reads bake one trace's value into every later call
of the compiled program, global mutation silently runs once at trace
time, and branching on a tracer raises (or worse, retraces per batch).

The checker finds jit roots — functions decorated with `@jax.jit` /
`@partial(jax.jit, ...)`, or passed by name to `jax.jit(...)` /
`shard_map(...)` — and follows the call graph across modules (import-
alias aware), including function names passed as arguments inside
reachable code (`lax.scan(body, ...)` bodies). Every reachable function
body is then screened:

  JP001  .item()/.tolist()/.block_until_ready(): host sync inside trace
  JP002  float()/int()/bool() over a jnp/jax expression: tracer cast
  JP003  global mutation (global stmt, or writes to module-level state)
  JP004  wall-clock / RNG read (time.*, datetime.now, random, os.urandom)
  JP005  if/while/assert on a jnp/jax expression: tracer truthiness
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.analysis.core import (
    Checker,
    Finding,
    ParsedModule,
    dotted_name,
    import_aliases,
    resolve_call_name,
)

JIT_WRAPPERS = ("jax.jit", "jit", "jax.experimental.shard_map.shard_map",
                "jax.shard_map", "shard_map")
HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
WALLCLOCK = {
    "time.time", "time.monotonic", "time.perf_counter", "time.time_ns",
    "time.process_time", "datetime.datetime.now", "datetime.datetime.utcnow",
    "os.urandom",
}
WALLCLOCK_PREFIXES = ("random.", "numpy.random.")
MUTATORS = {"append", "add", "update", "extend", "setdefault", "pop",
            "clear", "insert", "remove", "popitem"}

_MESSAGES = {
    "JP001": "host sync inside a jitted function",
    "JP002": "Python scalar cast of a traced jnp/jax expression",
    "JP003": "global state mutation inside a jitted function (runs once "
             "at trace time, not per call)",
    "JP004": "wall-clock/RNG read inside a jitted function (frozen at "
             "trace time)",
    "JP005": "truthiness branch on a jnp/jax expression (tracer boolean)",
}


def _module_dotted(rel: str) -> str:
    dn = rel[:-3].replace("/", ".")
    if dn.endswith(".__init__"):
        dn = dn[: -len(".__init__")]
    return dn


class _FnInfo:
    __slots__ = ("mod", "node", "symbol")

    def __init__(self, mod: ParsedModule, node, symbol: str):
        self.mod = mod
        self.node = node
        self.symbol = symbol


class JitPurityChecker(Checker):
    name = "jit"
    codes = dict(_MESSAGES)

    def begin(self, modules: Sequence[ParsedModule]) -> None:
        # function tables + aliases + module globals for every module
        self._aliases: Dict[str, Dict[str, str]] = {}
        self._funcs: Dict[Tuple[str, str], List[_FnInfo]] = {}
        self._globals: Dict[str, Set[str]] = {}
        self._mods: Dict[str, ParsedModule] = {}
        roots: List[Tuple[str, str]] = []

        for mod in modules:
            dn = _module_dotted(mod.rel)
            self._mods[dn] = mod
            aliases = import_aliases(mod.tree)
            self._aliases[dn] = aliases
            g: Set[str] = set()
            for stmt in mod.tree.body:
                targets = []
                if isinstance(stmt, ast.Assign):
                    targets = stmt.targets
                elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
                    targets = [stmt.target]
                for t in targets:
                    if isinstance(t, ast.Name):
                        g.add(t.id)
            self._globals[dn] = g

            syms: Dict[ast.AST, str] = {}

            def collect(node, prefix):
                for child in ast.iter_child_nodes(node):
                    if isinstance(
                        child, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        sym = (
                            f"{prefix}.{child.name}" if prefix
                            else child.name
                        )
                        syms[child] = sym
                        self._funcs.setdefault(
                            (dn, child.name), []
                        ).append(_FnInfo(mod, child, sym))
                        collect(child, sym)
                    elif isinstance(child, ast.ClassDef):
                        collect(
                            child,
                            f"{prefix}.{child.name}" if prefix
                            else child.name,
                        )
                    else:
                        collect(child, prefix)

            collect(mod.tree, "")
            roots.extend(self._find_roots(dn, mod, aliases))

        self._reachable = self._traverse(roots)

    # -- root discovery ----------------------------------------------------
    def _find_roots(self, dn, mod, aliases) -> List[Tuple[str, str]]:
        roots: List[Tuple[str, str]] = []

        def is_jit_wrapper(node) -> bool:
            name = resolve_call_name(node, aliases)
            # `partial(jax.jit, ...)` decorators
            if name in ("functools.partial", "partial"):
                return False
            return name in JIT_WRAPPERS

        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    name = resolve_call_name(target, aliases)
                    if name in JIT_WRAPPERS:
                        roots.append((dn, node.name))
                    elif (
                        isinstance(dec, ast.Call)
                        and name in ("functools.partial", "partial")
                        and dec.args
                        and resolve_call_name(dec.args[0], aliases)
                        in JIT_WRAPPERS
                    ):
                        roots.append((dn, node.name))
            elif isinstance(node, ast.Call) and is_jit_wrapper(node.func):
                for arg in node.args[:1]:
                    roots.extend(self._ref_targets(dn, arg, aliases))
        return roots

    def _ref_targets(self, dn, node, aliases) -> List[Tuple[str, str]]:
        """Resolve a function *reference* (not call) to table keys."""
        if isinstance(node, ast.Name):
            canon = aliases.get(node.id)
            if canon and "." in canon:
                mod_part, _, fn_part = canon.rpartition(".")
                return [(mod_part, fn_part), (dn, node.id)]
            return [(dn, node.id)]
        dn_full = dotted_name(node)
        if dn_full:
            head, _, rest = dn_full.partition(".")
            canon = aliases.get(head, head)
            full = f"{canon}.{rest}" if rest else canon
            mod_part, _, fn_part = full.rpartition(".")
            return [(mod_part, fn_part)]
        return []

    # -- reachability ------------------------------------------------------
    def _traverse(self, roots) -> List[_FnInfo]:
        seen: Set[Tuple[str, str]] = set()
        reachable: List[_FnInfo] = []
        work = [r for r in roots]
        while work:
            key = work.pop()
            if key in seen:
                continue
            seen.add(key)
            for info in self._funcs.get(key, []):
                reachable.append(info)
                dn = _module_dotted(info.mod.rel)
                work.extend(self._edges(dn, info.node))
        return reachable

    def _edges(self, dn, fn) -> List[Tuple[str, str]]:
        aliases = self._aliases[dn]
        out: List[Tuple[str, str]] = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            # direct calls
            out.extend(self._ref_targets(dn, node.func, aliases))
            # function names passed as arguments (lax.scan/cond bodies,
            # shard_map closures): follow them too
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, (ast.Name, ast.Attribute)):
                    out.extend(self._ref_targets(dn, arg, aliases))
        return out

    # -- screening ---------------------------------------------------------
    def finalize(self) -> Iterable[Finding]:
        findings: List[Finding] = []
        flagged: Set[Tuple[str, int, str]] = set()
        for info in self._reachable:
            dn = _module_dotted(info.mod.rel)
            for f in self._screen(dn, info):
                key = (f.path, f.line, f.code)
                if key not in flagged:
                    flagged.add(key)
                    findings.append(f)
        return findings

    def _screen(self, dn, info: _FnInfo) -> Iterable[Finding]:
        mod, fn = info.mod, info.node
        aliases = self._aliases[dn]
        mod_globals = self._globals[dn]
        findings: List[Finding] = []

        def emit(code, node, detail):
            findings.append(Finding(
                code=code,
                path=mod.rel,
                line=node.lineno,
                symbol=info.symbol,
                detail=detail,
                message=f"{detail}: {_MESSAGES[code]}",
            ))

        def has_jax_call(node) -> bool:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    name = resolve_call_name(sub.func, aliases)
                    if name and (
                        name.startswith("jax.")
                        or name.startswith("jnp.")
                        or name.startswith("jax.numpy")
                    ):
                        return True
            return False

        def walk(node):
            for child in ast.iter_child_nodes(node):
                # nested defs are separate reachable entries
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
                ):
                    continue
                self._screen_node(
                    child, aliases, mod_globals, emit, has_jax_call
                )
                walk(child)

        walk(fn)
        return findings

    def _screen_node(self, node, aliases, mod_globals, emit, has_jax_call):
        if isinstance(node, ast.Global):
            for n in node.names:
                emit("JP003", node, f"global {n}")
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                base = t
                while isinstance(base, (ast.Subscript, ast.Attribute)):
                    base = base.value
                if isinstance(base, ast.Name) and base.id in mod_globals \
                        and base is not t:
                    emit("JP003", node, base.id)
        elif isinstance(node, ast.Call):
            name = resolve_call_name(node.func, aliases)
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in HOST_SYNC_METHODS
            ):
                emit("JP001", node, f".{node.func.attr}()")
            elif name in WALLCLOCK or (
                name is not None
                and name.startswith(WALLCLOCK_PREFIXES)
            ):
                emit("JP004", node, name)
            elif (
                isinstance(node.func, ast.Name)
                and node.func.id in ("float", "int", "bool")
                and node.args
                and has_jax_call(node.args[0])
            ):
                emit("JP002", node, f"{node.func.id}(...)")
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in MUTATORS
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in mod_globals
            ):
                emit("JP003", node,
                     f"{node.func.value.id}.{node.func.attr}")
        elif isinstance(node, (ast.If, ast.While)):
            if has_jax_call(node.test):
                emit("JP005", node.test, "if/while")
        elif isinstance(node, ast.Assert):
            if has_jax_call(node.test):
                emit("JP005", node.test, "assert")
        elif isinstance(node, ast.IfExp):
            if has_jax_call(node.test):
                emit("JP005", node.test, "ifexp")
