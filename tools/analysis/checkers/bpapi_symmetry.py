"""BP: sender/receiver symmetry for cluster protocols.

The reference broker freezes every inter-node API in a versioned BPAPI
module and CI fails when a call site and a handler disagree. Here the
frozen tables live in emqx_tpu/proto/registry.py (`kind="proto"` for
the rpc method tables, `kind="tags"` with a `#pos0`/`#key=K` source
fragment for the tuple-discriminator families), and this checker does
the static cross-check:

- BP001 — an rpc send site (`*.rpc.call/cast/multicall(peer, api,
  method, ...)`, `rpc_call(peer, api, method, ...)`) whose (api, method)
  pair is in NO registered proto version: the receiver will raise at
  dispatch, but only at runtime, on a peer.
- BP002 — a registered (api, method) that no local code ever sends.
  Either dead protocol surface or a receiver-only method; the latter is
  declared in `BPAPI_SERVE_ONLY` next to the registry table, so the
  exemption is versioned with the contract instead of living in the
  checker.
- BP003 — the in-code proto tables (`rpc.registry.register(api, v,
  {method: handler})`) drifted from the registry declaration: the
  frozen table and the served table must spell the same methods.
- BP004 — tag-family asymmetry: a tag sent with no handler compare, a
  registered tag nobody sends, or a tuple sent at a bus boundary whose
  discriminator is registered nowhere. A tag added on one side only is
  exactly the rolling-upgrade wreck BPAPI exists to prevent.

Method names that reach the rpc site through a variable propagate one
level through the enclosing function's parameter (the `_replicate(
"add_route")` / `_shared_cast("join")` indirections), so the real
sender set is visible without executing anything.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from emqx_tpu.proto.digest import proto_digest
from tools.analysis.core import Checker, Finding, ParsedModule, dotted_name
from tools.analysis.checkers.wire_common import (
    Registration,
    extract_registrations,
    module_index,
    resolve_literal,
    toplevel_assigns,
)

RPC_METHODS = frozenset({"call", "cast", "multicall"})

# call names that put a tuple on the cluster wire
TUPLE_BOUNDARY = frozenset({
    "send", "sendall", "cast", "enqueue", "send_frame", "_send_frame",
})


def _call_name(node: ast.Call) -> Optional[str]:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class _TagFamily:
    """One registered tuple-discriminator family."""

    def __init__(self, reg: Registration, handler_rel: str, frag: str):
        self.reg = reg
        self.handler_rel = handler_rel
        self.key: Optional[str] = None  # None => position-0 family
        if frag.startswith("key="):
            self.key = frag[4:]
        self.tags: Set[str] = set()
        if isinstance(reg.structure, dict):
            self.tags = {str(k) for k in reg.structure.values()}
        self.sent: Set[str] = set()
        self.handled: Set[str] = set()


class BpapiSymmetryChecker(Checker):
    name = "bpapi"
    codes = {
        "BP001": "rpc send site targets an unregistered (api, method)",
        "BP002": "registered rpc method has no sender (and is not "
                 "declared serve-only)",
        "BP003": "in-code proto table drifted from the registry BPAPI",
        "BP004": "cluster tag family sender/handler asymmetry",
    }

    def __init__(self):
        self._modules: Sequence[ParsedModule] = ()
        self._by_rel: Dict[str, ParsedModule] = {}
        # every kind="proto" registration with its own table and its
        # module's BPAPI_SERVE_ONLY (fixture trees carry several)
        self._protos: List[
            Tuple[Registration, Dict[str, Dict[int, Tuple[str, ...]]],
                  Set[Tuple[str, str]]]
        ] = []
        self._families: List[_TagFamily] = []
        # sent (api, method) -> first (mod, line) seen
        self._sent: Dict[Tuple[str, str], Tuple[ParsedModule, int]] = {}
        # in-code rpc.registry.register tables: (api, v) -> (methods, site)
        self._code_tables: Dict[
            Tuple[str, int], Tuple[Set[str], ParsedModule, int]
        ] = {}
        # pending one-level propagations: (func_name, param_pos, api, site)
        self._pending: List[Tuple[str, int, str, ParsedModule, int]] = []

    # -- begin: load registry declarations --------------------------------
    def begin(self, modules: Sequence[ParsedModule]) -> None:
        self.__init__()
        self._modules = modules
        self._by_rel = module_index(modules)
        for reg in extract_registrations(modules):
            if reg.kind == "proto" and isinstance(reg.structure, dict):
                bpapi = {
                    str(api): {
                        int(v): tuple(methods)
                        for v, methods in vers.items()
                    }
                    for api, vers in reg.structure.items()
                }
                serve_only: Set[Tuple[str, str]] = set()
                only = toplevel_assigns(reg.mod).get("BPAPI_SERVE_ONLY")
                if only is not None:
                    val = resolve_literal(reg.mod, only)
                    if isinstance(val, (set, frozenset, list, tuple)):
                        serve_only = {
                            tuple(t) for t in val
                            if isinstance(t, (list, tuple)) and len(t) == 2
                        }
                self._protos.append((reg, bpapi, serve_only))
            elif reg.kind == "tags":
                path, _symbol, frag = reg.source_parts()
                if frag == "pos0" or frag.startswith("key="):
                    self._families.append(_TagFamily(reg, path, frag))
        for mod in modules:
            self._collect_rpc_sites(mod)
            self._collect_code_tables(mod)
            self._collect_tuples(mod)
        self._propagate()
        for fam in self._families:
            self._collect_handlers(fam)

    # -- rpc send sites ----------------------------------------------------
    def _collect_rpc_sites(self, mod: ParsedModule) -> None:
        funcs = {
            n.name: n
            for n in ast.walk(mod.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(mod.tree):
            for child in ast.iter_child_nodes(parent):
                parents[child] = parent

        def enclosing_func(node: ast.AST):
            cur = parents.get(node)
            while cur is not None:
                if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    return cur
                cur = parents.get(cur)
            return None

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            is_rpc = False
            if isinstance(fn, ast.Attribute) and fn.attr in RPC_METHODS:
                recv = dotted_name(fn.value) or ""
                is_rpc = "rpc" in recv.split(".")
            elif isinstance(fn, ast.Name) and "rpc" in fn.id:
                is_rpc = True
            if not is_rpc:
                continue
            # api = first positional str const; method = the next arg
            api = None
            method_node = None
            for i, arg in enumerate(node.args):
                s = _str_const(arg)
                if s is not None:
                    api = s
                    if i + 1 < len(node.args):
                        method_node = node.args[i + 1]
                    break
            if api is None or method_node is None:
                continue
            method = _str_const(method_node)
            if method is not None:
                self._sent.setdefault((api, method), (mod, node.lineno))
                continue
            if isinstance(method_node, ast.Name):
                # the send often sits in a worker closure (`def one(p)`)
                # with the method a free variable of the OUTER
                # indirection (`_replicate`, `_shared_cast`): walk out
                # until a function binds it as a parameter
                outer = enclosing_func(node)
                while outer is not None:
                    params = [a.arg for a in outer.args.args]
                    if method_node.id in params:
                        self._pending.append((
                            outer.name, params.index(method_node.id),
                            api, mod, node.lineno,
                        ))
                        break
                    outer = enclosing_func(outer)

    def _propagate(self) -> None:
        """One-level constant propagation: str consts at the matching
        positional index of call sites of the indirection function."""
        for fname, ppos, api, site_mod, site_line in self._pending:
            for mod in self._modules:
                for node in ast.walk(mod.tree):
                    if not isinstance(node, ast.Call):
                        continue
                    fn = node.func
                    if isinstance(fn, ast.Attribute) and fn.attr == fname:
                        argpos = ppos - 1  # self-call: drop the self param
                    elif isinstance(fn, ast.Name) and fn.id == fname:
                        argpos = ppos
                    else:
                        continue
                    if 0 <= argpos < len(node.args):
                        m = _str_const(node.args[argpos])
                        if m is not None:
                            self._sent.setdefault(
                                (api, m), (mod, node.lineno)
                            )

    # -- in-code proto tables ----------------------------------------------
    def _collect_code_tables(self, mod: ParsedModule) -> None:
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call) and len(node.args) >= 3):
                continue
            fn = node.func
            if not (isinstance(fn, ast.Attribute) and fn.attr == "register"):
                continue
            recv = dotted_name(fn.value) or ""
            if "registry" not in recv.split("."):
                continue  # metric/fault registries etc. are not protos
            api = _str_const(node.args[0])
            ver = node.args[1]
            table = node.args[2]
            if (
                api is None
                or not isinstance(ver, ast.Constant)
                or not isinstance(ver.value, int)
                or not isinstance(table, ast.Dict)
            ):
                continue
            methods = set()
            ok = True
            for k in table.keys:
                s = _str_const(k) if k is not None else None
                if s is None:
                    ok = False
                    break
                methods.add(s)
            if ok:
                self._code_tables[(api, ver.value)] = (
                    methods, mod, node.lineno
                )

    # -- tag families -------------------------------------------------------
    def _tuple_head(self, t: ast.Tuple) -> Optional[str]:
        if t.elts:
            return _str_const(t.elts[0])
        return None

    def _collect_tuples(self, mod: ParsedModule) -> None:
        pos0_universe = set()
        keys = {}
        for fam in self._families:
            if fam.key is None:
                pos0_universe |= fam.tags
            else:
                keys[fam.key] = fam
        # modules in scope for the sent-unregistered check: family
        # handler modules + modules that demonstrably speak a family
        in_scope = any(fam.handler_rel == mod.rel for fam in self._families)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Tuple):
                head = self._tuple_head(node)
                if head is None:
                    continue
                # sends (for the no-sender direction): any tuple literal
                # counts — replies are built into a variable before the
                # send call, so boundary-arg position can't be required
                for fam in self._families:
                    if fam.key is None:
                        if head in fam.tags:
                            fam.sent.add(head)
                            in_scope = True
                    elif head == fam.key and len(node.elts) > 1:
                        tag = _str_const(node.elts[1])
                        if tag is not None:
                            fam.sent.add(tag)
                            in_scope = True
        if not (in_scope and self._families):
            return
        # sent-unregistered: tuples handed DIRECTLY to a wire boundary
        # in a module that speaks the protocol
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if _call_name(node) not in TUPLE_BOUNDARY:
                continue
            for arg in node.args:
                if not isinstance(arg, ast.Tuple):
                    continue
                head = self._tuple_head(arg)
                if head is None or head in keys:
                    # unregistered tags UNDER a key are caught at the
                    # family level (fam.sent - fam.tags)
                    continue
                if pos0_universe and head not in pos0_universe:
                    self._unregistered_head(head, mod, arg.lineno)

    def _collect_handlers(self, fam: _TagFamily) -> None:
        mod = self._by_rel.get(fam.handler_rel)
        if mod is None:
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Compare):
                continue
            consts: List[str] = []
            for side in [node.left, *node.comparators]:
                s = _str_const(side)
                if s is not None:
                    consts.append(s)
                elif isinstance(side, (ast.Tuple, ast.List, ast.Set)):
                    for e in side.elts:
                        es = _str_const(e)
                        if es is not None:
                            consts.append(es)
            for s in consts:
                if s in fam.tags:
                    fam.handled.add(s)

    def _unregistered_head(self, head, mod, line) -> None:
        self._deferred_findings().append(Finding(
            code="BP004",
            path=mod.rel,
            line=line,
            symbol="<module>",
            detail=f"head:{head}:sent-unregistered",
            message=(
                f"tuple with discriminator {head!r} reaches a wire "
                "boundary but no registered tag family covers it"
            ),
        ))

    def _deferred_findings(self) -> List[Finding]:
        if not hasattr(self, "_deferred_list"):
            self._deferred_list: List[Finding] = []
        return self._deferred_list

    # -- finalize -----------------------------------------------------------
    def finalize(self) -> Iterable[Finding]:
        yield from self._deferred_findings()
        if self._protos:
            yield from self._check_bpapi()
        for fam in self._families:
            yield from self._check_family(fam)

    def _check_bpapi(self) -> Iterable[Finding]:
        registered_pairs = {
            (api, m)
            for _reg, bpapi, _so in self._protos
            for api, vers in bpapi.items()
            for methods in vers.values()
            for m in methods
        }
        # BP001: sends with no registration (unknown api included)
        for (api, method), (mod, line) in sorted(self._sent.items()):
            if (api, method) not in registered_pairs:
                yield Finding(
                    code="BP001",
                    path=mod.rel,
                    line=line,
                    symbol="<module>",
                    detail=f"{api}.{method}",
                    message=(
                        f"rpc send targets {api}.{method} but no "
                        f"registered {api!r} proto version declares it"
                    ),
                )
        # BP002: registered methods nobody sends
        sent_pairs = set(self._sent)
        for reg, bpapi, serve_only in self._protos:
            for api, vers in sorted(bpapi.items()):
                union = {m for methods in vers.values() for m in methods}
                for method in sorted(union):
                    if (api, method) in sent_pairs:
                        continue
                    if (api, method) in serve_only:
                        continue
                    yield Finding(
                        code="BP002",
                        path=reg.mod.rel,
                        line=reg.lineno,
                        symbol="<module>",
                        detail=f"{api}.{method}",
                        message=(
                            f"registered proto method {api}.{method} has "
                            "no local send site — dead surface, or add it "
                            "to BPAPI_SERVE_ONLY with a justification"
                        ),
                    )
        # BP003: in-code tables vs registry tables (only when the tree
        # actually serves protos — fixtures without a node are exempt)
        if not self._code_tables:
            return
        declared = {}
        declaring_reg = {}
        for reg, bpapi, _so in self._protos:
            for api, vers in bpapi.items():
                for v, methods in vers.items():
                    declared[(api, v)] = set(methods)
                    declaring_reg[(api, v)] = (reg, bpapi)
        for key in sorted(set(declared) | set(self._code_tables)):
            api, v = key
            if key not in self._code_tables:
                reg, _bpapi = declaring_reg[key]
                yield Finding(
                    code="BP003",
                    path=reg.mod.rel,
                    line=reg.lineno,
                    symbol="<module>",
                    detail=f"{api}.v{v}:unserved",
                    message=(
                        f"registry declares {api} v{v} but no in-code "
                        "proto table registers it"
                    ),
                )
                continue
            methods, mod, line = self._code_tables[key]
            if key not in declared:
                yield Finding(
                    code="BP003",
                    path=mod.rel,
                    line=line,
                    symbol="<module>",
                    detail=f"{api}.v{v}:undeclared",
                    message=(
                        f"in-code proto table registers {api} v{v} but "
                        "the registry BPAPI does not declare that version"
                    ),
                )
            elif methods != declared[key]:
                missing = sorted(declared[key] - methods)
                extra = sorted(methods - declared[key])
                _reg, bpapi = declaring_reg[key]
                yield Finding(
                    code="BP003",
                    path=mod.rel,
                    line=line,
                    symbol="<module>",
                    detail=f"{api}.v{v}",
                    message=(
                        f"proto table {api} v{v} drifted from the "
                        f"registry: missing={missing} extra={extra} "
                        f"(registry digest {proto_digest(bpapi)})"
                    ),
                )

    def _check_family(self, fam: _TagFamily) -> Iterable[Finding]:
        reg = fam.reg
        for tag in sorted(fam.sent - fam.tags):
            # universe-filtered collection can't produce these for pos0
            # (filtered on membership); key= families can
            yield Finding(
                code="BP004",
                path=reg.mod.rel,
                line=reg.lineno,
                symbol="<module>",
                detail=f"{reg.name}:{tag}:sent-unregistered",
                message=(
                    f"tag {tag!r} is sent but not registered in "
                    f"{reg.name!r}"
                ),
            )
        for tag in sorted(fam.tags - fam.sent):
            yield Finding(
                code="BP004",
                path=reg.mod.rel,
                line=reg.lineno,
                symbol="<module>",
                detail=f"{reg.name}:{tag}:no-sender",
                message=(
                    f"registered tag {tag!r} of {reg.name!r} has no "
                    "send site in the tree"
                ),
            )
        for tag in sorted(fam.tags - fam.handled):
            yield Finding(
                code="BP004",
                path=reg.mod.rel,
                line=reg.lineno,
                symbol="<module>",
                detail=f"{reg.name}:{tag}:no-handler",
                message=(
                    f"registered tag {tag!r} of {reg.name!r} is never "
                    f"compared against in its handler module "
                    f"{fam.handler_rel} — a sent op nobody dispatches"
                ),
            )
