"""Shared extraction for the wire-contract checkers (WF/SS/BP).

All three checkers anchor on `register(...)` calls in the scanned tree
(the real registry is emqx_tpu/proto/registry.py; fixture trees carry
their own mini-registries) and on the golden digest pins under
tests/fixtures/analysis/wire/digests.json. Everything here is pure AST
plus `emqx_tpu.proto.digest` — a stdlib-only leaf module, so tier A
stays import-clean of broker/runtime code.
"""

from __future__ import annotations

import ast
import json
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from emqx_tpu.proto.digest import digest_for, parse_pin
from tools.analysis.core import ParsedModule

REPO_ROOT = Path(__file__).resolve().parents[3]
DEFAULT_PINS = (
    REPO_ROOT / "tests" / "fixtures" / "analysis" / "wire" / "digests.json"
)

FORMAT_KINDS = (
    "dtype", "struct", "tags", "schema", "class_state", "proto",
)


@dataclass
class Registration:
    """One AST-extracted `register(name, version, kind, structure,
    source, ...)` call."""

    name: str
    version: int
    kind: str
    structure: object        # literal-eval'd; None when unresolvable
    source: str              # "path.py[:SYMBOL][#fragment]"
    mod: ParsedModule
    lineno: int

    @property
    def digest(self) -> Optional[str]:
        if self.structure is None:
            return None
        try:
            return digest_for(self.kind, self.structure)
        except Exception:
            return None

    def source_parts(self) -> Tuple[str, str, str]:
        """-> (path, symbol, fragment)."""
        src = self.source
        frag = ""
        if "#" in src:
            src, frag = src.split("#", 1)
        path, _, symbol = src.partition(":")
        return path, symbol, frag


def toplevel_assigns(mod: ParsedModule) -> Dict[str, ast.AST]:
    """Module-level `NAME = <value>` nodes (last assignment wins)."""
    out: Dict[str, ast.AST] = {}
    for node in mod.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = node.value
    return out


def resolve_literal(mod: ParsedModule, node: ast.AST, _depth: int = 0):
    """literal_eval with one level of module-constant indirection:
    `register(..., FIELDS, ...)` where FIELDS is a module-level literal
    assignment resolves to its value. Returns None when not a literal."""
    if isinstance(node, ast.Name) and _depth < 2:
        target = toplevel_assigns(mod).get(node.id)
        if target is None:
            return None
        return resolve_literal(mod, target, _depth + 1)
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None


def extract_registrations(
    modules: Sequence[ParsedModule],
) -> List[Registration]:
    """Every wire-format `register(...)` call in the tree.

    Matched by shape, not import provenance: func named `register` with
    (str name, int version, str kind in FORMAT_KINDS, structure, str
    source) positional args — BPAPI `registry.register("api", 1, {...})`
    calls never match (their third arg is a dict, not a kind string)."""
    regs: List[Registration] = []
    for mod in modules:
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call) and len(node.args) >= 5):
                continue
            fn = node.func
            fname = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None
            )
            if fname != "register":
                continue
            a = node.args
            if not (
                isinstance(a[0], ast.Constant)
                and isinstance(a[0].value, str)
                and isinstance(a[1], ast.Constant)
                and isinstance(a[1].value, int)
                and isinstance(a[2], ast.Constant)
                and a[2].value in FORMAT_KINDS
                and isinstance(a[4], ast.Constant)
                and isinstance(a[4].value, str)
            ):
                continue
            regs.append(Registration(
                name=a[0].value,
                version=a[1].value,
                kind=a[2].value,
                structure=resolve_literal(mod, a[3]),
                source=a[4].value,
                mod=mod,
                lineno=node.lineno,
            ))
    return regs


def load_pins(path: Optional[Path] = None) -> Dict[str, Tuple[int, str]]:
    """Golden pins {name: (version, digest)}; {} when absent."""
    p = path or DEFAULT_PINS
    if not p.exists():
        return {}
    try:
        return parse_pin(json.loads(p.read_text()))
    except (ValueError, KeyError):
        return {}


def module_index(
    modules: Sequence[ParsedModule],
) -> Dict[str, ParsedModule]:
    return {m.rel: m for m in modules}


def find_def(
    mod: ParsedModule, symbol: str
) -> Optional[ast.AST]:
    """Resolve 'Func' / 'Class' / 'Class.method' to its def node."""
    want = symbol.split(".")
    scope: List[ast.AST] = list(mod.tree.body)
    node = None
    for part in want:
        node = None
        for child in scope:
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ) and child.name == part:
                node = child
                break
        if node is None:
            return None
        scope = list(getattr(node, "body", []))
    return node


def dict_key_groups(func: ast.AST) -> List[Tuple[str, ...]]:
    """Key tuples of every non-empty all-string-keyed dict literal in a
    function body — the statically visible snapshot shapes."""
    groups: List[Tuple[str, ...]] = []
    for node in ast.walk(func):
        if not (isinstance(node, ast.Dict) and node.keys):
            continue
        keys = []
        ok = True
        for k in node.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                keys.append(k.value)
            else:
                ok = False
                break
        if ok and keys:
            groups.append(tuple(keys))
    return groups


def class_fields(cls: ast.ClassDef) -> List[str]:
    """__getstate__-visible instance surface: dataclass-style annotated
    class attrs + `self.X = ...` targets in __init__ (ordered, deduped).
    """
    out: List[str] = []
    seen = set()

    def add(name: str) -> None:
        if name not in seen:
            seen.add(name)
            out.append(name)

    for node in cls.body:
        if isinstance(node, ast.AnnAssign) and isinstance(
            node.target, ast.Name
        ):
            add(node.target.id)
    init = find_def_in(cls, "__init__")
    if init is not None:
        for node in ast.walk(init):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):
                targets = [node.target]
            for t in targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    add(t.attr)
    return out


def find_def_in(cls: ast.ClassDef, name: str) -> Optional[ast.AST]:
    for node in cls.body:
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)
        ) and node.name == name:
            return node
    return None


def getstate_drops(cls: ast.ClassDef) -> List[str]:
    """Fields the class's __getstate__ nulls or removes from the pickled
    dict: `d["x"] = None`, `d.pop("x", ...)`, `del d["x"]`."""
    gs = find_def_in(cls, "__getstate__")
    if gs is None:
        return []
    drops: List[str] = []
    for node in ast.walk(gs):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (
                    isinstance(t, ast.Subscript)
                    and isinstance(t.slice, ast.Constant)
                    and isinstance(t.slice.value, str)
                    and isinstance(node.value, ast.Constant)
                    and node.value.value is None
                ):
                    drops.append(t.slice.value)
        elif isinstance(node, ast.Call):
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr == "pop"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                drops.append(node.args[0].value)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if (
                    isinstance(t, ast.Subscript)
                    and isinstance(t.slice, ast.Constant)
                    and isinstance(t.slice.value, str)
                ):
                    drops.append(t.slice.value)
    return drops


def prefix_constants(
    mod: ParsedModule, prefix: str
) -> Dict[str, object]:
    """Module-level `<PREFIX><NAME> = <int|str>` constant groups (frame
    type bytes, kv namespace names)."""
    out: Dict[str, object] = {}
    for name, value in toplevel_assigns(mod).items():
        if not name.startswith(prefix):
            continue
        if isinstance(value, ast.Constant) and isinstance(
            value.value, (int, str)
        ) and not isinstance(value.value, bool):
            out[name] = value.value
    return out
