"""FT: fault-injection + degradation contract lint.

Two registries must not drift:

- **FT001 — site/schema lockstep.** Every fault site registered in the
  injector (`SITES = (...)` in a module named ``faults.py``) must appear
  in the config schema's literal site list (`FAULT_SITES = frozenset({...})`
  in a module named ``schema.py``) and vice versa — a site the injector
  knows but config validation rejects (or a schema ghost the injector
  never fires) surfaces at lint time, not in a midnight soak.

- **FT002 — degrade/faults series declaration.** Every ``degrade.*`` /
  ``faults.*`` metric series referenced statically — as the first arg of
  a metric call (`inc`/`observe`/`observe_many`/`gauge_set`) or as any
  ``*_series=`` keyword (the breaker constructors take their series
  names this way precisely so this checker can see them) — must be
  `declare()`d in the metric-kind registry. The MN checker already
  guards plain call sites; FT002 additionally covers the series handed
  to breakers, which MN's call-site scan cannot reach.

Both checks are cross-module (`begin` collects, `finalize` reports) and
no-op gracefully when the tree has no faults/schema modules (fixture
subsets, third-party scans).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Sequence, Set, Tuple

from tools.analysis.checkers.metric_names import declared_names
from tools.analysis.core import Checker, Finding, ParsedModule

# a plausible series/site literal: dotted lowercase words. Anchored on
# the WHOLE string so prose in docstrings never matches.
_SERIES_RE = re.compile(r"^(degrade|faults)\.[a-z0-9_]+(\.[a-z0-9_]+)*$")

_METRIC_METHODS = ("inc", "observe", "observe_many", "gauge_set")


def _const_str_elts(node: ast.AST) -> List[str]:
    """String constants inside a tuple/list/set/frozenset(...) literal."""
    if isinstance(node, ast.Call) and node.args:
        # frozenset({...}) / tuple([...]) wrappers
        return _const_str_elts(node.args[0])
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return [
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        ]
    return []


def _toplevel_assign(mod: ParsedModule, name: str):
    """(lineno, value-node) of a module-level `NAME = ...`, else None."""
    for node in mod.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    return node.lineno, node.value
    return None


class FaultContractChecker(Checker):
    name = "fault"
    codes = {
        "FT001": "fault site registry and config schema site list drift",
        "FT002": "degrade.*/faults.* series referenced but not declared "
                 "in the metric-kind registry",
    }

    def begin(self, modules: Sequence[ParsedModule]) -> None:
        self._declared: Set[str] = declared_names(modules)
        # (site, mod, lineno) from SITES in any faults.py
        self._sites: List[Tuple[str, ParsedModule, int]] = []
        # (site, mod, lineno) from FAULT_SITES in any schema.py
        self._schema_sites: List[Tuple[str, ParsedModule, int]] = []
        # series -> first (mod, lineno, context) reference
        self._series: Dict[str, Tuple[ParsedModule, int, str]] = {}
        for mod in modules:
            base = mod.rel.rsplit("/", 1)[-1]
            if base == "faults.py":
                got = _toplevel_assign(mod, "SITES")
                if got is not None:
                    line, val = got
                    for s in _const_str_elts(val):
                        self._sites.append((s, mod, line))
            if base == "schema.py":
                got = _toplevel_assign(mod, "FAULT_SITES")
                if got is not None:
                    line, val = got
                    for s in _const_str_elts(val):
                        self._schema_sites.append((s, mod, line))
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METRIC_METHODS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and _SERIES_RE.match(node.args[0].value)
                ):
                    self._series.setdefault(
                        node.args[0].value,
                        (mod, node.lineno, node.func.attr),
                    )
                for kw in node.keywords:
                    if (
                        kw.arg
                        and kw.arg.endswith("_series")
                        and isinstance(kw.value, ast.Constant)
                        and isinstance(kw.value.value, str)
                        and _SERIES_RE.match(kw.value.value)
                    ):
                        self._series.setdefault(
                            kw.value.value, (mod, node.lineno, kw.arg)
                        )

    def check(self, mod: ParsedModule) -> Iterable[Finding]:
        return ()

    def finalize(self) -> Iterable[Finding]:
        findings: List[Finding] = []
        # FT001 only when BOTH registries exist in the scanned tree — a
        # fixture subset or foreign tree has nothing to keep in lockstep
        if self._sites and self._schema_sites:
            schema_set = {s for s, _, _ in self._schema_sites}
            site_set = {s for s, _, _ in self._sites}
            for s, mod, line in self._sites:
                if s not in schema_set:
                    findings.append(Finding(
                        code="FT001",
                        path=mod.rel,
                        line=line,
                        symbol="SITES",
                        detail=s,
                        message=(
                            f"fault site {s!r} registered in the injector "
                            "but missing from config schema FAULT_SITES — "
                            "config can never arm it"
                        ),
                    ))
            for s, mod, line in self._schema_sites:
                if s not in site_set:
                    findings.append(Finding(
                        code="FT001",
                        path=mod.rel,
                        line=line,
                        symbol="FAULT_SITES",
                        detail=s,
                        message=(
                            f"schema fault site {s!r} has no registered "
                            "injector site — a rule naming it never fires"
                        ),
                    ))
        for series, (mod, line, ctx) in sorted(self._series.items()):
            if series not in self._declared:
                findings.append(Finding(
                    code="FT002",
                    path=mod.rel,
                    line=line,
                    symbol=ctx,
                    detail=series,
                    message=(
                        f"undeclared degradation series {series!r}; "
                        "declare() it in emqx_tpu/broker/metrics.py"
                    ),
                ))
        return findings
