"""BV: buffer-view escape — own_buffers() before any slab sink.

The slab protocol plane (PR 12) parses a fabric read buffer into
`SlabMessage`s whose topic/payload are *views* into that buffer, and
`TopicRef`/`memoryview` values with the same lifetime. The moment the
buffer is recycled (and item 2's shared-memory rings will recycle
aggressively), any view that escaped into long-lived state reads
garbage. The runtime discipline is `own_buffers()` — materialize and
drop the slab reference — enforced today by convention at five
`# slab-escape site:` comments and PR 12's recycle tests. This
checker is the static twin: it taints view-producing expressions
(`SlabMessage(...)`, `TopicRef(...)`, `memoryview(...)`,
`.payload_view()`, `.topic_key()`, and project functions returning
them, via a returns-taint fixpoint over the call graph) and flags

  BV001  a tainted value stored into object state (`self.*` container
         or attribute) without `own_buffers()` first; and, inside a
         function annotated `# slab-escape`, any store of a
         parameter-derived value that no preceding `own_buffers()`
         call covers (the `getattr(msg, "own_buffers", None)` duck
         form counts)
  BV002  a rotted `# slab-escape` annotation: the enclosing function
         no longer stores anything after the comment

Deliberately under-approximate: locals appended to transient lists
(codec pack scratch) are not flagged — only self-rooted state and
declared sink functions, where a pinned view is a real failure.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.analysis.callgraph import (
    FuncKey,
    ProjectGraph,
    module_dotted,
    shared_graph,
)
from tools.analysis.core import (
    Checker,
    Finding,
    ParsedModule,
    enclosing_symbols,
)

_ESCAPE_RE = re.compile(r"#\s*slab-escape")
_TAINT_CTORS = frozenset({"SlabMessage", "TopicRef", "memoryview"})
_TAINT_METHODS = frozenset({"payload_view", "topic_key"})
_OWNING_CASTS = frozenset({"bytes", "bytearray", "str", "len", "int"})
# container method -> index of the *stored value* argument
_STORE_ARG = {
    "append": 0, "appendleft": 0, "add": 0, "put": 0, "put_nowait": 0,
    "insert": -1, "setdefault": 1,
}


def _local_walk(fn: ast.AST):
    """ast.walk that does not descend into nested def/class bodies."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _self_rooted(node: ast.AST) -> bool:
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return isinstance(node, ast.Name) and node.id == "self"


def _root_name(node: ast.AST) -> Optional[str]:
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _value_names(expr: ast.AST) -> List[str]:
    """Names plausibly *stored* by this value expression: the name
    itself, tuple/list elements, or the direct Name args of a wrapping
    constructor call (`Entry(msg, ...)` stores msg inside the entry)."""
    if isinstance(expr, ast.Name):
        return [expr.id]
    if isinstance(expr, (ast.Tuple, ast.List)):
        out: List[str] = []
        for e in expr.elts:
            out.extend(_value_names(e))
        return out
    if isinstance(expr, ast.Call):
        out = []
        for a in list(expr.args) + [kw.value for kw in expr.keywords]:
            if isinstance(a, ast.Name):
                out.append(a.id)
        return out
    return []


class _Event:
    __slots__ = ("line", "kind", "data")

    def __init__(self, line: int, kind: str, data):
        self.line = line
        self.kind = kind
        self.data = data


class BufferViewChecker(Checker):
    name = "bufview"
    codes = {
        "BV001": "slab/buffer view escapes into long-lived state "
                 "without own_buffers()",
        "BV002": "stale `# slab-escape` annotation (no store follows)",
    }

    def begin(self, modules: Sequence[ParsedModule]) -> None:
        self._graph = shared_graph(modules)
        self._returns_taint: Set[FuncKey] = set()
        # fixpoint: a function returning a taint expr (or a tainted
        # local) taints its callers' results
        for _ in range(4):
            new = set(self._returns_taint)
            for info in self._graph.infos:
                if info.key in new:
                    continue
                if self._fn_returns_taint(info.dn, info.node):
                    new.add(info.key)
            if new == self._returns_taint:
                break
            self._returns_taint = new

    # -- taint expression evaluation ----------------------------------------
    def _call_taints(self, dn: str, call: ast.Call) -> bool:
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr in _TAINT_METHODS:
            return True
        tail = self._graph.call_name(dn, f).rpartition(".")[2]
        if tail in _TAINT_CTORS:
            return True
        for key in self._graph.ref_targets(dn, f):
            if key in self._returns_taint:
                return True
        return False

    def _expr_taints(self, dn: str, expr: ast.AST,
                     tainted: Set[str]) -> bool:
        if expr is None:
            return False
        if isinstance(expr, ast.Name):
            return expr.id in tainted
        if isinstance(expr, ast.Call):
            if (
                isinstance(expr.func, ast.Name)
                and expr.func.id in _OWNING_CASTS
            ):
                return False  # bytes(view) copies: the result is owned
            if self._call_taints(dn, expr):
                return True
            return any(
                self._expr_taints(dn, a, tainted)
                for a in list(expr.args)
                + [kw.value for kw in expr.keywords]
            )
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            return any(
                self._expr_taints(dn, e, tainted) for e in expr.elts
            )
        if isinstance(expr, ast.Dict):
            return any(
                self._expr_taints(dn, v, tainted)
                for v in expr.values if v is not None
            )
        if isinstance(expr, (ast.IfExp,)):
            return self._expr_taints(dn, expr.body, tainted) or \
                self._expr_taints(dn, expr.orelse, tainted)
        if isinstance(expr, ast.BoolOp):
            return any(
                self._expr_taints(dn, v, tainted) for v in expr.values
            )
        if isinstance(expr, (ast.Await, ast.NamedExpr, ast.Starred)):
            return self._expr_taints(dn, expr.value, tainted)
        return False

    def _fn_returns_taint(self, dn: str, fn: ast.AST) -> bool:
        tainted: Set[str] = set()
        nodes = sorted(
            (
                n for n in _local_walk(fn)
                if isinstance(n, (ast.Assign, ast.Return))
            ),
            key=lambda n: (n.lineno, n.col_offset),
        )
        for n in nodes:
            if isinstance(n, ast.Assign):
                names = [
                    t.id for t in n.targets if isinstance(t, ast.Name)
                ]
                if self._expr_taints(dn, n.value, tainted):
                    tainted.update(names)
                else:
                    tainted.difference_update(names)
            elif n.value is not None and self._expr_taints(
                dn, n.value, tainted
            ):
                return True
        return False

    # -- per module ---------------------------------------------------------
    def check(self, mod: ParsedModule) -> Iterable[Finding]:
        findings: List[Finding] = []
        dn = module_dotted(mod.rel)
        symbols = enclosing_symbols(mod.tree)
        fns = [
            node for node in symbols
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        # each `# slab-escape` comment belongs to the INNERMOST def
        # whose span contains it (nested defs are separate functions)
        claimed: Dict[ast.AST, List[int]] = {}
        for i, text in enumerate(mod.lines):
            if not _ESCAPE_RE.search(text):
                continue
            ln = i + 1
            best = None
            for node in fns:
                end = getattr(node, "end_lineno", node.lineno)
                if node.lineno <= ln <= end and (
                    best is None or node.lineno > best.lineno
                ):
                    best = node
            if best is not None:
                claimed.setdefault(best, []).append(ln)
        for node in fns:
            findings.extend(self._check_fn(
                mod, dn, node, symbols[node], claimed.get(node, [])
            ))
        return findings

    def _check_fn(self, mod: ParsedModule, dn: str, fn: ast.AST,
                  sym: str, escape_lines: List[str]):
        params = {
            a.arg
            for a in list(fn.args.args) + list(fn.args.posonlyargs)
            + list(fn.args.kwonlyargs)
            + ([fn.args.vararg] if fn.args.vararg else [])
            + ([fn.args.kwarg] if fn.args.kwarg else [])
            if a.arg not in ("self", "cls")
        }
        derived = set(params)
        tainted: Set[str] = set()
        owned: Set[str] = set()
        own_alias: Dict[str, str] = {}  # getattr(m,"own_buffers") holder
        escape_at = min(escape_lines) if escape_lines else None
        stores_after_escape = 0
        findings: List[Finding] = []

        def emit(code: str, line: int, detail: str, message: str):
            findings.append(Finding(
                code=code, path=mod.rel, line=line, symbol=sym,
                detail=detail, message=message,
            ))

        def handle_store(line: int, receiver: ast.AST,
                         value: Optional[ast.AST],
                         key: Optional[ast.AST] = None):
            nonlocal stores_after_escape
            if escape_at is not None and line > escape_at:
                stores_after_escape += 1
            cands = _value_names(value) if value is not None else []
            live = [c for c in cands if c in tainted and c not in owned]
            taints = value is not None and self._expr_taints(
                dn, value, tainted - owned
            )
            key_taints = key is not None and self._expr_taints(
                dn, key, tainted - owned
            )
            if _self_rooted(receiver) and (taints or key_taints):
                what = live[0] if live else (
                    _root_name(value) if value is not None else None
                ) or "view"
                emit(
                    "BV001", line, what,
                    f"slab/buffer view {what!r} escapes into self."
                    f"{_attr_chain(receiver)} without own_buffers() — "
                    "it dangles when the slab is recycled",
                )
                return
            if escape_at is not None and line > escape_at:
                hot = [c for c in cands if c in derived]
                if hot and not (set(cands) & owned):
                    emit(
                        "BV001", line, hot[0],
                        f"store of {hot[0]!r} in a `# slab-escape` "
                        "sink with no preceding own_buffers() call on "
                        "it — the declared discipline is own-then-"
                        "store",
                    )

        nodes = sorted(
            _local_walk(fn), key=lambda n: (
                getattr(n, "lineno", 0), getattr(n, "col_offset", 0)
            )
        )
        for n in nodes:
            if isinstance(n, (ast.For, ast.AsyncFor)):
                it = n.iter
                root = _root_name(it) if not isinstance(it, ast.Call) \
                    else None
                if root in derived:
                    for t in ast.walk(n.target):
                        if isinstance(t, ast.Name):
                            derived.add(t.id)
            elif isinstance(n, ast.Assign):
                names = [
                    t.id for t in n.targets if isinstance(t, ast.Name)
                ]
                v = n.value
                # getattr(m, "own_buffers", None) duck-typed own
                if (
                    isinstance(v, ast.Call)
                    and isinstance(v.func, ast.Name)
                    and v.func.id == "getattr"
                    and len(v.args) >= 2
                    and isinstance(v.args[0], ast.Name)
                    and isinstance(v.args[1], ast.Constant)
                    and v.args[1].value == "own_buffers"
                ):
                    for name in names:
                        own_alias[name] = v.args[0].id
                if names:
                    if self._expr_taints(dn, v, tainted):
                        tainted.update(names)
                    else:
                        tainted.difference_update(names)
                    vr = None if isinstance(v, ast.Call) else \
                        _root_name(v)
                    if vr in derived:
                        derived.update(names)
                for t in n.targets:
                    if isinstance(t, ast.Subscript):
                        handle_store(t.lineno, t.value, v, t.slice)
                    elif isinstance(t, ast.Attribute):
                        handle_store(t.lineno, t, v)
            elif isinstance(n, ast.Call):
                f = n.func
                if isinstance(f, ast.Attribute) and \
                        f.attr == "own_buffers" and \
                        isinstance(f.value, ast.Name):
                    owned.add(f.value.id)
                    tainted.discard(f.value.id)
                elif isinstance(f, ast.Name) and f.id in own_alias:
                    owner = own_alias[f.id]
                    owned.add(owner)
                    tainted.discard(owner)
                elif isinstance(f, ast.Attribute) and \
                        f.attr in _STORE_ARG and n.args:
                    idx = _STORE_ARG[f.attr]
                    if -len(n.args) <= idx < len(n.args):
                        handle_store(n.lineno, f.value, n.args[idx])

        if escape_at is not None and stores_after_escape == 0:
            emit(
                "BV002", escape_at, "slab-escape",
                "`# slab-escape` annotation with no store following "
                "it in this function — the sink moved or the "
                "annotation rotted",
            )
        return findings


def _attr_chain(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        if isinstance(node, ast.Attribute):
            parts.append(node.attr)
        else:
            parts.append("[]")
        node = node.value
    return ".".join(reversed(parts)) or "<state>"
