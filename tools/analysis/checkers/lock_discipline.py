"""LK: guarded-attribute lock discipline.

An attribute becomes *guarded* two ways:

- a trailing `# guarded-by: <lock>` comment on the line that first
  assigns it (`self._counters = ...  # guarded-by: _lock`), or
- a class-level `GUARDED_BY = {"_counters": "_lock", ...}` dict literal.

Every other `self.<attr>` load/store in that class must then sit
lexically inside `with self.<lock>:`. A method whose *caller* holds the
lock is annotated with a trailing `# holds-lock: <lock>` on its `def`
line. `__init__` is exempt (the object is not shared while it is being
constructed).

This is the PR 1 bug class made mechanical: `Metrics.snapshot` read the
gauge table without `_lock` while executor threads wrote it.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List

from tools.analysis.core import Checker, Finding, ParsedModule

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*(\w+)")
_HOLDS_RE = re.compile(r"#\s*holds-lock:\s*(\w+)")


def _self_attr(node: ast.AST) -> str:
    """'attr' when node is `self.attr`, else ''."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return ""


def guarded_attrs(mod: ParsedModule, cls: ast.ClassDef) -> Dict[str, str]:
    """attr -> lock name, from comments and GUARDED_BY.

    Shared with the CX checker: a lock-guarded attribute is exempt from
    cross-context escape findings because THIS checker enforces its
    discipline."""
    guarded: Dict[str, str] = {}
    for node in ast.walk(cls):
        # GUARDED_BY = {"attr": "lock"} at class level
        if (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == "GUARDED_BY"
                for t in node.targets
            )
            and isinstance(node.value, ast.Dict)
        ):
            for k, v in zip(node.value.keys, node.value.values):
                if (
                    isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, str)
                ):
                    guarded[k.value] = v.value
        # trailing `# guarded-by: <lock>` on a self.X assignment
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            m = _GUARDED_RE.search(mod.line_text(node.lineno))
            if m:
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    attr = _self_attr(t)
                    if attr:
                        guarded[attr] = m.group(1)
    return guarded


class LockDisciplineChecker(Checker):
    name = "lock"
    codes = {
        "LK001": "guarded attribute accessed outside its lock",
        "LK002": "guarded-by annotation names a lock the class never "
                 "creates",
    }

    def check(self, mod: ParsedModule) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(mod, node))
        return findings

    # -- per class ---------------------------------------------------------
    def _guarded_attrs(self, mod: ParsedModule,
                       cls: ast.ClassDef) -> Dict[str, str]:
        return guarded_attrs(mod, cls)

    def _check_class(self, mod: ParsedModule,
                     cls: ast.ClassDef) -> Iterable[Finding]:
        guarded = self._guarded_attrs(mod, cls)
        if not guarded:
            return ()
        findings: List[Finding] = []
        symbol_base = cls.name

        # the lock itself must exist as an attribute somewhere in the class
        created = {
            _self_attr(t)
            for node in ast.walk(cls)
            if isinstance(node, ast.Assign)
            for t in node.targets
        }
        for attr, lock in sorted(guarded.items()):
            if lock not in created:
                findings.append(Finding(
                    code="LK002",
                    path=mod.rel,
                    line=cls.lineno,
                    symbol=symbol_base,
                    detail=f"{attr}->{lock}",
                    message=(
                        f"attribute {attr!r} is guarded-by {lock!r} but "
                        f"the class never assigns self.{lock}"
                    ),
                ))

        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if item.name == "__init__":
                continue
            held = set()
            m = _HOLDS_RE.search(mod.line_text(item.lineno))
            if m:
                held.add(m.group(1))
            self._walk(
                mod, item, guarded, frozenset(held),
                f"{symbol_base}.{item.name}", findings,
            )
        return findings

    def _walk(self, mod, node, guarded, held, symbol, findings) -> None:
        for child in ast.iter_child_nodes(node):
            child_held = held
            if isinstance(child, (ast.With, ast.AsyncWith)):
                acquired = {
                    _self_attr(it.context_expr)
                    for it in child.items
                    if _self_attr(it.context_expr)
                }
                if acquired:
                    # the body runs under the lock(s); the item exprs
                    # themselves (the `self._lock` reads) do not
                    for it in child.items:
                        self._walk(
                            mod, it, guarded, held, symbol, findings
                        )
                    for stmt in child.body:
                        self._walk(
                            mod, stmt, guarded,
                            frozenset(held | acquired), symbol, findings,
                        )
                    continue
            attr = _self_attr(child)
            if attr and attr in guarded and guarded[attr] not in child_held:
                findings.append(Finding(
                    code="LK001",
                    path=mod.rel,
                    line=child.lineno,
                    symbol=symbol,
                    detail=attr,
                    message=(
                        f"self.{attr} accessed outside "
                        f"`with self.{guarded[attr]}:` (guarded-by "
                        f"{guarded[attr]!r})"
                    ),
                ))
                continue  # don't re-flag sub-attributes of the same access
            self._walk(mod, child, guarded, child_held, symbol, findings)
