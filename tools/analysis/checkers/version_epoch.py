"""VC: version/epoch discipline on device-mirrored tables.

The DeviceSegmentManager sync contract keys everything off two
monotonic counters per source: `version` (total mutation count — the
delta path replays `oplog[pos:]` up to it) and `epoch` (generation —
a bump clears the log and forces a full re-upload). A public mutating
method that returns *without* moving either counter leaves the
manager believing the device mirror is current — the standby replica
silently misses the write. And because the mirror protocol is
single-writer by design (the serving loop owns the tables), a
mutation reachable from any *other* execution context needs the same
declared discipline the CX checker enforces.

Mirrored sources and their fields come from the OL checker's
discovery (`tools/analysis/checkers/oplog_complete.py`); execution
contexts come from the shared context map (`tools/analysis/
contexts.py`).

  VC001  a public (non-underscore) method of a mirrored source
         mutates a mirrored field but cannot reach a
         `self.version`/`self.epoch` bump through its intra-class
         call closure before returning
  VC002  a mirrored-field mutation runs under a non-loop execution
         context with no `# guarded-by:`/GUARDED_BY or
         `# single-writer:` declaration on the field (reuses the CX
         discipline — CX only fires at >= 2 contexts; for mirror
         state even ONE off-loop writer breaks the sync contract)
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Sequence, Set

from tools.analysis.callgraph import ProjectGraph, module_dotted, shared_graph
from tools.analysis.checkers.cross_context import single_writer_attrs
from tools.analysis.checkers.lock_discipline import guarded_attrs
from tools.analysis.checkers.oplog_complete import (
    _class_methods,
    _self_attr,
    covered_reason,
    method_mutations,
    mirror_source,
)
from tools.analysis.contexts import LOOP, ContextMap, shared_context_map
from tools.analysis.core import Checker, Finding, ParsedModule


def _assign_targets(node: ast.AST) -> List[ast.AST]:
    if isinstance(node, ast.Assign):
        return list(node.targets)
    if isinstance(node, (ast.AnnAssign, ast.AugAssign)):
        return [node.target]
    return []


def bump_closure(cls: ast.ClassDef) -> Set[str]:
    """Method names that write self.version/self.epoch, directly or
    through intra-class self-calls (fixpoint). A `self._log*`/
    `self._bump*` attribute *assigned* in the class (the CsrTable
    idiom: the facade injects version-bumping callbacks) counts as a
    bumping callee too."""
    methods = {m.name: m for m in _class_methods(cls)}
    bumps: Set[str] = set()
    # delegated-bump callbacks: self._log = log or ..., self._bump = ...
    for node in ast.walk(cls):
        for t in _assign_targets(node):
            attr = _self_attr(t)
            if attr and attr not in methods and (
                attr.startswith("_log") or attr.startswith("_bump")
            ):
                bumps.add(attr)
    for name, m in methods.items():
        for node in ast.walk(m):
            if any(
                _self_attr(t) in ("version", "epoch")
                for t in _assign_targets(node)
            ):
                bumps.add(name)
                break
    changed = True
    while changed:
        changed = False
        for name, m in methods.items():
            if name in bumps:
                continue
            for node in ast.walk(m):
                if (
                    isinstance(node, ast.Call)
                    and _self_attr(node.func) in bumps
                ):
                    bumps.add(name)
                    changed = True
                    break
    return bumps


class VersionDisciplineChecker(Checker):
    name = "version"
    codes = {
        "VC001": "public mutating method of a mirrored source returns "
                 "without a version/epoch bump",
        "VC002": "mirrored-field mutation reachable from a non-loop "
                 "context without guard/single-writer discipline",
    }

    def begin(self, modules: Sequence[ParsedModule]) -> None:
        self._graph = shared_graph(modules)
        self._cmap = shared_context_map(self._graph)

    def check(self, mod: ParsedModule) -> Iterable[Finding]:
        findings: List[Finding] = []
        dn = module_dotted(mod.rel)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(mod, dn, node))
        return findings

    def _check_class(self, mod: ParsedModule, dn: str,
                     cls: ast.ClassDef) -> Iterable[Finding]:
        src = mirror_source(mod, cls)
        if src is None or not src.protocol:
            return ()
        findings: List[Finding] = []
        bumps = bump_closure(cls)
        guarded = guarded_attrs(mod, cls)
        declared_sw = single_writer_attrs(mod, cls)
        for item in _class_methods(cls):
            if item.name == "__init__":
                continue
            muts = method_mutations(src.fields, item)
            if not muts:
                continue
            first_attr, first_line, _ = muts[0]
            if (
                not item.name.startswith("_")
                and item.name not in bumps
                and covered_reason(mod, item) is None
            ):
                findings.append(Finding(
                    code="VC001", path=mod.rel, line=first_line,
                    symbol=f"{cls.name}.{item.name}", detail=first_attr,
                    message=(
                        f"public method mutates mirrored self."
                        f"{first_attr} but never bumps self.version/"
                        "self.epoch (directly or via a self-call) — "
                        "the segment manager will treat the mirror as "
                        "already synced"
                    ),
                ))
            ctxs = self._cmap.contexts((dn, item.name))
            off_loop = sorted(c for c in ctxs if c != LOOP)
            if not off_loop:
                continue
            seen: Set[str] = set()
            for attr, line, _kind in muts:
                if attr in seen or attr in guarded or attr in declared_sw:
                    continue
                seen.add(attr)
                findings.append(Finding(
                    code="VC002", path=mod.rel, line=line,
                    symbol=f"{cls.name}.{item.name}", detail=attr,
                    message=(
                        f"mirrored self.{attr} is mutated under "
                        f"context(s) [{', '.join(off_loop)}] — mirror "
                        "tables are loop-owned; add `# guarded-by:` / "
                        "`# single-writer:` or move the write"
                    ),
                ))
        return findings
