"""OL: op-log completeness for device-mirrored tables.

ROADMAP item 5 streams each shard's op-log suffix to a warm standby —
which is only sound if the log is *complete*: every mutation of a
device-mirrored array must land in the op-log, force a `!resync`
marker, or ride an epoch bump (full re-upload). The invariant is
maintained by convention across five hand-written segment owners;
this checker makes it structural.

A class is a *mirrored source* when it speaks the
`DeviceSegmentManager` source protocol: it defines
``device_snapshot()`` and owns a ``self.oplog``. Its mirrored fields
are discovered from the snapshot body — the self-attributes inside a
``return {...}`` dict literal, or the names of a
``{k: getattr(self, k) for k in KEYS}`` comprehension resolved through
a module-level tuple constant — plus any assignment carrying a
trailing ``# mirrored-array`` annotation (for fields a snapshot builds
dynamically).

  OL001  a store / in-place mutation of a mirrored field in a method
         with no sanctioned provenance path in the *same* method:
         a `self._log*` / `self._bump*` call, a direct
         `self.oplog.append/extend` (or oplog slot store — the
         `!resync` rewrite idiom), or an epoch assignment. A helper
         whose callers provide the coverage (e.g. a bulk-place loop
         that every caller follows with an epoch bump) declares it
         with `# oplog-covered-by: <why>` on its `def` header.
  OL002  a stale `# mirrored-array` annotation — the attribute is
         absent from a statically-readable `device_snapshot()`, or the
         class is not a mirrored source at all (the way HT002/CX002
         catch rotted annotations).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.analysis.core import Checker, Finding, ParsedModule

_MIRROR_RE = re.compile(r"#\s*mirrored-array\b")
_COVERED_RE = re.compile(r"#\s*oplog-covered-by:\s*(\S[^#]*)")

# in-place ndarray mutators worth tracking on a mirrored field
_INPLACE_METHODS = ("fill", "sort", "partition", "resize", "put")


def _self_attr(node: ast.AST) -> str:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return ""


def _assign_targets(node: ast.AST) -> List[ast.AST]:
    if isinstance(node, ast.Assign):
        return list(node.targets)
    if isinstance(node, (ast.AnnAssign, ast.AugAssign)):
        return [node.target]
    return []


def _str_tuple_consts(tree: ast.Module) -> Dict[str, Tuple[str, ...]]:
    """Module-level NAME = ("a", "b", ...) constants (SEM_KEYS idiom)."""
    out: Dict[str, Tuple[str, ...]] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not isinstance(node.value, (ast.Tuple, ast.List)):
            continue
        elts = node.value.elts
        if not elts or not all(
            isinstance(e, ast.Constant) and isinstance(e.value, str)
            for e in elts
        ):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                out[t.id] = tuple(e.value for e in elts)  # type: ignore
    return out


class MirrorSource:
    """One mirrored-source class and what the analyzer knows about it."""

    __slots__ = ("cls", "fields", "snapshot_fields", "annotated",
                 "dynamic", "protocol")

    def __init__(self, cls: ast.ClassDef, fields: Set[str],
                 snapshot_fields: Set[str],
                 annotated: Dict[str, int], dynamic: bool,
                 protocol: bool):
        self.cls = cls
        self.fields = fields  # snapshot-discovered + annotated
        self.snapshot_fields = snapshot_fields
        self.annotated = annotated  # attr -> annotation lineno
        self.dynamic = dynamic  # snapshot has a non-literal return
        self.protocol = protocol  # device_snapshot() + self.oplog seen


def _class_methods(cls: ast.ClassDef):
    for item in cls.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield item


def annotated_mirror_attrs(mod: ParsedModule,
                           cls: ast.ClassDef) -> Dict[str, int]:
    """attr -> lineno for `# mirrored-array` trailing annotations."""
    out: Dict[str, int] = {}
    for node in ast.walk(cls):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        if not _MIRROR_RE.search(mod.line_text(node.lineno)):
            continue
        for t in _assign_targets(node):
            attr = _self_attr(t)
            if attr:
                out[attr] = node.lineno
    return out


def _snapshot_fields(tree: ast.Module,
                     snap: ast.AST) -> Tuple[Set[str], bool]:
    """Self-attrs a device_snapshot() statically exposes + dynamic flag."""
    fields: Set[str] = set()
    dynamic = False
    consts: Optional[Dict[str, Tuple[str, ...]]] = None
    # `out = {...}; ...; return out` — resolve the returned name through
    # its local assignments (SemanticTable's dtype-cast copy idiom)
    assigned: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(snap):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    assigned.setdefault(t.id, []).append(node.value)
    for node in ast.walk(snap):
        if not isinstance(node, ast.Return) or node.value is None:
            continue
        v = node.value
        if isinstance(v, ast.Name):
            exprs = [
                e for e in assigned.get(v.id, ())
                if isinstance(e, (ast.Dict, ast.DictComp))
            ]
            if exprs:
                v = exprs[0]
            else:
                dynamic = True
                continue
        if isinstance(v, ast.Dict):
            for val in v.values:
                if val is None:
                    continue
                for sub in ast.walk(val):
                    attr = _self_attr(sub)
                    if attr:
                        fields.add(attr)
        elif isinstance(v, ast.DictComp) and v.generators:
            it = v.generators[0].iter
            if consts is None:
                consts = _str_tuple_consts(tree)
            names = (
                consts.get(it.id) if isinstance(it, ast.Name) else None
            )
            if names:
                fields.update(names)
            else:
                dynamic = True
        else:
            # delegation (`return self._sp.device_snapshot()`) or any
            # other computed shape: the static view is incomplete
            dynamic = True
    return fields, dynamic


def mirror_source(mod: ParsedModule,
                  cls: ast.ClassDef) -> Optional[MirrorSource]:
    """The MirrorSource view of `cls`, or None if it does not speak the
    DeviceSegmentManager source protocol (device_snapshot + oplog)."""
    snap = None
    has_oplog = False
    for item in _class_methods(cls):
        if item.name == "device_snapshot":
            snap = item
    for node in ast.walk(cls):
        for t in _assign_targets(node):
            # either the class owns the log, or it delegates the bump
            # to its facade via an injected `self._bump` callback (the
            # CsrTable idiom) — both speak the source protocol
            if _self_attr(t) in ("oplog", "_bump"):
                has_oplog = True
    annotated = annotated_mirror_attrs(mod, cls)
    if snap is None or not has_oplog:
        if annotated:
            # still materialize so OL002 can flag the rotted annotation
            return MirrorSource(
                cls, set(annotated), set(), annotated, False, False
            )
        return None
    fields, dynamic = _snapshot_fields(mod.tree, snap)
    return MirrorSource(
        cls, fields | set(annotated), fields, annotated, dynamic, True
    )


def method_mutations(fields: Set[str],
                     fn: ast.AST) -> List[Tuple[str, int, str]]:
    """(attr, lineno, kind) mirrored-field mutations inside `fn`."""
    out: List[Tuple[str, int, str]] = []
    for node in ast.walk(fn):
        for t in _assign_targets(node):
            elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            for e in elts:
                if isinstance(e, ast.Subscript):
                    root = e.value
                    while isinstance(root, ast.Subscript):
                        root = root.value  # self._host_b[c][i] = v
                    attr = _self_attr(root)
                    if attr in fields:
                        out.append((attr, e.lineno, "slot store"))
                else:
                    attr = _self_attr(e)
                    if attr in fields:
                        out.append((attr, e.lineno, "rebind"))
        if isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ):
            recv = _self_attr(node.func.value)
            if recv in fields and node.func.attr in _INPLACE_METHODS:
                out.append((recv, node.lineno, f".{node.func.attr}()"))
            # ufunc scatter: np.add.at(self.arr, idx, v)
            if node.func.attr == "at" and node.args:
                a0 = _self_attr(node.args[0])
                if a0 in fields:
                    out.append((a0, node.lineno, "ufunc .at"))
    return out


def method_is_sanctioned(fn: ast.AST) -> bool:
    """Does `fn` itself touch the provenance channel? (op-log append,
    `!resync` rewrite, epoch bump, or a `self._log*`/`self._bump*`
    helper call — the sanction must be in the SAME method so the log
    records exactly the writes made.)"""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ):
            helper = _self_attr(node.func)
            if helper.startswith("_log") or helper.startswith("_bump"):
                return True
            if (
                node.func.attr in ("append", "extend")
                and _self_attr(node.func.value) == "oplog"
            ):
                return True
        for t in _assign_targets(node):
            if _self_attr(t) in ("epoch", "oplog"):
                return True
            if isinstance(t, ast.Subscript) and \
                    _self_attr(t.value) == "oplog":
                return True
    return False


def covered_reason(mod: ParsedModule, fn: ast.AST) -> Optional[str]:
    """`# oplog-covered-by: <why>` on the def header (or the comment
    line directly above it, for long signatures), if any."""
    body = getattr(fn, "body", None)
    end = body[0].lineno if body else fn.lineno + 1
    for ln in range(fn.lineno - 1, end):
        m = _COVERED_RE.search(mod.line_text(ln))
        if m:
            return m.group(1).strip()
    return None


class OplogCompleteChecker(Checker):
    name = "oplog"
    codes = {
        "OL001": "mirrored-field mutation bypasses the op-log "
                 "(no same-method log append / resync / epoch bump)",
        "OL002": "stale `# mirrored-array` annotation",
    }

    def check(self, mod: ParsedModule) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(mod, node))
        return findings

    def _check_class(self, mod: ParsedModule,
                     cls: ast.ClassDef) -> Iterable[Finding]:
        src = mirror_source(mod, cls)
        if src is None:
            return ()
        findings: List[Finding] = []
        # OL002: rotted annotations first — they also poison `fields`
        for attr, line in sorted(src.annotated.items()):
            if not src.protocol:
                findings.append(Finding(
                    code="OL002", path=mod.rel, line=line,
                    symbol=cls.name, detail=attr,
                    message=(
                        f"`# mirrored-array` on {attr!r} but "
                        f"{cls.name} is not a mirrored source (no "
                        "device_snapshot()/oplog protocol)"
                    ),
                ))
            elif not src.dynamic and attr not in src.snapshot_fields:
                findings.append(Finding(
                    code="OL002", path=mod.rel, line=line,
                    symbol=cls.name, detail=attr,
                    message=(
                        f"`# mirrored-array` on {attr!r} but "
                        "device_snapshot() does not expose it — the "
                        "annotation rotted (or the snapshot lost a "
                        "field)"
                    ),
                ))
        if not src.protocol:
            return findings
        for item in _class_methods(src.cls):
            if item.name == "__init__":
                continue  # nothing is mirrored before first sync
            muts = method_mutations(src.fields, item)
            if not muts:
                continue
            if method_is_sanctioned(item):
                continue
            if covered_reason(mod, item) is not None:
                continue
            seen: Set[str] = set()
            for attr, line, kind in muts:
                if attr in seen:
                    continue
                seen.add(attr)
                findings.append(Finding(
                    code="OL001", path=mod.rel, line=line,
                    symbol=f"{cls.name}.{item.name}", detail=attr,
                    message=(
                        f"{kind} of device-mirrored self.{attr} with no "
                        "op-log provenance in this method (append, "
                        "`!resync`, or epoch bump); a standby replaying "
                        "the log would diverge — log it, or declare "
                        "`# oplog-covered-by: <why>` on the def"
                    ),
                ))
        return findings
