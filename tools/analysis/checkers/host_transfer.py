"""HT: host-transfer discipline — device values cross to host only at
annotated readback sites.

PR 3 made the serving readback a budget (`dispatch.readback.bytes`);
one stray `np.asarray(out[...])` on a device value silently adds a
device->host transfer + sync and reverts it. The legal transfer points
are *named*: a function is a sanctioned readback boundary iff its
header carries a `# readback-site` comment. Everything else that pulls
a device value to host is a finding.

  HT001  device->host transfer outside a `# readback-site` function
  HT002  `# readback-site` annotation on a function with no transfer
         calls (stale annotation — the boundary moved)

"Device value" is tracked, not guessed, by a light taint analysis:

  sources   calls to jit-wrapped callables (decorated `@jax.jit` /
            `@partial(jax.jit, ...)`, or `name = [device_contract(...)](
            partial(jax.jit, ...)(impl))` module assignments), calls
            through variables holding a jit-wrapped callable (e.g. the
            builder pattern `fn = _dist_step_fn(...); fn(...)`), and
            `jax.device_put`
  flow      assignment, tuple unpack, subscript/attribute access,
            arithmetic/comparison, list/tuple literals, `.append`,
            `for` targets, `enumerate`/`zip`, comprehension targets;
            function parameters and returns propagate through the
            project call graph to a fixpoint
  cleared   `.shape`/`.dtype`/`.ndim`/`.size`/`len()` (static metadata)
            and the result of a transfer itself (it IS host data)

  sinks     `np.*` calls over a tainted argument (asarray/array/
            concatenate/count_nonzero/... — numpy converts implicitly),
            `float()/int()/bool()` of tainted, `.item()`/`.tolist()` on
            tainted, and — unconditionally, they are device-only APIs —
            `.block_until_ready()`, `jax.block_until_ready`,
            `jax.device_get`

The checker cannot see through containers of containers or attribute
stores (`self._dev[c]`), so it under-approximates; that is the right
failure mode for a lint that gates CI.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.analysis.callgraph import (
    FnInfo,
    FuncKey,
    ProjectGraph,
    header_lines,
    module_dotted,
    shared_graph,
)
from tools.analysis.core import Checker, Finding, ParsedModule

ANNOTATION = "# readback-site"

JIT_WRAP_NAMES = {"jax.jit", "jit"}
SHARD_WRAP_NAMES = {
    "jax.shard_map", "shard_map", "jax.experimental.shard_map.shard_map",
}
PARTIAL_NAMES = {"functools.partial", "partial"}
ALWAYS_SINKS = {"jax.block_until_ready", "jax.device_get"}
STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "nbytes", "weak_type"}
TRANSFER_METHODS = {"item", "tolist", "block_until_ready"}
PASSTHROUGH_BUILTINS = {"enumerate", "zip", "list", "tuple", "reversed",
                        "sorted", "iter"}

_MESSAGES = {
    "HT001": "device->host transfer outside a `# readback-site` function",
    "HT002": "stale `# readback-site` annotation (no transfer calls in "
             "this function)",
}

# taint states
HOST = 0
TAINT = 1  # device value
DEVCALL = 2  # a jit-wrapped callable (calling it yields a device value)


def _is_jit_wrap_call(graph: ProjectGraph, dn: str, node: ast.AST) -> bool:
    """`jax.jit(f)`, `partial(jax.jit, ...)(f)`, `shard_map(f, ...)`."""
    if not isinstance(node, ast.Call):
        return False
    name = graph.call_name(dn, node.func)
    if name in JIT_WRAP_NAMES or name in SHARD_WRAP_NAMES:
        return True
    if isinstance(node.func, ast.Call):
        inner = graph.call_name(dn, node.func.func)
        if inner in PARTIAL_NAMES and node.func.args:
            first = graph.call_name(dn, node.func.args[0])
            return first in JIT_WRAP_NAMES or first in SHARD_WRAP_NAMES
    return False


class HostTransferChecker(Checker):
    name = "transfer"
    codes = dict(_MESSAGES)

    def begin(self, modules: Sequence[ParsedModule]) -> None:
        g = self._graph = shared_graph(modules)
        # module-level device callables: decorated jit fns + assignments
        # whose RHS contains a jit-wrap call anywhere (covers the
        # `device_contract(...)(partial(jax.jit, ...)(impl))` chain)
        self._dev_callables: Set[FuncKey] = set()
        for info in g.infos:
            for dec in info.node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                name = g.call_name(info.dn, target)
                if name in JIT_WRAP_NAMES or name in SHARD_WRAP_NAMES:
                    self._dev_callables.add(info.key)
                elif (
                    isinstance(dec, ast.Call)
                    and name in PARTIAL_NAMES
                    and dec.args
                    and g.call_name(info.dn, dec.args[0]) in JIT_WRAP_NAMES
                ):
                    self._dev_callables.add(info.key)
        for dn, mod in g.mods.items():
            for stmt in mod.tree.body:
                if not isinstance(stmt, ast.Assign):
                    continue
                if any(
                    _is_jit_wrap_call(g, dn, sub)
                    for sub in ast.walk(stmt.value)
                ):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            self._dev_callables.add((dn, t.id))
        # cross-function facts, grown to a fixpoint
        self._ret_taint: Set[FuncKey] = set()
        self._ret_devcall: Set[FuncKey] = set()
        self._param_taint: Dict[FuncKey, Set[str]] = {}
        # screen only functions that can see device values: those in
        # jax-importing modules, plus anything facts propagate into
        jaxish = {
            dn for dn, aliases in g.aliases.items()
            if any(v == "jax" or v.startswith("jax.")
                   for v in aliases.values())
            or self._imports_jax(g.mods[dn].tree)
        }
        candidates = [i for i in g.infos if i.dn in jaxish]
        extra_keys: Set[FuncKey] = set()
        for _ in range(12):  # fixpoint (bounded; facts only grow)
            before = (
                len(self._ret_taint), len(self._ret_devcall),
                sum(len(v) for v in self._param_taint.values()),
                len(extra_keys),
            )
            todo = candidates + [
                i for k in extra_keys for i in g.funcs.get(k, [])
                if i.dn not in jaxish
            ]
            for info in todo:
                self._screen(info, emit=None, new_keys=extra_keys)
            after = (
                len(self._ret_taint), len(self._ret_devcall),
                sum(len(v) for v in self._param_taint.values()),
                len(extra_keys),
            )
            if after == before:
                break
        self._final = candidates + [
            i for k in extra_keys for i in g.funcs.get(k, [])
            if i.dn not in jaxish
        ]

    @staticmethod
    def _imports_jax(tree: ast.Module) -> bool:
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                if any(a.name == "jax" or a.name.startswith("jax.")
                       for a in node.names):
                    return True
            elif isinstance(node, ast.ImportFrom):
                if node.module and (
                    node.module == "jax" or node.module.startswith("jax.")
                ):
                    return True
        return False

    def finalize(self) -> Iterable[Finding]:
        findings: List[Finding] = []
        seen: Set[Tuple[str, int, str, str]] = set()

        def emit(code: str, info: FnInfo, node: ast.AST, detail: str):
            key = (info.mod.rel, node.lineno, code, detail)
            if key in seen:
                return
            seen.add(key)
            findings.append(Finding(
                code=code, path=info.mod.rel, line=node.lineno,
                symbol=info.symbol, detail=detail,
                message=f"{detail}: {_MESSAGES[code]}",
            ))

        done: Set[int] = set()
        for info in self._final:
            if id(info.node) in done:
                continue
            done.add(id(info.node))
            self._screen(info, emit=emit, new_keys=set())
        return findings

    # -- per-function taint walk -------------------------------------------
    def _screen(self, info: FnInfo, emit, new_keys: Set[FuncKey]) -> None:
        g = self._graph
        dn = info.dn
        fn = info.node
        annotated = any(ANNOTATION in ln for ln in header_lines(info))
        env: Dict[str, int] = {}
        params = [a.arg for a in fn.args.args + fn.args.kwonlyargs]
        for p in self._param_taint.get(info.key, ()):
            env[p] = TAINT
        sink_seen = False

        def state(e: ast.AST) -> int:
            nonlocal sink_seen
            if isinstance(e, ast.Name):
                if e.id in env:
                    return env[e.id]
                if (dn, e.id) in self._dev_callables:
                    return DEVCALL
                return HOST
            if isinstance(e, ast.Starred):
                return state(e.value)
            if isinstance(e, ast.Attribute):
                if e.attr in STATIC_ATTRS:
                    return HOST
                return TAINT if state(e.value) == TAINT else HOST
            if isinstance(e, ast.Subscript):
                return TAINT if state(e.value) == TAINT else HOST
            if isinstance(e, (ast.Tuple, ast.List)):
                return (
                    TAINT
                    if any(state(x) == TAINT for x in e.elts)
                    else HOST
                )
            if isinstance(e, ast.BinOp):
                return (
                    TAINT
                    if TAINT in (state(e.left), state(e.right))
                    else HOST
                )
            if isinstance(e, ast.UnaryOp):
                return state(e.operand)
            if isinstance(e, ast.BoolOp):
                return (
                    TAINT
                    if any(state(v) == TAINT for v in e.values)
                    else HOST
                )
            if isinstance(e, ast.Compare):
                ops = [e.left] + list(e.comparators)
                return (
                    TAINT if any(state(o) == TAINT for o in ops) else HOST
                )
            if isinstance(e, ast.IfExp):
                return (
                    TAINT
                    if TAINT in (state(e.body), state(e.orelse))
                    else HOST
                )
            if isinstance(e, ast.Call):
                return call_state(e)
            return HOST

        def call_state(e: ast.Call) -> int:
            nonlocal sink_seen
            if _is_jit_wrap_call(g, dn, e):
                return DEVCALL  # `partial(jax.jit, ...)(impl)` in a local
            name = g.call_name(dn, e.func)
            arg_states = [state(a) for a in e.args]
            kw_states = {kw.arg: state(kw.value) for kw in e.keywords
                         if kw.arg}
            any_taint = (
                TAINT in arg_states or TAINT in kw_states.values()
            )
            # ---- sinks ----
            if name in ALWAYS_SINKS:
                sink_seen = True
                if emit and not annotated:
                    emit("HT001", info, e, name.replace("jax.", "jax."))
                return HOST  # the result of a transfer is host data
            if (
                isinstance(e.func, ast.Attribute)
                and e.func.attr in TRANSFER_METHODS
            ):
                always = e.func.attr == "block_until_ready"
                if always or state(e.func.value) == TAINT:
                    sink_seen = True
                    if emit and not annotated:
                        emit("HT001", info, e, f".{e.func.attr}()")
                    return HOST
            if name.startswith("numpy."):
                sink_seen = True  # syntactic transfer form (for HT002)
                if any_taint:
                    if emit and not annotated:
                        emit(
                            "HT001", info, e,
                            f"np.{name.rpartition('.')[2]}",
                        )
                    return HOST
                return HOST
            if (
                isinstance(e.func, ast.Name)
                and e.func.id in ("float", "int", "bool")
                and e.args
                and arg_states and arg_states[0] == TAINT
            ):
                sink_seen = True
                if emit and not annotated:
                    emit("HT001", info, e, f"{e.func.id}(...)")
                return HOST
            # ---- sources / propagation ----
            if name == "jax.device_put":
                return TAINT
            if (
                isinstance(e.func, ast.Attribute)
                and not name
                and state(e.func.value) == TAINT
            ):
                # unknown method on a device value (`.sum()`, `.items()`,
                # `.astype()`, ...) stays a device value
                return TAINT
            if state(e.func) == DEVCALL:
                return TAINT
            if name == "len":
                return HOST
            if (
                isinstance(e.func, ast.Name)
                and e.func.id in PASSTHROUGH_BUILTINS
            ):
                return TAINT if any_taint else HOST
            targets = g.ref_targets(dn, e.func)
            hit = [t for t in targets if t in g.funcs]
            if any(t in self._dev_callables for t in targets):
                return TAINT
            if any(t in self._ret_devcall for t in hit):
                return DEVCALL
            # propagate tainted arguments into callee parameters
            for t in hit:
                for callee in g.funcs.get(t, []):
                    cparams = [
                        a.arg
                        for a in callee.node.args.args
                        + callee.node.args.kwonlyargs
                    ]
                    is_method = bool(cparams) and cparams[0] in (
                        "self", "cls"
                    )
                    shift = 1 if (
                        is_method
                        and isinstance(e.func, ast.Attribute)
                    ) else 0
                    names: List[str] = []
                    for i, s in enumerate(arg_states):
                        if s == TAINT and i + shift < len(cparams):
                            names.append(cparams[i + shift])
                    for kwname, s in kw_states.items():
                        if s == TAINT and kwname in cparams:
                            names.append(kwname)
                    if names:
                        cur = self._param_taint.setdefault(t, set())
                        if not set(names) <= cur:
                            cur.update(names)
                            new_keys.add(t)
            if any(t in self._ret_taint for t in hit):
                return TAINT
            return HOST

        def assign(target: ast.AST, st: int) -> None:
            if isinstance(target, ast.Name):
                env[target.id] = st
            elif isinstance(target, (ast.Tuple, ast.List)):
                for elt in target.elts:
                    assign(elt, st)
            elif isinstance(target, ast.Starred):
                assign(target.value, st)
            # attribute/subscript stores are not tracked

        def walk(stmts: List[ast.stmt]) -> None:
            nonlocal sink_seen
            for s in stmts:
                if isinstance(
                    s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                ):
                    continue  # nested defs are separate entries
                if isinstance(s, ast.Assign):
                    st = state(s.value)
                    for t in s.targets:
                        assign(t, st)
                elif isinstance(s, (ast.AnnAssign, ast.AugAssign)):
                    if getattr(s, "value", None) is not None:
                        assign(s.target, state(s.value))
                elif isinstance(s, ast.Return):
                    if s.value is not None:
                        st = state(s.value)
                        if st == TAINT and info.key not in self._ret_taint:
                            self._ret_taint.add(info.key)
                        if (
                            st == DEVCALL
                            and info.key not in self._ret_devcall
                        ):
                            self._ret_devcall.add(info.key)
                        if s.value is not None and _is_jit_wrap_call(
                            g, dn, s.value
                        ):
                            self._ret_devcall.add(info.key)
                elif isinstance(s, ast.Expr):
                    st = state(s.value)
                    # `acc.append(tainted)` taints the container
                    v = s.value
                    if (
                        isinstance(v, ast.Call)
                        and isinstance(v.func, ast.Attribute)
                        and v.func.attr in ("append", "extend", "insert")
                        and isinstance(v.func.value, ast.Name)
                        and any(state(a) == TAINT for a in v.args)
                    ):
                        env[v.func.value.id] = TAINT
                elif isinstance(s, ast.For):
                    assign(s.target, state(s.iter))
                    walk(s.body)
                    walk(s.orelse)
                elif isinstance(s, ast.While):
                    state(s.test)
                    walk(s.body)
                    walk(s.orelse)
                elif isinstance(s, ast.If):
                    state(s.test)
                    walk(s.body)
                    walk(s.orelse)
                elif isinstance(s, ast.With):
                    for item in s.items:
                        state(item.context_expr)
                        if item.optional_vars is not None:
                            assign(
                                item.optional_vars,
                                state(item.context_expr),
                            )
                    walk(s.body)
                elif isinstance(s, ast.Try):
                    walk(s.body)
                    for h in s.handlers:
                        walk(h.body)
                    walk(s.orelse)
                    walk(s.finalbody)
                else:
                    for sub in ast.walk(s):
                        if isinstance(sub, ast.Call):
                            state(sub)
                # comprehensions: bind targets from their iterables so
                # sinks inside see the taint
                for sub in ast.walk(s):
                    if isinstance(
                        sub,
                        (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                         ast.DictComp),
                    ):
                        for gen in sub.generators:
                            assign(gen.target, state(gen.iter))
                        if isinstance(sub, ast.DictComp):
                            state(sub.key)
                            state(sub.value)
                        else:
                            state(sub.elt)

        walk(fn.body)
        if emit and annotated and not sink_seen:
            emit("HT002", info, fn, fn.name)
        # make `self.attr` param-free functions visible: not tracked
        _ = params
