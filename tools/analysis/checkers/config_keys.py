"""CK: config-key drift against the `config/schema.py` dataclass tree.

The AppConfig dataclass tree is the single source of config truth
(schema, defaults, REST payload, env overrides). Python only catches a
misspelled field when the code path actually runs; this checker catches
it statically:

  CK001  attribute path on a typed dataclass object that the schema
         does not declare (`cfg.router.ingest_windw_us`)
  CK002  string config-key read (`config.get("...")` in the gateway
         layer, or a dotted `cfg.get("a.b")`) not declared in the
         schema (gateway keys: `GATEWAY_OPT_KEYS` in config/schema.py)
  CK003  schema key nothing in emqx_tpu/ ever reads (dead key)

Typing is inferred, never guessed: a chain is only validated when its
root is (a) a parameter/variable annotated with a known dataclass, or
(b) `self.X` where `__init__` assigns X from such a parameter or a
dataclass constructor. Everything else is left alone — gateway `config`
dicts, channel/session configs on untyped paths, etc.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.analysis.core import Checker, Finding, ParsedModule

_MESSAGES = {
    "CK001": "config attribute not declared in the schema",
    "CK002": "string config key not declared in the schema",
    "CK003": "schema key is never read anywhere (dead key)",
}


class _DcInfo:
    __slots__ = ("name", "fields", "members", "mod", "lines",
                 "_raw_annotations")

    def __init__(self, name: str, mod: ParsedModule):
        self.name = name
        self.mod = mod
        self.fields: Dict[str, Optional[str]] = {}  # field -> dc type name
        self.members: Set[str] = set()  # methods/properties/class attrs
        self.lines: Dict[str, int] = {}


def _is_dataclass_decorated(cls: ast.ClassDef) -> bool:
    for dec in cls.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        if isinstance(target, ast.Name) and target.id == "dataclass":
            return True
        if isinstance(target, ast.Attribute) and target.attr == "dataclass":
            return True
    return False


class ConfigKeyChecker(Checker):
    name = "config"
    codes = dict(_MESSAGES)

    ROOT_CLASS = "AppConfig"
    GATEWAY_KEY_REGISTRY = "GATEWAY_OPT_KEYS"
    # modules whose `*.config.get("key")` reads are checked against the
    # gateway opt-key registry
    GATEWAY_SCOPES = ("/gateway/", "/transport/dtls.py")

    # -- cross-module collection -------------------------------------------
    def begin(self, modules: Sequence[ParsedModule]) -> None:
        self._dcs: Dict[str, _DcInfo] = {}
        self._gateway_keys: Set[str] = set()
        self._attr_reads: Set[str] = set()
        self._str_consts: Set[str] = set()

        for mod in modules:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef) and \
                        _is_dataclass_decorated(node):
                    self._collect_dataclass(mod, node)
                elif isinstance(node, ast.Attribute):
                    self._attr_reads.add(node.attr)
                elif isinstance(node, ast.Constant) and \
                        isinstance(node.value, str):
                    self._str_consts.add(node.value)
                elif (
                    isinstance(node, ast.Assign)
                    and any(
                        isinstance(t, ast.Name)
                        and t.id == self.GATEWAY_KEY_REGISTRY
                        for t in node.targets
                    )
                ):
                    for sub in ast.walk(node.value):
                        if isinstance(sub, ast.Constant) and \
                                isinstance(sub.value, str):
                            self._gateway_keys.add(sub.value)

        self._resolve_field_types()
        # only the dataclasses reachable from AppConfig are *config*
        # classes; chains on other dataclasses (Message, wire frames...)
        # are not config reads and are left alone
        self._config_classes: Set[str] = set()
        work = [self.ROOT_CLASS]
        while work:
            cname = work.pop()
            if cname in self._config_classes or cname not in self._dcs:
                continue
            self._config_classes.add(cname)
            work.extend(
                t for t in self._dcs[cname].fields.values() if t
            )

    def _collect_dataclass(self, mod: ParsedModule, cls: ast.ClassDef):
        info = _DcInfo(cls.name, mod)
        for stmt in cls.body:
            if isinstance(stmt, ast.AnnAssign) and \
                    isinstance(stmt.target, ast.Name):
                info.fields[stmt.target.id] = None  # resolved later
                info.lines[stmt.target.id] = stmt.lineno
                info.members.add(stmt.target.id)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info.members.add(stmt.name)
            elif isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        info.members.add(t.id)
        # store annotation name candidates for second pass
        info._raw_annotations = {  # type: ignore[attr-defined]
            stmt.target.id: stmt.annotation
            for stmt in cls.body
            if isinstance(stmt, ast.AnnAssign)
            and isinstance(stmt.target, ast.Name)
        }
        self._dcs[cls.name] = info

    def _resolve_field_types(self) -> None:
        for info in self._dcs.values():
            raw = getattr(info, "_raw_annotations", {})
            for fname, ann in raw.items():
                names = [
                    n.id for n in ast.walk(ann) if isinstance(n, ast.Name)
                ]
                dc = next((n for n in names if n in self._dcs), None)
                info.fields[fname] = dc

    def _ann_dc(self, ann) -> Optional[str]:
        """Config-class name when the annotation IS that class (directly,
        or `Optional[C]`); containers (`List[C]`, `Dict[str, C]`) do NOT
        type the variable as C."""
        if isinstance(ann, ast.Name) and ann.id in self._config_classes:
            return ann.id
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str) \
                and ann.value in self._config_classes:
            return ann.value
        if (
            isinstance(ann, ast.Subscript)
            and isinstance(ann.value, ast.Name)
            and ann.value.id == "Optional"
        ):
            return self._ann_dc(ann.slice)
        return None

    # -- per-module checks --------------------------------------------------
    def check(self, mod: ParsedModule) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                attr_types = self._class_attr_types(node)
                for item in node.body:
                    if isinstance(
                        item, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        self._check_function(
                            mod, item, f"{node.name}.{item.name}",
                            attr_types, findings,
                        )
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_function(
                    mod, node, node.name, {}, findings
                )
        if self._applies_gateway_scope(mod):
            self._check_string_keys(mod, findings)
        return findings

    def _applies_gateway_scope(self, mod: ParsedModule) -> bool:
        probe = "/" + mod.rel
        return any(s in probe for s in self.GATEWAY_SCOPES)

    # annotated-parameter / constructor typing for `self.X`
    def _class_attr_types(self, cls: ast.ClassDef) -> Dict[str, str]:
        out: Dict[str, str] = {}
        init = next(
            (
                s for s in cls.body
                if isinstance(s, ast.FunctionDef) and s.name == "__init__"
            ),
            None,
        )
        if init is None:
            return out
        param_types = self._annotated_params(init)
        for node in ast.walk(init):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            t = node.targets[0]
            if not (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                continue
            dc = self._expr_dc_type(node.value, param_types)
            if dc is not None:
                out[t.attr] = dc
        return out

    def _annotated_params(self, fn) -> Dict[str, str]:
        out: Dict[str, str] = {}
        args = list(fn.args.posonlyargs) + list(fn.args.args) + \
            list(fn.args.kwonlyargs)
        for a in args:
            if a.annotation is None:
                continue
            dc = self._ann_dc(a.annotation)
            if dc is not None:
                out[a.arg] = dc
        return out

    def _expr_dc_type(self, expr, param_types: Dict[str, str]) \
            -> Optional[str]:
        """Type of an expression when confidently a known dataclass."""
        if isinstance(expr, ast.Name):
            return param_types.get(expr.id)
        if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Name) \
                and expr.func.id in self._config_classes:
            return expr.func.id
        if isinstance(expr, ast.BoolOp) and isinstance(expr.op, ast.Or):
            # `config or AppConfig()` — all branches must agree
            kinds = {
                self._expr_dc_type(v, param_types) for v in expr.values
            }
            kinds.discard(None)
            if len(kinds) == 1:
                return kinds.pop()
        return None

    def _check_function(self, mod, fn, symbol, attr_types, findings):
        param_types = self._annotated_params(fn)
        # local annotated variables
        local_types = dict(param_types)
        for node in ast.walk(fn):
            if isinstance(node, ast.AnnAssign) and \
                    isinstance(node.target, ast.Name):
                dc = self._ann_dc(node.annotation)
                if dc is not None:
                    local_types[node.target.id] = dc

        # only outermost attribute of each chain (inner nodes are the
        # `.value` of another Attribute)
        inner = {
            id(n.value) for n in ast.walk(fn)
            if isinstance(n, ast.Attribute)
            and isinstance(n.value, ast.Attribute)
        }
        for node in ast.walk(fn):
            if not isinstance(node, ast.Attribute) or id(node) in inner:
                continue
            chain: List[str] = []
            base = node
            while isinstance(base, ast.Attribute):
                chain.append(base.attr)
                base = base.value
            chain.reverse()
            root_type = None
            if isinstance(base, ast.Name):
                root_type = local_types.get(base.id)
            if root_type is None and (
                isinstance(base, ast.Name) and base.id == "self"
                and chain and chain[0] in attr_types
            ):
                root_type = attr_types[chain[0]]
                chain = chain[1:]
            if root_type is None or not chain:
                continue
            self._validate_chain(
                mod, node, symbol, root_type, chain, findings
            )

    def _validate_chain(self, mod, node, symbol, root_type, chain,
                        findings):
        cur = self._dcs.get(root_type)
        consumed: List[str] = []
        for attr in chain:
            if cur is None:
                return
            consumed.append(attr)
            if attr in cur.fields:
                nxt = cur.fields[attr]
                cur = self._dcs.get(nxt) if nxt else None
                continue
            if attr in cur.members:
                return  # method/property/class attr: fine, stop typing
            findings.append(Finding(
                code="CK001",
                path=mod.rel,
                line=node.lineno,
                symbol=symbol,
                detail=f"{cur.name}.{attr}",
                message=(
                    f"{'.'.join([root_type] + consumed)}: {attr!r} is not "
                    f"a field of {cur.name} (config/schema.py drift)"
                ),
            ))
            return

    # -- CK002: string keys -------------------------------------------------
    def _check_string_keys(self, mod: ParsedModule, findings) -> None:
        from tools.analysis.core import enclosing_symbols

        syms = enclosing_symbols(mod.tree)

        def nearest_symbol(target):
            best = "<module>"
            for n, s in syms.items():
                if (
                    n.lineno <= target.lineno
                    and getattr(n, "end_lineno", 1 << 30) >=
                    (target.end_lineno or target.lineno)
                ):
                    best = s
            return best

        for node in ast.walk(mod.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                continue
            recv = node.func.value
            recv_attr = (
                recv.attr if isinstance(recv, ast.Attribute)
                else recv.id if isinstance(recv, ast.Name) else ""
            )
            if recv_attr not in ("config", "cfg"):
                continue
            key = node.args[0].value
            if key in self._gateway_keys:
                continue
            findings.append(Finding(
                code="CK002",
                path=mod.rel,
                line=node.lineno,
                symbol=nearest_symbol(node),
                detail=key,
                message=(
                    f"config key {key!r} not declared in "
                    f"config/schema.py {self.GATEWAY_KEY_REGISTRY}"
                ),
            ))

    # -- CK003: dead keys ---------------------------------------------------
    def finalize(self) -> Iterable[Finding]:
        if self.ROOT_CLASS not in self._dcs:
            return ()
        findings: List[Finding] = []
        seen: Set[str] = set()
        work = [self.ROOT_CLASS]
        while work:
            cname = work.pop()
            if cname in seen:
                continue
            seen.add(cname)
            info = self._dcs[cname]
            for fname, ftype in info.fields.items():
                if ftype:
                    work.append(ftype)
                    continue  # container nodes are "read" via their leaves
                if fname in self._attr_reads or fname in self._str_consts:
                    continue
                findings.append(Finding(
                    code="CK003",
                    path=info.mod.rel,
                    line=info.lines.get(fname, 1),
                    symbol=cname,
                    detail=fname,
                    message=(
                        f"schema key {cname}.{fname} is never read "
                        "anywhere in the scanned tree (dead key?)"
                    ),
                ))
        return findings
