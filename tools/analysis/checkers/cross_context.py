"""CX: concurrency discipline — cross-context escape analysis.

PRs 6–7 made the hot path genuinely concurrent: the `tpu-dispatch`
executor overlaps device launches with the event loop, cluster sender
threads and exhook pools mutate breaker state, the bus reader threads
feed reply events. Every one of those threads shares objects with the
loop, and the lock checker (LK) only sees attributes someone *already*
annotated. This checker closes the gap from the other side: it computes
which execution contexts each method can run under (tools/analysis/
contexts.py — loop, named pools, raw threads) and flags object fields
that are **mutated** while **reachable from more than one context**
without a declared discipline.

A flagged field has three legal states:

- lock-guarded — add it to `GUARDED_BY` / a trailing `# guarded-by:`
  comment (the LK checker then enforces every access);
- single-writer — a trailing `# single-writer: <context>` on an
  assignment line (or a class-level `SINGLE_WRITER = {"attr": "ctx"}`)
  declares that exactly one context ever writes it and every other
  context only reads GIL-atomic snapshots (the publication pattern:
  DeviceRouter's prepare cache, TcpBus._handler);
- waived — `# lint: disable=CX001` with a justification, or a baseline
  entry (deliberate racy flags like a monotonic `alive` tombstone).

  CX001  field mutated while reachable from >= 2 execution contexts,
         with no guard, single-writer declaration, or waiver
  CX002  stale `# single-writer:` declaration — a *known* context other
         than the declared one writes the field, or the declared
         context name matches no context root discovered in the tree
         (the way HT002 catches a `# readback-site` that rotted)

The analysis is deliberately conservative where the context map is
blind: a method no context root reaches contributes nothing, so a
library class never used from two contexts stays silent even if it
*could* race in some other program.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Sequence, Set, Tuple

from tools.analysis.callgraph import ProjectGraph, module_dotted, shared_graph
from tools.analysis.checkers.lock_discipline import guarded_attrs
from tools.analysis.contexts import ContextMap, shared_context_map
from tools.analysis.core import Checker, Finding, ParsedModule

_SINGLE_RE = re.compile(r"#\s*single-writer:\s*([\w.\-*:]+)")


def _self_attr(node: ast.AST) -> str:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return ""


def single_writer_attrs(mod: ParsedModule,
                        cls: ast.ClassDef) -> Dict[str, Tuple[str, int]]:
    """attr -> (declared context, lineno), from trailing comments on
    self.X assignments and the class-level SINGLE_WRITER dict."""
    out: Dict[str, Tuple[str, int]] = {}
    for node in ast.walk(cls):
        if (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == "SINGLE_WRITER"
                for t in node.targets
            )
            and isinstance(node.value, ast.Dict)
        ):
            for k, v in zip(node.value.keys, node.value.values):
                if (
                    isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, str)
                ):
                    out[k.value] = (v.value, node.lineno)
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            m = _SINGLE_RE.search(mod.line_text(node.lineno))
            if m:
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    attr = _self_attr(t)
                    if attr:
                        out[attr] = (m.group(1), node.lineno)
    return out


def _ctx_matches(ctx: str, declared: str) -> bool:
    """`repl-*` style pool families match by prefix, both ways."""
    if ctx == declared:
        return True
    if declared.endswith("*") and ctx.startswith(declared[:-1]):
        return True
    if ctx.endswith("*") and declared.startswith(ctx[:-1]):
        return True
    return False


class _Access:
    __slots__ = ("line", "symbol", "ctxs", "write")

    def __init__(self, line: int, symbol: str, ctxs: Set[str], write: bool):
        self.line = line
        self.symbol = symbol
        self.ctxs = ctxs
        self.write = write


class CrossContextChecker(Checker):
    name = "cx"
    codes = {
        "CX001": "field mutated while reachable from >=2 execution "
                 "contexts without guard/single-writer/waiver",
        "CX002": "stale or unknown `# single-writer:` declaration",
    }

    def begin(self, modules: Sequence[ParsedModule]) -> None:
        self._graph = shared_graph(modules)
        self._cmap = shared_context_map(self._graph)

    def check(self, mod: ParsedModule) -> Iterable[Finding]:
        findings: List[Finding] = []
        dn = module_dotted(mod.rel)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(mod, dn, node))
        return findings

    # -- per class ---------------------------------------------------------
    def _method_accesses(self, dn: str,
                         cls: ast.ClassDef) -> Dict[str, List[_Access]]:
        """attr -> accesses with the contexts of the enclosing method."""
        cmap = self._cmap
        out: Dict[str, List[_Access]] = {}
        for item in cls.body:
            if not isinstance(
                item, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            if item.name == "__init__":
                continue  # the object is not shared mid-construction
            ctxs = set(cmap.contexts((dn, item.name)))
            if not ctxs:
                continue  # no root reaches it: nothing to judge
            symbol = f"{cls.name}.{item.name}"

            def visit(n: ast.AST) -> None:
                for child in ast.iter_child_nodes(n):
                    attr = _self_attr(child)
                    if attr:
                        write = isinstance(
                            child.ctx, (ast.Store, ast.Del)
                        ) if hasattr(child, "ctx") else False
                        out.setdefault(attr, []).append(
                            _Access(child.lineno, symbol, ctxs, write)
                        )
                    visit(child)

            visit(item)
            # an AugAssign store is also a read-modify-write; ast marks
            # the target Store, which we already record as a write
        return out

    def _check_class(self, mod: ParsedModule, dn: str,
                     cls: ast.ClassDef) -> Iterable[Finding]:
        accesses = self._method_accesses(dn, cls)
        if not accesses:
            return ()
        guarded = guarded_attrs(mod, cls)
        declared_sw = single_writer_attrs(mod, cls)
        findings: List[Finding] = []
        for attr, accs in sorted(accesses.items()):
            writes = [a for a in accs if a.write]
            write_ctxs: Set[str] = set()
            for a in writes:
                write_ctxs |= a.ctxs
            all_ctxs: Set[str] = set()
            for a in accs:
                all_ctxs |= a.ctxs
            if attr in declared_sw:
                decl, line = declared_sw[attr]
                if not self._cmap.known_context(decl):
                    findings.append(Finding(
                        code="CX002",
                        path=mod.rel,
                        line=line,
                        symbol=cls.name,
                        detail=f"{attr}->{decl}",
                        message=(
                            f"`# single-writer: {decl}` on {attr!r} names "
                            "a context no root in this tree creates "
                            "(typo, or the pool was renamed)"
                        ),
                    ))
                    continue
                stray = sorted(
                    c for c in write_ctxs if not _ctx_matches(c, decl)
                )
                if stray:
                    w = next(
                        a for a in writes
                        if any(not _ctx_matches(c, decl) for c in a.ctxs)
                    )
                    findings.append(Finding(
                        code="CX002",
                        path=mod.rel,
                        line=w.line,
                        symbol=w.symbol,
                        detail=f"{attr}->{decl}",
                        message=(
                            f"stale `# single-writer: {decl}`: {attr!r} "
                            f"is also written from context(s) "
                            f"{', '.join(stray)}"
                        ),
                    ))
                continue
            if attr in guarded:
                continue  # the LK checker owns its discipline
            if not writes or len(all_ctxs) < 2:
                continue
            w = writes[0]
            findings.append(Finding(
                code="CX001",
                path=mod.rel,
                line=w.line,
                symbol=w.symbol,
                detail=attr,
                message=(
                    f"self.{attr} is mutated while reachable from "
                    f"contexts [{', '.join(sorted(all_ctxs))}] with no "
                    "`# guarded-by:`/GUARDED_BY, `# single-writer:` "
                    "declaration, or waiver"
                ),
            ))
        return findings
