"""Execution-context map: which thread does each function run on?

The serving pipeline is a mixed asyncio/thread system — one event loop
plus a zoo of named pools (`tpu-dispatch`, the exhook notify/valued
lanes, the `repl-*`/`fwd-*` cluster executors) and raw
`threading.Thread` workers (cluster bus reader/acceptor, transport
fabric). The CX checker needs to know, for every function, the set of
execution contexts it can run under, so it can flag object fields
mutated from more than one.

The map is built from a registry of *context roots* discovered
syntactically:

- every ``async def`` runs on the event loop -> context ``"loop"``
  (module-level code and the sync call tree under coroutines rides the
  same thread);
- ``loop.run_in_executor(EXEC, fn, ...)`` and ``EXEC.submit(fn, ...)``
  make ``fn`` (and its call tree) run in EXEC's context. EXEC resolves
  to a *named* context through the pool table: every
  ``ThreadPoolExecutor(..., thread_name_prefix=...)`` assignment in the
  tree names the pool held by that variable/attribute, and a call like
  ``dispatch_pool()`` resolves through the function's body to the pool
  it creates. ``None`` is the asyncio default executor;
- ``threading.Thread(target=fn, ...)`` roots ``fn`` in a context named
  by the ``name=`` kwarg or the target function;
- ``fut.add_done_callback(cb)`` roots ``cb`` in the pool context when
  ``fut`` came from ``pool.submit(...)`` in the same function
  (concurrent.futures runs callbacks on the worker), and on the loop
  otherwise (asyncio futures run callbacks via call_soon).

Reachability follows the shared project call graph. Two deliberate
over/under-approximations, both inherited from callgraph.py's bias:
``self.method`` resolves by bare name within the module (methods of
sibling classes may merge), and a method reference through an arbitrary
variable (``dev.route_prepared``) falls back to a project-wide
bare-name lookup only when the name is rare (<= 3 definitions) — common
names (`get`, `close`) would otherwise wire the whole tree together.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.analysis.callgraph import FuncKey, ProjectGraph

LOOP = "loop"
DEFAULT_EXECUTOR = "default-executor"

def _const_prefix(node: ast.AST) -> Optional[str]:
    """Literal (or leading-literal, for f-strings) text of a name expr."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr) and node.values:
        first = node.values[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            return first.value.rstrip("-_") + "-*"
    return None


def _target_name(node: ast.AST) -> Optional[str]:
    """'x' for `x = ...`, '_pool' for `self._pool = ...`."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


class ContextMap:
    """contexts(key) -> the set of execution-context names a function
    (keyed like the project graph: (module, bare name)) may run under."""

    def __init__(self, graph: ProjectGraph):
        self.graph = graph
        # pool variable/attribute name -> context name
        self.pools: Dict[str, str] = {}
        # functions whose body creates-and-returns/assigns a named pool
        self._pool_factories: Dict[FuncKey, str] = {}
        self.context_names: Set[str] = {LOOP, DEFAULT_EXECUTOR}
        self._collect_pools()
        # context -> root function keys
        self.roots: Dict[str, Set[FuncKey]] = {}
        self._collect_roots()
        self._ctx: Dict[FuncKey, Set[str]] = {}
        self._propagate()

    # -- pool discovery -----------------------------------------------------
    def _pool_ctor_name(self, dn: str, call: ast.Call) -> Optional[str]:
        """Context name when `call` is ThreadPoolExecutor(...)."""
        name = self.graph.call_name(dn, call.func)
        if name.rpartition(".")[2] != "ThreadPoolExecutor":
            return None
        for kw in call.keywords:
            if kw.arg == "thread_name_prefix":
                got = _const_prefix(kw.value)
                if got:
                    return got
        return "executor"

    def _collect_pools(self) -> None:
        g = self.graph
        for dn, mod in g.mods.items():
            for node in ast.walk(mod.tree):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                value = node.value
                if value is None:
                    continue
                ctor = None
                for sub in ast.walk(value):
                    if isinstance(sub, ast.Call):
                        ctor = self._pool_ctor_name(dn, sub)
                        if ctor:
                            break
                if not ctor:
                    continue
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    tn = _target_name(t)
                    if tn:
                        self.pools[tn] = ctor
                        self.context_names.add(ctor)
        # functions that build a named pool anywhere in their body are
        # pool factories: `dispatch_pool()` resolves to "tpu-dispatch"
        for info in g.infos:
            for node in ast.walk(info.node):
                if isinstance(node, ast.Call):
                    ctor = self._pool_ctor_name(info.dn, node)
                    if ctor:
                        self._pool_factories[info.key] = ctor
                        self.context_names.add(ctor)
                        break

    def _executor_context(self, dn: str, node: ast.AST) -> str:
        """Context name of an executor expression at a submit site."""
        if isinstance(node, ast.Constant) and node.value is None:
            return DEFAULT_EXECUTOR
        tn = _target_name(node)
        if tn and tn in self.pools:
            return self.pools[tn]
        if isinstance(node, ast.Call):
            for key in self.graph.ref_targets(dn, node.func):
                if key in self._pool_factories:
                    return self._pool_factories[key]
        if tn:
            return f"executor:{tn}"
        return "executor"

    # -- root discovery -----------------------------------------------------
    def _fn_keys(self, dn: str, node: ast.AST) -> List[FuncKey]:
        """Function-reference -> keys; unique-name fallback for
        `obj.meth` references the alias table cannot see. Ambiguous
        names (a stdlib `t.join` shadowing three project `join`s) stay
        unresolved — a wrong root poisons every context downstream."""
        keys = [
            k for k in self.graph.ref_targets(dn, node)
            if k in self.graph.funcs
        ]
        if keys:
            return keys
        if isinstance(node, ast.Attribute):
            hits = [
                k for k in self.graph.funcs if k[1] == node.attr
            ]
            if len(hits) == 1 and len(self.graph.funcs[hits[0]]) == 1:
                return hits
        return []

    def _add_root(self, ctx: str, keys: Sequence[FuncKey]) -> None:
        if not keys:
            return
        self.context_names.add(ctx)
        self.roots.setdefault(ctx, set()).update(keys)

    def _collect_roots(self) -> None:
        g = self.graph
        for info in g.infos:
            if isinstance(info.node, ast.AsyncFunctionDef):
                self._add_root(LOOP, [info.key])
        for dn, mod in g.mods.items():
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                if not isinstance(func, ast.Attribute):
                    continue
                if func.attr == "run_in_executor" and len(node.args) >= 2:
                    ctx = self._executor_context(dn, node.args[0])
                    self._add_root(ctx, self._fn_keys(dn, node.args[1]))
                elif func.attr == "submit" and node.args:
                    tn = _target_name(func.value)
                    if tn in self.pools:
                        self._add_root(
                            self.pools[tn],
                            self._fn_keys(dn, node.args[0]),
                        )
                    elif isinstance(func.value, ast.Call):
                        ctx = self._executor_context(dn, func.value)
                        if ctx not in ("executor",):
                            self._add_root(
                                ctx, self._fn_keys(dn, node.args[0])
                            )
                elif func.attr in (
                    "call_soon", "call_later", "call_soon_threadsafe",
                    "call_at",
                ):
                    # scheduled callbacks run on the event loop
                    for arg in node.args:
                        if isinstance(arg, (ast.Name, ast.Attribute)):
                            got = self._fn_keys(dn, arg)
                            if got:
                                self._add_root(LOOP, got)
                                break
                else:
                    name = g.call_name(dn, func)
                    if name.rpartition(".")[2] == "Thread" or (
                        isinstance(func, ast.Attribute)
                        and func.attr == "Thread"
                    ):
                        self._thread_root(dn, node)
            # add_done_callback: pool future -> worker context,
            # asyncio future -> loop. Decided per enclosing function.
        for info in g.infos:
            self._done_callback_roots(info.dn, info.node)

    def _thread_root(self, dn: str, call: ast.Call) -> None:
        target = None
        ctx = None
        for kw in call.keywords:
            if kw.arg == "target":
                target = kw.value
            elif kw.arg == "name":
                got = _const_prefix(kw.value)
                if got:
                    ctx = got
        if target is None:
            return
        keys = self._fn_keys(dn, target)
        if not keys:
            return
        if ctx is None:
            ctx = f"thread:{keys[0][1]}"
        self._add_root(ctx, keys)

    def _done_callback_roots(self, dn: str, fn: ast.AST) -> None:
        """`fut.add_done_callback(cb)`: cb's context depends on where
        `fut` came from, tracked locally within this one function."""
        pool_futs: Set[str] = set()  # names assigned from pool.submit
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                vf = node.value.func
                if (
                    isinstance(vf, ast.Attribute)
                    and vf.attr == "submit"
                    and _target_name(vf.value) in self.pools
                ):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            pool_futs.add((t.id, self.pools[
                                _target_name(vf.value)]))
        pool_by_name = dict(pool_futs)
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_done_callback"
                and node.args
            ):
                cb_keys = self._fn_keys(dn, node.args[0])
                if not cb_keys:
                    continue
                holder = _target_name(node.func.value)
                ctx = pool_by_name.get(holder, LOOP)
                self._add_root(ctx, cb_keys)

    # -- propagation --------------------------------------------------------
    def _call_edges(self, dn: str, fn: ast.AST) -> List[FuncKey]:
        """graph.call_edges plus a unique-name fallback: a method call
        through an arbitrary receiver (`self.bus.send(...)`,
        `dev.route_prepared(...)`) resolves by bare name when exactly
        one function in the whole tree has that name — any ambiguity
        (`.inc()`, `.close()`) stays unresolved rather than wiring
        unrelated classes into every context."""
        g = self.graph
        out: List[FuncKey] = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            for ref in [node.func] + [
                a for a in list(node.args)
                + [kw.value for kw in node.keywords]
                if isinstance(a, (ast.Name, ast.Attribute))
            ]:
                keys = [
                    k for k in g.ref_targets(dn, ref) if k in g.funcs
                ]
                if not keys and isinstance(ref, ast.Attribute):
                    hits = [k for k in g.funcs if k[1] == ref.attr]
                    if len(hits) == 1 and len(g.funcs[hits[0]]) == 1:
                        keys = hits
                out.extend(keys)
        return out

    def _propagate(self) -> None:
        g = self.graph
        edges_cache: Dict[FuncKey, List[FuncKey]] = {}

        def edges(key: FuncKey) -> List[FuncKey]:
            got = edges_cache.get(key)
            if got is None:
                got = []
                for info in g.funcs.get(key, []):
                    got.extend(self._call_edges(info.dn, info.node))
                edges_cache[key] = got
            return got

        for ctx, roots in self.roots.items():
            seen: Set[FuncKey] = set()
            work = list(roots)
            while work:
                key = work.pop()
                if key in seen:
                    continue
                seen.add(key)
                self._ctx.setdefault(key, set()).add(ctx)
                work.extend(edges(key))

    # -- queries ------------------------------------------------------------
    def contexts(self, key: FuncKey) -> Set[str]:
        return self._ctx.get(key, set())

    def known_context(self, name: str) -> bool:
        """Is `name` a context this tree could discover? Glob-suffixed
        pool families (`repl-*`) match their prefix."""
        if name in self.context_names:
            return True
        for ctx in self.context_names:
            if ctx.endswith("*") and name.startswith(ctx[:-1]):
                return True
        return False


# -- per-run map sharing -----------------------------------------------------

# The CX and VC checkers both need the context map over the same shared
# graph; propagation is the single most expensive step of a repo scan,
# so it is built once per graph (identity-keyed, one slot — see
# callgraph.shared_graph for the invalidation argument).
_shared: Tuple[Optional[ProjectGraph], Optional["ContextMap"]] = (None, None)


def shared_context_map(graph: ProjectGraph) -> "ContextMap":
    global _shared
    if _shared[0] is not graph:
        _shared = (graph, ContextMap(graph))
    return _shared[1]
