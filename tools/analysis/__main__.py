"""CLI: `python -m tools.analysis [root] [options]`.

Exit-code contract (wired into CI):
  0  clean (no non-baseline findings)
  1  findings
  2  internal analyzer error
"""

from __future__ import annotations

import argparse
import json
import sys
import traceback
from pathlib import Path

from tools.analysis.core import Baseline, run_analysis

DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"
DEFAULT_ROOT = Path(__file__).resolve().parents[2] / "emqx_tpu"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.analysis",
        description="tpu_lint: project static analysis for emqx_tpu",
    )
    p.add_argument(
        "root", nargs="?", default=None,
        help=f"tree to scan (default: {DEFAULT_ROOT})",
    )
    p.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="report format (default text)",
    )
    p.add_argument(
        "--checks", default=None,
        help="comma-separated subset of checks to run "
             "(lock,async,jit,config,metrics,shard,transfer,retrace,"
             "fault,cx,oplog,version,bufview,wire,snapshot,bpapi)",
    )
    p.add_argument(
        "--changed-only", action="store_true",
        help="only report findings in files touched per git (working "
        "tree vs HEAD, plus untracked); the whole tree is still parsed "
        "so cross-module checks stay exact. Tier B audits (--contracts, "
        "--replay) are whole-system checks with no per-file subset — "
        "they are SKIPPED under --changed-only (noted on stderr); run "
        "the full gate for them",
    )
    p.add_argument(
        "--jobs", type=int, default=0, metavar="N",
        help="parse source files with N worker threads (0 = serial)",
    )
    p.add_argument(
        "--contracts", action="store_true",
        help="additionally run the jaxpr-level device-contract audit "
        "(imports jax + kernel code; see tools/analysis/device_contract)",
    )
    p.add_argument(
        "--replay", action="store_true",
        help="additionally run the shadow-replica replication audit "
        "(emqx_tpu/observe/replay_check.py): randomized churn across "
        "the five mirrored owners with raced compaction must converge "
        "array-exact, and the seeded incomplete-log control must be "
        "detected",
    )
    p.add_argument(
        "--replay-rounds", type=int, default=48, metavar="N",
        help="churn rounds for --replay (default 48; CI --fast uses a "
        "smaller bound)",
    )
    p.add_argument(
        "--replay-seed", type=int, default=0, metavar="S",
        help="RNG seed for --replay churn (default 0)",
    )
    p.add_argument(
        "--update-snapshots", action="store_true",
        help="with --contracts: refresh the golden jaxpr snapshots "
        "instead of failing on a diff",
    )
    p.add_argument(
        "--wirecompat", action="store_true",
        help="additionally run the wire-compatibility audit "
        "(tools/analysis/wirecompat.py): replay the committed golden "
        "byte corpus through CURRENT decoders, cross-check live "
        "struct/dtype layouts against the format registry, require the "
        "seeded drift control to be detected, and fail any registered "
        "format with no corpus coverage",
    )
    p.add_argument(
        "--update-corpus", action="store_true",
        help="with --wirecompat: regenerate the golden corpus with the "
        "current encoders; REFUSES when bytes change without a registry "
        "version bump, rewrites the digest pins otherwise",
    )
    p.add_argument(
        "--audit", action="store_true",
        help="the consolidated tier-B gate: --contracts + --replay + "
        "--wirecompat in one run, shared report and exit contract "
        "(rc = worst of the three)",
    )
    p.add_argument(
        "--smoke", action="store_true",
        help="with --audit: the bounded ci_gate.sh --fast variant — "
        "skips the jaxpr contract audit (compile-heavy) and caps "
        "--replay-rounds at 8; the wirecompat corpus replay is cheap "
        "and always runs in full",
    )
    p.add_argument(
        "--baseline", default=None,
        help=f"baseline file (default: {DEFAULT_BASELINE}; only applied "
        "when scanning the default root unless given explicitly)",
    )
    p.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline: report every finding",
    )
    p.add_argument(
        "--write-baseline", action="store_true",
        help="record current non-baseline findings into the baseline "
        "file (new entries get a TODO justification to fill in)",
    )
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    root = Path(args.root) if args.root else DEFAULT_ROOT
    if not root.is_dir():
        print(f"error: scan root {root} is not a directory",
              file=sys.stderr)
        return 2

    baseline_path = None
    if args.baseline:
        baseline_path = Path(args.baseline)
    elif root.resolve() == DEFAULT_ROOT.resolve():
        baseline_path = DEFAULT_BASELINE
    if args.no_baseline:
        baseline = Baseline(path=baseline_path)
    elif baseline_path is not None:
        baseline = Baseline.load(baseline_path)
    else:
        baseline = Baseline()

    checks = (
        [c.strip() for c in args.checks.split(",") if c.strip()]
        if args.checks else None
    )
    only_paths = None
    if args.changed_only:
        only_paths = _git_changed_paths(root)
        if only_paths is None:
            print(
                "warning: --changed-only needs a git checkout; "
                "running a full scan",
                file=sys.stderr,
            )
    try:
        report = run_analysis(
            root, baseline=baseline, checks=checks, jobs=args.jobs,
            only_paths=only_paths,
        )
    except Exception:
        traceback.print_exc()
        return 2

    if args.write_baseline:
        target = baseline_path or DEFAULT_BASELINE
        for f in report.findings:
            baseline.entries.setdefault(
                f.fingerprint, "TODO: justify this grandfathered finding"
            )
        baseline.save(target)
        print(
            f"baseline: {len(report.findings)} finding(s) recorded into "
            f"{target}"
        )
        return 0

    rc = 0 if report.clean else 1
    # --audit is the consolidated tier-B entrypoint: one flag, every
    # whole-system gate, one exit contract. --smoke bounds it for the
    # fast CI lane (replay churn capped, compile-heavy contracts
    # skipped; the corpus replay is cheap and stays full).
    if args.audit:
        args.replay = True
        args.wirecompat = True
        if args.smoke:
            args.replay_rounds = min(args.replay_rounds, 8)
        else:
            args.contracts = True
    # Tier B audits are whole-system: there is no meaningful "changed
    # files only" subset of a jaxpr contract or a replication replay,
    # so --changed-only skips them instead of running a misleading
    # partial audit (the full CI gate runs them unconditionally).
    tier_b = (args.contracts or args.update_snapshots or args.replay
              or args.wirecompat or args.update_corpus)
    if args.changed_only and tier_b:
        print(
            "note: --changed-only skips Tier B audits "
            "(--contracts/--replay/--wirecompat); run without "
            "--changed-only for the whole-system gates",
            file=sys.stderr,
        )
    audit_doc = None
    if (args.contracts or args.update_snapshots) and not args.changed_only:
        from tools.analysis.device_contract import run_audit

        audit = run_audit(update_snapshots=args.update_snapshots)
        audit_doc = audit.to_json()
        if not audit.clean:
            rc = max(rc, 1)

    replay_doc = None
    if args.replay and not args.changed_only:
        from emqx_tpu.observe.replay_check import run_replay_audit

        replay_doc = run_replay_audit(
            seed=args.replay_seed, rounds=args.replay_rounds
        )
        if replay_doc["divergence"] or not replay_doc["negative_detected"]:
            rc = max(rc, 1)

    wirecompat_doc = None
    if (args.wirecompat or args.update_corpus) and not args.changed_only:
        from tools.analysis.wirecompat import run_wirecompat_audit

        wirecompat_doc = run_wirecompat_audit(update=args.update_corpus)
        if not wirecompat_doc["ok"]:
            rc = max(rc, 1)
        _emit_wirecompat_metrics(wirecompat_doc)

    if args.format == "json":
        doc = report.to_json()
        if audit_doc is not None:
            doc["contract_audit"] = audit_doc
        if replay_doc is not None:
            doc["replay_audit"] = replay_doc
        if wirecompat_doc is not None:
            doc["wirecompat_audit"] = wirecompat_doc
        print(json.dumps(doc, indent=2))
    else:
        print(report.render_text())
        if audit_doc is not None:
            from tools.analysis.device_contract import render_audit

            print(render_audit(audit_doc))
        if replay_doc is not None:
            print(_render_replay(replay_doc))
        if wirecompat_doc is not None:
            from tools.analysis.wirecompat import render_wirecompat_text

            print(render_wirecompat_text(wirecompat_doc))
    return rc


def _emit_wirecompat_metrics(doc) -> None:
    """Best-effort metric stamps so audit runs show up on the
    observability plane alongside broker series (declared in
    broker/metrics.py: analysis.wirecompat.*, proto.registry.formats)."""
    try:
        from emqx_tpu.broker.metrics import Metrics
        from emqx_tpu.proto.registry import formats

        m = Metrics()
        m.inc("analysis.wirecompat.runs")
        if not doc.get("ok", False):
            m.inc("analysis.wirecompat.failures")
        m.gauge_set("proto.registry.formats", len(formats()))
    except Exception:
        pass  # metrics are an observability nicety, never a gate


def _render_replay(doc) -> str:
    lines = [
        f"replay audit: seed={doc['seed']} rounds={doc['rounds']} "
        f"compactions={doc['compactions']} "
        f"(aborted {doc['compactions_aborted']})"
    ]
    for name, o in sorted(doc["owners"].items()):
        lines.append(
            f"  {name:<9} syncs={o['syncs']:<3} full={o['full']:<2} "
            f"offers={o['offers']}"
        )
    if doc["divergence"]:
        lines.append("  DIVERGED:")
        for name, problems in sorted(doc["divergence"].items()):
            for p in problems:
                lines.append(f"    {name}: {p}")
    else:
        lines.append("  converged: all owners array-exact")
    lines.append(
        "  negative control "
        + ("DETECTED" if doc["negative_detected"] else "MISSED (BUG)")
        + f" ({doc['negative_control']})"
    )
    return "\n".join(lines)


def _git_changed_paths(root: Path):
    """Changed + untracked .py files as `Finding.path`-style rel paths
    (posix, relative to the scan root's parent), or None without git."""
    import subprocess

    base = root.resolve().parent
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", "HEAD", "--"],
            cwd=base, capture_output=True, text=True, timeout=30,
        )
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=base, capture_output=True, text=True, timeout=30,
        )
        toplevel = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            cwd=base, capture_output=True, text=True, timeout=30,
        )
        if diff.returncode or toplevel.returncode:
            return None
    except (OSError, subprocess.SubprocessError):
        return None
    top = Path(toplevel.stdout.strip())
    out = set()
    names = diff.stdout.splitlines() + untracked.stdout.splitlines()
    for name in names:
        if not name.endswith(".py"):
            continue
        p = (top / name).resolve()
        try:
            out.add(p.relative_to(base).as_posix())
        except ValueError:
            continue  # outside the scan root's parent
    return sorted(out)


if __name__ == "__main__":
    sys.exit(main())
