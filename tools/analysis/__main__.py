"""CLI: `python -m tools.analysis [root] [options]`.

Exit-code contract (wired into CI):
  0  clean (no non-baseline findings)
  1  findings
  2  internal analyzer error
"""

from __future__ import annotations

import argparse
import json
import sys
import traceback
from pathlib import Path

from tools.analysis.core import Baseline, run_analysis

DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"
DEFAULT_ROOT = Path(__file__).resolve().parents[2] / "emqx_tpu"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.analysis",
        description="tpu_lint: project static analysis for emqx_tpu",
    )
    p.add_argument(
        "root", nargs="?", default=None,
        help=f"tree to scan (default: {DEFAULT_ROOT})",
    )
    p.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="report format (default text)",
    )
    p.add_argument(
        "--checks", default=None,
        help="comma-separated subset of checks to run "
             "(lock,async,jit,config,metrics)",
    )
    p.add_argument(
        "--baseline", default=None,
        help=f"baseline file (default: {DEFAULT_BASELINE}; only applied "
        "when scanning the default root unless given explicitly)",
    )
    p.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline: report every finding",
    )
    p.add_argument(
        "--write-baseline", action="store_true",
        help="record current non-baseline findings into the baseline "
        "file (new entries get a TODO justification to fill in)",
    )
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    root = Path(args.root) if args.root else DEFAULT_ROOT
    if not root.is_dir():
        print(f"error: scan root {root} is not a directory",
              file=sys.stderr)
        return 2

    baseline_path = None
    if args.baseline:
        baseline_path = Path(args.baseline)
    elif root.resolve() == DEFAULT_ROOT.resolve():
        baseline_path = DEFAULT_BASELINE
    if args.no_baseline:
        baseline = Baseline(path=baseline_path)
    elif baseline_path is not None:
        baseline = Baseline.load(baseline_path)
    else:
        baseline = Baseline()

    checks = (
        [c.strip() for c in args.checks.split(",") if c.strip()]
        if args.checks else None
    )
    try:
        report = run_analysis(root, baseline=baseline, checks=checks)
    except Exception:
        traceback.print_exc()
        return 2

    if args.write_baseline:
        target = baseline_path or DEFAULT_BASELINE
        for f in report.findings:
            baseline.entries.setdefault(
                f.fingerprint, "TODO: justify this grandfathered finding"
            )
        baseline.save(target)
        print(
            f"baseline: {len(report.findings)} finding(s) recorded into "
            f"{target}"
        )
        return 0

    if args.format == "json":
        print(json.dumps(report.to_json(), indent=2))
    else:
        print(report.render_text())
    return 0 if report.clean else 1


if __name__ == "__main__":
    sys.exit(main())
