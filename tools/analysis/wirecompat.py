"""Tier-B wire-compatibility audit: golden-corpus replay through CURRENT
decoders.

Tier A (checkers/wire_format.py et al.) proves the *declared* formats
haven't drifted from their defining code. This module proves the code
still *reads old bytes*: a committed corpus of frames, snapshots and
pickles — captured by the encoders of the version that wrote them —
is replayed through today's decode paths and the result compared,
deep-equal, against pinned JSON expectations.

Four gates, one report:

  1. live registry cross-check — every registered struct/dtype format is
     imported and its LIVE object's digest recomputed against the
     registry (the AST view can't see a runtime-constructed layout);
  2. corpus replay — every case in tests/fixtures/wire_corpus/
     manifest.json decodes clean and matches expected/<case>.json;
  3. seeded drift control — one corpus byte is flipped IN MEMORY and the
     decode MUST fail or diverge (a gate that can't catch its own
     negative control is not a gate);
  4. staleness — every repo-registered format must be covered by at
     least one corpus case, so new formats can't ship corpus-less.

`--update-corpus` regenerates the corpus with the current encoders but
REFUSES when a case's bytes change while every format it covers still
carries its pinned version — exactly the silent-break the audit exists
to stop. A legitimate format change bumps the registry version first;
the update then rewrites the golden pins alongside the corpus.

Legacy cases (PR 11 raw-"ts" inflight snapshots, PR 15 wall-"deadline"
expiry snapshots, pre-interval "due" delayed entries) are hand-crafted:
their encoders no longer exist, which is the point — the current
decoders must keep reading them.
"""

from __future__ import annotations

import base64
import importlib
import json
import pickle
import struct
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

REPO_ROOT = Path(__file__).resolve().parents[2]
CORPUS_DIR = REPO_ROOT / "tests" / "fixtures" / "wire_corpus"
PINS_PATH = REPO_ROOT / "tests" / "fixtures" / "analysis" / "wire" / "digests.json"

# fixed stamps: corpus bytes must be reproducible byte-for-byte so
# --update-corpus can tell "format changed" from "regenerated"
T_WALL = 1754000000.0  # 2025-08-01: a committed past instant
T_FAR = 4102444800.0  # 2100-01-01: survives restore-time expiry math


# -- canonicalization ---------------------------------------------------

def _b64(b) -> str:
    return base64.b64encode(bytes(b)).decode()


def _canon(obj: Any) -> Any:
    """JSON-safe canonical form of a decoded value (tuples -> lists,
    bytes -> b64, Message -> its registered JSON shape)."""
    from emqx_tpu.broker.message import Message
    from emqx_tpu.storage.codec import msg_to_json

    if isinstance(obj, Message):
        return {"__msg__": _canon(msg_to_json(obj))}
    if isinstance(obj, dict):
        return {str(k): _canon(v) for k, v in sorted(obj.items(), key=lambda kv: str(kv[0]))}
    if isinstance(obj, (list, tuple)):
        return [_canon(v) for v in obj]
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return {"__b64__": _b64(obj)}
    if isinstance(obj, float):
        return round(obj, 6)
    return obj


def _canon_session(doc: Dict) -> Dict:
    """session_to_json output, with clock-sensitive fields coarsened:
    inflight ages re-read monotonic at encode time, so a decode->encode
    round trip shifts them by scheduler noise."""
    out = _canon(doc)
    for e in out.get("inflight", []):
        e["age"] = round(float(e.get("age", 0.0)), 1)
    return out


def _split_frames(data: bytes, hdr: struct.Struct, extra: int = 0) -> List[bytes]:
    """Split a concatenation of length-prefixed frames. `extra` is the
    prefix overhead beyond the length field (fabric: 1 type byte,
    already inside hdr)."""
    out = []
    off = 0
    while off < len(data):
        fields = hdr.unpack_from(data, off)
        length = fields[0]
        end = off + hdr.size + extra + length
        if end > len(data):
            raise ValueError("torn frame in corpus stream")
        out.append(data[off:end])
        off = end
    return out


# -- stubs for the restore-path decoders --------------------------------

class _DictKv:
    """In-memory FileKv twin: the corpus file IS the namespace payload."""

    def __init__(self, payloads: Dict[str, Dict]):
        self._p = payloads

    def read(self, namespace: str) -> Optional[Dict]:
        return self._p.get(namespace)

    def write(self, namespace: str, obj: Dict) -> None:
        self._p[namespace] = obj


class _StubCm:
    def __init__(self):
        self._detached: Dict[str, Tuple[Any, float]] = {}


class _StubBroker:
    def __init__(self):
        self.routes: List[Tuple[str, str]] = []

    def subscribe(self, node, cid, topic_filter, opts, deliver) -> None:
        self.routes.append((cid, topic_filter))


# -- decoders -----------------------------------------------------------
# Each decoder: (data: bytes, params: dict) -> JSON-canonical object.
# They call the repo's CURRENT decode paths — never a reimplementation.

def _dec_pub_frame(data: bytes, params: Dict) -> Any:
    from emqx_tpu.transport import fabric

    seq, records = fabric.unpack_pub_frame(data)
    return {"seq": seq, "records": _canon(records)}


def _dec_dlv_frames(data: bytes, params: Dict) -> Any:
    from emqx_tpu.transport import fabric

    frames = _split_frames(data, fabric._HDR)
    return {"frames": [_canon(fabric.unpack_dlv_frame(f)) for f in frames]}


def _dec_raw_frame(data: bytes, params: Dict) -> Any:
    from emqx_tpu.transport import fabric

    length, ftype = fabric._HDR.unpack_from(data, 0)
    if ftype != fabric.T_RAW:
        raise ValueError(f"expected T_RAW frame, got type {ftype}")
    return {"records": _canon(fabric.unpack_raw_batch(data[5:]))}


def _dec_pub_ack(data: bytes, params: Dict) -> Any:
    from emqx_tpu.transport import fabric

    length, ftype = fabric._HDR.unpack_from(data, 0)
    if ftype != fabric.T_PUBB_ACK:
        raise ValueError(f"expected T_PUBB_ACK frame, got type {ftype}")
    seq, counts = fabric.unpack_pub_ack(data[5:])
    return {"seq": seq, "counts": counts}


def _dec_cluster_bus(data: bytes, params: Dict) -> Any:
    from emqx_tpu.cluster import tcp_transport

    out = []
    off = 0
    while off < len(data):
        (n,) = tcp_transport._LEN.unpack_from(data, off)
        off += tcp_transport._LEN.size
        frame = pickle.loads(data[off : off + n])
        off += n
        out.append(_canon(frame))
    return {"frames": out}


def _dec_session_json(data: bytes, params: Dict) -> Any:
    from emqx_tpu.broker.session import SessionConfig
    from emqx_tpu.storage.codec import session_from_json, session_to_json

    doc = json.loads(data.decode())
    sess = session_from_json(doc, SessionConfig())
    return _canon_session(session_to_json(sess))


def _dec_sessions_kv(data: bytes, params: Dict) -> Any:
    from emqx_tpu.broker.persistent_session import NS_SESSIONS, SessionPersistence
    from emqx_tpu.broker.session import SessionConfig
    from emqx_tpu.storage.codec import session_to_json

    kv = _DictKv({NS_SESSIONS: json.loads(data.decode())})
    cm, broker = _StubCm(), _StubBroker()
    sp = SessionPersistence(broker, cm, kv, SessionConfig())
    n = sp.restore()
    sessions = {
        cid: _canon_session(session_to_json(sess))
        for cid, (sess, _deadline) in sorted(cm._detached.items())
    }
    return {
        "restored": n,
        "routes": sorted(broker.routes),
        "sessions": sessions,
    }


def _dec_durable_kv(data: bytes, params: Dict) -> Any:
    from emqx_tpu.broker.banned import Banned
    from emqx_tpu.broker.delayed import DelayedPublish
    from emqx_tpu.broker.persistent_session import DurableState
    from emqx_tpu.broker.retainer import Retainer

    kv = _DictKv(json.loads(data.decode()))
    retainer = Retainer()
    delayed = DelayedPublish(broker=None)
    banned = Banned()
    out = DurableState(kv, retainer=retainer, delayed=delayed, banned=banned).restore()
    return {
        "counts": out,
        "retained": sorted(
            (t, _b64(retainer.get(t).payload)) for t in retainer.topics()
        ),
        "delayed_topics": sorted(m.topic for _due, m in delayed.pending()),
        "banned": sorted((e.kind, e.value) for e in banned.entries()),
    }


def _dec_segment_snapshot(data: bytes, params: Dict) -> Any:
    import io

    import numpy as np

    state = pickle.load(io.BytesIO(data))
    out = {}
    for k in sorted(state):
        v = state[k]
        if isinstance(v, np.ndarray):
            out[k] = {
                "dtype": str(v.dtype),
                "shape": list(v.shape),
                "values": _canon(v.tolist()),
            }
        else:
            out[k] = _canon(v)
    return {"keys": sorted(state), "state": out}


def _dec_session_store(data: bytes, params: Dict) -> Any:
    import io

    from emqx_tpu.broker.session_store import SessionStore

    state = pickle.load(io.BytesIO(data))
    store = SessionStore(capacity=int(params.get("capacity", 64)), sweep_slots=16)
    restored = store.install(state)
    return {"keys": sorted(state), "restored": restored}


def _dec_router_pickle(data: bytes, params: Dict) -> Any:
    import io

    router = pickle.load(io.BytesIO(data))
    fields = vars(router)
    return {
        "fields": sorted(fields),
        "device_handles_nulled": fields.get("_matcher") is None
        and fields.get("mesh") is None,
        "exact": _canon(fields.get("_exact", {})),
    }


def _dec_message_pickle(data: bytes, params: Dict) -> Any:
    import io

    from emqx_tpu.storage.codec import msg_to_json

    return _canon(msg_to_json(pickle.load(io.BytesIO(data))))


def _dec_misc_structs(data: bytes, params: Dict) -> Any:
    from emqx_tpu.mqtt import slab_serializer
    from emqx_tpu.transport import dtls, fabric

    off = 0
    rec = dtls._REC.unpack_from(data, off)
    off += dtls._REC.size
    (u16be,) = slab_serializer._U16BE.unpack_from(data, off)
    off += slab_serializer._U16BE.size
    (u16,) = fabric._U16.unpack_from(data, off)
    off += fabric._U16.size
    (u32,) = fabric._U32.unpack_from(data, off)
    off += fabric._U32.size
    if off != len(data):
        raise ValueError("misc_structs corpus has trailing bytes")
    return {"dtls_record": list(rec), "u16be": u16be, "u16": u16, "u32": u32}


DECODERS: Dict[str, Callable[[bytes, Dict], Any]] = {
    "pub_frame": _dec_pub_frame,
    "dlv_frames": _dec_dlv_frames,
    "raw_frame": _dec_raw_frame,
    "pub_ack": _dec_pub_ack,
    "cluster_bus": _dec_cluster_bus,
    "session_json": _dec_session_json,
    "sessions_kv": _dec_sessions_kv,
    "durable_kv": _dec_durable_kv,
    "segment_snapshot": _dec_segment_snapshot,
    "session_store": _dec_session_store,
    "router_pickle": _dec_router_pickle,
    "message_pickle": _dec_message_pickle,
    "misc_structs": _dec_misc_structs,
}


# -- generators ---------------------------------------------------------
# Current-encoder corpus capture, deterministic byte-for-byte. Legacy
# cases are hand-crafted: their writers no longer exist.

def _mk_msg(i: int, topic: Optional[str] = None, **kw) -> Any:
    from emqx_tpu.broker.message import Message

    defaults = dict(
        topic=topic or f"sensors/{i}/temp",
        payload=(b"%d:" % i) + b"x" * (16 + 7 * i),
        qos=i % 3,
        retain=bool(i & 1),
        from_client=f"dev-{i}",
        mid=1000 + i,
        timestamp=T_WALL + i,
    )
    defaults.update(kw)
    return Message(**defaults)


def _gen_pubb_slab() -> bytes:
    from emqx_tpu.transport import fabric

    msgs = [_mk_msg(i) for i in range(6)]
    msgs[2].properties = {"Content-Type": "text/plain", "User-Property": [["k", "v"]]}
    msgs[4].dup = True
    return fabric.pack_pub_slab(msgs, seq=42)


def _gen_pubb_legacy() -> bytes:
    from emqx_tpu.transport import fabric

    msgs = [_mk_msg(i) for i in range(4)]
    msgs[1].properties = {"Message-Expiry-Interval": 3600}
    return fabric.pack_pub_batch(msgs, seq=7)


def _gen_dlv_slab_split() -> bytes:
    from emqx_tpu.transport import fabric

    records = [
        (_mk_msg(i, headers={"retained": bool(i == 1)}), list(range(i * 3, i * 3 + 5)))
        for i in range(8)
    ]
    # a tiny max_body forces the MAX_BODY split path with small files
    return b"".join(fabric.pack_dlv_slabs(records, max_body=256))


def _gen_dlv_legacy() -> bytes:
    from emqx_tpu.transport import fabric

    records = [(_mk_msg(i), [100 + i, 200 + i]) for i in range(3)]
    records[1][0].properties = {"Response-Topic": "replies/1"}
    return b"".join(fabric.pack_dlv_batches(records, max_body=128))


def _gen_raw_legacy() -> bytes:
    from emqx_tpu.transport import fabric

    records = [(b"\x30\x0a\x00\x03abcHELLO", [1, 2, 3]), (b"\xd0\x00", [9])]
    return b"".join(fabric.pack_raw_batches(records))


def _gen_pub_ack() -> bytes:
    from emqx_tpu.transport import fabric

    return fabric.pack_pub_ack(42, [3, 0, -1, 7])


def _gen_cluster_bus() -> bytes:
    from emqx_tpu.cluster import tcp_transport

    fwd = _mk_msg(
        1,
        topic="cluster/fwd",
        headers={"traceparent": "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"},
    )
    park = {
        "client_id": "edge-9",
        "session": {"client_id": "edge-9", "expiry_interval": 300},
        "expiry_remaining_s": 120.0,
    }
    frames = [
        ("hello", 0, ("node-a", "10.0.0.1", 7400)),
        ("cast", 0, ("membership", "join", {"node": "node-a", "epoch": 3})),
        ("cast", 0, ("membership", "heartbeat")),
        ("call", 7, ("rpc", "call", "broker", 1, "route_publish", (fwd,))),
        ("reply", 7, (True, "ok")),
        ("call", 8, ("sess", "park_remote", park)),
        ("cast", 0, ("rpc", "announce", {"node": "node-a", "apis": ["broker"]})),
    ]
    out = bytearray()
    for f in frames:
        blob = pickle.dumps(f, protocol=pickle.HIGHEST_PROTOCOL)
        out += tcp_transport._LEN.pack(len(blob)) + blob
    return bytes(out)


def _session_doc_current() -> Dict:
    from emqx_tpu.broker.session import SessionConfig
    from emqx_tpu.storage.codec import (
        msg_to_json,
        session_from_json,
        session_to_json,
    )

    doc = {
        "client_id": "dev-42",
        "created_at": T_WALL,
        "expiry_interval": 3600,
        "next_pid": 17,
        "subscriptions": {
            "sensors/#": {"qos": 1, "no_local": False,
                          "retain_as_published": False, "retain_handling": 0},
            "alerts/+/hi": {"qos": 2, "no_local": True,
                            "retain_as_published": True, "retain_handling": 1},
        },
        "mqueue": [msg_to_json(_mk_msg(1)), msg_to_json(_mk_msg(2))],
        "inflight": [
            {"pid": 5, "phase": "pub", "age": 0.0, "msg": msg_to_json(_mk_msg(3))},
            {"pid": 6, "phase": "rel", "age": 0.0, "msg": None},
        ],
        "awaiting_rel": [9, 11],
    }
    # round-trip through the CURRENT codec so the committed file is
    # genuine encoder output, not a hand-approximation of it
    sess = session_from_json(doc, SessionConfig())
    out = session_to_json(sess)
    for e in out["inflight"]:
        e["age"] = 0.0  # strip decode->encode monotonic jitter
    return out


def _gen_session_current() -> bytes:
    return json.dumps(_session_doc_current(), indent=1, sort_keys=True).encode()


def _gen_session_legacy_ts() -> bytes:
    """PR 11 legacy shape: inflight entries carried raw MONOTONIC "ts"
    stamps (meaningless in this process). The current decoder must read
    them as age-0 entries rather than crash or mis-age them."""
    from emqx_tpu.storage.codec import msg_to_json

    doc = {
        "client_id": "old-node-client",
        "created_at": T_WALL - 500.0,
        "expiry_interval": 7200,
        "next_pid": 3,
        "subscriptions": {
            "legacy/topic": {"qos": 1, "no_local": False,
                             "retain_as_published": False, "retain_handling": 0},
        },
        "mqueue": [msg_to_json(_mk_msg(4, topic="legacy/q"))],
        "inflight": [
            {"pid": 1, "phase": "pub", "ts": 123456.789,
             "msg": msg_to_json(_mk_msg(5, topic="legacy/infl"))},
            {"pid": 2, "phase": "rel", "ts": 123460.0, "msg": None},
        ],
        "awaiting_rel": [2],
    }
    return json.dumps(doc, indent=1, sort_keys=True).encode()


def _gen_sessions_kv_current() -> bytes:
    snap = _session_doc_current()
    # interval must outlive (decode wall-now - T_WALL): ~32 years
    snap["expiry_remaining_s"] = 1.0e9
    stale = dict(_session_doc_current(), client_id="stale-1")
    stale["expiry_remaining_s"] = 5.0  # expired during downtime -> dropped
    return json.dumps(
        {"at": T_WALL, "sessions": {"dev-42": snap, "stale-1": stale}},
        indent=1, sort_keys=True,
    ).encode()


def _gen_sessions_kv_legacy_deadline() -> bytes:
    """PR 15 legacy shape: per-session wall-clock "deadline" instead of
    expiry_remaining_s. Restore must rebase it once (deadline - now)."""
    snap = _session_doc_current()
    snap["deadline"] = T_FAR  # 2100: survives the rebase
    gone = dict(_session_doc_current(), client_id="gone-1")
    gone["deadline"] = 1000.0  # 1970-adjacent: expired while down
    return json.dumps(
        {"at": T_WALL, "sessions": {"dev-42": snap, "gone-1": gone}},
        indent=1, sort_keys=True,
    ).encode()


def _gen_durable_kv_current() -> bytes:
    from emqx_tpu.broker.banned import BanEntry, Banned
    from emqx_tpu.broker.delayed import DelayedPublish
    from emqx_tpu.broker.persistent_session import (
        NS_BANNED,
        NS_DELAYED,
        NS_RETAINED,
        DurableState,
    )
    from emqx_tpu.broker.retainer import Retainer

    retainer = Retainer()
    for i in range(3):
        retainer.on_publish(_mk_msg(i, topic=f"retained/{i}", retain=True))
    delayed = DelayedPublish(broker=None)
    delayed.load(1.0e9, _mk_msg(7, topic="later/a"))
    delayed.load(2.0e9, _mk_msg(8, topic="later/b"))
    banned = Banned()
    banned.add(BanEntry(kind="clientid", value="evil-1", reason="abuse",
                        until=T_FAR, by="admin"))
    kv = _DictKv({})
    DurableState(kv, retainer=retainer, delayed=delayed, banned=banned).flush()
    doc = kv._p
    doc[NS_DELAYED]["at"] = T_WALL
    # remaining intervals must outlive decode-time downtime charging
    for d in doc[NS_DELAYED]["messages"]:
        d["remaining_s"] = 1.0e9
    # a banned entry the restore must SKIP (until in the past)
    doc[NS_BANNED]["entries"].append(
        {"kind": "clientid", "value": "expired-ban", "reason": "old",
         "until": 1000.0, "by": "admin"}
    )
    assert NS_RETAINED in doc
    return json.dumps(doc, indent=1, sort_keys=True).encode()


def _gen_durable_kv_legacy() -> bytes:
    """Pre-interval delayed entries carried wall-clock "due" deadlines;
    one is already past (dropped), one message carries an expired
    Message-Expiry-Interval (dropped by is_expired)."""
    from emqx_tpu.broker.persistent_session import (
        NS_BANNED,
        NS_DELAYED,
        NS_RETAINED,
    )
    from emqx_tpu.storage.codec import msg_to_json

    expired = _mk_msg(3, topic="retained/expired", retain=True,
                      properties={"Message-Expiry-Interval": 10})
    doc = {
        NS_RETAINED: {
            "messages": [
                msg_to_json(_mk_msg(0, topic="retained/keep", retain=True)),
                msg_to_json(expired),
            ]
        },
        NS_DELAYED: {
            "at": T_WALL,
            "messages": [
                {"due": T_FAR, "msg": msg_to_json(_mk_msg(5, topic="later/live"))},
                {"due": 1000.0, "msg": msg_to_json(_mk_msg(6, topic="later/past"))},
            ],
        },
        NS_BANNED: {
            "entries": [
                {"kind": "peerhost", "value": "10.9.9.9", "reason": "flood",
                 "until": T_FAR, "by": "ops"},
            ]
        },
    }
    return json.dumps(doc, indent=1, sort_keys=True).encode()


def _gen_segment_state() -> bytes:
    import io

    import numpy as np

    state = {
        "route_index": {"sensors/1/temp": 0, "alerts/+/hi": 1},
        "hot_segments": np.arange(8, dtype=np.int32),
        "sub_bitmap": np.array([1, 0, 1, 1], dtype=np.uint8),
        "generation": 3,
    }
    buf = io.BytesIO()
    pickle.dump(state, buf, protocol=pickle.HIGHEST_PROTOCOL)
    return buf.getvalue()


def _gen_session_store() -> bytes:
    import io

    from emqx_tpu.broker.session_store import SessionStore

    store = SessionStore(capacity=64, sweep_slots=16)
    state = store.capture()
    state["t0_age_ds"] = 0  # clock reading: normalize for reproducibility
    buf = io.BytesIO()
    pickle.dump(state, buf, protocol=pickle.HIGHEST_PROTOCOL)
    return buf.getvalue()


def _gen_router_state() -> bytes:
    from emqx_tpu.broker.router import Router

    r = Router(enable_tpu=False)
    return pickle.dumps(r, protocol=pickle.HIGHEST_PROTOCOL)


def _gen_message_pickle() -> bytes:
    m = _mk_msg(
        11,
        topic="cluster/traced",
        headers={"traceparent": "00-" + "12" * 16 + "-" + "34" * 8 + "-01",
                 "retained": False},
        properties={"Correlation-Data": b"\x01\x02"},
    )
    return pickle.dumps(m, protocol=pickle.HIGHEST_PROTOCOL)


def _gen_misc_structs() -> bytes:
    from emqx_tpu.mqtt import slab_serializer
    from emqx_tpu.transport import dtls, fabric

    return (
        dtls._REC.pack(22, 0xFEFD, 1, 0x0002, 0x00000003, 48)
        + slab_serializer._U16BE.pack(0x1234)
        + fabric._U16.pack(0x2345)
        + fabric._U32.pack(0xDEADBEEF)
    )


# (name, file, decoder, generator, covers, params)
CASES: List[Tuple[str, str, str, Callable[[], bytes], List[str], Dict]] = [
    ("pubb_slab", "pubb_slab.bin", "pub_frame", _gen_pubb_slab,
     ["fabric.slab.pub_hdr", "fabric.frame_hdr", "fabric.frame_types"], {}),
    ("pubb_legacy", "pubb_legacy.bin", "pub_frame", _gen_pubb_legacy,
     ["fabric.u16", "fabric.u32", "fabric.frame_hdr", "fabric.frame_types"], {}),
    ("dlv_slab_split", "dlv_slab_split.bin", "dlv_frames", _gen_dlv_slab_split,
     ["fabric.slab.dlv_hdr", "fabric.frame_hdr", "fabric.frame_types"], {}),
    ("dlv_legacy", "dlv_legacy.bin", "dlv_frames", _gen_dlv_legacy,
     ["fabric.u16", "fabric.u32"], {}),
    ("raw_legacy", "raw_legacy.bin", "raw_frame", _gen_raw_legacy,
     ["fabric.u16", "fabric.u32", "fabric.frame_types"], {}),
    ("pub_ack", "pub_ack.bin", "pub_ack", _gen_pub_ack,
     ["fabric.u32", "fabric.frame_types"], {}),
    ("cluster_bus", "cluster_bus.bin", "cluster_bus", _gen_cluster_bus,
     ["cluster.bus.len_prefix", "cluster.bus.kinds", "cluster.payload.kinds",
      "membership.tags", "cluster.rpc.kinds", "cluster.bpapi",
      "cluster.sess.park", "message.pickle"], {}),
    ("session_current", "session_current.json", "session_json",
     _gen_session_current,
     ["codec.session_json", "codec.msg_json", "codec.subopts_json"], {}),
    ("session_legacy_ts", "session_legacy_ts.json", "session_json",
     _gen_session_legacy_ts, ["codec.session_json"], {}),
    ("sessions_kv_current", "sessions_kv_current.json", "sessions_kv",
     _gen_sessions_kv_current, ["durable.sessions_ns", "codec.session_json"], {}),
    ("sessions_kv_legacy_deadline", "sessions_kv_legacy_deadline.json",
     "sessions_kv", _gen_sessions_kv_legacy_deadline,
     ["durable.sessions_ns"], {}),
    ("durable_kv_current", "durable_kv_current.json", "durable_kv",
     _gen_durable_kv_current,
     ["durable.kv.namespaces", "durable.state", "codec.msg_json"], {}),
    ("durable_kv_legacy", "durable_kv_legacy.json", "durable_kv",
     _gen_durable_kv_legacy, ["durable.kv.namespaces", "durable.state"], {}),
    ("segment_state", "segment_state.pkl", "segment_snapshot",
     _gen_segment_state, ["snapshot.segment_meta"], {}),
    ("session_store", "session_store.pkl", "session_store",
     _gen_session_store, ["snapshot.session_store"], {"capacity": 64}),
    ("router_state", "router_state.pkl", "router_pickle", _gen_router_state,
     ["router.pickle"], {}),
    ("message_traced", "message_traced.pkl", "message_pickle",
     _gen_message_pickle, ["message.pickle"], {}),
    ("misc_structs", "misc_structs.bin", "misc_structs", _gen_misc_structs,
     ["transport.dtls.record_hdr", "mqtt.slab_serializer.u16be",
      "fabric.u16", "fabric.u32"], {}),
]

DRIFT_CASE = "pubb_slab"


# -- registry live cross-check ------------------------------------------

def _module_from_source(path: str):
    mod_name = path[:-3].replace("/", ".")
    return importlib.import_module(mod_name)


def _live_digest_failures() -> List[Dict]:
    """Recompute struct/dtype digests from the LIVE imported objects —
    the runtime view the AST checkers cannot reach."""
    import numpy as np

    from emqx_tpu.proto import registry
    from emqx_tpu.proto.digest import dtype_digest, struct_digest

    out = []
    for fmt in registry.formats():
        if fmt.kind not in ("struct", "dtype"):
            continue
        src = fmt.source.split("#", 1)[0]
        if ":" not in src:
            continue
        path, symbol = src.rsplit(":", 1)
        if symbol.endswith("*"):
            continue
        try:
            obj = getattr(_module_from_source(path), symbol)
        except (ImportError, AttributeError) as e:
            out.append({"format": fmt.name, "error": f"source rot: {e}"})
            continue
        if fmt.kind == "struct":
            live = struct_digest(obj.format)
        else:
            # numpy canonicalizes byte-order-free single-byte codes as
            # "|u1"; the registry declares them as written ("u1")
            live = dtype_digest(tuple(
                (n, c[1:] if c.startswith("|") else c)
                for n, c in np.dtype(obj).descr
            ))
        if live != fmt.digest:
            out.append({
                "format": fmt.name,
                "error": f"live {live} != registered {fmt.digest}",
            })
    return out


# -- the audit ----------------------------------------------------------

def _load_manifest(corpus_dir: Path) -> Dict:
    with open(corpus_dir / "manifest.json", encoding="utf-8") as f:
        return json.load(f)


def _decode_case(case: Dict, data: bytes) -> Any:
    dec = DECODERS.get(case["decoder"])
    if dec is None:
        raise ValueError(f"unknown decoder {case['decoder']!r}")
    # round-trip through JSON so float/tuple canon matches what the
    # expected files store
    return json.loads(json.dumps(dec(data, case.get("params", {}))))


def _expected_path(corpus_dir: Path, case: Dict) -> Path:
    return corpus_dir / "expected" / f"{case['name']}.json"


def _find_drift_offset(data: bytes, case: Dict, expected: Any) -> int:
    """Deterministic search for a byte whose flip the decoder detects —
    recorded in the manifest so the audit replays the same flip."""
    for off in range(len(data) // 2, len(data)):
        mutated = bytearray(data)
        mutated[off] ^= 0xFF
        try:
            if _decode_case(case, bytes(mutated)) != expected:
                return off
        except Exception:
            return off
    raise RuntimeError("no detectable drift offset found (corpus too forgiving)")


def run_wirecompat_audit(
    update: bool = False,
    corpus_dir: Optional[Path] = None,
    pins_path: Optional[Path] = None,
) -> Dict:
    corpus_dir = Path(corpus_dir or CORPUS_DIR)
    pins_path = Path(pins_path or PINS_PATH)
    if update:
        return _update_corpus(corpus_dir, pins_path)

    doc: Dict[str, Any] = {"ok": True, "cases": [], "failures": []}

    reg_fail = _live_digest_failures()
    doc["registry"] = {"live_mismatches": reg_fail}
    if reg_fail:
        doc["ok"] = False
        doc["failures"] += [f"registry: {f['format']}: {f['error']}" for f in reg_fail]

    try:
        manifest = _load_manifest(corpus_dir)
    except (OSError, json.JSONDecodeError) as e:
        doc["ok"] = False
        doc["failures"].append(f"manifest unreadable: {e}")
        return doc

    expected_by_name: Dict[str, Any] = {}
    for case in manifest.get("cases", []):
        entry = {"name": case["name"], "ok": True}
        try:
            data = (corpus_dir / case["file"]).read_bytes()
            with open(_expected_path(corpus_dir, case), encoding="utf-8") as f:
                expected = json.load(f)
            expected_by_name[case["name"]] = expected
            got = _decode_case(case, data)
            if got != expected:
                entry["ok"] = False
                entry["error"] = "decoded output diverged from pinned expectation"
        except Exception as e:  # decode failure IS the finding
            entry["ok"] = False
            entry["error"] = f"{type(e).__name__}: {e}"
        if not entry["ok"]:
            doc["ok"] = False
            doc["failures"].append(f"case {entry['name']}: {entry['error']}")
        doc["cases"].append(entry)

    # seeded drift negative control: the gate must catch its own plant
    ctl = manifest.get("drift_control") or {}
    drift = {"case": ctl.get("case"), "offset": ctl.get("offset"),
             "detected": False}
    case = next(
        (c for c in manifest.get("cases", []) if c["name"] == ctl.get("case")),
        None,
    )
    if case is not None and ctl.get("case") in expected_by_name:
        data = bytearray((corpus_dir / case["file"]).read_bytes())
        off = int(ctl["offset"])
        data[off] ^= 0xFF
        try:
            drift["detected"] = (
                _decode_case(case, bytes(data)) != expected_by_name[ctl["case"]]
            )
        except Exception:
            drift["detected"] = True
    doc["drift_control"] = drift
    if not drift["detected"]:
        doc["ok"] = False
        doc["failures"].append(
            "drift control NOT detected: the corpus gate cannot see byte-level "
            "drift — it is not protecting anything"
        )

    # staleness: every repo format must have corpus coverage
    from emqx_tpu.proto import registry

    covered = set()
    for c in manifest.get("cases", []):
        covered.update(c.get("covers", []))
    repo_formats = {f.name for f in registry.formats() if not f.name.startswith("fix.")}
    uncovered = sorted(repo_formats - covered)
    doc["staleness"] = {"formats": len(repo_formats), "uncovered": uncovered}
    if uncovered:
        doc["ok"] = False
        doc["failures"].append(
            "formats with no corpus coverage: " + ", ".join(uncovered)
        )
    return doc


# -- corpus regeneration ------------------------------------------------

def _update_corpus(corpus_dir: Path, pins_path: Path) -> Dict:
    """Regenerate the corpus with the CURRENT encoders. Refuses when a
    case's bytes change while every format it covers keeps its pinned
    version — that is silent wire drift, the exact failure this audit
    gates. Bump the registry version first; the pins follow."""
    from emqx_tpu.proto import registry

    try:
        with open(pins_path, encoding="utf-8") as f:
            pins = json.load(f).get("formats", {})
    except (OSError, json.JSONDecodeError):
        pins = {}

    bumped = {
        f.name
        for f in registry.formats()
        if f.name not in pins or pins[f.name].get("version") != f.version
    }

    doc: Dict[str, Any] = {"ok": True, "updated": [], "unchanged": [],
                           "refused": [], "failures": []}
    new_bytes: Dict[str, bytes] = {}
    for name, fname, decoder, gen, covers, params in CASES:
        data = gen()
        new_bytes[name] = data
        old = None
        fpath = corpus_dir / fname
        if fpath.exists():
            old = fpath.read_bytes()
        if old is not None and old != data and not (set(covers) & bumped):
            doc["refused"].append(name)
            doc["ok"] = False
            doc["failures"].append(
                f"case {name}: regenerated bytes differ but no covered format "
                f"({', '.join(covers)}) bumped its registry version"
            )
    if not doc["ok"]:
        return doc

    corpus_dir.mkdir(parents=True, exist_ok=True)
    (corpus_dir / "expected").mkdir(exist_ok=True)
    cases_out = []
    drift_ctl = None
    for name, fname, decoder, gen, covers, params in CASES:
        data = new_bytes[name]
        case = {"name": name, "file": fname, "decoder": decoder,
                "covers": covers, "params": params}
        fpath = corpus_dir / fname
        changed = not fpath.exists() or fpath.read_bytes() != data
        if changed:
            fpath.write_bytes(data)
            doc["updated"].append(name)
        else:
            doc["unchanged"].append(name)
        expected = _decode_case(case, data)
        with open(_expected_path(corpus_dir, case), "w", encoding="utf-8") as f:
            json.dump(expected, f, indent=1, sort_keys=True)
            f.write("\n")
        cases_out.append(case)
        if name == DRIFT_CASE:
            drift_ctl = {"case": name,
                         "offset": _find_drift_offset(data, case, expected)}

    manifest = {
        "version": 1,
        "note": "golden wire corpus: captured encoder output replayed "
                "through CURRENT decoders by `python -m tools.analysis "
                "--wirecompat`. Regenerate ONLY via --update-corpus, which "
                "enforces registry version bumps.",
        "cases": cases_out,
        "drift_control": drift_ctl,
    }
    with open(corpus_dir / "manifest.json", "w", encoding="utf-8") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.write("\n")

    # pins follow the registry — fixture ("fix.*") pins are tier-A
    # property and are preserved untouched
    pin_doc = {"version": 1, "note": "", "formats": {}}
    try:
        with open(pins_path, encoding="utf-8") as f:
            pin_doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        pass
    live = registry.pin_doc()["formats"]
    kept = {k: v for k, v in pin_doc.get("formats", {}).items()
            if k.startswith("fix.")}
    kept.update(live)
    pin_doc["formats"] = {k: kept[k] for k in sorted(kept)}
    with open(pins_path, "w", encoding="utf-8") as f:
        json.dump(pin_doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return doc


def render_wirecompat_text(doc: Dict) -> str:
    lines = []
    if "updated" in doc:  # --update-corpus report
        lines.append(
            f"wirecompat corpus update: {len(doc['updated'])} written, "
            f"{len(doc['unchanged'])} unchanged, {len(doc['refused'])} refused"
        )
    else:
        reg = doc.get("registry", {}).get("live_mismatches", [])
        cases = doc.get("cases", [])
        bad = [c for c in cases if not c["ok"]]
        drift = doc.get("drift_control", {})
        stale = doc.get("staleness", {})
        lines.append(
            f"wirecompat: {len(cases) - len(bad)}/{len(cases)} corpus cases "
            f"clean, {len(reg)} live registry mismatch(es), drift control "
            f"{'DETECTED' if drift.get('detected') else 'MISSED'}, "
            f"{len(stale.get('uncovered', []))} uncovered format(s) "
            f"of {stale.get('formats', 0)}"
        )
    for f in doc.get("failures", []):
        lines.append(f"  FAIL {f}")
    return "\n".join(lines)
