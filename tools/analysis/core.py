"""Analyzer core: parsed-module cache, finding model, baseline, runner.

Every checker gets the same `ParsedModule` objects (one `ast.parse` per
file, shared), emits `Finding`s, and may run a cross-module pre-pass
(`begin`) and post-pass (`finalize`) — the jit-purity call graph and the
dead-config-key scan need whole-project views.

Findings are identified by a *fingerprint* that deliberately excludes the
line number (`code|path|symbol|detail`), so the checked-in baseline
survives unrelated edits to the same file. Inline suppression:
`# lint: disable=CODE[,CODE...]` on the flagged line.
"""

from __future__ import annotations

import ast
import json
import re
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence

_DISABLE_RE = re.compile(r"#\s*lint:\s*disable=([A-Z0-9, ]+)")

# generated protobuf modules: huge, machine-written, not ours to lint
EXCLUDE_GLOBS = ("*_pb2.py",)


@dataclass(frozen=True)
class Finding:
    code: str  # e.g. "LK001"
    path: str  # posix path relative to the scan root's parent
    line: int
    symbol: str  # enclosing "Class.method" / "func" / "<module>"
    detail: str  # stable token (attr/call/key name) for the fingerprint
    message: str

    @property
    def fingerprint(self) -> str:
        return f"{self.code}|{self.path}|{self.symbol}|{self.detail}"

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}: {self.code} [{self.symbol}] "
            f"{self.message}"
        )

    def to_json(self) -> Dict:
        return {
            "code": self.code,
            "path": self.path,
            "line": self.line,
            "symbol": self.symbol,
            "detail": self.detail,
            "message": self.message,
            "fingerprint": self.fingerprint,
        }


class ParsedModule:
    """One parsed source file, shared by every checker."""

    def __init__(self, path: Path, rel: str, source: str):
        self.path = path
        self.rel = rel  # posix, relative to scan root's parent
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.Module] = None
        self.syntax_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(source, filename=str(path))
        except SyntaxError as e:
            self.syntax_error = e

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def disabled_codes(self, lineno: int) -> frozenset:
        """Codes suppressed on this physical line via `# lint: disable=`."""
        m = _DISABLE_RE.search(self.line_text(lineno))
        if not m:
            return frozenset()
        return frozenset(c.strip() for c in m.group(1).split(",") if c.strip())

    def suppressed(self, lineno: int, code: str) -> bool:
        codes = self.disabled_codes(lineno)
        return code in codes or "ALL" in codes


class Checker:
    """Base checker. Subclasses set `name` + `codes` and override
    `check` (per module) and/or `begin`/`finalize` (cross-module)."""

    name: str = ""
    codes: Dict[str, str] = {}

    def begin(self, modules: Sequence[ParsedModule]) -> None:
        pass

    def check(self, mod: ParsedModule) -> Iterable[Finding]:
        return ()

    def finalize(self) -> Iterable[Finding]:
        return ()


class Baseline:
    """Checked-in grandfather list: fingerprint -> justification."""

    def __init__(self, entries: Optional[Dict[str, str]] = None,
                 path: Optional[Path] = None):
        self.entries: Dict[str, str] = dict(entries or {})
        self.path = path

    @classmethod
    def load(cls, path: Path) -> "Baseline":
        if not path.exists():
            return cls(path=path)
        data = json.loads(path.read_text())
        return cls(entries=data.get("entries", {}), path=path)

    def save(self, path: Optional[Path] = None) -> None:
        path = path or self.path
        assert path is not None
        doc = {
            "version": 1,
            "note": (
                "Grandfathered tpu_lint findings. Keys are finding "
                "fingerprints (code|path|symbol|detail); values JUSTIFY "
                "why the finding is intentional. New code must not add "
                "entries without a real justification."
            ),
            "entries": dict(sorted(self.entries.items())),
        }
        path.write_text(json.dumps(doc, indent=2) + "\n")

    def __contains__(self, finding: Finding) -> bool:
        return finding.fingerprint in self.entries


@dataclass
class Report:
    findings: List[Finding] = field(default_factory=list)  # non-baseline
    baselined: List[Finding] = field(default_factory=list)
    suppressed: int = 0
    files: int = 0
    checks: List[str] = field(default_factory=list)
    stale_baseline: List[str] = field(default_factory=list)
    elapsed: float = 0.0

    @property
    def clean(self) -> bool:
        return not self.findings

    def to_json(self) -> Dict:
        return {
            "clean": self.clean,
            "files": self.files,
            "checks": self.checks,
            "elapsed_seconds": round(self.elapsed, 3),
            "suppressed": self.suppressed,
            "baselined": len(self.baselined),
            "stale_baseline": self.stale_baseline,
            "findings": [f.to_json() for f in self.findings],
        }

    def render_text(self) -> str:
        out = []
        for f in sorted(self.findings, key=lambda f: (f.path, f.line, f.code)):
            out.append(f.render())
        out.append(
            f"tpu_lint: {len(self.findings)} finding(s), "
            f"{len(self.baselined)} baselined, {self.suppressed} "
            f"suppressed, {self.files} files, "
            f"{self.elapsed:.2f}s [{', '.join(self.checks)}]"
        )
        if self.stale_baseline:
            out.append(
                f"note: {len(self.stale_baseline)} stale baseline "
                "entr(y/ies) no longer match any finding — prune them:"
            )
            out.extend(f"  {fp}" for fp in self.stale_baseline)
        return "\n".join(out)


def iter_sources(root: Path) -> List[Path]:
    paths = []
    for p in sorted(root.rglob("*.py")):
        if any(p.match(g) for g in EXCLUDE_GLOBS):
            continue
        paths.append(p)
    return paths


def parse_modules(root: Path) -> List[ParsedModule]:
    root = root.resolve()
    base = root.parent
    mods = []
    for p in iter_sources(root):
        rel = p.relative_to(base).as_posix()
        mods.append(ParsedModule(p, rel, p.read_text(errors="replace")))
    return mods


def default_checkers() -> List[Checker]:
    from tools.analysis.checkers.async_blocking import AsyncBlockingChecker
    from tools.analysis.checkers.config_keys import ConfigKeyChecker
    from tools.analysis.checkers.jit_purity import JitPurityChecker
    from tools.analysis.checkers.lock_discipline import LockDisciplineChecker
    from tools.analysis.checkers.metric_names import MetricNameChecker

    return [
        LockDisciplineChecker(),
        AsyncBlockingChecker(),
        JitPurityChecker(),
        ConfigKeyChecker(),
        MetricNameChecker(),
    ]


def run_analysis(
    root: Path,
    checkers: Optional[Sequence[Checker]] = None,
    baseline: Optional[Baseline] = None,
    checks: Optional[Sequence[str]] = None,
) -> Report:
    """Run the selected checkers over every .py under `root`."""
    t0 = time.monotonic()
    if checkers is None:
        checkers = default_checkers()
    if checks:
        want = set(checks)
        unknown = want - {c.name for c in checkers}
        if unknown:
            raise ValueError(
                f"unknown check(s) {sorted(unknown)}; available: "
                f"{sorted(c.name for c in checkers)}"
            )
        checkers = [c for c in checkers if c.name in want]
    baseline = baseline or Baseline()
    modules = parse_modules(Path(root))
    by_rel = {m.rel: m for m in modules}

    raw: List[Finding] = []
    # parse failures are findings, not crashes: a file the analyzer cannot
    # see is a file none of the checkers guard
    for m in modules:
        if m.syntax_error is not None:
            raw.append(Finding(
                code="GEN001",
                path=m.rel,
                line=m.syntax_error.lineno or 0,
                symbol="<module>",
                detail="syntax-error",
                message=f"unparseable file: {m.syntax_error.msg}",
            ))
    parseable = [m for m in modules if m.tree is not None]
    for c in checkers:
        c.begin(parseable)
    for c in checkers:
        for m in parseable:
            raw.extend(c.check(m))
    for c in checkers:
        raw.extend(c.finalize())

    report = Report(files=len(modules), checks=[c.name for c in checkers])
    seen_fps = set()
    for f in raw:
        mod = by_rel.get(f.path)
        if mod is not None and mod.suppressed(f.line, f.code):
            report.suppressed += 1
            continue
        seen_fps.add(f.fingerprint)
        if f in baseline:
            report.baselined.append(f)
        else:
            report.findings.append(f)
    report.stale_baseline = sorted(
        fp for fp in baseline.entries if fp not in seen_fps
    )
    report.elapsed = time.monotonic() - t0
    return report


# -- shared AST helpers (used by several checkers) --------------------------

def dotted_name(node: ast.AST) -> Optional[str]:
    """Attribute/Name chain -> 'a.b.c', else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def import_aliases(tree: ast.Module) -> Dict[str, str]:
    """Local name -> canonical dotted name, from module-level imports.
    `import time as t` -> {'t': 'time'};
    `from time import sleep` -> {'sleep': 'time.sleep'}."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def resolve_call_name(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """Canonical dotted name of a call target, import-alias aware."""
    dn = dotted_name(node)
    if dn is None:
        return None
    head, _, rest = dn.partition(".")
    canon = aliases.get(head, head)
    return f"{canon}.{rest}" if rest else canon


def enclosing_symbols(tree: ast.Module) -> Dict[ast.AST, str]:
    """Map each function/class def node -> dotted symbol name."""
    out: Dict[ast.AST, str] = {}

    def walk(node: ast.AST, prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                sym = f"{prefix}.{child.name}" if prefix else child.name
                out[child] = sym
                walk(child, sym)
            else:
                walk(child, prefix)

    walk(tree, "")
    return out
