"""Analyzer core: parsed-module cache, finding model, baseline, runner.

Every checker gets the same `ParsedModule` objects (one `ast.parse` per
file, shared), emits `Finding`s, and may run a cross-module pre-pass
(`begin`) and post-pass (`finalize`) — the jit-purity call graph and the
dead-config-key scan need whole-project views.

Findings are identified by a *fingerprint* that deliberately excludes the
line number (`code|path|symbol|detail`), so the checked-in baseline
survives unrelated edits to the same file. Inline suppression:
`# lint: disable=CODE[,CODE...]` anywhere on the flagged *statement* —
for a multi-line call the directive may sit on any physical line of the
statement (e.g. after the closing paren), not just the line the finding
points at.
"""

from __future__ import annotations

import ast
import json
import re
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence

_DISABLE_RE = re.compile(r"#\s*lint:\s*disable=([A-Z0-9, ]+)")

# generated protobuf modules: huge, machine-written, not ours to lint
EXCLUDE_GLOBS = ("*_pb2.py",)


@dataclass(frozen=True)
class Finding:
    code: str  # e.g. "LK001"
    path: str  # posix path relative to the scan root's parent
    line: int
    symbol: str  # enclosing "Class.method" / "func" / "<module>"
    detail: str  # stable token (attr/call/key name) for the fingerprint
    message: str

    @property
    def fingerprint(self) -> str:
        return f"{self.code}|{self.path}|{self.symbol}|{self.detail}"

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}: {self.code} [{self.symbol}] "
            f"{self.message}"
        )

    def to_json(self) -> Dict:
        return {
            "code": self.code,
            "path": self.path,
            "line": self.line,
            "symbol": self.symbol,
            "detail": self.detail,
            "message": self.message,
            "fingerprint": self.fingerprint,
        }


class ParsedModule:
    """One parsed source file, shared by every checker."""

    def __init__(self, path: Path, rel: str, source: str):
        self.path = path
        self.rel = rel  # posix, relative to scan root's parent
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.Module] = None
        self.syntax_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(source, filename=str(path))
        except SyntaxError as e:
            self.syntax_error = e

        self._spans: Optional[List[tuple]] = None

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def disabled_codes(self, lineno: int) -> frozenset:
        """Codes suppressed on this physical line via `# lint: disable=`."""
        m = _DISABLE_RE.search(self.line_text(lineno))
        if not m:
            return frozenset()
        return frozenset(c.strip() for c in m.group(1).split(",") if c.strip())

    def _stmt_spans(self) -> List[tuple]:
        """(start, end) physical-line spans of every statement.

        Simple statements span their full source extent; compound
        statements (if/for/def/...) contribute only their HEADER lines
        (up to the first body statement), so a directive inside a block
        never suppresses findings on the block's header and vice versa.
        """
        if self._spans is not None:
            return self._spans
        spans: List[tuple] = []
        if self.tree is not None:
            for node in ast.walk(self.tree):
                if not isinstance(node, ast.stmt):
                    continue
                start = node.lineno
                end = getattr(node, "end_lineno", None) or start
                body = getattr(node, "body", None)
                if body and isinstance(body, list) and body \
                        and isinstance(body[0], ast.AST):
                    end = max(start, body[0].lineno - 1)
                spans.append((start, end))
        self._spans = spans
        return spans

    def stmt_lines(self, lineno: int) -> range:
        """Physical lines of the innermost statement containing `lineno`."""
        best = None
        for start, end in self._stmt_spans():
            if start <= lineno <= end:
                if best is None or (end - start) < (best[1] - best[0]):
                    best = (start, end)
        if best is None:
            return range(lineno, lineno + 1)
        return range(best[0], best[1] + 1)

    def suppressed(self, lineno: int, code: str) -> bool:
        # honor directives on ANY line of the flagged statement, so a
        # `# lint: disable=` after the closing paren of a multi-line
        # call still matches the finding (reported at the first line)
        for ln in self.stmt_lines(lineno):
            codes = self.disabled_codes(ln)
            if code in codes or "ALL" in codes:
                return True
        return False


class Checker:
    """Base checker. Subclasses set `name` + `codes` and override
    `check` (per module) and/or `begin`/`finalize` (cross-module)."""

    name: str = ""
    codes: Dict[str, str] = {}

    def begin(self, modules: Sequence[ParsedModule]) -> None:
        pass

    def check(self, mod: ParsedModule) -> Iterable[Finding]:
        return ()

    def finalize(self) -> Iterable[Finding]:
        return ()


class Baseline:
    """Checked-in grandfather list: fingerprint -> justification."""

    def __init__(self, entries: Optional[Dict[str, str]] = None,
                 path: Optional[Path] = None):
        self.entries: Dict[str, str] = dict(entries or {})
        self.path = path

    @classmethod
    def load(cls, path: Path) -> "Baseline":
        if not path.exists():
            return cls(path=path)
        data = json.loads(path.read_text())
        return cls(entries=data.get("entries", {}), path=path)

    def save(self, path: Optional[Path] = None) -> None:
        path = path or self.path
        assert path is not None
        doc = {
            "version": 1,
            "note": (
                "Grandfathered tpu_lint findings. Keys are finding "
                "fingerprints (code|path|symbol|detail); values JUSTIFY "
                "why the finding is intentional. New code must not add "
                "entries without a real justification."
            ),
            "entries": dict(sorted(self.entries.items())),
        }
        path.write_text(json.dumps(doc, indent=2) + "\n")

    def __contains__(self, finding: Finding) -> bool:
        return finding.fingerprint in self.entries


@dataclass
class Report:
    findings: List[Finding] = field(default_factory=list)  # non-baseline
    baselined: List[Finding] = field(default_factory=list)
    suppressed: int = 0
    files: int = 0
    checks: List[str] = field(default_factory=list)
    stale_baseline: List[str] = field(default_factory=list)
    elapsed: float = 0.0

    @property
    def clean(self) -> bool:
        return not self.findings

    def to_json(self) -> Dict:
        return {
            "clean": self.clean,
            "files": self.files,
            "checks": self.checks,
            "elapsed_seconds": round(self.elapsed, 3),
            "suppressed": self.suppressed,
            "baselined": len(self.baselined),
            "stale_baseline": self.stale_baseline,
            "findings": [f.to_json() for f in self.findings],
        }

    def render_text(self) -> str:
        out = []
        for f in sorted(self.findings, key=lambda f: (f.path, f.line, f.code)):
            out.append(f.render())
        out.append(
            f"tpu_lint: {len(self.findings)} finding(s), "
            f"{len(self.baselined)} baselined, {self.suppressed} "
            f"suppressed, {self.files} files, "
            f"{self.elapsed:.2f}s [{', '.join(self.checks)}]"
        )
        if self.stale_baseline:
            out.append(
                f"note: {len(self.stale_baseline)} stale baseline "
                "entr(y/ies) no longer match any finding — prune them:"
            )
            out.extend(f"  {fp}" for fp in self.stale_baseline)
        return "\n".join(out)


def iter_sources(root: Path) -> List[Path]:
    paths = []
    for p in sorted(root.rglob("*.py")):
        if any(p.match(g) for g in EXCLUDE_GLOBS):
            continue
        paths.append(p)
    return paths


def parse_modules(root: Path, jobs: int = 0) -> List[ParsedModule]:
    """Parse every source under `root`; `jobs > 1` parses concurrently.

    The checker set keeps growing, and one `ast.parse` per file is the
    analyzer's fixed cost — a thread pool overlaps the file reads and
    the (C-level) parses so the tier-1 time budget survives the growth.
    Results keep `iter_sources` order regardless of completion order.
    """
    root = root.resolve()
    base = root.parent
    paths = iter_sources(root)

    def load(p: Path) -> ParsedModule:
        rel = p.relative_to(base).as_posix()
        return ParsedModule(p, rel, p.read_text(errors="replace"))

    if jobs and jobs > 1 and len(paths) > 1:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=jobs) as pool:
            return list(pool.map(load, paths))
    return [load(p) for p in paths]


def default_checkers() -> List[Checker]:
    from tools.analysis.checkers.async_blocking import AsyncBlockingChecker
    from tools.analysis.checkers.bpapi_symmetry import BpapiSymmetryChecker
    from tools.analysis.checkers.buffer_view import BufferViewChecker
    from tools.analysis.checkers.config_keys import ConfigKeyChecker
    from tools.analysis.checkers.cross_context import CrossContextChecker
    from tools.analysis.checkers.fault_contracts import FaultContractChecker
    from tools.analysis.checkers.host_transfer import HostTransferChecker
    from tools.analysis.checkers.jit_purity import JitPurityChecker
    from tools.analysis.checkers.lock_discipline import LockDisciplineChecker
    from tools.analysis.checkers.metric_names import MetricNameChecker
    from tools.analysis.checkers.oplog_complete import OplogCompleteChecker
    from tools.analysis.checkers.retrace import RetraceChecker
    from tools.analysis.checkers.sharding import ShardingChecker
    from tools.analysis.checkers.snapshot_schema import SnapshotSchemaChecker
    from tools.analysis.checkers.version_epoch import VersionDisciplineChecker
    from tools.analysis.checkers.wire_format import WireFormatChecker

    return [
        LockDisciplineChecker(),
        AsyncBlockingChecker(),
        JitPurityChecker(),
        ConfigKeyChecker(),
        MetricNameChecker(),
        ShardingChecker(),
        HostTransferChecker(),
        RetraceChecker(),
        FaultContractChecker(),
        CrossContextChecker(),
        OplogCompleteChecker(),
        VersionDisciplineChecker(),
        BufferViewChecker(),
        WireFormatChecker(),
        SnapshotSchemaChecker(),
        BpapiSymmetryChecker(),
    ]


def run_analysis(
    root: Path,
    checkers: Optional[Sequence[Checker]] = None,
    baseline: Optional[Baseline] = None,
    checks: Optional[Sequence[str]] = None,
    jobs: int = 0,
    only_paths: Optional[Sequence[str]] = None,
) -> Report:
    """Run the selected checkers over every .py under `root`.

    `only_paths` (rel posix paths, as in `Finding.path`) restricts the
    *reported* findings to those files — the whole tree is still parsed
    and every cross-module pre/post pass still sees it, so call-graph
    and registry checkers stay exact on a git-diff-scoped run. Staleness
    of the baseline is not judged on a scoped run (a partial view cannot
    tell a pruned finding from an out-of-scope one).
    """
    t0 = time.monotonic()
    if checkers is None:
        checkers = default_checkers()
    if checks:
        want = set(checks)
        unknown = want - {c.name for c in checkers}
        if unknown:
            raise ValueError(
                f"unknown check(s) {sorted(unknown)}; available: "
                f"{sorted(c.name for c in checkers)}"
            )
        checkers = [c for c in checkers if c.name in want]
    baseline = baseline or Baseline()
    modules = parse_modules(Path(root), jobs=jobs)
    by_rel = {m.rel: m for m in modules}
    only = frozenset(only_paths) if only_paths is not None else None

    raw: List[Finding] = []
    # parse failures are findings, not crashes: a file the analyzer cannot
    # see is a file none of the checkers guard
    for m in modules:
        if m.syntax_error is not None:
            raw.append(Finding(
                code="GEN001",
                path=m.rel,
                line=m.syntax_error.lineno or 0,
                symbol="<module>",
                detail="syntax-error",
                message=f"unparseable file: {m.syntax_error.msg}",
            ))
    parseable = [m for m in modules if m.tree is not None]
    for c in checkers:
        c.begin(parseable)
    for c in checkers:
        for m in parseable:
            raw.extend(c.check(m))
    for c in checkers:
        raw.extend(c.finalize())

    report = Report(files=len(modules), checks=[c.name for c in checkers])
    seen_fps = set()
    for f in raw:
        mod = by_rel.get(f.path)
        if mod is not None and mod.suppressed(f.line, f.code):
            report.suppressed += 1
            continue
        seen_fps.add(f.fingerprint)
        if only is not None and f.path not in only:
            continue
        if f in baseline:
            report.baselined.append(f)
        else:
            report.findings.append(f)
    if only is None and not checks:
        # staleness is only judged on a full, unscoped run: a checks
        # subset or a changed-only view cannot tell a pruned finding
        # from one its scope simply didn't produce
        report.stale_baseline = sorted(
            fp for fp in baseline.entries if fp not in seen_fps
        )
    report.elapsed = time.monotonic() - t0
    return report


# -- shared AST helpers (used by several checkers) --------------------------

def dotted_name(node: ast.AST) -> Optional[str]:
    """Attribute/Name chain -> 'a.b.c', else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def import_aliases(tree: ast.Module) -> Dict[str, str]:
    """Local name -> canonical dotted name, from module-level imports.
    `import time as t` -> {'t': 'time'};
    `from time import sleep` -> {'sleep': 'time.sleep'}."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def resolve_call_name(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """Canonical dotted name of a call target, import-alias aware."""
    dn = dotted_name(node)
    if dn is None:
        return None
    head, _, rest = dn.partition(".")
    canon = aliases.get(head, head)
    return f"{canon}.{rest}" if rest else canon


def enclosing_symbols(tree: ast.Module) -> Dict[ast.AST, str]:
    """Map each function/class def node -> dotted symbol name."""
    out: Dict[ast.AST, str] = {}

    def walk(node: ast.AST, prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                sym = f"{prefix}.{child.name}" if prefix else child.name
                out[child] = sym
                walk(child, sym)
            else:
                walk(child, prefix)

    walk(tree, "")
    return out
