"""Tier B of the device-contract auditor: read the COMPILED artifact.

The AST checkers (SD/HT/RT) pin what the *source* may say; this module
pins what the *jaxpr* may contain. Every kernel registered through
`emqx_tpu.ops.contract.device_contract` is traced with `jax.make_jaxpr`
/ `jax.eval_shape` over a small config matrix (batch size, bitmap
width, Kslot, mesh shape) — abstract tracing on CPU, nothing executes —
and the trace is held against the declaration and a golden snapshot:

  * dtype discipline — forbidden dtypes (f64/i64 widenings by default)
    may appear nowhere: not as a `convert_element_type` target, not in
    any intermediate or output aval;
  * collective set — the union of collective primitives over the matrix
    must EQUAL the contract's declaration (a new `psum` is a new ICI
    dependency; a vanished one means the declaration rots);
  * readback bounds — declared outputs must stay under their byte
    bounds (`slots` is O(B*Kslot), never O(B*W));
  * trace stability — tracing the same config twice must produce an
    identical jaxpr, and distinct configs must produce exactly one
    program each (a retrace-regression gate);
  * golden snapshots — the normalized trace summary (primitive counts,
    collectives, output avals, digest) is diffed against
    `tests/fixtures/analysis/jaxprs/<kernel>.json`; refresh with
    `python -m tools.analysis --contracts --update-snapshots` after a
    DELIBERATE kernel change.

Configs that need more devices than the process has are skipped with a
note (the tier-1 suite provides the virtual 8-device CPU mesh).
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional

ROOT = Path(__file__).resolve().parents[2]
DEFAULT_SNAPSHOT_DIR = ROOT / "tests" / "fixtures" / "analysis" / "jaxprs"

COLLECTIVE_PRIMS = {
    "psum", "pmax", "pmin", "pmean", "all_gather", "all_to_all",
    "ppermute", "pshuffle", "psum_scatter", "axis_index",
}
# trace-level spellings -> the contract's canonical collective names.
# `pbroadcast` is deliberately NOT a collective here: shard_map's
# replication-rule machinery inserts it implicitly (hundreds per trace)
# and it lowers to a device-local no-op, so it is not a contractual ICI
# dependency the way a psum is.
CANON_PRIM = {"psum2": "psum", "all_gather_invariant": "all_gather"}


@dataclass
class AuditReport:
    problems: List[str] = field(default_factory=list)
    skipped: List[str] = field(default_factory=list)
    kernels: Dict[str, Dict] = field(default_factory=dict)
    updated: List[str] = field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.problems

    def to_json(self) -> Dict:
        return {
            "clean": self.clean,
            "problems": self.problems,
            "skipped": self.skipped,
            "updated": self.updated,
            "kernels": self.kernels,
        }


def render_audit(doc: Dict) -> str:
    out = []
    for name, summary in sorted(doc.get("kernels", {}).items()):
        out.append(
            f"contract {name}: {len(summary)} config(s) traced"
        )
    for note in doc.get("skipped", []):
        out.append(f"contract skip: {note}")
    for name in doc.get("updated", []):
        out.append(f"contract snapshot updated: {name}")
    n = len(doc.get("problems", []))
    for p in doc.get("problems", []):
        out.append(f"contract VIOLATION: {p}")
    out.append(
        f"device-contract audit: {n} problem(s), "
        f"{len(doc.get('kernels', {}))} kernel(s)"
    )
    return "\n".join(out)


def _ensure_jax():
    """Import jax for ABSTRACT tracing: CPU platform, enough virtual
    devices for the mesh configs. Only effective before first import —
    inside the test suite the conftest already provides the 8-device
    CPU topology."""
    if "jax" not in sys.modules:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    import jax  # noqa: F401

    return jax


# -- jaxpr introspection ----------------------------------------------------

def _iter_jaxprs(jaxpr):
    """Yield a jaxpr and every sub-jaxpr reachable through eqn params."""
    yield jaxpr
    for eqn in jaxpr.eqns:
        for val in eqn.params.values():
            for sub in _as_jaxprs(val):
                yield from _iter_jaxprs(sub)


def _as_jaxprs(val):
    import jax.core as jcore

    closed = getattr(jcore, "ClosedJaxpr", None)
    if closed is not None and isinstance(val, closed):
        return [val.jaxpr]
    if isinstance(val, jcore.Jaxpr):
        return [val]
    if isinstance(val, (tuple, list)):
        out = []
        for v in val:
            out.extend(_as_jaxprs(v))
        return out
    return []


def _trace_summary(closed_jaxpr, out_shapes) -> Dict:
    """Normalize one trace into the snapshot form."""
    prims: Dict[str, int] = {}
    bad_dtypes: Dict[str, List[str]] = {}
    for j in _iter_jaxprs(closed_jaxpr.jaxpr):
        for eqn in j.eqns:
            pname = CANON_PRIM.get(eqn.primitive.name, eqn.primitive.name)
            prims[pname] = prims.get(pname, 0) + 1
            for var in list(eqn.outvars) + list(eqn.invars):
                aval = getattr(var, "aval", None)
                dt = getattr(aval, "dtype", None)
                if dt is not None:
                    bad_dtypes.setdefault(str(dt), []).append(
                        eqn.primitive.name
                    )
    collectives = sorted(set(prims) & COLLECTIVE_PRIMS)
    outputs = {}
    from jax.tree_util import tree_flatten_with_path

    leaves, _ = tree_flatten_with_path(out_shapes)
    for path, leaf in leaves:
        name = ".".join(_path_part(p) for p in path) or "out"
        outputs[name] = f"{leaf.dtype}[{','.join(map(str, leaf.shape))}]"
    import re

    # `lax.reduce(..., bitwise_or, ...)` prints its computation as
    # `<function bitwise_or at 0x7f...>` — strip the per-process address
    # (and any other embedded object id) or the digest is not portable
    text = re.sub(r" at 0x[0-9a-fA-F]+", "", str(closed_jaxpr))
    # multi-axis collective params print their axis names in SET order,
    # which follows the per-process string-hash seed — sort them
    text = re.sub(
        r"axes=\(([^)]*)\)",
        lambda m: "axes=(%s)" % ", ".join(
            sorted(p.strip() for p in m.group(1).split(",") if p.strip())
        ),
        text,
    )
    return {
        "primitives": dict(sorted(prims.items())),
        "collectives": collectives,
        "outputs": dict(sorted(outputs.items())),
        "digest": hashlib.sha256(text.encode()).hexdigest()[:16],
        "_dtypes": sorted(bad_dtypes),  # all dtypes seen (for the check)
    }


def _path_part(p) -> str:
    for attr in ("key", "idx", "name"):
        if hasattr(p, attr):
            return str(getattr(p, attr))
    return str(p)


# -- kernel harnesses -------------------------------------------------------
# One tiny host-built workload (real table builders, so invariants like
# pow2 capacities hold) shared by every kernel; per-kernel closures bind
# the static args and name the outputs.

def _workload(max_subs: int = 512):
    if str(ROOT) not in sys.path:
        sys.path.insert(0, str(ROOT))
    import __graft_entry__ as ge

    return ge._workload(max_subs=max_subs)


def _configs_single() -> List[Dict]:
    return [
        {"B": 8, "kslot": 0},
        {"B": 8, "kslot": 8},
        {"B": 16, "kslot": 8},
    ]


def _configs_mesh() -> List[Dict]:
    return [
        {"B": 8, "kslot": 0, "dp": 1, "tp": 1},
        {"B": 8, "kslot": 8, "dp": 2, "tp": 2},
    ]


def _cfg_key(cfg: Dict) -> str:
    parts = [f"B{cfg['B']}", f"k{cfg['kslot']}"]
    if "D" in cfg:
        parts.append(f"D{cfg['D']}")
    if "dp" in cfg:
        parts.append(f"dp{cfg['dp']}tp{cfg['tp']}")
    return "_".join(parts)


def _harness(name: str):
    """-> (configs, build(cfg) -> (traceable, args)) for a kernel, or
    None for registered kernels the audit has no recipe for."""
    import numpy as np

    if name == "segment_scatter_insert":
        # B = the pow2 delta bucket; two buckets pin the recompile story
        configs = [
            {"B": 16, "kslot": 0},
            {"B": 64, "kslot": 0},
        ]
    elif name == "session_ack_step":
        # B = the pow2 rider-write bucket; kslot doubles as sweep_k
        # (kslot=0: pure scatter ride, no sweep stage traces)
        configs = [
            {"B": 16, "kslot": 0},
            {"B": 16, "kslot": 8},
            {"B": 64, "kslot": 8},
        ]
    elif name == "compact_fanout_slots":
        # kslot=0 means "compaction off" — the stage never traces
        configs = [
            {"B": 8, "kslot": 8},
            {"B": 16, "kslot": 8},
            {"B": 8, "kslot": 32},
        ]
    elif name == "sparse_fanout_slots":
        # the CSR gather-union stage exists only with a positive cap
        configs = [
            {"B": 8, "kslot": 8},
            {"B": 16, "kslot": 8},
            {"B": 8, "kslot": 32},
        ]
    elif name == "semantic_match_step":
        # kslot doubles as topk; the matrix pins the embedding-dim axis
        # too (docs/semantic_routing.md)
        configs = [
            {"B": 8, "kslot": 4, "D": 16},
            {"B": 8, "kslot": 8, "D": 16},
            {"B": 8, "kslot": 4, "D": 32},
        ]
    elif name == "sem_dist_shape_step":
        # the serving builder traced WITH a semantic table (+ one
        # compiled rule predicate): 1x1 and 2x2 mesh rows
        configs = [
            {"B": 8, "kslot": 8, "D": 16, "dp": 1, "tp": 1},
            {"B": 8, "kslot": 8, "D": 16, "dp": 2, "tp": 2},
        ]
    elif name == "sparse_shape_route_step":
        # the serving jit traced against a CSR subscriber table
        configs = [
            {"B": 8, "kslot": 8},
            {"B": 16, "kslot": 8},
        ]
    elif name in (
        "route_step", "shape_route_step", "fused_route_retained_step"
    ):
        configs = _configs_single()
    elif name in (
        "dist_step", "dist_shape_step", "dist_fused_step",
        "sparse_dist_shape_step",
    ):
        configs = (
            [
                {"B": 8, "kslot": 8, "dp": 2, "tp": 2},
                {"B": 8, "kslot": 16, "dp": 2, "tp": 2},
            ]
            if name == "sparse_dist_shape_step"
            else _configs_mesh()
        )
    else:
        return None

    def build(cfg):
        from functools import partial

        index, subs, bytes_mat, lengths, m_active = _workload()
        B = cfg["B"]
        bytes_mat = bytes_mat[:B]
        lengths = np.asarray(lengths[:B])
        bits = subs.pack(index.num_filters_capacity)
        salt = index.salt
        kw = dict(max_levels=8, frontier=8, max_matches=8, probes=8)
        if name == "segment_scatter_insert":
            from emqx_tpu.ops.segments import segment_scatter_impl

            nb = cfg["B"]
            flats = {
                "shape_tab": np.full(4096, -1, np.int32),
                "sub_bitmaps": np.zeros(2048, np.uint32),
            }
            idxs = {
                k: np.arange(nb, dtype=np.int32) for k in flats
            }
            vals = {
                k: np.ones(nb, v.dtype) for k, v in flats.items()
            }
            return segment_scatter_impl, (flats, idxs, vals)
        if name == "session_ack_step":
            from emqx_tpu.ops.session_table import (
                ROW_LANES,
                SessionTable,
                session_ack_impl,
            )

            t = SessionTable(capacity=1024, slots=256)
            tables = {
                k: v.copy() for k, v in t.device_snapshot().items()
            }
            nb = cfg["B"]
            idxs = {k: np.arange(nb, dtype=np.int32) for k in ROW_LANES}
            vals = {k: np.ones(nb, np.int32) for k in ROW_LANES}
            clock = np.asarray([100, 300], np.int32)
            fn = partial(session_ack_impl, sweep_k=cfg["kslot"])
            return fn, (tables, idxs, vals, clock)
        if name == "compact_fanout_slots":
            from emqx_tpu.models.router_model import compact_fanout_slots

            W = bits.shape[1]
            bm = np.zeros((B, W), np.uint32)

            def fn(bm):
                slots, count, over = compact_fanout_slots(
                    bm, cfg["kslot"]
                )
                return {"slots": slots, "count": count, "overflow": over}

            return fn, (bm,)
        if name == "sparse_fanout_slots":
            from emqx_tpu.models.router_model import SubscriberTable
            from emqx_tpu.ops.csr_table import sparse_fanout_slots

            st = SubscriberTable(mode="sparse")
            for i in range(64):
                st.add(i % 16, i)
            csr = {
                k: v.copy() for k, v in st.device_snapshot().items()
            }
            matched = np.full((B, 8), -1, np.int32)
            matched[:, 0] = np.arange(B, dtype=np.int32) % 16

            def sfn(csr, matched):
                slots, count, over, live = sparse_fanout_slots(
                    csr, matched, kslot=cfg["kslot"]
                )
                return {
                    "slots": slots,
                    "count": count,
                    "overflow": over,
                    "live": live,
                }

            return sfn, (csr, matched)
        if name == "semantic_match_step":
            from emqx_tpu.ops.semantic_table import (
                SemanticTable,
                semantic_match_step,
            )

            sem = _sem_workload(cfg["D"], cfg["kslot"], shards=1)
            st_sem = {
                k: v.copy() for k, v in sem.device_snapshot().items()
            }
            matched = np.full((B, 8), -1, np.int32)
            matched[:, 0] = np.arange(B, dtype=np.int32) % 4
            qv = np.zeros((B, cfg["D"]), np.float32)

            def qfn(st_sem, qv, matched):
                sl, cnt = semantic_match_step(
                    st_sem, qv, matched, cfg["kslot"]
                )
                return {"sem_slots": sl, "sem_count": cnt}

            return qfn, (st_sem, qv, matched)
        if name == "sparse_shape_route_step":
            from emqx_tpu.models.router_model import shape_route_step

            subs.set_mode("sparse")
            subs.pack(index.num_filters_capacity)
            csr = {
                k: v.copy() for k, v in subs.device_snapshot().items()
            }
            with_nfa = index.residual_count > 0
            fn = partial(
                shape_route_step,
                m_active=m_active,
                with_nfa=with_nfa,
                salt=salt,
                kslot=cfg["kslot"],
                **kw,
            )
            nfa = index.nfa.device_snapshot() if with_nfa else None
            return fn, (
                index.shapes.device_snapshot(), nfa, csr,
                bytes_mat, lengths,
            )
        if name == "route_step":
            from emqx_tpu.models.router_model import route_step

            tables = index.nfa.device_snapshot()
            fn = partial(
                route_step, salt=salt, kslot=cfg["kslot"], **kw
            )
            return fn, (tables, bits, bytes_mat, lengths)
        if name == "shape_route_step":
            from emqx_tpu.models.router_model import shape_route_step

            with_nfa = index.residual_count > 0
            fn = partial(
                shape_route_step,
                m_active=m_active,
                with_nfa=with_nfa,
                salt=salt,
                kslot=cfg["kslot"],
                **kw,
            )
            nfa = index.nfa.device_snapshot() if with_nfa else None
            return fn, (
                index.shapes.device_snapshot(), nfa, bits,
                bytes_mat, lengths,
            )
        if name == "fused_route_retained_step":
            from emqx_tpu.models.router_model import (
                fused_route_retained_step,
            )
            from emqx_tpu.ops.route_index import RouteIndex

            with_nfa = index.residual_count > 0
            nfa = index.nfa.device_snapshot() if with_nfa else None
            # retained half: a small deterministic storm-filter table +
            # one (scaled-down) topic chunk — abstract tracing only, so
            # the real 1M-row CHUNK is unnecessary
            ridx = RouteIndex()
            for f in ("site/+/a", "site/#"):
                ridx.add(f)
            rst = ridx.shapes.device_snapshot()
            r_with_nfa = ridx.residual_count > 0
            rnt = ridx.nfa.device_snapshot() if r_with_nfa else None
            ret_bytes = np.zeros((64, 16), np.uint8)
            fn = partial(
                fused_route_retained_step,
                m_active=m_active,
                with_nfa=with_nfa,
                salt=salt,
                ret_m_active=ridx.shapes.m_active(floor=1),
                ret_with_nfa=r_with_nfa,
                ret_salt=ridx.salt,
                ret_max_levels=8,
                ret_narrow=True,
                kslot=cfg["kslot"],
                **kw,
            )
            return fn, (
                index.shapes.device_snapshot(), nfa, bits,
                bytes_mat, lengths, rst, rnt, ret_bytes,
            )
        # mesh builders
        import jax

        from emqx_tpu.parallel.mesh import make_mesh

        n = cfg["dp"] * cfg["tp"]
        if len(jax.devices()) < n:
            raise _SkipConfig(
                f"{name} {_cfg_key(cfg)}: needs {n} devices, have "
                f"{len(jax.devices())}"
            )
        mesh = make_mesh(n, tp=cfg["tp"])
        # batch divisible by dp, lanes by tp
        if B % cfg["dp"]:
            raise _SkipConfig(f"{name}: B={B} not divisible by dp")
        if name == "dist_step":
            from emqx_tpu.parallel.mesh import _dist_step_fn

            tables = index.nfa.device_snapshot()
            fn = _dist_step_fn(
                mesh, tuple(sorted(tables)), salt, kw["max_levels"],
                kw["frontier"], kw["max_matches"], kw["probes"],
            )
            return fn, (tables, bits, bytes_mat, lengths)
        if name == "dist_fused_step":
            from emqx_tpu.ops.route_index import RouteIndex
            from emqx_tpu.parallel.mesh import _dist_fused_step_fn

            with_nfa = index.residual_count > 0
            st = index.shapes.device_snapshot()
            nt = index.nfa.device_snapshot() if with_nfa else None
            # retained half: small storm-filter table + a dp-divisible
            # topic-chunk slab (abstract tracing — no 1M-row CHUNK)
            ridx = RouteIndex()
            for f in ("site/+/a", "site/#"):
                ridx.add(f)
            rst = ridx.shapes.device_snapshot()
            r_with_nfa = ridx.residual_count > 0
            rnt = ridx.nfa.device_snapshot() if r_with_nfa else None
            ret_bytes = np.zeros((64, 16), np.uint8)
            fn = _dist_fused_step_fn(
                mesh,
                tuple(sorted(st)),
                tuple(sorted(nt)) if nt is not None else None,
                None,  # group_keys
                tuple(sorted(rst)),
                tuple(sorted(rnt)) if rnt is not None else None,
                0,  # share_strategy
                m_active,
                salt,
                kw["max_levels"],
                kw["frontier"],
                kw["max_matches"],
                kw["probes"],
                cfg["kslot"],
                ridx.shapes.m_active(floor=1),
                r_with_nfa,
                ridx.salt,
                8,  # ret_max_levels
                True,  # ret_narrow
            )
            return fn, (st, nt, None, None, None, None, bits, bytes_mat,
                        lengths, rst, rnt, ret_bytes,
                        None, None, None, None)
        from emqx_tpu.parallel.mesh import _dist_shape_step_fn

        with_nfa = index.residual_count > 0
        st = index.shapes.device_snapshot()
        nt = index.nfa.device_snapshot() if with_nfa else None
        if name == "sem_dist_shape_step":
            sem = _sem_workload(cfg["D"], cfg["kslot"], shards=cfg["tp"])
            st_sem = {
                k: v.copy() for k, v in sem.device_snapshot().items()
            }
            qv = np.zeros((B, cfg["D"]), np.float32)
            # one compiled WHERE predicate rides the same golden: the
            # in-launch rule-mask stage is pinned here too
            prog = (("feat", 0), ("lit", 1.0), ("ge",))
            rfeats = np.zeros((B, 1), np.float32)
            rvalid = np.ones((B, 1), bool)
            fn = _dist_shape_step_fn(
                mesh,
                tuple(sorted(st)),
                tuple(sorted(nt)) if nt is not None else None,
                None,  # group_keys
                0,  # share_strategy
                m_active,
                salt,
                kw["max_levels"],
                kw["frontier"],
                kw["max_matches"],
                kw["probes"],
                cfg["kslot"],
                False,  # donate
                None,  # sub_keys (dense fan-out)
                0,  # kg
                tuple(sorted(st_sem)),
                cfg["kslot"],  # sem_topk
                (prog,),
            )
            return fn, (st, nt, None, None, None, None, bits, bytes_mat,
                        lengths, st_sem, qv, rfeats, rvalid)
        if name == "sparse_dist_shape_step":
            subs.set_mode("sparse")
            subs.set_shards(cfg["tp"])
            subs.pack(index.num_filters_capacity)
            csr = {
                k: v.copy() for k, v in subs.device_snapshot().items()
            }
            fn = _dist_shape_step_fn(
                mesh,
                tuple(sorted(st)),
                tuple(sorted(nt)) if nt is not None else None,
                None,  # group_keys
                0,  # share_strategy
                m_active,
                salt,
                kw["max_levels"],
                kw["frontier"],
                kw["max_matches"],
                kw["probes"],
                cfg["kslot"],
                False,  # donate
                tuple(sorted(csr)),
                0,  # kg (auto: 2 x kslot)
            )
            return fn, (st, nt, None, None, None, None, csr, bytes_mat,
                        lengths, None, None, None, None)
        fn = _dist_shape_step_fn(
            mesh,
            tuple(sorted(st)),
            tuple(sorted(nt)) if nt is not None else None,
            None,  # group_keys
            0,  # share_strategy
            m_active,
            salt,
            kw["max_levels"],
            kw["frontier"],
            kw["max_matches"],
            kw["probes"],
            cfg["kslot"],
        )
        return fn, (st, nt, None, None, None, None, bits, bytes_mat,
                    lengths, None, None, None, None)

    return configs, build


def _sem_workload(dim: int, topk: int, shards: int = 1):
    """Deterministic SemanticTable: scoped + unscoped + a tombstone."""
    import numpy as np

    from emqx_tpu.ops.semantic_table import SemanticTable

    sem = SemanticTable(dim=dim, topk=topk, shards=shards)
    rng = np.random.default_rng(0x5E)
    for i in range(12):
        sem.add(
            64 + i, rng.normal(size=dim), 0.4,
            fid=-1 if i % 3 == 0 else i % 4,
        )
    sem.remove(64 + 5)  # a tombstone lane in the golden
    return sem


class _SkipConfig(Exception):
    pass


# -- the audit --------------------------------------------------------------

def run_audit(
    update_snapshots: bool = False,
    snapshot_dir: Optional[Path] = None,
    registry: Optional[Dict] = None,
    harness=None,
) -> AuditReport:
    """Trace every registered kernel and hold it to its contract.

    `registry`/`harness` are injectable for the fixture-kernel tests;
    the default is the product registry (importing the kernel modules
    populates it) and `_harness`.
    """
    jax = _ensure_jax()
    snapshot_dir = Path(snapshot_dir or DEFAULT_SNAPSHOT_DIR)
    harness = harness or _harness
    report = AuditReport()

    if registry is None:
        # importing the kernel modules populates the registry
        import emqx_tpu.models.router_model  # noqa: F401
        import emqx_tpu.ops.session_table  # noqa: F401
        from emqx_tpu.ops.contract import REGISTRY

        try:
            import emqx_tpu.parallel.mesh  # noqa: F401
        except Exception as e:  # pragma: no cover - no shard_map image
            report.skipped.append(f"mesh kernels unavailable: {e}")
        registry = REGISTRY

    for name, contract in sorted(registry.items()):
        recipe = harness(name)
        if recipe is None:
            report.problems.append(
                f"{name}: registered but the audit has no harness for it"
            )
            continue
        configs, build = recipe
        traced: Dict[str, Dict] = {}
        for cfg in configs:
            key = _cfg_key(cfg)
            try:
                fn, args = build(dict(cfg))
            except _SkipConfig as e:
                report.skipped.append(str(e))
                continue
            jaxpr1 = jax.make_jaxpr(fn)(*args)
            jaxpr2 = jax.make_jaxpr(fn)(*args)
            shapes = jax.eval_shape(fn, *args)
            summary = _trace_summary(jaxpr1, shapes)
            if str(jaxpr1) != str(jaxpr2):
                report.problems.append(
                    f"{name} {key}: tracing twice produced different "
                    "jaxprs (nondeterministic trace)"
                )
            self_check(report, name, key, cfg, contract, summary)
            traced[key] = summary
        if not traced:
            continue
        # collective declaration must match the union over the matrix
        union = sorted(
            {c for s in traced.values() for c in s["collectives"]}
        )
        declared = sorted(contract.collectives)
        if union != declared:
            report.problems.append(
                f"{name}: collective set over the matrix is {union}, "
                f"contract declares {declared} — the declaration must "
                "match exactly"
            )
        digests = {s["digest"] for s in traced.values()}
        if len(digests) != len(traced):
            report.problems.append(
                f"{name}: {len(traced)} configs produced "
                f"{len(digests)} distinct programs — two configs "
                "compiled to the same trace (dead config knob?) "
            )
        # snapshot diff
        public = {
            k: {kk: vv for kk, vv in s.items() if not kk.startswith("_")}
            for k, s in traced.items()
        }
        snap_path = snapshot_dir / f"{name}.json"
        if update_snapshots:
            snapshot_dir.mkdir(parents=True, exist_ok=True)
            snap_path.write_text(json.dumps(public, indent=2) + "\n")
            report.updated.append(name)
        elif not snap_path.exists():
            report.problems.append(
                f"{name}: no golden snapshot at {snap_path}; run "
                "`python -m tools.analysis --contracts "
                "--update-snapshots`"
            )
        else:
            golden = json.loads(snap_path.read_text())
            for key, summary in public.items():
                if key not in golden:
                    report.problems.append(
                        f"{name} {key}: config missing from snapshot — "
                        "refresh with --update-snapshots"
                    )
                    continue
                diffs = _diff_summary(golden[key], summary)
                for d in diffs:
                    report.problems.append(f"{name} {key}: {d}")
        report.kernels[name] = public
    return report


def self_check(report, name, key, cfg, contract, summary) -> None:
    """Per-config declaration checks (dtypes, collectives, bounds)."""
    for dt in summary["_dtypes"]:
        if dt in contract.forbid_dtypes:
            report.problems.append(
                f"{name} {key}: forbidden dtype {dt} appears in the "
                "trace (widening breaks the readback/HBM budget)"
            )
    extra = set(summary["collectives"]) - set(contract.collectives)
    if extra:
        report.problems.append(
            f"{name} {key}: undeclared collective(s) {sorted(extra)} "
            f"(contract allows {sorted(contract.collectives)})"
        )
    for out_name, bound in contract.out_bounds.items():
        spec = summary["outputs"].get(out_name)
        if spec is None:
            continue  # output not present in this config (e.g. kslot=0)
        limit = bound(cfg)
        nbytes = _spec_nbytes(spec)
        if nbytes > limit:
            report.problems.append(
                f"{name} {key}: output {out_name} is {spec} "
                f"({nbytes}B) > contract bound {limit}B — the compact "
                "output scaled with the wrong dimension"
            )


def _spec_nbytes(spec: str) -> int:
    import numpy as np

    dtype, _, dims = spec.partition("[")
    shape = [int(d) for d in dims.rstrip("]").split(",") if d]
    n = 1
    for d in shape:
        n *= d
    return n * np.dtype(dtype).itemsize


def _diff_summary(golden: Dict, current: Dict) -> List[str]:
    out = []
    if golden.get("digest") != current.get("digest"):
        out.append(
            f"jaxpr digest {current.get('digest')} != golden "
            f"{golden.get('digest')} (kernel trace changed; if "
            "deliberate, refresh with --update-snapshots)"
        )
    if golden.get("collectives") != current.get("collectives"):
        out.append(
            f"collectives {current.get('collectives')} != golden "
            f"{golden.get('collectives')}"
        )
    if golden.get("outputs") != current.get("outputs"):
        out.append(
            f"outputs {current.get('outputs')} != golden "
            f"{golden.get('outputs')}"
        )
    gp, cp = golden.get("primitives", {}), current.get("primitives", {})
    if gp != cp:
        changed = sorted(
            k for k in set(gp) | set(cp) if gp.get(k) != cp.get(k)
        )
        out.append(
            "primitive counts changed: "
            + ", ".join(
                f"{k} {gp.get(k, 0)}->{cp.get(k, 0)}" for k in changed[:8]
            )
        )
    return out


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m tools.analysis.device_contract",
        description="jaxpr-level device-contract audit",
    )
    p.add_argument("--update-snapshots", action="store_true")
    p.add_argument("--format", choices=("text", "json"), default="text")
    args = p.parse_args(argv)
    report = run_audit(update_snapshots=args.update_snapshots)
    if args.format == "json":
        print(json.dumps(report.to_json(), indent=2))
    else:
        print(render_audit(report.to_json()))
    return 0 if report.clean else 1


if __name__ == "__main__":
    sys.exit(main())
