"""Cross-module call-graph scaffolding shared by the device-contract
checkers (SD/HT/RT).

The jit-purity checker grew the first project call graph; the
sharding/host-transfer/retrace checkers need the same three ingredients
— a (module, name) -> function-def table that includes nested defs, an
import-alias-aware reference resolver, and call edges that follow
function names passed as *arguments* (`lax.scan(body, ...)`,
`shard_map(step, ...)`) — so they live here once.

Resolution is by bare name within a module plus canonical dotted name
across modules. Method calls through `self.` resolve by bare method
name in the same module (over-approximate across classes, which is the
right bias for taint-style analyses: a false edge can only make a
checker more conservative).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Sequence, Set, Tuple

from tools.analysis.core import (
    ParsedModule,
    dotted_name,
    import_aliases,
    resolve_call_name,
)

FuncKey = Tuple[str, str]  # (dotted module, bare function name)


def module_dotted(rel: str) -> str:
    dn = rel[:-3].replace("/", ".")
    if dn.endswith(".__init__"):
        dn = dn[: -len(".__init__")]
    return dn


class FnInfo:
    __slots__ = ("mod", "node", "symbol", "dn")

    def __init__(self, mod: ParsedModule, node: ast.AST, symbol: str,
                 dn: str):
        self.mod = mod
        self.node = node
        self.symbol = symbol
        self.dn = dn

    @property
    def key(self) -> FuncKey:
        return (self.dn, self.node.name)  # type: ignore[attr-defined]


class ProjectGraph:
    """One pass over every parsed module: function table + aliases."""

    def __init__(self, modules: Sequence[ParsedModule]):
        self.mods: Dict[str, ParsedModule] = {}
        self.aliases: Dict[str, Dict[str, str]] = {}
        self.funcs: Dict[FuncKey, List[FnInfo]] = {}
        self.infos: List[FnInfo] = []
        for mod in modules:
            dn = module_dotted(mod.rel)
            self.mods[dn] = mod
            self.aliases[dn] = import_aliases(mod.tree)
            self._collect(dn, mod)

    def _collect(self, dn: str, mod: ParsedModule) -> None:
        def walk(node: ast.AST, prefix: str):
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    sym = f"{prefix}.{child.name}" if prefix else child.name
                    info = FnInfo(mod, child, sym, dn)
                    self.funcs.setdefault((dn, child.name), []).append(info)
                    self.infos.append(info)
                    walk(child, sym)
                elif isinstance(child, ast.ClassDef):
                    walk(
                        child,
                        f"{prefix}.{child.name}" if prefix else child.name,
                    )
                else:
                    walk(child, prefix)

        walk(mod.tree, "")

    # -- resolution ---------------------------------------------------------
    def ref_targets(self, dn: str, node: ast.AST) -> List[FuncKey]:
        """Function *reference* (Name/Attribute, not a call) -> table keys."""
        aliases = self.aliases.get(dn, {})
        if isinstance(node, ast.Name):
            canon = aliases.get(node.id)
            if canon and "." in canon:
                mod_part, _, fn_part = canon.rpartition(".")
                return [(mod_part, fn_part), (dn, node.id)]
            return [(dn, node.id)]
        # `self.method` / `cls.method`: bare-name lookup in this module
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id in ("self", "cls")
        ):
            return [(dn, node.attr)]
        dn_full = dotted_name(node)
        if dn_full:
            head, _, rest = dn_full.partition(".")
            canon = aliases.get(head, head)
            full = f"{canon}.{rest}" if rest else canon
            mod_part, _, fn_part = full.rpartition(".")
            if mod_part:
                return [(mod_part, fn_part)]
        return []

    def call_name(self, dn: str, node: ast.AST) -> str:
        """Canonical dotted name of a call target ('' when unresolvable)."""
        return resolve_call_name(node, self.aliases.get(dn, {})) or ""

    def call_edges(self, dn: str, fn: ast.AST) -> List[FuncKey]:
        """Call targets of `fn`, including fn names passed as arguments."""
        out: List[FuncKey] = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            out.extend(self.ref_targets(dn, node.func))
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, (ast.Name, ast.Attribute)):
                    out.extend(self.ref_targets(dn, arg))
        return out

    def reachable_from(self, roots: Sequence[FuncKey]) -> Set[FuncKey]:
        """Transitive closure over call_edges starting at `roots`."""
        seen: Set[FuncKey] = set()
        work = list(roots)
        while work:
            key = work.pop()
            if key in seen:
                continue
            seen.add(key)
            for info in self.funcs.get(key, []):
                work.extend(self.call_edges(info.dn, info.node))
        return seen


# -- per-run graph sharing --------------------------------------------------

# Six checkers (shard, cx, retrace, transfer, version, bufview) need the
# project graph; run_analysis hands every begin() hook the SAME parsed-
# modules list object, so a one-slot identity-keyed cache dedupes the
# builds with no invalidation hazard — a new run allocates a new list.
_shared: Tuple[object, "ProjectGraph"] = (None, None)  # type: ignore


def shared_graph(modules: Sequence[ParsedModule]) -> "ProjectGraph":
    global _shared
    if _shared[0] is not modules:
        _shared = (modules, ProjectGraph(modules))
    return _shared[1]


# -- shared syntax helpers --------------------------------------------------

def header_lines(info: FnInfo) -> Iterator[str]:
    """Source lines of a def's header: first decorator through the line
    before the first body statement (annotation comments live here)."""
    node = info.node
    start = node.lineno
    if node.decorator_list:
        start = min(start, min(d.lineno for d in node.decorator_list))
    body = getattr(node, "body", None)
    end = body[0].lineno - 1 if body else node.lineno
    end = max(end, node.lineno)
    for ln in range(start, end + 1):
        yield info.mod.line_text(ln)


def str_constants(node: ast.AST) -> List[str]:
    """String literals in an expression (a str, or a tuple/list of strs)."""
    out: List[str] = []
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.append(node.value)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append(elt.value)
    return out


def is_literal_axes(node: ast.AST) -> bool:
    """True when the expression is entirely literal axis name(s)."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, str)
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(
            isinstance(e, ast.Constant) and isinstance(e.value, str)
            for e in node.elts
        )
    return False
