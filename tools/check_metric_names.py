#!/usr/bin/env python
"""DEPRECATED thin wrapper: the metric-name lint now lives in
`tools/analysis` (checker `metrics`, code MN001), alongside the other
project checkers. Prefer:

    python -m tools.analysis --checks metrics

This wrapper keeps the old entry point and its small API
(`find_call_sites` / `find_violations` / `main`) working for existing
invocations (tests/test_metric_names.py, CI scripts). Unlike the old
script it never imports broker code: the declared set is collected
statically from the `declare(...)` calls in the scanned tree.
"""

from __future__ import annotations

import sys
from pathlib import Path

_REPO_ROOT = Path(__file__).resolve().parents[1]
if str(_REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(_REPO_ROOT))

from tools.analysis.checkers.metric_names import (  # noqa: E402
    call_sites,
    declared_names,
)
from tools.analysis.core import parse_modules  # noqa: E402


def find_call_sites(root: Path):
    """-> [(path, lineno, name)] for every static-name metric call."""
    sites = []
    for mod in parse_modules(Path(root)):
        if mod.syntax_error is not None:
            sites.append((
                mod.path, mod.syntax_error.lineno or 0,
                f"<unparseable: {mod.syntax_error.msg}>",
            ))
            continue
        for lineno, name in call_sites(mod):
            sites.append((mod.path, lineno, name))
    return sites


def find_violations(root: Path):
    """-> [(path, lineno, name)] of call sites naming undeclared series."""
    mods = [m for m in parse_modules(Path(root)) if m.tree is not None]
    declared = declared_names(mods)
    return [
        (path, lineno, name)
        for path, lineno, name in find_call_sites(root)
        if name not in declared
    ]


def main(argv) -> int:
    print(
        "note: tools/check_metric_names.py is deprecated; use "
        "`python -m tools.analysis --checks metrics`",
        file=sys.stderr,
    )
    root = Path(argv[1]) if len(argv) > 1 else (_REPO_ROOT / "emqx_tpu")
    bad = find_violations(root)
    if not bad:
        print(f"metric names OK ({len(find_call_sites(root))} call sites)")
        return 0
    for path, lineno, name in bad:
        print(f"{path}:{lineno}: undeclared metric name {name!r}")
    print(
        f"{len(bad)} undeclared metric name(s); declare them in "
        "emqx_tpu/broker/metrics.py (see the series declarations block)"
    )
    return 1


if __name__ == "__main__":
    sys.exit(main(sys.argv))
