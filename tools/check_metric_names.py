#!/usr/bin/env python
"""Static metric-name lint: every `metrics.inc/observe/gauge_set` call site
in emqx_tpu/ must name a series declared in the metric-kind registry
(emqx_tpu.broker.metrics). Catches typo'd series names at test time —
a misspelled counter otherwise just creates a silent parallel series that
no dashboard, exporter, or alarm ever reads.

Scans with `ast`: any Call whose func is an attribute named inc/observe/
gauge_set and whose first argument is a string literal. Dynamic names
(f-strings, variables) are skipped — they must be composed from declared
prefixes (e.g. matcher.fallback.rows.<cause>, all declared explicitly).

Run directly (exit 1 on violations) or via tests/test_metric_names.py
(tier-1).
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

METHODS = ("inc", "observe", "observe_many", "gauge_set")


def find_call_sites(root: Path):
    """-> [(path, lineno, name)] for every static-name metric call."""
    sites = []
    for path in sorted(root.rglob("*.py")):
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError as e:
            sites.append((path, e.lineno or 0, f"<unparseable: {e.msg}>"))
            continue
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in METHODS
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                sites.append((path, node.lineno, node.args[0].value))
    return sites


def find_violations(root: Path):
    """-> [(path, lineno, name)] of call sites naming undeclared series."""
    from emqx_tpu.broker.metrics import registry

    declared = set(registry())
    return [
        (path, lineno, name)
        for path, lineno, name in find_call_sites(root)
        if name not in declared
    ]


def main(argv) -> int:
    root = Path(argv[1]) if len(argv) > 1 else (
        Path(__file__).resolve().parents[1] / "emqx_tpu"
    )
    sys.path.insert(0, str(root.parent))
    bad = find_violations(root)
    if not bad:
        print(f"metric names OK ({len(find_call_sites(root))} call sites)")
        return 0
    for path, lineno, name in bad:
        print(f"{path}:{lineno}: undeclared metric name {name!r}")
    print(
        f"{len(bad)} undeclared metric name(s); declare them in "
        "emqx_tpu/broker/metrics.py (see the series declarations block)"
    )
    return 1


if __name__ == "__main__":
    sys.exit(main(sys.argv))
