import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np, jax, jax.numpy as jnp
t0=time.perf_counter()
def mark(s): print(f"[+{time.perf_counter()-t0:6.1f}s] {s}", flush=True)
from emqx_tpu.models.retained_index import DeviceRetainedIndex, CHUNK
N, STORM = 5_000_000, 512
topics = [f"site/{i % 211}/dev/{i % 7919}/ch/{i}" for i in range(N)]
dev = DeviceRetainedIndex(max_bytes=64, max_levels=8)
dev.bulk_add(topics)
mark("built")
filters = [f"site/{i % 211}/dev/+/ch/#" for i in range(STORM)]
dev.warm(filters)
mark("warm (no readback) done")
t1=time.perf_counter()
res = dev.match_many(filters)
t2=time.perf_counter()
print(f"storm1: {t2-t1:.2f}s = {(t2-t1)/STORM*1e3:.1f}ms/sub, pairs={sum(len(v) for v in res.values())}")
t1=time.perf_counter()
res = dev.match_many(filters)
t2=time.perf_counter()
print(f"storm2 (degraded?): {t2-t1:.2f}s")
