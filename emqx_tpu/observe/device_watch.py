"""Device runtime telemetry: compile/retrace watch + HBM & transfer gauges.

The static RT checker (tools/analysis, PR 4) PREDICTS retrace hazards;
this module OBSERVES them on the live broker. Three signals, all polled
from the housekeeping tick (`DeviceWatch.poll`):

- **compiles vs cache hits**: every `@device_contract`-registered jit
  entry point (route_step, shape_route_step, the mesh step builders)
  exposes its jit cache size; the summed size is the
  `device.compile.cache_size` gauge and its growth is a compile. A
  process-wide `jax.monitoring` duration listener additionally captures
  every backend compile's wall seconds (`device.compile.seconds`) and —
  where the monitoring API exists — drives the `device.compile.count`
  counter, catching compiles of programs the registry does not know
  about. Steady-state serving should show a FLAT cache size and zero
  compile-count growth; sustained growth is a retrace storm (a dynamic
  value leaking into a shape/static position — exactly what RT001/RT002
  flag statically) and trips `RetraceStormWatch`
  (emqx_tpu/observe/alarm.py).

- **HBM live bytes** (`device.hbm.bytes` gauge): the accelerator
  allocator's `bytes_in_use` when the backend reports memory stats
  (TPU/GPU), else the summed nbytes of live jax arrays (CPU fallback —
  tracks the same table-growth signal, without allocator overheads).

- **transfer accounting** (`device.transfer.bytes` counter): cumulative
  device->host readback bytes, incremented at the two readback sites
  (DeviceRouter._readback, TpuMatcher.match_batch) next to the per-batch
  `dispatch.readback.bytes` histogram. The counter's RATE is the
  sustained link bandwidth the broker consumes.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

# -- process-global compile-event accumulator -------------------------------
# jax.monitoring listeners cannot be unregistered per-instance, so ONE
# module-level listener feeds monotonic totals; each DeviceWatch keeps its
# own cursor (multiple in-process brokers — cluster tests — each see their
# own deltas).
_mon_lock = threading.Lock()
_mon_compiles = 0  # guarded-by: _mon_lock
_mon_seconds = 0.0  # guarded-by: _mon_lock
_mon_registered = False

# the once-per-backend-compile event in jax's monitoring stream; the
# jaxpr_trace / mlir_module events fire alongside it and would overcount
_COMPILE_EVENT = "backend_compile"


def _on_event(event: str, duration: float, **_kw) -> None:
    global _mon_compiles, _mon_seconds
    if _COMPILE_EVENT not in event:
        return
    with _mon_lock:
        _mon_compiles += 1
        _mon_seconds += duration


def _install_listener() -> bool:
    global _mon_registered
    if _mon_registered:
        return True
    try:
        from jax import monitoring

        monitoring.register_event_duration_secs_listener(_on_event)
    except Exception:
        return False
    _mon_registered = True
    return True


def _mon_totals() -> tuple:
    with _mon_lock:
        return _mon_compiles, _mon_seconds


def hbm_bytes() -> int:
    """Live device memory: allocator stats when the backend exposes them
    (TPU/GPU `memory_stats()["bytes_in_use"]`), else summed nbytes of
    live arrays (CPU — same growth signal, no allocator overhead)."""
    import jax

    total = 0
    saw_stats = False
    try:
        for d in jax.local_devices():
            stats = d.memory_stats()
            if stats and "bytes_in_use" in stats:
                total += int(stats["bytes_in_use"])
                saw_stats = True
    except Exception:
        saw_stats = False
    if saw_stats:
        return total
    try:
        return int(sum(a.nbytes for a in jax.live_arrays()))
    except Exception:
        return 0


class DeviceWatch:
    """Polls the device runtime signals into the metrics registry.

    `registry`: name -> DeviceContract (default: the process REGISTRY
    from emqx_tpu.ops.contract). jit-kind entries contribute their
    `_cache_size()`; builder-kind entries are covered by
    `parallel.mesh.jit_cache_size` (the built mesh programs register
    themselves there).
    """

    def __init__(self, metrics, registry: Optional[Dict] = None):
        self.metrics = metrics
        self._registry = registry
        self._monitoring = _install_listener()
        self._last_cache: Optional[int] = None
        self._mon_cursor = _mon_totals()

    def _contracts(self) -> Dict:
        if self._registry is not None:
            return self._registry
        from emqx_tpu.ops.contract import REGISTRY

        return REGISTRY

    def cache_size(self) -> int:
        """Summed jit-cache entries across every registered kernel plus
        the built mesh step programs."""
        n = 0
        for contract in self._contracts().values():
            fn = getattr(contract, "fn", contract)
            cs = getattr(fn, "_cache_size", None)
            if cs is None:
                continue
            try:
                n += int(cs())
            except Exception:
                continue
        try:
            from emqx_tpu.parallel.mesh import jit_cache_size

            n += jit_cache_size()
        except Exception:
            pass
        return n

    def poll(self, now: Optional[float] = None) -> Dict[str, float]:
        """One telemetry tick; call from housekeeping. Returns the sampled
        values (handy for tests and the REST summary)."""
        m = self.metrics
        cs = self.cache_size()
        kernel_compiles = (
            max(0, cs - self._last_cache)
            if self._last_cache is not None
            else 0
        )
        self._last_cache = cs
        m.gauge_set("device.compile.cache_size", cs)
        mon_c, mon_s = _mon_totals()
        d_compiles = mon_c - self._mon_cursor[0]
        d_seconds = mon_s - self._mon_cursor[1]
        self._mon_cursor = (mon_c, mon_s)
        if not self._monitoring:
            # no monitoring API on this jax: the registry cache growth is
            # the compile signal (misses non-registered programs)
            d_compiles, d_seconds = kernel_compiles, 0.0
        if d_compiles:
            m.inc("device.compile.count", d_compiles)
            if d_seconds > 0:
                # the listener holds window totals, not per-compile
                # samples: record the window mean per compile
                m.observe_many(
                    "device.compile.seconds",
                    [d_seconds / d_compiles] * d_compiles,
                )
        hbm = hbm_bytes()
        m.gauge_set("device.hbm.bytes", hbm)
        return {
            "compile_cache_size": cs,
            "compiles": d_compiles,
            "compile_seconds": d_seconds,
            "kernel_compiles": kernel_compiles,
            "hbm_bytes": hbm,
        }

    def summary(self) -> Dict[str, float]:
        """Current totals for the REST surface (no side effects)."""
        m = self.metrics
        return {
            "compile_count": m.get("device.compile.count"),
            "compile_cache_size": m.gauge("device.compile.cache_size"),
            "hbm_bytes": m.gauge("device.hbm.bytes"),
            "transfer_bytes": m.get("device.transfer.bytes"),
        }
