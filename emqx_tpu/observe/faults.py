"""Deterministic fault injection for the serving pipeline.

PR 6 made the device-resident pipeline fast; every fast path it added is
also a new way to die — a failed `tpu-dispatch` launch, a torn
delta-sync, a wedged readback, a dropped cluster forward. This module
makes those failures *injectable* so the degradation ladder
(broker/degrade.py) is proven by tests and chaos soaks
(`bench.py chaos_soak`), not by production incidents.

Model: a registry of named fault SITES, each a single `faults.hit(site)`
call on the real code path. A site with no armed rule costs ONE dict
lookup (the `is None` fast path below) — safe to leave compiled into
production binaries. Armed rules fire one of four behaviors:

- ``raise``   raise `FaultError` at the site (launch/readback/forward
              failure; the caller's recovery path takes over);
- ``delay``   sleep `delay_ms` at the site (wedged readback / slow
              sidecar; drives deadline + backoff paths);
- ``drop``    return "drop" — the site interprets it (ingest sheds the
              enqueue, a forward is dead-lettered);
- ``corrupt`` return "corrupt" — the site treats its fresh state as
              torn (delta-sync rolls back to the last good epoch).

Triggers compose: fire on every `nth` call, with `probability`, at most
`max_fires` times (1 = one-shot). Rules arm from config
(`faults.rules`, default off), at runtime via `GET/POST/DELETE
/api/v5/faults` (soak testing against a live broker), or directly in
tests (`default_faults.arm(...)` + `disarm()` in teardown).

Every fire counts into the `faults.injected` series and the per-rule
`fired` counter the REST endpoint reports, so a soak's fault schedule is
auditable next to the `degrade.*` series it provokes.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

# every injectable site, in pipeline order. Adding a site here requires
# adding the same literal to config.schema.FAULT_SITES (the FT checker
# in tools/analysis cross-checks the two — config validation must know
# every site a rule could name).
SITES = (
    "ingest.enqueue",  # publish entering the batch window
    "device.launch",  # route_prepared kernel launch (executor thread)
    "device.readback",  # the device->host transfer of a routed batch
    "router.delta_sync",  # table pack + delta upload (dirty prepare)
    "retained.storm",  # fused retained-replay storm prepare
    "cluster.forward",  # cross-node send on the cluster bus
    "exhook.call",  # gRPC call into an exhook sidecar
)

MODES = ("raise", "delay", "drop", "corrupt")


class FaultError(RuntimeError):
    """An injected failure (mode=raise). Carries the site so recovery
    paths and tests can tell injected faults from organic ones."""

    def __init__(self, site: str):
        super().__init__(f"injected fault at {site}")
        self.site = site


@dataclass
class FaultRule:
    """One armed behavior at one site (mutable: carries fire counters)."""

    site: str
    mode: str = "raise"
    probability: float = 1.0
    nth: int = 0  # fire only on every nth eligible call (0 = every)
    max_fires: int = 0  # stop firing after this many (0 = unlimited)
    delay_ms: float = 0.0
    calls: int = 0  # guarded-by: injector lock
    fired: int = 0  # guarded-by: injector lock

    def to_json(self) -> Dict:
        return {
            "site": self.site,
            "mode": self.mode,
            "probability": self.probability,
            "nth": self.nth,
            "max_fires": self.max_fires,
            "delay_ms": self.delay_ms,
            "calls": self.calls,
            "fired": self.fired,
        }


class FaultInjector:
    """The site registry. One process-wide instance (`default_faults`)
    backs the module-level `hit()` the pipeline calls."""

    def __init__(self, metrics=None, seed: int = 0):
        self.metrics = metrics
        self._rules: Dict[str, FaultRule] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self._rng = random.Random(seed)

    # -- control surface (config / REST / tests) ---------------------------
    def arm(
        self,
        site: str,
        mode: str = "raise",
        probability: float = 1.0,
        nth: int = 0,
        max_fires: int = 0,
        delay_ms: float = 0.0,
    ) -> FaultRule:
        if site not in SITES:
            raise ValueError(
                f"unknown fault site {site!r} (one of {', '.join(SITES)})"
            )
        if mode not in MODES:
            raise ValueError(
                f"unknown fault mode {mode!r} (one of {', '.join(MODES)})"
            )
        if not 0.0 <= probability <= 1.0:
            raise ValueError("fault probability must be in [0, 1]")
        rule = FaultRule(
            site=site,
            mode=mode,
            probability=float(probability),
            nth=int(nth),
            max_fires=int(max_fires),
            delay_ms=float(delay_ms),
        )
        with self._lock:
            self._rules[site] = rule
        return rule

    def disarm(self, site: Optional[str] = None) -> None:
        """Remove one site's rule, or every rule when `site` is None."""
        with self._lock:
            if site is None:
                self._rules.clear()
            else:
                self._rules.pop(site, None)

    def rules(self) -> List[Dict]:
        with self._lock:
            return [r.to_json() for r in self._rules.values()]

    @property
    def armed(self) -> bool:
        # GIL-atomic dict truthiness; same fast-path read as hit()
        return bool(self._rules)  # lint: disable=LK001

    # -- the hot-path hook --------------------------------------------------
    def hit(self, site: str) -> Optional[str]:
        """Consult the registry at a fault site.

        Disarmed (the production steady state): one dict lookup, returns
        None. Armed: evaluates the rule's triggers under the lock; a
        firing rule raises (`raise`), sleeps (`delay` — call sites run on
        executor/bus threads, never the event loop's hot section), or
        returns its mode string for the site to interpret (`drop`,
        `corrupt`). Non-firing calls return None.
        """
        rule = self._rules.get(site)  # lint: disable=LK001
        if rule is None:
            return None
        with self._lock:
            rule = self._rules.get(site)
            if rule is None:
                return None
            rule.calls += 1
            if rule.max_fires and rule.fired >= rule.max_fires:
                return None
            if rule.nth > 1 and rule.calls % rule.nth:
                return None
            if rule.probability < 1.0 and (
                self._rng.random() >= rule.probability
            ):
                return None
            rule.fired += 1
        if self.metrics is not None:
            self.metrics.inc("faults.injected")
        if rule.mode == "delay":
            time.sleep(rule.delay_ms / 1e3)
            return "delay"
        if rule.mode == "raise":
            raise FaultError(site)
        return rule.mode  # "drop" | "corrupt"

    def snapshot(self) -> Dict:
        """REST payload: armed rules + aggregate counters."""
        rules = self.rules()
        return {
            "enabled": bool(rules),
            "sites": list(SITES),
            "modes": list(MODES),
            "rules": rules,
            "injected": (
                self.metrics.get("faults.injected")
                if self.metrics is not None
                else sum(r["fired"] for r in rules)
            ),
        }


# the process-wide injector every pipeline fault site consults; the app
# wires its broker metrics in at assembly (faults.injected accounting)
default_faults = FaultInjector()


def hit(site: str) -> Optional[str]:
    """Module-level shorthand: `faults.hit("device.launch")`."""
    return default_faults.hit(site)
