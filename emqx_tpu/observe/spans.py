"""Causal span tracing across the batch boundary.

The flight recorder (PR 1) answers "how is the pipeline doing"; this
module answers "why was THIS publish slow". A batched TPU pipeline
destroys per-message causality — N publishes fan IN to one ingest batch,
one `route_step` launch, then fan OUT to M deliveries — so a per-message
trace needs more than parent/child edges. The model here is the OTLP
span model (trace_id / span_id / parent + **links**):

  mqtt.publish  ──link──▶  ingest.batch  ──parent──▶  router.device_step
       │  (fan-in: each sampled publish                     ▲
       │   links into exactly one batch)                    │ link
       └──parent──▶  mqtt.deliver  ────────────────────────┘
          (fan-out: deliver spans keep the PUBLISH trace_id, so one
           message's trace survives publish → batch → device → deliver
           — including across cluster forwards, where the context rides
           the message headers — while the link to the device-step span
           keeps batch attribution)

Head-based sampling: the decision is made ONCE at the publish head from
a deterministic seeded hash of (client, topic) — so one flow is either
always traced or never, and repeated runs see the same sample — with
per-client / per-topic-filter rate overrides and an always-sample escape
hatch for clients matched by an active `TraceSpec` (emqx_trace-style
debugging gets full fidelity). Downstream stages never re-sample: the
presence of the `traceparent` header IS the decision.

Export: a bounded in-memory ring (served by `GET /api/v5/trace/spans`)
plus an optional OTLP-shaped JSON file exporter
(`observe.trace_span_file`) a collector can tail.

Reference analogs: emqx_trace / emqx_slow_subs measure per-message
latency externally; OpenTelemetry semantic conventions shape the export.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
import zlib
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from emqx_tpu.ops import topics as T

# message-header key carrying the span context (W3C traceparent shape:
# "00-<32 hex trace_id>-<16 hex span_id>-01"); rides cluster forwards
# (headers pickle with the Message) and exhook calls (stringified into
# pb.Message.headers AND sent as gRPC metadata)
TRACE_HEADER = "traceparent"


def format_ctx(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


def parse_ctx(header: Optional[str]) -> Optional[Tuple[str, str]]:
    """traceparent string -> (trace_id, span_id) | None."""
    if not header or not isinstance(header, str):
        return None
    parts = header.split("-")
    if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    return parts[1], parts[2]


@dataclass
class Span:
    """One span. Times are unix nanoseconds (the OTLP convention)."""

    name: str
    trace_id: str
    span_id: str
    parent_id: str = ""
    start_ns: int = 0
    end_ns: int = 0
    attrs: Dict = field(default_factory=dict)
    # links: fan-in/fan-out edges to spans in OTHER traces
    links: List[Tuple[str, str]] = field(default_factory=list)
    status: str = "ok"  # ok | error

    def ctx(self) -> str:
        return format_ctx(self.trace_id, self.span_id)

    def to_otlp(self) -> Dict:
        """One OTLP/JSON span object (trace service JSON encoding)."""
        out = {
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "name": self.name,
            "startTimeUnixNano": str(self.start_ns),
            "endTimeUnixNano": str(self.end_ns),
            "kind": "SPAN_KIND_INTERNAL",
            "attributes": [
                {"key": k, "value": _otlp_value(v)}
                for k, v in self.attrs.items()
            ],
            "status": {"code": "STATUS_CODE_ERROR"}
            if self.status == "error"
            else {"code": "STATUS_CODE_OK"},
        }
        if self.parent_id:
            out["parentSpanId"] = self.parent_id
        if self.links:
            out["links"] = [
                {"traceId": t, "spanId": s} for t, s in self.links
            ]
        return out


def _otlp_value(v):
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


class OtlpFileExporter:
    """OTLP-shaped JSON file sink: one `resourceSpans` envelope per line,
    buffered (the hot path must never wait on a disk flush per span)."""

    def __init__(self, path: str, service_name: str = "emqx_tpu",
                 flush_every: int = 64):
        self.path = path
        self.service_name = service_name
        self.flush_every = flush_every
        # hardware provenance rides every envelope: a span file replayed
        # months later still says what silicon produced the latencies
        # (observe/provenance.py; hw.* resource attribute keys)
        from emqx_tpu.observe.provenance import resource_attrs

        self._resource_attrs = resource_attrs()
        self._buf: List[Dict] = []  # guarded-by: _lock
        self._lock = threading.Lock()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)

    def export(self, spans: Sequence[Span]) -> None:
        with self._lock:
            self._buf.extend(s.to_otlp() for s in spans)
            if len(self._buf) < self.flush_every:
                return
            batch, self._buf = self._buf, []
        self._write(batch)

    def _write(self, batch: List[Dict]) -> None:
        if not batch:
            return
        envelope = {
            "resourceSpans": [
                {
                    "resource": {
                        "attributes": [
                            {
                                "key": "service.name",
                                "value": {"stringValue": self.service_name},
                            }
                        ]
                        + [
                            {"key": k, "value": _otlp_value(v)}
                            for k, v in sorted(
                                self._resource_attrs.items()
                            )
                        ]
                    },
                    "scopeSpans": [
                        {
                            "scope": {"name": "emqx_tpu.observe.spans"},
                            "spans": batch,
                        }
                    ],
                }
            ]
        }
        with open(self.path, "a", encoding="utf-8") as f:
            f.write(json.dumps(envelope) + "\n")

    def flush(self) -> None:
        with self._lock:
            batch, self._buf = self._buf, []
        self._write(batch)


class SpanRecorder:
    """Owns sampling, the open-span registry, the finished-span ring, and
    the exporter. One instance per broker (like `Metrics`).

    Hot-path cost profile: an UNSAMPLED publish pays one crc32 + two dict
    gets; downstream stages pay one header `.get` per message. Span
    construction happens only on the sampled fraction.
    """

    def __init__(
        self,
        metrics=None,
        sample_rate: float = 0.01,
        sample_clients: Optional[Dict[str, float]] = None,
        sample_topics: Optional[Dict[str, float]] = None,
        seed: int = 0,
        ring: int = 2048,
        exporter: Optional[OtlpFileExporter] = None,
        always_sample: Optional[Callable[[str, str], bool]] = None,
    ):
        """`always_sample(client_id, topic)`: full-fidelity escape hatch —
        wired to `TraceManager.should_sample` so clients/topics under an
        active emqx_trace-style spec are sampled at 100%."""
        self.metrics = metrics
        self.sample_rate = float(sample_rate)
        self.sample_clients = dict(sample_clients or {})
        self.sample_topics = dict(sample_topics or {})
        self.seed = int(seed)
        self.exporter = exporter
        self.always_sample = always_sample
        self._ring: deque = deque(maxlen=ring)  # guarded-by: _lock
        self._lock = threading.Lock()
        # publish spans awaiting settle, keyed by span_id; bounded so a
        # publish that never settles (crashed dispatch) cannot leak
        self._open: Dict[str, Span] = {}  # guarded-by: _lock
        self._open_max = 8192
        # ids: process-random prefix + counter => unique, no per-span
        # entropy syscall; next() is GIL-atomic
        self._prefix = int.from_bytes(os.urandom(8), "big")
        self._seq = itertools.count(1)

    # -- ids ---------------------------------------------------------------
    def _ids(self) -> Tuple[str, str]:
        n = next(self._seq)
        return f"{self._prefix:016x}{n:016x}", f"{(self._prefix ^ n) & 0xFFFFFFFF:08x}{n & 0xFFFFFFFF:08x}"

    def _span_id(self) -> str:
        n = next(self._seq)
        return f"{(self._prefix ^ n) & 0xFFFFFFFF:08x}{n & 0xFFFFFFFF:08x}"

    @staticmethod
    def now_ns() -> int:
        return time.time_ns()

    _now = now_ns

    # -- sampling ----------------------------------------------------------
    def rate_for(self, client_id: str, topic: str) -> float:
        """Most specific knob wins: client override, then the first
        matching topic-filter override, then the base rate."""
        r = self.sample_clients.get(client_id)
        if r is not None:
            return r
        for filt, fr in self.sample_topics.items():
            if T.match(topic, filt):
                return fr
        return self.sample_rate

    def sample(self, client_id: str, topic: str) -> bool:
        """Deterministic head decision: seeded hash of (client, topic)
        against the effective rate — one flow samples consistently."""
        if self.always_sample is not None and self.always_sample(
            client_id, topic
        ):
            return True
        rate = self.rate_for(client_id, topic)
        if rate <= 0.0:
            return False
        if rate >= 1.0:
            return True
        h = zlib.crc32(f"{self.seed}:{client_id}:{topic}".encode())
        return h < rate * 4294967296.0

    # -- core span ops -----------------------------------------------------
    def start(
        self,
        name: str,
        *,
        trace_id: Optional[str] = None,
        parent_id: str = "",
        links: Sequence[Tuple[str, str]] = (),
        attrs: Optional[Dict] = None,
        start_ns: int = 0,
    ) -> Span:
        if trace_id is None:
            trace_id, span_id = self._ids()
        else:
            span_id = self._span_id()
        return Span(
            name=name,
            trace_id=trace_id,
            span_id=span_id,
            parent_id=parent_id,
            start_ns=start_ns or self._now(),
            attrs=dict(attrs or {}),
            links=list(links),
        )

    def finish(self, span: Span, attrs: Optional[Dict] = None,
               status: Optional[str] = None) -> None:
        span.end_ns = self._now()
        if attrs:
            span.attrs.update(attrs)
        if status is not None:
            span.status = status
        with self._lock:
            self._ring.append(span)
        if self.metrics is not None:
            self.metrics.inc("trace.spans.sampled")
        if self.exporter is not None:
            self.exporter.export((span,))

    # -- hot-path helpers (publish / batch / device / deliver) -------------
    def publish_links(self, msgs) -> List[Tuple[str, str]]:
        """Parsed span contexts of the sampled messages in a batch."""
        out = []
        for m in msgs:
            parsed = parse_ctx(m.headers.get(TRACE_HEADER))
            if parsed is not None:
                out.append(parsed)
        return out

    def publish_begin(self, msg) -> Optional[Span]:
        """Head of a trace: sample once, stamp the context header, open
        the span until the batch settles. Returns None when unsampled.
        Broker-generated `$`-rooted chatter ($SYS heartbeats, $event
        lifecycle messages) never head-samples — flow-consistent
        sampling would otherwise trace it forever — unless an active
        TraceSpec explicitly targets it."""
        if msg.topic.startswith("$"):
            if self.always_sample is None or not self.always_sample(
                msg.from_client, msg.topic
            ):
                return None
        elif not self.sample(msg.from_client, msg.topic):
            return None
        span = self.start(
            "mqtt.publish",
            attrs={
                "messaging.destination": msg.topic,
                "messaging.client_id": msg.from_client,
                "messaging.qos": msg.qos,
            },
        )
        msg.headers[TRACE_HEADER] = span.ctx()
        with self._lock:
            if len(self._open) >= self._open_max:
                # evict the oldest unfinished span rather than grow
                evicted_id = next(iter(self._open))
                evicted = self._open.pop(evicted_id)
                if self.metrics is not None:
                    self.metrics.inc("trace.spans.dropped")
                evicted.status = "error"
                evicted.attrs["dropped"] = "open_overflow"
            self._open[span.span_id] = span
        return span

    def publish_finish(self, ctx: Optional[str], deliveries: int,
                       status: str = "ok") -> None:
        """Settle a publish span by its context header (the ingest path
        holds contexts, not span objects)."""
        parsed = parse_ctx(ctx)
        if parsed is None:
            return
        _, span_id = parsed
        with self._lock:
            span = self._open.pop(span_id, None)
        if span is None:
            if self.metrics is not None:
                self.metrics.inc("trace.spans.dropped")
            return
        self.finish(span, {"messaging.deliveries": deliveries},
                    status=status)

    def finish_span(self, span: Optional[Span], deliveries: int,
                    status: str = "ok") -> None:
        """Settle a publish span held as an object (sync publish path)."""
        if span is None:
            return
        with self._lock:
            self._open.pop(span.span_id, None)
        self.finish(span, {"messaging.deliveries": deliveries},
                    status=status)

    def batch_begin(self, seq: int, msgs, max_batch: int) -> Optional[Span]:
        """Fan-in: one batch span whose links are the sampled publishes'
        contexts (keyed by the same batch seq the `ingest.launch`
        tracepoint carries). None when nothing in the batch is sampled —
        unsampled traffic never materializes batch spans."""
        links = []
        for m in msgs:
            parsed = parse_ctx(m.headers.get(TRACE_HEADER))
            if parsed is not None:
                links.append(parsed)
        if not links:
            return None
        return self.start(
            "ingest.batch",
            links=links,
            attrs={
                "batch.seq": seq,
                "batch.size": len(msgs),
                "batch.occupancy": len(msgs) / max_batch,
            },
        )

    def device_step(self, batch_span: Optional[Span], n_rows: int, results,
                    start_ns: int, links: Sequence = (),
                    extra: Optional[Dict] = None) -> Optional[Span]:
        """The kernel launch+readback span, annotated from the
        `RouteResult`: readback bytes, compact/overflow rows, fallback
        rows. Child of the batch span (same trace); standalone with links
        to the sampled publishes on batch-less (sync) dispatches.
        `extra`: engine attributes (DeviceRouter.span_attrs) — the mesh
        engine stamps `device.mesh_shape`/`device.shard` here so a trace
        records WHICH slice of the sharded table served the batch."""
        if batch_span is None and not links:
            return None
        import numpy as np

        attrs = {
            "device.rows": n_rows,
            "device.readback_bytes": int(
                getattr(results, "readback_bytes", 0)
            ),
            "device.fallback_rows": int(np.count_nonzero(results.flags)),
        }
        if extra:
            attrs.update(extra)
        if results.slots is not None:
            n_ovf = int(np.count_nonzero(results.overflow))
            attrs["device.compact_rows"] = n_rows - n_ovf
            attrs["device.overflow_rows"] = n_ovf
        span = self.start(
            "router.device_step",
            trace_id=batch_span.trace_id if batch_span else None,
            parent_id=batch_span.span_id if batch_span else "",
            links=() if batch_span else links,
            attrs=attrs,
            start_ns=start_ns,
        )
        self.finish(span)
        return span

    def deliver(self, msg, deliveries: int, *, start_ns: int = 0,
                device_span: Optional[Span] = None,
                fallback: bool = False, remote: bool = False) -> None:
        """Fan-out: a deliver span in the PUBLISH's trace (so the
        trace_id survives end-to-end, including a cluster hop), linked to
        the device-step span for batch attribution."""
        parsed = parse_ctx(msg.headers.get(TRACE_HEADER))
        if parsed is None:
            return
        trace_id, parent_id = parsed
        attrs = {
            "messaging.destination": msg.topic,
            "messaging.deliveries": deliveries,
        }
        if fallback:
            attrs["device.fallback"] = True
        if remote:
            attrs["cluster.forwarded"] = True
        span = self.start(
            "mqtt.deliver",
            trace_id=trace_id,
            parent_id=parent_id,
            links=[(device_span.trace_id, device_span.span_id)]
            if device_span is not None
            else [],
            attrs=attrs,
            start_ns=start_ns,
        )
        self.finish(span)

    def forward(self, msg, peer: str) -> None:
        """A cross-node forward of a sampled message (publisher side):
        records where the trace context left this node."""
        parsed = parse_ctx(msg.headers.get(TRACE_HEADER))
        if parsed is None:
            return
        trace_id, parent_id = parsed
        span = self.start(
            "cluster.forward",
            trace_id=trace_id,
            parent_id=parent_id,
            attrs={"cluster.peer": peer,
                   "messaging.destination": msg.topic},
        )
        self.finish(span)

    # -- read side ---------------------------------------------------------
    def recent(self, limit: int = 100,
               trace_id: Optional[str] = None) -> List[Dict]:
        """Newest-first OTLP-shaped span dicts from the ring."""
        with self._lock:
            spans = list(self._ring)
        spans.reverse()
        if trace_id:
            spans = [s for s in spans if s.trace_id == trace_id]
        return [s.to_otlp() for s in spans[: max(0, int(limit))]]

    def spans(self) -> List[Span]:
        """Raw Span objects (oldest first) — test/assertion surface."""
        with self._lock:
            return list(self._ring)

    def close(self) -> None:
        if self.exporter is not None:
            self.exporter.flush()
