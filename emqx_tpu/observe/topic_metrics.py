"""Per-topic-filter metrics (reference: apps/emqx_modules/src/
emqx_topic_metrics.erl): operators register topic filters; the module counts
messages in/out/dropped and per-QoS breakdown for messages whose topic
matches, with rate estimates. Registration is capped (the reference caps at
512 filters).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from emqx_tpu.ops import topics as T

MAX_TOPICS = 512
_COUNTERS = (
    "messages.in",
    "messages.out",
    "messages.dropped",
    "messages.qos0.in",
    "messages.qos1.in",
    "messages.qos2.in",
)


class TopicMetrics:
    def __init__(self) -> None:
        self._table: Dict[str, Dict[str, float]] = {}
        self._rate_base: Dict[str, Dict[str, float]] = {}
        self._rate_ts: float = time.time()
        self._rates: Dict[str, Dict[str, float]] = {}

    # -- registration ------------------------------------------------------
    def register(self, topic_filter: str) -> bool:
        T.validate(topic_filter, kind="filter")
        if topic_filter in self._table:
            return False
        if len(self._table) >= MAX_TOPICS:
            raise OverflowError("quota_exceeded")
        self._table[topic_filter] = {c: 0 for c in _COUNTERS}
        return True

    def deregister(self, topic_filter: str) -> bool:
        self._rates.pop(topic_filter, None)
        self._rate_base.pop(topic_filter, None)
        return self._table.pop(topic_filter, None) is not None

    def deregister_all(self) -> None:
        self._table.clear()
        self._rates.clear()
        self._rate_base.clear()

    def topics(self) -> List[str]:
        return list(self._table)

    # -- counting ----------------------------------------------------------
    def _bump(self, topic: str, counter: str, extra: Optional[str] = None):
        for f, counters in self._table.items():
            if T.match(topic, f):
                counters[counter] += 1
                if extra:
                    counters[extra] += 1

    # hooks
    def on_message_publish(self, msg, acc=None):
        self._bump(msg.topic, "messages.in", f"messages.qos{msg.qos}.in")
        return acc if acc is not None else msg

    def on_message_delivered(self, client_info, msg):
        self._bump(msg.topic, "messages.out")

    def on_message_dropped(self, msg, reason):
        self._bump(msg.topic, "messages.dropped")

    def attach(self, hooks) -> None:
        # priority above default so counts include messages later dropped
        hooks.add("message.publish", self.on_message_publish, priority=100,
                  tag="topic_metrics")
        hooks.add("message.delivered", self.on_message_delivered,
                  tag="topic_metrics")
        hooks.add("message.dropped", self.on_message_dropped,
                  tag="topic_metrics")

    # -- rates (called from housekeeping) ----------------------------------
    def tick_rates(self, now: Optional[float] = None) -> None:
        now = now or time.time()
        dt = now - self._rate_ts
        if dt <= 0:
            return
        for f, counters in self._table.items():
            base = self._rate_base.get(f, {})
            self._rates[f] = {
                c: (counters[c] - base.get(c, 0)) / dt for c in _COUNTERS
            }
            self._rate_base[f] = dict(counters)
        self._rate_ts = now

    def metrics(self, topic_filter: Optional[str] = None):
        if topic_filter is not None:
            if topic_filter not in self._table:
                return None
            return self._one(topic_filter)
        return [self._one(f) for f in self._table]

    def _one(self, f: str) -> Dict:
        out = {"topic": f, "metrics": dict(self._table[f])}
        rates = self._rates.get(f)
        if rates:
            out["metrics"].update(
                {c + ".rate": round(v, 3) for c, v in rates.items()}
            )
        return out
