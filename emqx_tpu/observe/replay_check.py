"""Shadow-replica divergence harness: replication readiness as a
runtime check.

`DeviceSegmentManager` is, structurally, a replication protocol: a
standby broker that received every epoch upload, op-log suffix,
`!resync` marker, and compaction offer MUST be able to reconstruct the
exact host tables. Nothing in the repo exercised that end-to-end —
op-log completeness was only ever checked statically (the OL/VC
checkers in `tools/analysis`). This module closes the loop:

- `ReplayCheck.arm(manager)` swaps the manager's `__class__` for a
  generated subclass (the `racetrack`/`faults` idiom — ZERO cost while
  disarmed, nothing is wrapped or patched globally) whose `sync`
  captures, per call, exactly the record a standby would receive:

    * a full-resync sync  -> ("full", epoch, host snapshot copy, pos)
    * a delta sync        -> ("delta", op-log suffix, copies of the
                              re-uploaded arrays for `!resync`-marked
                              and newly-appearing names, pos)

- `ShadowReplica` applies those records to plain host arrays with the
  manager's own suffix semantics (resync supersedes suffix writes to
  that array; last-write-wins per flat slot; values cast through the
  destination dtype) — it never sees the live table object.

- `ReplayTap.diverged()` compares the replica against the live
  `device_snapshot()` array-exact (names, shapes, dtypes, values).

The capture reads `_pos`/`full_resyncs` around the inner `sync` call
without taking the manager lock, so the harness assumes the audited
tables follow the documented single-writer discipline (the loop owns
mutation + sync). That is the property being audited — a torn capture
IS a finding, not a harness bug.

`run_replay_audit()` is the batteries-included entry point used by
`python -m tools.analysis --replay`, the race suite, and the
chaos_soak probe: randomized churn across all five mirrored owners
(shape index, sparse subscriber CSR, semantic table, session table,
retained index), compaction cycles racing loop inserts through the
journal-replay path, an array-exact convergence assertion, and a
seeded incomplete-log negative control that MUST be detected.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from emqx_tpu.ops.segments import RESYNC

Record = Tuple  # ("full", epoch, arrays, pos) | ("delta", ops, uploads, pos)


class ShadowReplica:
    """Offline standby: plain numpy arrays reconstructed purely from
    captured sync records. Deliberately knows nothing about the live
    source object — if the op-log stream is incomplete, this is where
    it shows."""

    def __init__(self) -> None:
        self.arrays: Dict[str, np.ndarray] = {}
        self.epoch = -1
        self.pos = 0
        self.applied = 0

    def apply(self, record: Record) -> None:
        kind = record[0]
        if kind == "full":
            _, epoch, arrays, pos = record
            self.arrays = {k: np.array(v, copy=True) for k, v in arrays.items()}
            self.epoch = epoch
            self.pos = pos
        else:
            _, ops, uploads, pos = record
            # re-uploaded arrays supersede every suffix write to them
            # (the manager drops those ops on the floor; so do we)
            superseded = set(uploads)
            for name, arr in uploads.items():
                if arr is None:  # resync'd name absent from snapshot
                    self.arrays.pop(name, None)
                else:
                    self.arrays[name] = np.array(arr, copy=True)
            for name, idx, val in ops:
                if name == RESYNC or name in superseded:
                    continue
                arr = self.arrays.get(name)
                if arr is None:
                    # an op for an array the capture never shipped:
                    # the stream itself is incomplete — surface it at
                    # diverged() time rather than crashing mid-apply
                    continue
                arr.reshape(-1)[int(idx)] = arr.dtype.type(val)
            self.pos = pos
        self.applied += 1

    def diverged(self, snapshot: Dict[str, np.ndarray]) -> List[str]:
        """Array-exact comparison against a live host snapshot. Returns
        human-readable divergence descriptions (empty == converged)."""
        problems: List[str] = []
        live = {k: np.asarray(v) for k, v in snapshot.items()}
        for name in sorted(set(live) - set(self.arrays)):
            problems.append(f"{name}: missing from replica")
        for name in sorted(set(self.arrays) - set(live)):
            problems.append(f"{name}: stale in replica (dropped live)")
        for name in sorted(set(live) & set(self.arrays)):
            a, b = live[name], self.arrays[name]
            if a.shape != b.shape:
                problems.append(f"{name}: shape {b.shape} != live {a.shape}")
            elif a.dtype != b.dtype:
                problems.append(f"{name}: dtype {b.dtype} != live {a.dtype}")
            elif not np.array_equal(a, b):
                flat_a, flat_b = a.reshape(-1), b.reshape(-1)
                bad = np.nonzero(flat_a != flat_b)[0]
                i = int(bad[0])
                problems.append(
                    f"{name}: {len(bad)} slot(s) differ, first flat[{i}] "
                    f"replica={flat_b[i]!r} live={flat_a[i]!r}"
                )
        return problems


class ReplayTap:
    """Per-manager capture state. Created by `ReplayCheck.arm`; applies
    each captured record to its `ShadowReplica` eagerly (a streaming
    standby, not a batch importer)."""

    def __init__(self, manager, metrics=None) -> None:
        self.manager = manager
        self.metrics = metrics
        self.replica = ShadowReplica()
        self.records: List[Record] = []
        self.syncs = 0
        self.offers = 0
        self.src = None  # last source seen by sync()

    def capture(self, manager, src, pos0: int, fulls0: int) -> None:
        self.syncs += 1
        self.src = src
        if manager.full_resyncs > fulls0:
            # epoch upload (possibly with an adopted compaction offer
            # plus a delta on top) — the standby receives the whole
            # post-sync host image
            arrays = {
                k: np.array(v, copy=True)
                for k, v in src.device_snapshot().items()
            }
            rec: Record = ("full", src.epoch, arrays, manager._pos)
        else:
            ops = list(src.oplog[pos0:manager._pos])
            needed = {a for name, a, _v in ops if name == RESYNC}
            for name, _idx, _val in ops:
                if name != RESYNC and name not in self.replica.arrays:
                    needed.add(name)  # defensive re-upload of a new array
            uploads: Dict[str, Optional[np.ndarray]] = {}
            if needed:
                snap = src.device_snapshot()
                for name in needed:
                    v = snap.get(name)
                    uploads[name] = None if v is None else np.array(v, copy=True)
            rec = ("delta", ops, uploads, manager._pos)
        self.records.append(rec)
        self.replica.apply(rec)
        if self.metrics is not None:
            self.metrics.inc("replay.captures")
            self.metrics.inc("replay.syncs")

    def diverged(self, src=None) -> List[str]:
        src = src if src is not None else self.src
        if src is None:
            return ["no sync captured yet"]
        return self.replica.diverged(src.device_snapshot())


class ReplayCheck:
    """Arm/disarm registry in the `racetrack`/`faults` idiom: swaps a
    live manager's `__class__` for a capture subclass, restores it on
    disarm. Zero cost while disarmed — no global patching, untapped
    managers are untouched."""

    def __init__(self, metrics=None) -> None:
        self.metrics = metrics
        self._armed: Dict[int, Tuple[Any, type, ReplayTap]] = {}

    @property
    def armed(self) -> bool:
        return bool(self._armed)

    def arm(self, manager) -> ReplayTap:
        if id(manager) in self._armed:
            return self._armed[id(manager)][2]
        tap = ReplayTap(manager, metrics=self.metrics)
        orig = manager.__class__

        class _Tapped(orig):  # type: ignore[misc, valid-type]
            def sync(self, src):  # noqa: D102 - contract of orig
                pos0, fulls0 = self._pos, self.full_resyncs
                out = orig.sync(self, src)
                tap.capture(self, src, pos0, fulls0)
                return out

            def offer(self, epoch, arrays, pos=0):  # noqa: D102
                tap.offers += 1
                if tap.metrics is not None:
                    tap.metrics.inc("replay.offers")
                return orig.offer(self, epoch, arrays, pos)

        _Tapped.__name__ = orig.__name__
        _Tapped.__qualname__ = orig.__qualname__
        manager.__class__ = _Tapped
        self._armed[id(manager)] = (manager, orig, tap)
        return tap

    def disarm(self) -> None:
        for manager, orig, _tap in self._armed.values():
            manager.__class__ = orig
        self._armed.clear()

    def taps(self) -> List[ReplayTap]:
        return [t for _m, _c, t in self._armed.values()]


# -- the audit: five owners, randomized churn, raced compaction --------------


def _compact_racing(compactor, owner, race: Callable[[], None]) -> bool:
    """One compaction cycle with loop inserts racing the background
    build — `SegmentCompactor.compact_now` with churn injected between
    `build` and `apply`, so `apply`'s journal replay has to absorb it."""
    cap = owner.begin()
    built = owner.build(cap)
    race()  # loop mutations land while the "executor" holds the build
    applied = owner.apply(built)
    if applied is None:
        compactor.aborted += 1
        return False
    epoch, bufs, pos, merged = applied
    owner.manager.offer(epoch, bufs, pos)
    compactor.runs += 1
    return True


class _Churn:
    """One mirrored owner under audit: a source table, its manager,
    a mutation step, and (optionally) a compaction owner."""

    def __init__(self, name: str, src, manager, step, compact_owner=None,
                 pre_sync: Optional[Callable[[], None]] = None):
        self.name = name
        self.src = src
        self.manager = manager
        self.step = step  # fn(rng, i) -> None
        self.compact_owner = compact_owner
        self.pre_sync = pre_sync  # e.g. retained match() drives sync itself

    def sync(self):
        if self.pre_sync is not None:
            self.pre_sync()
        else:
            self.manager.sync(self.src)


def _build_churns() -> List[_Churn]:
    from emqx_tpu.models.retained_index import DeviceRetainedIndex
    from emqx_tpu.models.router_model import SubscriberTable
    from emqx_tpu.ops.csr_table import CsrSegmentOwner
    from emqx_tpu.ops.segments import DeviceSegmentManager, ShapeSegmentOwner
    from emqx_tpu.ops.semantic_table import (
        SemanticSegmentOwner,
        SemanticTable,
    )
    from emqx_tpu.ops.session_table import SessionSegmentOwner, SessionTable
    from emqx_tpu.ops.shape_index import ShapeIndex

    churns: List[_Churn] = []

    # 1. shape index: subscribe/unsubscribe filter churn
    si = ShapeIndex()
    man_si = DeviceSegmentManager(name="shapes")
    live_filters: List[str] = []

    def step_shapes(rng: random.Random, i: int) -> None:
        if live_filters and rng.random() < 0.3:
            si.remove(live_filters.pop(rng.randrange(len(live_filters))))
        else:
            f = f"r/{i}/{rng.randrange(8)}/+"
            si.add(f, i)
            live_filters.append(f)

    churns.append(_Churn(
        "shapes", si, man_si, step_shapes,
        ShapeSegmentOwner(si, man_si, hot_entries=1),
    ))

    # 2. sparse subscriber table (CSR representation behind the facade)
    subs = SubscriberTable(max_subscribers=128, mode="sparse")
    man_subs = DeviceSegmentManager(name="bitmaps")
    live_subs: List[Tuple[int, int]] = []

    def step_subs(rng: random.Random, i: int) -> None:
        if live_subs and rng.random() < 0.3:
            fid, slot = live_subs.pop(rng.randrange(len(live_subs)))
            subs.remove(fid, slot)
        else:
            fid, slot = rng.randrange(32), rng.randrange(128)
            subs.add(fid, slot)
            live_subs.append((fid, slot))

    churns.append(_Churn(
        "bitmaps", subs, man_subs, step_subs,
        CsrSegmentOwner(subs, man_subs, hot_entries=1),
    ))

    # 3. semantic table: embedding-filter churn
    sem = SemanticTable(dim=8, topk=4)
    man_sem = DeviceSegmentManager(name="semantic")
    live_sem: List[int] = []

    def step_sem(rng: random.Random, i: int) -> None:
        if live_sem and rng.random() < 0.3:
            sem.remove(live_sem.pop(rng.randrange(len(live_sem))))
        else:
            slot = rng.randrange(64)
            vec = np.asarray(
                [rng.uniform(-1, 1) for _ in range(8)], dtype=np.float32
            )
            if sem.add(slot, vec, threshold=0.5, fid=i):
                live_sem.append(slot)

    churns.append(_Churn(
        "semantic", sem, man_sem, step_sem,
        SemanticSegmentOwner(sem, man_sem, hot_entries=1),
    ))

    # 4. session table: insert/ack/expiry churn
    st = SessionTable(capacity=64, slots=32)
    man_st = DeviceSegmentManager(name="sessions")
    live_rows: List[int] = []

    def step_sessions(rng: random.Random, i: int) -> None:
        r = rng.random()
        if live_rows and r < 0.3:
            st.clear(live_rows.pop(rng.randrange(len(live_rows))))
        elif r < 0.4:
            st.set_expiry(rng.randrange(32), 1000 + i)
        else:
            row = st.insert(
                rng.randrange(32), (i % 65535) + 1, 1, i, i % 97
            )
            if row >= 0:
                live_rows.append(row)

    churns.append(_Churn(
        "sessions", st, man_st, step_sessions,
        SessionSegmentOwner(st, man_st, tombstone_frac=0.0),
    ))

    # 5. retained index: topic churn; match() drives its own sync
    ret = DeviceRetainedIndex(max_bytes=32)
    live_topics: List[str] = []

    def step_retained(rng: random.Random, i: int) -> None:
        if live_topics and rng.random() < 0.3:
            ret.remove(live_topics.pop(rng.randrange(len(live_topics))))
        else:
            t = f"s/{i}/t"
            ret.add(t)
            live_topics.append(t)

    churns.append(_Churn(
        "retained", ret, ret._seg, step_retained,
        pre_sync=lambda: ret.match("s/+/t"),
    ))
    return churns


def run_replay_audit(seed: int = 0, rounds: int = 48,
                     metrics=None) -> Dict[str, Any]:
    """Randomized five-owner churn under armed taps; returns a report:

      divergence        {owner: [problem, ...]} — MUST be empty
      negative_control  description of the seeded incomplete-log write;
                        `negative_detected` MUST be True
      per-owner sync/record/compaction counts

    Deterministic for a given (seed, rounds).
    """
    from emqx_tpu.ops.segments import SegmentCompactor

    rng = random.Random(seed)
    if metrics is not None:
        metrics.inc("analysis.replay.runs")
    churns = _build_churns()
    compactor = SegmentCompactor()
    check = ReplayCheck(metrics=metrics)
    taps = {c.name: check.arm(c.manager) for c in churns}
    try:
        for i in range(rounds):
            for c in churns:
                for _ in range(rng.randrange(1, 4)):
                    c.step(rng, i)
                if rng.random() < 0.5:
                    c.sync()
            # compaction racing loop inserts, through the journal path
            if i % 7 == 3:
                c = churns[rng.randrange(len(churns))]
                if c.compact_owner is not None:
                    _compact_racing(
                        compactor, c.compact_owner,
                        lambda: [c.step(rng, i) for _ in range(3)],
                    )
                    c.sync()
        # quiesce: final sync, then array-exact convergence per owner
        divergence: Dict[str, List[str]] = {}
        for c in churns:
            c.sync()
            problems = taps[c.name].diverged(c.src)
            if problems:
                divergence[c.name] = problems
        # negative control: a write that skips the op-log entirely must
        # surface as divergence (the mirror AND the standby both miss it)
        st_churn = next(c for c in churns if c.name == "sessions")
        st = st_churn.src
        st.slot_expiry[0] = np.int64(123456789)  # deliberately unlogged
        st_churn.sync()  # version unchanged -> sync ships nothing
        neg_problems = taps["sessions"].diverged(st)
        negative_detected = any("slot_expiry" in p for p in neg_problems)
        report: Dict[str, Any] = {
            "divergence": divergence,
            "negative_control": "unlogged slot_expiry[0] write on sessions",
            "negative_detected": negative_detected,
            "owners": {
                c.name: {
                    "syncs": taps[c.name].syncs,
                    "records": len(taps[c.name].records),
                    "full": sum(
                        1 for r in taps[c.name].records if r[0] == "full"
                    ),
                    "offers": taps[c.name].offers,
                }
                for c in churns
            },
            "compactions": compactor.runs,
            "compactions_aborted": compactor.aborted,
            "rounds": rounds,
            "seed": seed,
        }
        if metrics is not None:
            metrics.inc("replay.divergence", len(divergence))
            failures = len(divergence) + (0 if negative_detected else 1)
            if failures:
                metrics.inc("analysis.replay.failures", failures)
        return report
    finally:
        check.disarm()
