"""Telemetry reporter (opt-in usage statistics).

Parity: apps/emqx_modules/src/emqx_telemetry.erl — periodically collects
an anonymized report (node uuid, version, uptime, feature usage and
broker-scale counters, NO payloads/topics/identities) and POSTs it to a
configurable endpoint. Disabled by default; the report surface doubles as
`GET /telemetry/data` for operators to inspect exactly what would leave
the node.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
import uuid
from typing import Dict, Optional

log = logging.getLogger("emqx_tpu.telemetry")


class Telemetry:
    def __init__(
        self,
        app,
        enable: bool = False,
        url: str = "",
        interval: float = 7 * 24 * 3600.0,
        uuid_path: Optional[str] = None,
    ):
        self.app = app
        self.enable = enable
        self.url = url
        self.interval = interval
        # stable node identity across restarts (the reference persists its
        # telemetry UUID in mnesia); ephemeral only when no data dir exists
        self.node_uuid = self._load_uuid(uuid_path)
        self._task: Optional[asyncio.Task] = None
        self.last_report_at: Optional[float] = None

    @staticmethod
    def _load_uuid(path: Optional[str]) -> str:
        if path is None:
            return uuid.uuid4().hex
        try:
            with open(path) as f:
                existing = f.read().strip()
            if existing:
                return existing
        except OSError:
            pass
        fresh = uuid.uuid4().hex
        try:
            import os

            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(path, "w") as f:
                f.write(fresh)
        except OSError as e:
            log.warning("cannot persist telemetry uuid: %s", e)
        return fresh

    def get_telemetry_data(self) -> Dict:
        """The full (anonymized) report — what `enable` would transmit."""
        from emqx_tpu import __version__

        broker = self.app.broker
        c = self.app.config
        return {
            "uuid": self.node_uuid,
            "version": __version__,
            "license": {"edition": "opensource"},
            "uptime_seconds": int(
                time.time() - (self.app.started_at or time.time())
            ),
            "connections": self.app.cm.channel_count(),
            "subscriptions": broker.subscription_count(),
            "routes": len(broker.router),
            "messages_received": broker.metrics.snapshot().get(
                "messages.received", 0
            ),
            "active_plugins": [
                p["name"] for p in getattr(self.app, "plugins", None).list()
            ]
            if getattr(self.app, "plugins", None)
            else [],
            "features": {
                "tpu_routing": c.router.enable_tpu,
                "gateways": [g.type for g in c.gateways],
                "bridges": [b.id.partition(":")[0] for b in c.bridges],
                "authn": c.authn.enable,
                "rule_engine": bool(c.rules),
                "cluster": False,
            },
        }

    def start(self) -> None:
        if self.enable and self.url:
            self._task = asyncio.get_event_loop().create_task(self._loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        try:
            while True:
                await self.report_now()
                await asyncio.sleep(self.interval)
        except asyncio.CancelledError:
            pass

    async def report_now(self) -> bool:
        try:
            import aiohttp

            async with aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=10)
            ) as s:
                async with s.post(
                    self.url, json=self.get_telemetry_data()
                ) as resp:
                    ok = resp.status < 300
            self.last_report_at = time.time()
            return ok
        except Exception as e:
            log.debug("telemetry report failed: %s", e)
            return False
