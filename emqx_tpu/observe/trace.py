"""Live packet/event tracing to files (reference: apps/emqx/src/emqx_trace/).

The reference manages named trace specs (filter by clientid, topic, or IP)
in mnesia, installs logger handlers per trace writing formatted lines to
per-trace files, with start/end windows and REST download
(emqx_trace.erl:30-50, emqx_trace_handler.erl:26-45). Trace points are
invoked inline from broker ops (emqx_broker.erl:129,177,205).

Here: `TraceManager` owns the spec table and open files; it attaches to the
same hookpoints the reference traces (publish/subscribe/unsubscribe,
connect/disconnect, deliver) and writes one formatted line per matching
event. Files live under `base_dir`; finished traces stay on disk for
download until deleted.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from emqx_tpu.ops import topics as T


@dataclass
class TraceSpec:
    name: str
    type: str  # clientid | topic | ip_address
    value: str
    start_at: float = field(default_factory=time.time)
    end_at: Optional[float] = None  # None = until stopped
    enabled: bool = True

    def status(self, now: Optional[float] = None) -> str:
        now = now or time.time()
        if not self.enabled:
            return "stopped"
        if now < self.start_at:
            return "waiting"
        if self.end_at is not None and now >= self.end_at:
            return "stopped"
        return "running"

    def matches(self, meta: Dict) -> bool:
        if self.type == "clientid":
            return meta.get("clientid") == self.value
        if self.type == "topic":
            topic = meta.get("topic")
            return topic is not None and T.match(topic, self.value)
        if self.type == "ip_address":
            return meta.get("peerhost") == self.value
        return False


class TraceManager:
    MAX_TRACES = 30  # reference caps concurrent traces

    def __init__(self, base_dir: str = "trace"):
        self.base_dir = base_dir
        self._specs: Dict[str, TraceSpec] = {}
        self._files: Dict[str, object] = {}

    # -- spec management ---------------------------------------------------
    def create(
        self,
        name: str,
        type: str,
        value: str,
        start_at: Optional[float] = None,
        end_at: Optional[float] = None,
    ) -> TraceSpec:
        if name in self._specs:
            raise ValueError("already_existed")
        if type not in ("clientid", "topic", "ip_address"):
            raise ValueError(f"bad trace type {type!r}")
        if type == "topic":
            T.validate(value, kind="filter")
        if sum(1 for s in self._specs.values() if s.status() != "stopped") \
                >= self.MAX_TRACES:
            raise OverflowError("max_traces")
        spec = TraceSpec(
            name=name,
            type=type,
            value=value,
            start_at=start_at or time.time(),
            end_at=end_at,
        )
        self._specs[name] = spec
        os.makedirs(self.base_dir, exist_ok=True)
        self._files[name] = open(self.filepath(name), "a", encoding="utf-8")
        return spec

    def stop(self, name: str) -> bool:
        spec = self._specs.get(name)
        if spec is None:
            return False
        spec.enabled = False
        f = self._files.pop(name, None)
        if f:
            f.close()
        return True

    def delete(self, name: str) -> bool:
        self.stop(name)
        if self._specs.pop(name, None) is None:
            return False
        try:
            os.unlink(self.filepath(name))
        except OSError:
            pass
        return True

    def list(self) -> List[Dict]:
        now = time.time()
        return [
            {
                "name": s.name,
                "type": s.type,
                s.type: s.value,
                "status": s.status(now),
                "start_at": s.start_at,
                "end_at": s.end_at,
            }
            for s in self._specs.values()
        ]

    def filepath(self, name: str) -> str:
        import zlib

        # hash suffix keeps distinct names distinct after sanitization
        # (e.g. 'a/b' vs 'a_b')
        safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in name)
        tag = zlib.crc32(name.encode()) & 0xFFFFFFFF
        return os.path.join(self.base_dir, f"trace_{safe}_{tag:08x}.log")

    def read(self, name: str) -> Optional[str]:
        if name not in self._specs:
            return None
        f = self._files.get(name)
        if f:
            f.flush()
        try:
            with open(self.filepath(name), encoding="utf-8") as fh:
                return fh.read()
        except OSError:
            return ""

    def close(self) -> None:
        for f in self._files.values():
            f.close()
        self._files.clear()

    def sweep(self, now: Optional[float] = None) -> None:
        """Close file handles of specs whose window has passed. A spec
        that expires via `end_at` keeps status "stopped" without anyone
        calling stop() — without this, its handle leaks until delete()
        or process exit (the finished trace stays on disk for download)."""
        now = now or time.time()
        for name, spec in self._specs.items():
            if name in self._files and spec.status(now) == "stopped":
                self._files.pop(name).close()

    def should_sample(self, client_id: str, topic: str) -> bool:
        """Always-sample hook for the span recorder (observe/spans.py):
        a client or topic under an ACTIVE trace spec gets 100% span
        sampling, so emqx_trace-style debugging sees every span of the
        flow being traced. ip_address specs don't apply (the publish head
        has no peer address)."""
        if not self._specs:
            return False
        now = time.time()
        for spec in self._specs.values():
            if spec.status(now) != "running":
                continue
            if spec.type == "clientid" and spec.value == client_id:
                return True
            if spec.type == "topic" and T.match(topic, spec.value):
                return True
        return False

    # -- logging -----------------------------------------------------------
    def log(self, event: str, meta: Dict) -> None:
        now = time.time()
        line = None
        stopped = None
        for name, spec in self._specs.items():
            status = spec.status(now)
            if status != "running":
                # expired-window specs surface here first: close their
                # files inline so the hot path never carries leaked fds
                # ("waiting" specs keep theirs — they start later)
                if status == "stopped" and name in self._files:
                    stopped = [name] if stopped is None else stopped + [name]
                continue
            if not spec.matches(meta):
                continue
            f = self._files.get(name)
            if f is None:
                continue
            if line is None:
                ts = time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(now))
                kv = " ".join(
                    f"{k}: {v}" for k, v in meta.items() if v is not None
                )
                line = f"{ts}.{int(now * 1000) % 1000:03d} [{event}] {kv}\n"
            f.write(line)
            f.flush()
        if stopped:
            for name in stopped:
                self._files.pop(name).close()

    # -- hook wiring (the reference traces these ops inline) ----------------
    def attach(self, hooks) -> None:
        def payload_preview(msg):
            p = msg.payload[:64]
            try:
                return p.decode("utf-8")
            except UnicodeDecodeError:
                return p.hex()

        def on_publish(msg, acc=None):
            # no active traces: skip the meta-dict build — this and
            # on_delivered run per message/delivery
            if self._specs:
                self.log(
                    "PUBLISH",
                    {
                        "clientid": msg.from_client or None,
                        "topic": msg.topic,
                        "qos": msg.qos,
                        "retain": msg.retain,
                        "payload": payload_preview(msg),
                    },
                )
            return acc if acc is not None else msg

        def on_subscribed(ci, topic, opts, _ch=None):
            self.log(
                "SUBSCRIBE",
                {
                    "clientid": ci.get("client_id"),
                    "peerhost": ci.get("peerhost"),
                    "topic": topic,
                    "qos": getattr(opts, "qos", 0),
                },
            )

        def on_unsubscribed(ci, topic):
            self.log(
                "UNSUBSCRIBE",
                {
                    "clientid": ci.get("client_id"),
                    "peerhost": ci.get("peerhost"),
                    "topic": topic,
                },
            )

        def on_connected(ci, _ch):
            self.log(
                "CONNECT",
                {
                    "clientid": ci.get("client_id"),
                    "username": ci.get("username"),
                    "peerhost": ci.get("peerhost"),
                },
            )

        def on_disconnected(ci, reason):
            self.log(
                "DISCONNECT",
                {
                    "clientid": ci.get("client_id"),
                    "peerhost": ci.get("peerhost"),
                    "reason": reason,
                },
            )

        def on_delivered(ci, msg):
            if not self._specs:
                return
            self.log(
                "DELIVER",
                {
                    "clientid": ci.get("client_id"),
                    "topic": msg.topic,
                    "qos": msg.qos,
                    "payload": payload_preview(msg),
                },
            )

        hooks.add("message.publish", on_publish, priority=90, tag="trace")
        hooks.add("session.subscribed", on_subscribed, tag="trace")
        hooks.add("session.unsubscribed", on_unsubscribed, tag="trace")
        hooks.add("client.connected", on_connected, tag="trace")
        hooks.add("client.disconnected", on_disconnected, tag="trace")
        hooks.add("message.delivered", on_delivered, tag="trace")
