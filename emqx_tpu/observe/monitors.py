"""Runtime health monitors -> alarms.

The reference watches its runtime with three processes (SURVEY.md §5.2/§5.3):
- emqx_sys_mon: BEAM scheduler anomalies (long_gc, long_schedule, large_heap,
  busy_port) -> alarms (apps/emqx/src/emqx_sys_mon.erl:63-76)
- emqx_os_mon: OS cpu/mem watermarks (emqx_os_mon.erl)
- emqx_vm_mon: process-count watermarks (emqx_vm_mon.erl)

The asyncio/CPython equivalents of the runtime anomalies:
- event-loop lag (a blocked loop is the moral twin of long_schedule)
- GC pause spikes (gc callbacks time each collection ~ long_gc)
- task count (asyncio tasks are the process analog) and fd count.

All are polled by `check(now)` from the app's housekeeping tick; no threads.
"""

from __future__ import annotations

import asyncio
import gc
import os
import time
from typing import Optional

from emqx_tpu.observe.alarm import AlarmManager


class SysMon:
    """Event-loop lag + GC pause detector (emqx_sys_mon analog).

    Both alarms are transient (level-triggered): they raise when an anomaly
    occurs and clear after `clear_after` seconds without a recurrence.
    The gc callback only RECORDS the pause — it must not run alarm/publish
    code, since gc can fire re-entrantly at any allocation point; `check`
    (the housekeeping tick) surfaces the recorded anomaly safely.
    """

    def __init__(
        self,
        alarms: AlarmManager,
        long_schedule_ms: float = 240.0,
        long_gc_ms: float = 100.0,
        clear_after: float = 60.0,
    ):
        self.alarms = alarms
        self.long_schedule_ms = long_schedule_ms
        self.long_gc_ms = long_gc_ms
        self.clear_after = clear_after
        self._expected: Optional[float] = None
        self._interval: Optional[float] = None
        self._gc_start: Optional[float] = None
        self.max_gc_ms = 0.0
        self._pending_gc_ms: Optional[float] = None
        self._last_long_gc: float = 0.0
        self._last_long_schedule: float = 0.0
        gc.callbacks.append(self._on_gc)

    def close(self) -> None:
        try:
            gc.callbacks.remove(self._on_gc)
        except ValueError:
            pass

    def _on_gc(self, phase: str, info: dict) -> None:
        # record-only: no allocation-heavy work inside the gc hook
        if phase == "start":
            self._gc_start = time.perf_counter()
        elif self._gc_start is not None:
            ms = (time.perf_counter() - self._gc_start) * 1000.0
            self._gc_start = None
            if ms > self.max_gc_ms:
                self.max_gc_ms = ms
            if ms > self.long_gc_ms and (
                self._pending_gc_ms is None or ms > self._pending_gc_ms
            ):
                self._pending_gc_ms = ms

    def _raise_transient(self, name: str, details: dict, message: str) -> None:
        # refresh an already-active alarm so repeats update the details
        if self.alarms.is_active(name):
            self.alarms.deactivate(name)
        self.alarms.activate(name, details, message)

    def check(self, now: float, tick_interval: float) -> None:
        """Called each housekeeping tick; lag = how late the tick fired."""
        if self._pending_gc_ms is not None:
            ms = self._pending_gc_ms
            self._pending_gc_ms = None
            self._last_long_gc = now
            self._raise_transient(
                "long_gc",
                {"duration_ms": round(ms, 2)},
                f"gc pause {ms:.1f}ms > {self.long_gc_ms}ms",
            )
        if self._expected is not None and self._interval == tick_interval:
            lag_ms = (now - self._expected) * 1000.0
            if lag_ms > self.long_schedule_ms:
                self._last_long_schedule = now
                self._raise_transient(
                    "long_schedule",
                    {"lag_ms": round(lag_ms, 2)},
                    f"event loop lagged {lag_ms:.0f}ms behind its timer",
                )
        # auto-clear after a quiet period
        if (
            self.alarms.is_active("long_gc")
            and now - self._last_long_gc > self.clear_after
        ):
            self.alarms.deactivate("long_gc")
        if (
            self.alarms.is_active("long_schedule")
            and now - self._last_long_schedule > self.clear_after
        ):
            self.alarms.deactivate("long_schedule")
        self._expected = now + tick_interval
        self._interval = tick_interval


def _meminfo() -> dict:
    out = {}
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                k, _, rest = line.partition(":")
                out[k.strip()] = int(rest.strip().split()[0]) * 1024
    except OSError:
        pass
    return out


def _cpu_times() -> Optional[tuple]:
    try:
        with open("/proc/stat") as f:
            parts = f.readline().split()
        vals = [int(x) for x in parts[1:]]
        idle = vals[3] + (vals[4] if len(vals) > 4 else 0)
        return sum(vals), idle
    except OSError:
        return None


class OsMon:
    """CPU/memory watermark alarms from /proc (emqx_os_mon analog)."""

    def __init__(
        self,
        alarms: AlarmManager,
        cpu_high_watermark: float = 0.80,
        cpu_low_watermark: float = 0.60,
        mem_high_watermark: float = 0.70,
    ):
        self.alarms = alarms
        self.cpu_high = cpu_high_watermark
        self.cpu_low = cpu_low_watermark
        self.mem_high = mem_high_watermark
        self._prev_cpu = _cpu_times()
        self.cpu_usage = 0.0
        self.mem_usage = 0.0

    def check(self, now: float) -> None:
        cur = _cpu_times()
        if cur and self._prev_cpu:
            dt = cur[0] - self._prev_cpu[0]
            didle = cur[1] - self._prev_cpu[1]
            if dt > 0:
                self.cpu_usage = max(0.0, 1.0 - didle / dt)
                # hysteresis: raise above high, clear below low
                if self.cpu_usage > self.cpu_high:
                    self.alarms.activate(
                        "high_cpu_usage",
                        {"usage": round(self.cpu_usage, 3)},
                        f"cpu usage {self.cpu_usage:.0%} > {self.cpu_high:.0%}",
                    )
                elif self.cpu_usage < self.cpu_low:
                    self.alarms.deactivate("high_cpu_usage")
        self._prev_cpu = cur

        mi = _meminfo()
        total = mi.get("MemTotal")
        avail = mi.get("MemAvailable")
        if total and avail is not None and total > 0:
            self.mem_usage = 1.0 - avail / total
            self.alarms.ensure(
                "high_system_memory_usage",
                self.mem_usage > self.mem_high,
                {"usage": round(self.mem_usage, 3)},
                f"memory usage {self.mem_usage:.0%} > {self.mem_high:.0%}",
            )


class VmMon:
    """Task/fd watermark alarms (emqx_vm_mon's process-count analog)."""

    def __init__(
        self,
        alarms: AlarmManager,
        task_high_watermark: float = 0.80,
        task_low_watermark: float = 0.60,
        max_tasks: int = 1_000_000,
    ):
        self.alarms = alarms
        self.task_high = task_high_watermark
        self.task_low = task_low_watermark
        self.max_tasks = max_tasks
        self.task_count = 0
        self.fd_count = 0

    def check(self, now: float) -> None:
        try:
            self.task_count = len(asyncio.all_tasks())
        except RuntimeError:
            self.task_count = 0
        try:
            self.fd_count = len(os.listdir("/proc/self/fd"))
        except OSError:
            pass
        usage = self.task_count / self.max_tasks if self.max_tasks else 0.0
        if usage > self.task_high:
            self.alarms.activate(
                "too_many_processes",
                {"usage": round(usage, 3), "tasks": self.task_count},
                f"task count {self.task_count} > {self.task_high:.0%} of limit",
            )
        elif usage < self.task_low:
            self.alarms.deactivate("too_many_processes")
