"""Observability: alarms, runtime monitors, slow-subscriber tracking,
per-topic metrics, $event messages, Prometheus/StatsD export, packet trace.

Reference surface: apps/emqx/src/emqx_alarm.erl, emqx_sys_mon/os_mon/vm_mon,
apps/emqx_slow_subs, emqx_topic_metrics.erl, emqx_event_message.erl,
apps/emqx_prometheus, apps/emqx_statsd, apps/emqx/src/emqx_trace/
(SURVEY.md §5.1, §5.5).
"""
