"""Alarm lifecycle: activate/deactivate named alarms with history.

Parity with the reference (apps/emqx/src/emqx_alarm.erl): alarms are named,
carry details + message, live in an activated table until deactivated, then
move to a capped history; every transition republishes to
$SYS/brokers/<node>/alarms/activate|deactivate so MQTT clients can watch
them (the reference's emqx_alarm_handler behavior).
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from emqx_tpu.utils.node import node_name


@dataclass
class Alarm:
    name: str
    details: Dict = field(default_factory=dict)
    message: str = ""
    activated_at: float = field(default_factory=time.time)
    deactivated_at: Optional[float] = None

    def to_json(self) -> Dict:
        return {
            "name": self.name,
            "node": node_name(),
            "details": self.details,
            "message": self.message,
            "activated_at": self.activated_at,
            "deactivated_at": self.deactivated_at,
            "duration": (
                (self.deactivated_at or time.time()) - self.activated_at
            ),
        }


class AlarmManager:
    def __init__(
        self,
        publish: Optional[Callable] = None,
        size_limit: int = 1000,
        validity_period: float = 24 * 3600.0,
    ):
        """`publish(topic, payload_bytes)` republishes transitions ($SYS)."""
        self._active: Dict[str, Alarm] = {}
        self._history: List[Alarm] = []
        self._publish = publish
        self.size_limit = size_limit
        self.validity_period = validity_period

    def activate(
        self, name: str, details: Optional[Dict] = None, message: str = ""
    ) -> bool:
        """Returns False when already active (reference: {error, duplicated})."""
        if name in self._active:
            return False
        alarm = Alarm(name=name, details=details or {}, message=message)
        self._active[name] = alarm
        self._republish("activate", alarm)
        return True

    def deactivate(self, name: str) -> bool:
        alarm = self._active.pop(name, None)
        if alarm is None:
            return False
        alarm.deactivated_at = time.time()
        self._history.append(alarm)
        if len(self._history) > self.size_limit:
            del self._history[: len(self._history) - self.size_limit]
        self._republish("deactivate", alarm)
        return True

    def ensure(self, name: str, active: bool, details=None, message="") -> None:
        """Level-triggered helper: (de)activate to match a boolean condition."""
        if active:
            self.activate(name, details, message)
        else:
            self.deactivate(name)

    def is_active(self, name: str) -> bool:
        return name in self._active

    def list(self, activated: Optional[bool] = None) -> List[Dict]:
        if activated is True:
            items = list(self._active.values())
        elif activated is False:
            items = list(self._history)
        else:
            items = list(self._active.values()) + list(self._history)
        return [a.to_json() for a in items]

    def delete_all_deactivated(self) -> int:
        n = len(self._history)
        self._history.clear()
        return n

    def sweep(self, now: Optional[float] = None) -> None:
        """Expire history entries past validity_period (emqx_alarm GC)."""
        now = now or time.time()
        self._history = [
            a
            for a in self._history
            if (a.deactivated_at or now) + self.validity_period > now
        ]

    def _republish(self, kind: str, alarm: Alarm) -> None:
        if self._publish is None:
            return
        topic = f"$SYS/brokers/{node_name()}/alarms/{kind}"
        try:
            self._publish(topic, json.dumps(alarm.to_json()).encode())
        except Exception:
            pass


class FallbackRateWatch:
    """Level-triggered alarm on the TPU-path fallback-row rate.

    Sustained fallback means the device kernel has effectively degraded to
    the CPU trie (frontier/match caps too small for the live workload, or
    topics deeper/longer than the compiled budgets) — the broker still
    answers correctly, but at per-message CPU cost. This watch reads the
    flight-recorder counters (broker serving path + TpuMatcher), computes
    the fallback rate over a sliding window, and (de)activates one alarm
    against the configured threshold.

    Windows with fewer than `min_rows` routed rows are ignored in BOTH
    directions: too little traffic neither raises nor clears the alarm
    (an idle broker must not flap an operator page)."""

    ALARM = "tpu_fallback_rate"

    def __init__(
        self,
        alarms: AlarmManager,
        metrics,
        threshold: float = 0.2,
        window: float = 10.0,
        min_rows: int = 64,
    ):
        self.alarms = alarms
        self.metrics = metrics
        self.threshold = threshold
        self.window = window
        self.min_rows = min_rows
        self._last_at: Optional[float] = None
        self._last_fallback = 0
        self._last_total = 0

    def _totals(self) -> tuple:
        m = self.metrics
        fallback = m.get("messages.routed.device_fallback") + m.get(
            "matcher.fallback.rows"
        )
        total = (
            m.get("messages.routed.device")
            + m.get("messages.routed.device_fallback")
            + m.get("matcher.rows")
        )
        return fallback, total

    def check(self, now: Optional[float] = None) -> Optional[float]:
        """Evaluate once per elapsed window; returns the window's fallback
        rate when a window closed (None otherwise). Call from the
        housekeeping tick."""
        now = now if now is not None else time.time()
        if self._last_at is None:
            self._last_at = now
            self._last_fallback, self._last_total = self._totals()
            return None
        if now - self._last_at < self.window:
            return None
        fallback, total = self._totals()
        d_fb = fallback - self._last_fallback
        d_total = total - self._last_total
        self._last_at = now
        self._last_fallback, self._last_total = fallback, total
        if d_total < self.min_rows:
            return None
        rate = d_fb / d_total
        self.alarms.ensure(
            self.ALARM,
            rate > self.threshold,
            details={
                "rate": round(rate, 4),
                "threshold": self.threshold,
                "window_seconds": self.window,
                "fallback_rows": d_fb,
                "routed_rows": d_total,
            },
            message=(
                f"TPU route fallback rate {rate:.1%} over the last "
                f"{self.window:g}s exceeds {self.threshold:.1%}: the "
                "device fast path is degrading to the CPU trie"
            ),
        )
        return rate


class SloViolationWatch:
    """Level-triggered alarm on sustained SLO p99 target misses.

    The adaptive-batching controller (broker/slo.py) closes one
    evaluation window per `slo.eval.interval` and counts a violation
    when the observed enqueue->settle p99 missed the configured target.
    One miss is the controller's job to absorb (widen the window, walk
    the ladder); this watch pages only when the MISSES THEMSELVES are
    sustained — the violation *rate* over its sliding window stays at or
    above `threshold` — meaning the ladder ran out of rungs and the
    broker is serving outside its latency contract.

    Windows with fewer than `min_windows` controller evaluations are
    ignored in BOTH directions (an idle broker, or one with the
    controller off, must not flap an operator page) — the
    FallbackRateWatch min-traffic convention."""

    ALARM = "slo_p99_violation"

    def __init__(
        self,
        alarms: AlarmManager,
        metrics,
        threshold: float = 0.5,
        window: float = 10.0,
        min_windows: int = 4,
    ):
        self.alarms = alarms
        self.metrics = metrics
        self.threshold = threshold
        self.window = window
        self.min_windows = max(1, int(min_windows))
        self._last_at: Optional[float] = None
        self._last_viol = 0
        self._last_evals = 0

    def check(self, now: Optional[float] = None) -> Optional[float]:
        """Evaluate once per elapsed window; returns the window's
        violation rate when a window closed (None otherwise). Call from
        the housekeeping tick."""
        now = now if now is not None else time.time()
        m = self.metrics
        if self._last_at is None:
            self._last_at = now
            self._last_viol = m.get("slo.violations")
            self._last_evals = m.get("slo.eval.windows")
            return None
        if now - self._last_at < self.window:
            return None
        viol = m.get("slo.violations")
        evals = m.get("slo.eval.windows")
        d_viol = viol - self._last_viol
        d_evals = evals - self._last_evals
        self._last_at = now
        self._last_viol, self._last_evals = viol, evals
        if d_evals < self.min_windows:
            return None
        rate = d_viol / d_evals
        self.alarms.ensure(
            self.ALARM,
            rate >= self.threshold,
            details={
                "violation_rate": round(rate, 4),
                "threshold": self.threshold,
                "window_seconds": self.window,
                "violations": d_viol,
                "eval_windows": d_evals,
                "observed_p99_ms": m.gauge("slo.p99.observed_ms"),
                "target_p99_ms": m.gauge("slo.p99.target_ms"),
                "ladder_rung": m.gauge("slo.ladder.rung"),
            },
            message=(
                f"ingest p99 missed the "
                f"{m.gauge('slo.p99.target_ms'):g}ms SLO target in "
                f"{rate:.0%} of controller windows over the last "
                f"{self.window:g}s: the adaptive-batching ladder is "
                "saturated (sustained overload or a degraded fast path)"
            ),
        )
        return rate


class RetraceStormWatch:
    """Level-triggered alarm on steady-state jit compile activity.

    Boot compiles are normal (warmup, first table growth). A compile rate
    that STAYS nonzero after warmup means some batch property keeps
    leaking into a shape or static jit position — every "new" batch
    recompiles the serving program, each compile costing seconds to tens
    of seconds of device stall. The static RT checker predicts the common
    sources; this watch observes the live symptom from the
    `device.compile.count` counter (fed by `DeviceWatch.poll`).

    Semantics: windows ending inside the warmup period only advance the
    cursor. After warmup, `sustain` CONSECUTIVE windows each seeing
    `threshold`+ compiles activate the alarm; any compile-free window
    clears it (level-triggered, like FallbackRateWatch).
    """

    ALARM = "tpu_retrace_storm"

    def __init__(
        self,
        alarms: AlarmManager,
        metrics,
        threshold: int = 1,
        window: float = 10.0,
        warmup: float = 60.0,
        sustain: int = 2,
    ):
        self.alarms = alarms
        self.metrics = metrics
        self.threshold = max(1, int(threshold))
        self.window = window
        self.warmup = warmup
        self.sustain = max(1, int(sustain))
        self.started_at = time.time()
        self._last_at: Optional[float] = None
        self._last_count = 0
        self._hot_windows = 0

    def check(self, now: Optional[float] = None) -> Optional[int]:
        """Evaluate once per elapsed window; returns the closed window's
        compile count (None when no window closed)."""
        now = now if now is not None else time.time()
        if self._last_at is None:
            self._last_at = now
            self._last_count = self.metrics.get("device.compile.count")
            return None
        if now - self._last_at < self.window:
            return None
        count = self.metrics.get("device.compile.count")
        d = count - self._last_count
        self._last_at = now
        self._last_count = count
        if now < self.started_at + self.warmup:
            return d  # boot compiles: observe, never alarm
        self._hot_windows = self._hot_windows + 1 if d >= self.threshold else 0
        self.alarms.ensure(
            self.ALARM,
            self._hot_windows >= self.sustain,
            details={
                "compiles_last_window": d,
                "threshold": self.threshold,
                "window_seconds": self.window,
                "consecutive_hot_windows": self._hot_windows,
                "compile_cache_size": self.metrics.gauge(
                    "device.compile.cache_size"
                ),
            },
            message=(
                f"jit compile rate nonzero for {self._hot_windows} "
                f"consecutive {self.window:g}s windows in steady state: "
                "a batch property is leaking into a jit shape/static "
                "position (retrace storm) — each recompile stalls the "
                "serving path"
            ),
        )
        return d
