"""Metric exporters: Prometheus text exposition + StatsD UDP push.

Reference surface: apps/emqx_prometheus (scrape endpoint
/api/v5/prometheus/stats + push-gateway client), apps/emqx_statsd (same
metric families over statsd UDP). Metric names follow the reference's
prometheus naming (emqx_ prefix, dots -> underscores).

Metric KIND (counter/gauge/histogram) comes from the declaration registry
in emqx_tpu.broker.metrics — never from name-substring guessing — so a new
series renders with the right `# TYPE` the moment it is declared.
Histograms render as real Prometheus histogram families
(`_bucket{le=...}` / `_sum` / `_count`); StatsD renders seconds-unit
histograms as timers.
"""

from __future__ import annotations

import asyncio
import socket
from typing import Dict, Optional

from emqx_tpu.broker.metrics import GAUGE, kind_of, spec


def _prom_name(name: str) -> str:
    return "emqx_" + name.replace(".", "_").replace("-", "_")


def _fmt_le(le: float) -> str:
    return "+Inf" if le == float("inf") else f"{le:g}"


def prometheus_exposition(
    metrics_snapshot: Dict[str, float],
    extra_gauges: Optional[Dict] = None,
    histograms: Optional[Dict[str, Dict]] = None,
) -> str:
    """Render one scrape body (text exposition format 0.0.4).

    `histograms`: Metrics.histograms() snapshots — rendered as
    `# TYPE ... histogram` families with _bucket/_sum/_count lines.
    """
    lines = []
    merged = dict(metrics_snapshot)
    if extra_gauges:
        merged.update(extra_gauges)
    for name in sorted(merged):
        v = merged[name]
        pname = _prom_name(name)
        kind = kind_of(name) or "untyped"
        lines.append(f"# TYPE {pname} {kind}")
        lines.append(f"{pname} {float(v):g}")
    for name in sorted(histograms or ()):
        snap = histograms[name]
        pname = _prom_name(name)
        lines.append(f"# TYPE {pname} histogram")
        for le, cum in snap["buckets"]:
            lines.append(f'{pname}_bucket{{le="{_fmt_le(le)}"}} {cum}')
        lines.append(f"{pname}_sum {float(snap['sum']):g}")
        lines.append(f"{pname}_count {snap['count']}")
    return "\n".join(lines) + "\n"


class StatsdExporter:
    """Periodic UDP push of the same families (emqx_statsd analog)."""

    def __init__(
        self,
        metrics,
        host: str = "127.0.0.1",
        port: int = 8125,
        interval: float = 30.0,
        prefix: str = "emqx",
    ):
        self.metrics = metrics
        self.addr = (host, port)
        self.interval = interval
        self.prefix = prefix
        self._task: Optional[asyncio.Task] = None
        self._sock: Optional[socket.socket] = None
        self._last: Dict[str, float] = {}
        # per-histogram (count, sum) at the previous render
        self._last_hist: Dict[str, tuple] = {}

    def render(self) -> bytes:
        """counters -> statsd 'c' deltas; gauges -> 'g'; seconds-unit
        histograms -> '|ms' timers (mean of the interval) + percentile
        gauges."""
        snap = self.metrics.snapshot()
        out = []
        for name, v in sorted(snap.items()):
            sname = f"{self.prefix}.{name}"
            if kind_of(name) == GAUGE:
                out.append(f"{sname}:{float(v):g}|g")
            else:  # counters (declared or not) push as deltas
                delta = v - self._last.get(name, 0)
                self._last[name] = v
                if delta:
                    out.append(f"{sname}:{float(delta):g}|c")
        hists = getattr(self.metrics, "histograms", None)
        for name, h in sorted(hists().items() if hists else ()):
            sname = f"{self.prefix}.{name}"
            lc, ls = self._last_hist.get(name, (0, 0.0))
            dc, ds = h["count"] - lc, h["sum"] - ls
            self._last_hist[name] = (h["count"], h["sum"])
            if dc <= 0:
                continue
            s = spec(name)
            if s is not None and s.unit == "seconds":
                # statsd timers are per-observation ms; we hold aggregates,
                # so push the interval mean as one weighted timer line
                out.append(f"{sname}:{ds / dc * 1e3:g}|ms|@{1.0 / dc:g}")
            else:
                out.append(f"{sname}.mean:{ds / dc:g}|g")
            out.append(f"{sname}.count:{float(dc):g}|c")
        return "\n".join(out).encode()

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            self.push()

    def push(self) -> int:
        payload = self.render()
        if not payload:
            return 0
        if self._sock is None:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            # chunk to stay under typical UDP MTU
            sent = 0
            buf = b""
            for line in payload.split(b"\n"):
                if len(buf) + len(line) + 1 > 1400 and buf:
                    self._sock.sendto(buf, self.addr)
                    sent += 1
                    buf = b""
                buf += (b"\n" if buf else b"") + line
            if buf:
                self._sock.sendto(buf, self.addr)
                sent += 1
            return sent
        except OSError:
            return 0

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.ensure_future(self._loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None
        if self._sock is not None:
            self._sock.close()
            self._sock = None
