"""Metric exporters: Prometheus text exposition + StatsD UDP push.

Reference surface: apps/emqx_prometheus (scrape endpoint
/api/v5/prometheus/stats + push-gateway client), apps/emqx_statsd (same
metric families over statsd UDP). Metric names follow the reference's
prometheus naming (emqx_ prefix, dots -> underscores).
"""

from __future__ import annotations

import asyncio
import socket
from typing import Dict, Optional


def _prom_name(name: str) -> str:
    return "emqx_" + name.replace(".", "_").replace("-", "_")


def prometheus_exposition(
    metrics_snapshot: Dict[str, float], extra_gauges: Optional[Dict] = None
) -> str:
    """Render one scrape body (text exposition format 0.0.4)."""
    lines = []
    merged = dict(metrics_snapshot)
    if extra_gauges:
        merged.update(extra_gauges)
    for name in sorted(merged):
        v = merged[name]
        pname = _prom_name(name)
        kind = "counter" if ("." in name and not name.endswith("count")
                             and "usage" not in name
                             and "uptime" not in name) else "gauge"
        lines.append(f"# TYPE {pname} {kind}")
        lines.append(f"{pname} {float(v):g}")
    return "\n".join(lines) + "\n"


class StatsdExporter:
    """Periodic UDP push of the same families (emqx_statsd analog)."""

    def __init__(
        self,
        metrics,
        host: str = "127.0.0.1",
        port: int = 8125,
        interval: float = 30.0,
        prefix: str = "emqx",
    ):
        self.metrics = metrics
        self.addr = (host, port)
        self.interval = interval
        self.prefix = prefix
        self._task: Optional[asyncio.Task] = None
        self._sock: Optional[socket.socket] = None
        self._last: Dict[str, float] = {}

    def render(self) -> bytes:
        """counters -> statsd 'c' deltas; gauges -> 'g'."""
        snap = self.metrics.snapshot()
        out = []
        for name, v in sorted(snap.items()):
            sname = f"{self.prefix}.{name}"
            if name.endswith("count") or "usage" in name or "uptime" in name:
                out.append(f"{sname}:{float(v):g}|g")
            else:
                delta = v - self._last.get(name, 0)
                self._last[name] = v
                if delta:
                    out.append(f"{sname}:{float(delta):g}|c")
        return "\n".join(out).encode()

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            self.push()

    def push(self) -> int:
        payload = self.render()
        if not payload:
            return 0
        if self._sock is None:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            # chunk to stay under typical UDP MTU
            sent = 0
            buf = b""
            for line in payload.split(b"\n"):
                if len(buf) + len(line) + 1 > 1400 and buf:
                    self._sock.sendto(buf, self.addr)
                    sent += 1
                    buf = b""
                buf += (b"\n" if buf else b"") + line
            if buf:
                self._sock.sendto(buf, self.addr)
                sent += 1
            return sent
        except OSError:
            return 0

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.ensure_future(self._loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None
        if self._sock is not None:
            self._sock.close()
            self._sock = None
