"""Runtime race harness: an Eraser-style lockset detector with
happens-before edges, over *registered* shared objects.

The CX checker (tools/analysis) proves cross-context discipline
statically; this module catches what static analysis cannot see —
container mutations, discipline that holds the wrong lock, annotations
that lie at runtime. It is the dynamic half of the PR 8 concurrency rig,
armed in the `race`-marked test suite and under `bench.py chaos_soak`,
never in production steady state.

Model (Eraser refined with vector clocks, FastTrack-lite):

- each thread carries a vector clock and a lockset (the tracked locks it
  currently holds);
- every probed access is labeled (thread, clock snapshot, lockset, trimmed
  stack);
- two accesses to the same field RACE when they come from different
  threads, at least one is a write, no happens-before edge orders them,
  and their locksets are disjoint. Both conditions must fail: a pure
  lockset detector false-positives on handoff patterns (loop builds, pool
  consumes), a pure HB detector misses races the schedule didn't happen
  to interleave — together they catch the discipline violation whenever
  either side witnesses it;
- happens-before edges come from the three sync idioms the broker uses:
  **executor submit -> task run** and **task completion -> Future.result**
  (both patched into `ThreadPoolExecutor.submit`/`Future.result` while
  armed — `loop.run_in_executor` rides the same pair, its result crossing
  back via `Future.result` on the loop thread), and **lock release ->
  acquire** (tracked locks publish the releaser's clock to the next
  acquirer).

Instrumentation is registration-based, the `faults.py` shape: production
classes carry no probes. `watch(obj)` registers a shared object (the
Metrics registry, DeviceRouter's prepare cache, DegradeController
breakers, RetainedStormFeed, route_sync tables); `arm()` swaps each
watched instance onto a generated subclass whose `__setattr__`/
`__getattribute__` probe the tracked fields, and wraps the instance's
locks so locksets and release->acquire edges are observed. `disarm()`
restores the original classes and locks — a disarmed tracker costs the
production pipeline literally nothing, and the explicit `probe()` hook
(for state the attribute probes cannot see, e.g. a dict entry) costs one
attribute check, exactly like a disarmed fault site.

Every candidate race is a `RaceReport` carrying the field, BOTH stack
traces, and both locksets; reports count into the `race.reports` series
and every probed access into `racetrack.events`. Known-benign fields are
waived by `waive("Class.field")` glob patterns.
"""

from __future__ import annotations

import fnmatch
import itertools
import sys
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

_LOCK_TYPES = (type(threading.Lock()), type(threading.RLock()))

_STACK_DEPTH = 6


def _stack() -> Tuple[str, ...]:
    """Trimmed caller stack, racetrack's own frames dropped.

    Hand-walked with sys._getframe instead of traceback.extract_stack:
    the latter pulls source lines through linecache, which is orders of
    magnitude too slow for a probe that fires on every watched access
    of a hot object (a chaos soak probes the Metrics registry millions
    of times)."""
    out = []
    f = sys._getframe(1)
    hops = 0
    while f is not None and hops < 40 and len(out) < _STACK_DEPTH:
        code = f.f_code
        if not code.co_filename.endswith("racetrack.py"):
            out.append(
                f"{code.co_filename}:{f.f_lineno} in {code.co_name}"
            )
        f = f.f_back
        hops += 1
    out.reverse()  # outermost first, the access site last
    return tuple(out)


def _iter_attrs(obj):
    """(name, value) pairs across __dict__ AND __slots__ instances."""
    seen = set()
    d = getattr(obj, "__dict__", None)
    if d:
        for k, v in list(d.items()):
            seen.add(k)
            yield k, v
    for klass in type(obj).__mro__:
        for s in getattr(klass, "__slots__", ()) or ():
            if s.startswith("__") or s in seen:
                continue
            seen.add(s)
            try:
                yield s, getattr(obj, s)
            except AttributeError:
                continue


@dataclass(frozen=True)
class Access:
    label: str  # "Class.field"
    thread: str
    tid: int
    write: bool
    locks: Tuple[str, ...]
    clock: Tuple[Tuple[int, int], ...]  # frozen vector-clock snapshot
    stack: Tuple[str, ...]

    def clock_of(self, tid: int) -> int:
        for t, e in self.clock:
            if t == tid:
                return e
        return 0


@dataclass(frozen=True)
class RaceReport:
    field: str
    prior: Access
    current: Access

    def render(self) -> str:
        def side(tag: str, a: Access) -> str:
            op = "WRITE" if a.write else "READ"
            locks = ", ".join(a.locks) or "<none>"
            stack = "\n      ".join(a.stack) or "<no stack>"
            return (
                f"  {tag}: {op} on thread {a.thread!r} "
                f"holding [{locks}]\n      {stack}"
            )

        return (
            f"race on {self.field}:\n"
            f"{side('prior', self.prior)}\n{side('current', self.current)}"
        )


class _FieldState:
    __slots__ = ("last_write", "reads")

    def __init__(self):
        self.last_write: Optional[Access] = None
        self.reads: Dict[int, Access] = {}


# logical thread ids, never reused: threading.get_ident() recycles the
# ids of dead threads, which would alias a fresh thread's clock onto a
# dead one's accesses and silently order unrelated work
_next_tid = itertools.count(1)


class _ThreadState:
    __slots__ = ("tid", "vc", "held", "busy")

    def __init__(self):
        self.tid = next(_next_tid)
        self.vc: Dict[int, int] = {self.tid: 1}
        self.held: List[str] = []
        self.busy = False  # re-entrancy guard (metrics calls inside probes)


class TrackedLock:
    """Wraps a real lock: lockset bookkeeping + release->acquire HB."""

    def __init__(self, inner, label: str, tracker: "RaceTracker"):
        self._inner = inner
        self._label = label
        self._tracker = tracker
        self._clock: Dict[int, int] = {}

    def acquire(self, *args, **kwargs) -> bool:
        got = self._inner.acquire(*args, **kwargs)
        if got:
            self._tracker._lock_acquired(self)
        return got

    def release(self) -> None:
        self._tracker._lock_released(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class RaceTracker:
    def __init__(self, metrics=None):
        self.metrics = metrics
        self._armed = False
        self._ilock = threading.Lock()
        self._tls = threading.local()
        self._fields: Dict[Tuple[int, str], _FieldState] = {}
        # id(obj) -> (obj, display name, fields, orig class or None,
        #             {attr: original lock})
        self._watched: Dict[int, list] = {}
        self._class_cache: Dict[Tuple[type, frozenset], type] = {}
        self._waived: List[str] = []
        self._report_keys: Set[Tuple] = set()
        self.reports: List[RaceReport] = []
        # metric deltas accumulate HERE and flush at disarm: the probe
        # often fires while the watched object's own lock is held (a
        # Metrics instance inside `inc`), so calling metrics.inc inline
        # would re-acquire that very lock and self-deadlock
        self._events = 0
        self._flushed_events = 0
        self._flushed_reports = 0
        self._orig_submit = None
        self._orig_result = None

    # -- public surface -----------------------------------------------------
    @property
    def armed(self) -> bool:
        return self._armed

    def waive(self, pattern: str) -> None:
        """Suppress reports for fields matching the glob (e.g.
        ``"Metrics.started_at"``, ``"*._rand_seq"``)."""
        self._waived.append(pattern)

    def waived(self, label: str) -> bool:
        return any(fnmatch.fnmatch(label, p) for p in self._waived)

    def watch(
        self,
        obj,
        fields: Optional[Sequence[str]] = None,
        name: Optional[str] = None,
        locks: bool = True,
    ):
        """Register a shared object. Instrumentation happens at arm():
        a watched-but-disarmed object is untouched. `fields` defaults to
        every data attribute in the instance dict; `locks` wraps the
        instance's Lock/RLock attributes for lockset + HB tracking."""
        if id(obj) in self._watched:
            return obj  # already registered (possibly instrumented)
        name = name or type(obj).__name__
        if fields is None:
            fields = [
                k
                for k, v in _iter_attrs(obj)
                if not k.startswith("__")
                and not callable(v)
                and not isinstance(v, (_LOCK_TYPES + (TrackedLock,)))
            ]
        entry = [obj, name, tuple(fields), None, {}, locks]
        self._watched[id(obj)] = entry
        if self._armed:
            self._instrument(entry)
        return obj

    def arm(self, metrics=None) -> None:
        """Instrument every watched object and patch the executor seams.
        Re-arming is a no-op."""
        if self._armed:
            return
        if metrics is not None:
            self.metrics = metrics
        self._armed = True
        for entry in self._watched.values():
            self._instrument(entry)
        self._patch_executors()

    def disarm(self) -> None:
        """Restore classes, locks, and the executor seams. Reports and
        waivers survive so a soak can disarm before reading them."""
        if not self._armed:
            return
        self._armed = False
        for entry in self._watched.values():
            self._deinstrument(entry)
        self._unpatch_executors()
        self.flush_metrics()

    def flush_metrics(self) -> None:
        """Push accumulated racetrack.events / race.reports deltas into
        the metric registry. Runs at disarm (no probes can be in flight
        holding a watched lock) or whenever a soak wants a live read."""
        if self.metrics is None:
            return
        with self._ilock:
            ev = self._events - self._flushed_events
            rp = len(self.reports) - self._flushed_reports
            self._flushed_events += ev
            self._flushed_reports += rp
        if ev:
            self.metrics.inc("racetrack.events", ev)
        if rp:
            self.metrics.inc("race.reports", rp)

    def reset(self) -> None:
        """Drop accumulated state (watched set stays registered)."""
        with self._ilock:
            self._fields.clear()
            self._report_keys.clear()
            self.reports = []

    def unwaived_reports(self) -> List[RaceReport]:
        return [r for r in self.reports if not self.waived(r.field)]

    # -- manual probe (the faults.hit analog) -------------------------------
    def probe(self, owner, field: str, write: bool = True,
              name: Optional[str] = None) -> None:
        """Hand-instrumented access for state the attribute probes cannot
        see (a dict entry, a list slot). One attribute check when
        disarmed."""
        if not self._armed:
            return
        label = f"{name or type(owner).__name__}.{field}"
        self._on_access(id(owner), label, write)

    # -- instrumentation ----------------------------------------------------
    def _instrument(self, entry) -> None:
        obj, name, fields, orig_cls, orig_locks, wrap_locks = entry
        if orig_cls is not None:
            return  # already instrumented
        if wrap_locks:
            for attr, val in _iter_attrs(obj):
                if isinstance(val, _LOCK_TYPES):
                    proxy = TrackedLock(val, f"{name}.{attr}", self)
                    object.__setattr__(obj, attr, proxy)
                    orig_locks[attr] = val
        cls = type(obj)
        entry[3] = cls
        obj.__class__ = self._tracked_class(cls, frozenset(fields), name)

    def _deinstrument(self, entry) -> None:
        obj, _name, _fields, orig_cls, orig_locks, _wrap = entry
        if orig_cls is None:
            return
        obj.__class__ = orig_cls
        entry[3] = None
        for attr, real in orig_locks.items():
            object.__setattr__(obj, attr, real)
        orig_locks.clear()

    def _tracked_class(self, cls: type, fields: frozenset,
                       name: str) -> type:
        key = (cls, fields)
        got = self._class_cache.get(key)
        if got is not None:
            return got
        tracker = self
        orig_setattr = cls.__setattr__
        orig_getattribute = cls.__getattribute__

        def __setattr__(self, attr, value):
            if attr in fields and tracker._armed:
                tracker._on_access(id(self), f"{name}.{attr}", True)
            orig_setattr(self, attr, value)

        def __getattribute__(self, attr):
            if attr in fields and tracker._armed:
                tracker._on_access(id(self), f"{name}.{attr}", False)
            return orig_getattribute(self, attr)

        sub = type(
            f"Racetracked{cls.__name__}",
            (cls,),
            {
                "__slots__": (),
                "__setattr__": __setattr__,
                "__getattribute__": __getattribute__,
            },
        )
        self._class_cache[key] = sub
        return sub

    # -- executor seams (HB edges) ------------------------------------------
    def _patch_executors(self) -> None:
        tracker = self
        self._orig_submit = orig_submit = ThreadPoolExecutor.submit
        self._orig_result = orig_result = Future.result

        def submit(pool, fn, *args, **kwargs):
            if not tracker._armed:
                return orig_submit(pool, fn, *args, **kwargs)
            snap = tracker._publish()  # submit -> run edge
            cell = {}

            def run(*a, **kw):
                tracker._merge(snap)
                try:
                    return fn(*a, **kw)
                finally:
                    cell["clock"] = tracker._publish()  # done -> result

            fut = orig_submit(pool, run, *args, **kwargs)
            try:
                fut._racetrack_cell = cell
            except Exception:  # noqa: BLE001 — slotted Future subclass
                pass
            return fut

        def result(fut, timeout=None):
            value = orig_result(fut, timeout)
            if tracker._armed:
                cell = getattr(fut, "_racetrack_cell", None)
                if cell is not None:
                    clk = cell.get("clock")
                    if clk:
                        tracker._merge(clk)
            return value

        ThreadPoolExecutor.submit = submit
        Future.result = result

    def _unpatch_executors(self) -> None:
        if self._orig_submit is not None:
            ThreadPoolExecutor.submit = self._orig_submit
            self._orig_submit = None
        if self._orig_result is not None:
            Future.result = self._orig_result
            self._orig_result = None

    # -- vector-clock plumbing ----------------------------------------------
    def _state(self) -> _ThreadState:
        st = getattr(self._tls, "st", None)
        if st is None:
            st = _ThreadState()
            self._tls.st = st
        return st

    def _publish(self) -> Dict[int, int]:
        """Snapshot this thread's clock, then tick it: later accesses by
        this thread are NOT covered by the snapshot."""
        st = self._state()
        snap = dict(st.vc)
        st.vc[st.tid] = st.vc.get(st.tid, 0) + 1
        return snap

    def _merge(self, clock: Dict[int, int]) -> None:
        st = self._state()
        for t, e in clock.items():
            if st.vc.get(t, 0) < e:
                st.vc[t] = e

    def _lock_acquired(self, lock: TrackedLock) -> None:
        st = self._state()
        st.held.append(lock._label)
        self._merge(lock._clock)

    def _lock_released(self, lock: TrackedLock) -> None:
        st = self._state()
        lock._clock = self._publish()
        try:
            st.held.remove(lock._label)
        except ValueError:
            pass

    # -- the detector -------------------------------------------------------
    @staticmethod
    def _ordered(prior: Access, vc: Dict[int, int]) -> bool:
        """Did the current thread observe the prior access (HB)?"""
        return vc.get(prior.tid, 0) >= prior.clock_of(prior.tid)

    def _on_access(self, obj_id: int, label: str, write: bool) -> None:
        st = self._state()
        if st.busy:
            return  # re-entrant probe (metrics call inside the tracker)
        st.busy = True
        try:
            acc = Access(
                label=label,
                thread=threading.current_thread().name,
                tid=st.tid,
                write=write,
                locks=tuple(st.held),
                clock=tuple(sorted(st.vc.items())),
                stack=_stack(),
            )
            with self._ilock:
                self._events += 1
                fs = self._fields.setdefault(
                    (obj_id, label), _FieldState()
                )
                if write:
                    if fs.last_write is not None:
                        self._check(fs.last_write, acc, st.vc)
                    for r in fs.reads.values():
                        self._check(r, acc, st.vc)
                    fs.last_write = acc
                    fs.reads = {}
                else:
                    if fs.last_write is not None:
                        self._check(fs.last_write, acc, st.vc)
                    fs.reads[st.tid] = acc
        finally:
            st.busy = False

    def _check(self, prior: Access, acc: Access,
               vc: Dict[int, int]) -> int:  # holds-lock: _ilock
        if prior.tid == acc.tid:
            return 0
        if not (prior.write or acc.write):
            return 0
        if self._ordered(prior, vc):
            return 0
        if set(prior.locks) & set(acc.locks):
            return 0  # a common lock serializes them
        key = (
            acc.label,
            prior.write,
            acc.write,
            prior.stack[-1] if prior.stack else "",
            acc.stack[-1] if acc.stack else "",
        )
        if key in self._report_keys:
            return 0
        self._report_keys.add(key)
        self.reports.append(
            RaceReport(field=acc.label, prior=prior, current=acc)
        )
        return 1


# the process-wide tracker the race suite and chaos_soak arm; production
# code never touches it (registration-based instrumentation only)
default_tracker = RaceTracker()


def probe(owner, field: str, write: bool = True) -> None:
    """Module-level shorthand mirroring `faults.hit`: one attribute
    check when the default tracker is disarmed."""
    default_tracker.probe(owner, field, write)
