"""Hardware provenance: the fingerprint every measurement is stamped with.

ROADMAP's re-anchor names "hardware honesty" as the standing debt: every
figure since BENCH_r05 was measured on a CPU box where jax-on-CPU is
noise, and nothing in the repo could *tell* a CPU-proxy number from a
number of record. This module is the fix's foundation: one dict —
platform, device kind/count, host cores, jax/jaxlib versions, git sha,
clock source — computed once per process and stamped into

- every BENCH/MULTICHIP JSON bench.py emits (bench refuses to print a
  headline without it),
- the management REST hotpath summary (`profile.provenance`),
- span resource attributes (observe/spans.py OTLP envelope),

with ``proxy: true`` whenever the detected platform is not a TPU, so a
CPU number can never again masquerade as a number of record.
`tools/bench_trend.py` groups runs by `fingerprint_key()` and refuses
cross-fingerprint comparisons.

Import-light on purpose: jax is imported lazily inside `fingerprint()`
(bench's parent process stamps its summary without paying a backend
init; the child sweeps already own one).
"""

from __future__ import annotations

import os
import platform as _platform
import subprocess
import time
from typing import Any, Dict, Optional

# the fields two runs must share to be COMPARABLE (bench_trend's
# grouping key). git sha is deliberately excluded — comparing across
# commits on the same hardware is the whole point of a trend report —
# and so is the clock source (informational, not a perf axis).
KEY_FIELDS = (
    "platform",
    "device_kind",
    "device_count",
    "host_cores",
    "jax",
    "jaxlib",
)

# platforms that count as the accelerator of record. "tpu" is the stock
# jax name; "axon" is the PJRT plugin name the chip registers under on
# the capture boxes — a number taken there must NOT be flagged proxy.
_RECORD_PLATFORMS = ("tpu", "axon")

_CACHE: Optional[Dict[str, Any]] = None


def _git_sha() -> str:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short=12", "HEAD"],
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)
            ))),
            capture_output=True,
            text=True,
            timeout=5,
        )
        if out.returncode == 0:
            return out.stdout.strip()
    except Exception:  # noqa: BLE001 — provenance must never raise
        pass
    return ""


def _clock_source() -> str:
    """Which clock perf_counter timings actually stand on: the kernel's
    clocksource when readable (tsc vs hpet/acpi_pm changes what a
    microsecond histogram means), else python's perf_counter impl."""
    try:
        p = "/sys/devices/system/clocksource/clocksource0/current_clocksource"
        with open(p) as f:
            return f.read().strip()
    except OSError:
        pass
    try:
        return time.get_clock_info("perf_counter").implementation
    except Exception:  # noqa: BLE001 — informational field only
        return "unknown"


def fingerprint(refresh: bool = False) -> Dict[str, Any]:
    """The process-wide hardware fingerprint (computed once, cached).

    Returns a fresh dict each call (callers stamp it into JSON docs they
    then mutate). ``proxy`` is True on any non-TPU backend — the flag
    bench.py threads into every emitter so dashboards and the trend
    gate can refuse to headline a CPU number.
    """
    global _CACHE
    if _CACHE is None or refresh:
        info: Dict[str, Any] = {
            "platform": "unknown",
            "device_kind": "unknown",
            "device_count": 0,
            "host_cores": os.cpu_count() or 0,
            "machine": _platform.machine(),
            "python": _platform.python_version(),
            "jax": "",
            "jaxlib": "",
            "git_sha": _git_sha(),
            "clock_source": _clock_source(),
        }
        try:
            import jax

            info["jax"] = getattr(jax, "__version__", "")
            try:
                import jaxlib

                info["jaxlib"] = getattr(jaxlib, "__version__", "") or ""
            except Exception:  # noqa: BLE001 — version probe only
                pass
            devs = jax.devices()
            if devs:
                info["platform"] = devs[0].platform
                info["device_kind"] = getattr(
                    devs[0], "device_kind", devs[0].platform
                )
                info["device_count"] = len(devs)
        except Exception:  # noqa: BLE001 — no backend: still a fingerprint
            pass
        info["proxy"] = info["platform"] not in _RECORD_PLATFORMS
        _CACHE = info
    return dict(_CACHE)


def is_proxy() -> bool:
    """True when the detected backend is NOT a TPU (the number is a
    CPU/GPU proxy, never a number of record)."""
    return bool(fingerprint().get("proxy", True))


def fingerprint_key(fp: Optional[Dict[str, Any]] = None) -> str:
    """Stable comparability key over KEY_FIELDS. Two runs with different
    keys must never be compared (bench_trend rejects the pair)."""
    if fp is None:
        fp = fingerprint()
    return "|".join(str(fp.get(k, "")) for k in KEY_FIELDS)


def stamp(doc: Dict[str, Any]) -> Dict[str, Any]:
    """Stamp a JSON-bound dict in place: fingerprint + top-level proxy
    flag (the flag rides at top level so a grep of any BENCH JSON
    answers "is this a number of record?" without walking the nest)."""
    fp = fingerprint()
    doc["fingerprint"] = fp
    doc["proxy"] = bool(fp["proxy"])
    return doc


def resource_attrs() -> Dict[str, Any]:
    """Span resource attributes (OTLP envelope): the fingerprint fields
    flattened under the `hw.` prefix, the idiomatic resource keys."""
    fp = fingerprint()
    return {
        "hw.platform": fp["platform"],
        "hw.device_kind": fp["device_kind"],
        "hw.device_count": fp["device_count"],
        "hw.host_cores": fp["host_cores"],
        "hw.jax": fp["jax"],
        "hw.jaxlib": fp["jaxlib"],
        "hw.git_sha": fp["git_sha"],
        "hw.clock_source": fp["clock_source"],
        "hw.proxy": bool(fp["proxy"]),
    }
