"""Device profiling plane: launch waterfalls, per-kernel attribution,
on-demand trace capture, and static cost analysis.

Three instruments, one module (docs/observability.md "Profiling &
provenance"):

1. **Stage waterfall** — every device batch decomposes into six stages
   (`profile.stage.*.seconds` histograms, observed from the hot path):

       prepare        table snapshot + upload (Broker.adispatch_begin)
       queue_wait     enqueue -> launch wait per message (BatchIngest)
       launch         host-side batch encode + kernel enqueue
                      (DeviceRouter._route_prepared up to readback)
       device_execute kernel completion wait (block_until_ready at the
                      readback boundary)
       readback       the coalesced device_get + host decode
       host_dispatch  settle-time fan-out (Broker device results)

   The stages are always-on flight-recorder histograms in the same
   spirit as `router.device.seconds` — a handful of perf_counter reads
   per *batch*, never per message. Per-kernel attribution rides the
   same path: each launch's wall time and readback bytes are observed
   into `device.kernel.<name>.seconds/.bytes`, keyed by the
   `@device_contract` registry names, so all 14 kernels are
   attributable without any kernel-side code.

2. **Trace capture** — an on-demand `jax.profiler` trace, armed via
   `POST /api/v5/profile` with a bounded duration and on-disk file
   budget. Disarmed is the structural zero of faults.py/racetrack: no
   hook exists on the hot path at all; arming only starts the global
   jax trace and housekeeping's 1 Hz tick enforces the deadline/budget.
   `capture is None` IS the disarmed state (asserted racetrack-style in
   tests/test_profiler.py).

3. **Static cost analysis** — `Compiled.cost_analysis()` (FLOPs, bytes
   accessed) harvested per contract kernel per config-matrix row by
   reusing the device-contract audit's harness recipes, rendered as a
   roofline-style estimate (arithmetic intensity vs the detected
   device's peak). On a CPU proxy the peaks are nominal and the whole
   block is tagged `proxy: true` — the estimate ranks kernels against
   each other, it is NOT a number of record.
"""

from __future__ import annotations

import os
import shutil
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from emqx_tpu.observe import provenance

# the waterfall stage set, in pipeline order (series:
# `profile.stage.<stage>.seconds`, declared in broker/metrics.py)
STAGES: Tuple[str, ...] = (
    "prepare",
    "queue_wait",
    "launch",
    "device_execute",
    "readback",
    "host_dispatch",
)

# roofline peaks by device_kind substring: (peak FLOP/s, peak HBM B/s).
# Public datasheet numbers (dense bf16/fp32-class); the ridge point
# ai = flops/bytes they imply is what the harvest renders against.
DEVICE_PEAKS: Tuple[Tuple[str, Tuple[float, float]], ...] = (
    ("v5p", (459e12, 2765e9)),
    ("v5 lite", (197e12, 819e9)),
    ("v5e", (197e12, 819e9)),
    ("v4", (275e12, 1228e9)),
    ("v3", (123e12, 900e9)),
    ("v2", (45e12, 700e9)),
)
# nominal single-host CPU peaks: ONLY for ranking kernels relative to
# each other on a proxy box; tagged proxy wherever rendered
PROXY_PEAKS: Tuple[float, float] = (1e11, 5e10)


def device_peaks() -> Dict[str, Any]:
    """(peak_flops, peak_bytes_per_s, proxy) for the detected device."""
    fp = provenance.fingerprint()
    kind = str(fp.get("device_kind", "")).lower()
    if not fp.get("proxy", True):
        for sub, peaks in DEVICE_PEAKS:
            if sub in kind:
                return {
                    "peak_flops": peaks[0],
                    "peak_bytes_per_s": peaks[1],
                    "proxy": False,
                    "device_kind": fp.get("device_kind"),
                }
        # unknown TPU generation: v4 numbers as a conservative stand-in
        return {
            "peak_flops": 275e12,
            "peak_bytes_per_s": 1228e9,
            "proxy": False,
            "device_kind": fp.get("device_kind"),
        }
    return {
        "peak_flops": PROXY_PEAKS[0],
        "peak_bytes_per_s": PROXY_PEAKS[1],
        "proxy": True,
        "device_kind": fp.get("device_kind"),
    }


def record_kernel_launch(
    metrics, kernels: Sequence[str], seconds: float, bytes_: int = 0
) -> None:
    """Attribute one launch's wall time + readback bytes to the contract
    kernels that rode it. A fused launch lists every registry name in
    the program (e.g. shape_route_step + compact_fanout_slots +
    semantic_match_step), so per-kernel series answer "what does this
    kernel cost when it is in the program" — launch-level attribution,
    not an intra-program split (cost_harvest gives the static split)."""
    if metrics is None:
        return
    for k in kernels:
        metrics.observe(f"device.kernel.{k}.seconds", seconds)
        if bytes_:
            metrics.observe(f"device.kernel.{k}.bytes", bytes_)


def kernel_summary(metrics) -> Dict[str, Dict]:
    """Per-kernel launch percentiles for every registry kernel a series
    exists for — the REST `profile.kernels` table."""
    from emqx_tpu.ops.contract import REGISTRY

    out: Dict[str, Dict] = {}
    for name in sorted(REGISTRY):
        h = metrics.histogram(f"device.kernel.{name}.seconds")
        if h is None or h.count == 0:
            continue
        hb = metrics.histogram(f"device.kernel.{name}.bytes")
        out[name] = {
            "launches": h.count,
            "mean_ms": (h.sum / h.count) * 1e3,
            "p50_ms": h.p50 * 1e3,
            "p99_ms": h.p99 * 1e3,
            "mean_readback_bytes": (
                hb.sum / hb.count if hb is not None and hb.count else None
            ),
        }
    return out


def waterfall(metrics) -> Dict[str, Optional[Dict]]:
    """The per-stage latency breakdown (seconds): one entry per STAGE
    with count/mean/p50/p95/p99, None where nothing observed yet."""
    out: Dict[str, Optional[Dict]] = {}
    for stage in STAGES:
        h = metrics.histogram(f"profile.stage.{stage}.seconds")
        if h is None or h.count == 0:
            out[stage] = None
            continue
        out[stage] = {
            "count": h.count,
            "mean": h.sum / h.count,
            "p50": h.p50,
            "p95": h.p95,
            "p99": h.p99,
        }
    return out


class Profiler:
    """On-demand jax trace capture + cached cost harvest.

    Disarmed state is `self.capture is None` — the hot path never
    consults this object (stage/kernel series observe straight into the
    metrics registry), so the disarmed overhead is structurally zero:
    there is no check to pay, let alone a branch. Arming starts the
    process-global `jax.profiler` trace into a fresh per-capture
    directory; the housekeeping tick (app.py, 1 Hz) enforces the
    duration bound and the on-disk file budget.
    """

    def __init__(
        self,
        metrics=None,
        trace_dir: str = "profile_traces",
        max_seconds: float = 30.0,
        max_bytes: int = 64 << 20,
        history: int = 16,
    ) -> None:
        self.metrics = metrics
        self.trace_dir = trace_dir
        self.max_seconds = float(max_seconds)
        self.max_bytes = int(max_bytes)
        self.capture: Optional[Dict[str, Any]] = None  # guarded-by: _lock
        self._history: List[Dict[str, Any]] = []  # guarded-by: _lock
        self._history_cap = history
        self._seq = 0
        self._cost: Optional[Dict[str, Any]] = None  # guarded-by: _lock
        self._lock = threading.Lock()

    @property
    def armed(self) -> bool:
        # racy read by design (REST status probe): arm/disarm mutate
        # under _lock; a stale one-word read here is harmless
        return self.capture is not None  # lint: disable=LK001

    # -- trace capture (REST-armed) ---------------------------------------

    def arm(
        self,
        duration_s: Optional[float] = None,
        max_bytes: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Start a bounded jax.profiler trace. Raises RuntimeError when a
        capture is already armed (one at a time: the jax trace is
        process-global) or when the backend refuses to start one."""
        dur = float(duration_s) if duration_s else self.max_seconds
        dur = max(0.1, min(dur, self.max_seconds))
        budget = int(max_bytes) if max_bytes else self.max_bytes
        budget = max(1 << 16, min(budget, self.max_bytes))
        with self._lock:
            if self.capture is not None:
                raise RuntimeError("profile capture already armed")
            self._seq += 1
            cap_dir = os.path.join(
                self.trace_dir, f"capture_{self._seq:04d}"
            )
            os.makedirs(cap_dir, exist_ok=True)
            import jax

            jax.profiler.start_trace(cap_dir)
            self.capture = {
                "dir": cap_dir,
                "started_at": time.time(),
                "deadline": time.time() + dur,
                "duration_s": dur,
                "max_bytes": budget,
            }
            return dict(self.capture)

    def disarm(self, reason: str = "rest") -> Optional[Dict[str, Any]]:
        """Stop the armed capture, settle the file budget, record the
        history entry. No-op (returns None) when disarmed."""
        with self._lock:
            cap = self.capture
            if cap is None:
                return None
            self.capture = None
            import jax

            try:
                jax.profiler.stop_trace()
            except Exception as e:  # noqa: BLE001 — budget still settles
                cap["error"] = str(e)
            entry = self._settle_locked(cap, reason)
            self._history.append(entry)
            del self._history[: -self._history_cap]
        if self.metrics is not None:
            self.metrics.inc("profile.captures")
            self.metrics.observe(
                "profile.capture.seconds", entry["seconds"]
            )
            self.metrics.observe("profile.capture.bytes", entry["bytes"])
        return entry

    def _settle_locked(self, cap, reason) -> Dict[str, Any]:
        bytes_ = _tree_bytes(cap["dir"])
        over = bytes_ > cap["max_bytes"]
        if over:
            # budget enforcement is REAL: an over-budget capture is
            # deleted, not kept with a warning — the bound exists so a
            # long-armed trace can never fill the data disk
            shutil.rmtree(cap["dir"], ignore_errors=True)
        return {
            "dir": cap["dir"],
            "seconds": round(time.time() - cap["started_at"], 3),
            "bytes": bytes_,
            "max_bytes": cap["max_bytes"],
            "over_budget": over,
            "deleted": over,
            "reason": reason,
            "error": cap.get("error"),
        }

    def tick(self, now: Optional[float] = None) -> None:
        """Housekeeping hook (1 Hz): auto-disarm past the deadline, and
        cut a capture short the moment it exceeds its file budget."""
        # racy read by design: the 1 Hz tick may see a capture another
        # thread is disarming; disarm() re-checks under _lock
        cap = self.capture  # lint: disable=LK001
        if cap is None:
            return
        now = time.time() if now is None else now
        if now >= cap["deadline"]:
            self.disarm(reason="deadline")
        elif _tree_bytes(cap["dir"]) > cap["max_bytes"]:
            self.disarm(reason="budget")

    # -- static cost analysis ---------------------------------------------

    def cost_harvest(
        self,
        max_configs_per_kernel: Optional[int] = None,
        refresh: bool = False,
    ) -> Dict[str, Any]:
        """FLOPs / bytes-accessed per contract kernel per config-matrix
        row, via the device-contract audit's own harness recipes (so
        the harvested matrix IS the audited matrix). Compiles every
        kernel — seconds to minutes of work — so the result is cached;
        REST exposes the cached copy and recomputes only on demand."""
        with self._lock:
            if self._cost is not None and not refresh:
                return self._cost
        result = harvest_cost(max_configs_per_kernel)
        with self._lock:
            self._cost = result
        if self.metrics is not None:
            self.metrics.gauge_set(
                "profile.cost.kernels",
                len({r["kernel"] for r in result["rows"]}),
            )
        return result

    def cost_cached(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._cost

    def snapshot(self) -> Dict[str, Any]:
        """REST-shaped state: armed capture, history, budgets."""
        with self._lock:
            cap = dict(self.capture) if self.capture is not None else None
            hist = list(self._history)
            cost = self._cost
        return {
            "armed": cap is not None,
            "capture": cap,
            "history": hist,
            "max_seconds": self.max_seconds,
            "max_bytes": self.max_bytes,
            "cost_harvested": cost is not None,
        }


def _tree_bytes(path: str) -> int:
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                pass
    return total


def harvest_cost(
    max_configs_per_kernel: Optional[int] = None,
) -> Dict[str, Any]:
    """Compile every registered contract kernel over (a prefix of) its
    audit config matrix and read `Compiled.cost_analysis()` back.

    Returns `{rows, skipped, peaks, proxy}`: one row per (kernel,
    config) with flops, bytes accessed, arithmetic intensity, and the
    roofline-attainable FLOP/s vs the detected device's peaks. Configs
    the audit itself would skip (e.g. a mesh row on too few devices)
    land in `skipped`, never as silently missing kernels."""
    import jax

    from emqx_tpu.ops.contract import REGISTRY
    # importing the kernel modules populates the registry (the audit's
    # own idiom); mesh kernels may be unavailable on exotic backends
    import emqx_tpu.models.router_model  # noqa: F401
    import emqx_tpu.ops.session_table  # noqa: F401

    skipped: List[str] = []
    try:
        import emqx_tpu.parallel.mesh  # noqa: F401
    except Exception as e:  # noqa: BLE001 — no shard_map image
        skipped.append(f"mesh kernels unavailable: {e}")

    from tools.analysis.device_contract import (
        _cfg_key,
        _harness,
        _SkipConfig,
    )

    peaks = device_peaks()
    rows: List[Dict[str, Any]] = []
    for name in sorted(REGISTRY):
        recipe = _harness(name)
        if recipe is None:
            skipped.append(f"{name}: no audit harness recipe")
            continue
        configs, build = recipe
        if max_configs_per_kernel:
            configs = configs[:max_configs_per_kernel]
        for cfg in configs:
            key = _cfg_key(cfg)
            try:
                fn, args = build(dict(cfg))
                compiled = jax.jit(fn).lower(*args).compile()
                ca = compiled.cost_analysis()
            except _SkipConfig as e:
                skipped.append(str(e))
                continue
            except Exception as e:  # noqa: BLE001 — backend-specific
                skipped.append(f"{name} {key}: cost analysis failed: {e}")
                continue
            rows.append(_cost_row(name, key, ca, peaks))
    return {
        "rows": rows,
        "skipped": skipped,
        "peaks": peaks,
        "proxy": bool(peaks["proxy"]),
    }


def _cost_row(name: str, key: str, ca, peaks) -> Dict[str, Any]:
    """Normalize one cost_analysis() result (dict, or a per-program
    list of dicts on some jax versions) into a roofline row."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        ca = {}
    flops = float(ca.get("flops", 0.0) or 0.0)
    bytes_ = float(ca.get("bytes accessed", 0.0) or 0.0)
    ai = flops / bytes_ if bytes_ > 0 else None
    peak_f = peaks["peak_flops"]
    peak_b = peaks["peak_bytes_per_s"]
    attainable = (
        min(peak_f, ai * peak_b) if ai is not None else None
    )
    bound = None
    if ai is not None:
        bound = "compute" if ai >= peak_f / peak_b else "memory"
    return {
        "kernel": name,
        "config": key,
        "flops": flops,
        "bytes_accessed": bytes_,
        "arithmetic_intensity": ai,
        "attainable_flops": attainable,
        "bound": bound,
    }


def roofline_summary(cost: Optional[Dict[str, Any]]) -> Optional[Dict]:
    """Condense a harvest result to the hotpath headline: per kernel,
    the heaviest config's arithmetic intensity and attainable FLOP/s
    against the detected device peaks. None until a harvest ran."""
    if not cost:
        return None
    best: Dict[str, Dict[str, Any]] = {}
    for r in cost["rows"]:
        cur = best.get(r["kernel"])
        if cur is None or r["flops"] > cur["flops"]:
            best[r["kernel"]] = r
    return {
        "peaks": cost["peaks"],
        "proxy": cost["proxy"],
        "kernels": {
            k: {
                "config": r["config"],
                "arithmetic_intensity": r["arithmetic_intensity"],
                "attainable_flops": r["attainable_flops"],
                "bound": r["bound"],
            }
            for k, r in sorted(best.items())
        },
    }


# the process-wide instance (faults.default_faults idiom): app.py points
# `.metrics` at the broker's registry and REST drives arm/disarm
default_profiler = Profiler()
