"""$event system messages (reference: apps/emqx_modules/src/
emqx_event_message.erl): republish broker lifecycle events as MQTT messages
on well-known topics so ordinary subscribers can watch them:

  $event/client_connected     $event/client_disconnected
  $event/session_subscribed   $event/session_unsubscribed
  $event/message_delivered    $event/message_acked
  $event/message_dropped

Each event class is individually enableable; payloads are JSON with the
reference's field names (clientid, username, topic, qos, ...).
"""

from __future__ import annotations

import base64
import json
import time
from dataclasses import dataclass, field
from typing import Set

from emqx_tpu.broker.message import Message


DEFAULT_EVENTS = frozenset(
    {
        "client_connected",
        "client_disconnected",
        "session_subscribed",
        "session_unsubscribed",
        "message_delivered",
        "message_acked",
        "message_dropped",
    }
)


def _payload_b64(payload: bytes) -> str:
    try:
        return payload.decode("utf-8")
    except UnicodeDecodeError:
        return base64.b64encode(payload).decode()


@dataclass
class EventMessage:
    broker: object
    enabled: Set[str] = field(default_factory=lambda: set(DEFAULT_EVENTS))

    def _emit(self, event: str, data: dict) -> None:
        if event not in self.enabled:
            return
        data["ts"] = int(time.time() * 1000)
        self.broker.publish(
            Message(topic=f"$event/{event}", payload=json.dumps(data).encode())
        )

    # -- hook callbacks ----------------------------------------------------
    def on_client_connected(self, client_info, channel) -> None:
        self._emit(
            "client_connected",
            {
                "clientid": client_info.get("client_id"),
                "username": client_info.get("username"),
                "ipaddress": client_info.get("peerhost"),
                "proto_ver": client_info.get("proto_ver"),
                "keepalive": client_info.get("keepalive"),
                "connected_at": int(time.time() * 1000),
            },
        )

    def on_client_disconnected(self, client_info, reason) -> None:
        self._emit(
            "client_disconnected",
            {
                "clientid": client_info.get("client_id"),
                "username": client_info.get("username"),
                "reason": str(reason),
                "disconnected_at": int(time.time() * 1000),
            },
        )

    def on_session_subscribed(self, client_info, topic, opts, _ch=None) -> None:
        self._emit(
            "session_subscribed",
            {
                "clientid": client_info.get("client_id"),
                "username": client_info.get("username"),
                "topic": topic,
                "qos": getattr(opts, "qos", 0),
            },
        )

    def on_session_unsubscribed(self, client_info, topic) -> None:
        self._emit(
            "session_unsubscribed",
            {
                "clientid": client_info.get("client_id"),
                "username": client_info.get("username"),
                "topic": topic,
            },
        )

    def on_message_delivered(self, client_info, msg) -> None:
        # enabled-check FIRST: this runs per delivery, and building the
        # payload dict (incl. base64) for a disabled event class was a
        # measurable share of the serving hot path
        if "message_delivered" not in self.enabled:
            return
        if msg.is_sys() or msg.topic.startswith("$event/"):
            return
        self._emit(
            "message_delivered",
            {
                "clientid": client_info.get("client_id"),
                "username": client_info.get("username"),
                "from_clientid": msg.from_client,
                "topic": msg.topic,
                "qos": msg.qos,
                "retain": msg.retain,
                "payload": _payload_b64(msg.payload),
                "publish_received_at": int(msg.timestamp * 1000),
            },
        )

    def on_message_acked(self, client_info, msg_or_pid) -> None:
        if "message_acked" not in self.enabled:
            return
        if isinstance(msg_or_pid, Message) and (
            msg_or_pid.is_sys() or msg_or_pid.topic.startswith("$event/")
        ):
            # same guard as delivered/dropped: acking a $event QoS1 delivery
            # must not spawn another $event publish (self-sustaining loop)
            return
        data = {
            "clientid": client_info.get("client_id"),
            "username": client_info.get("username"),
        }
        if isinstance(msg_or_pid, Message):
            data.update(
                {
                    "topic": msg_or_pid.topic,
                    "qos": msg_or_pid.qos,
                    "from_clientid": msg_or_pid.from_client,
                }
            )
        else:
            data["packet_id"] = msg_or_pid
        self._emit("message_acked", data)

    def on_message_dropped(self, msg, reason) -> None:
        if "message_dropped" not in self.enabled:
            return
        if msg.is_sys() or msg.topic.startswith("$event/"):
            return
        self._emit(
            "message_dropped",
            {
                "clientid": msg.from_client,
                "topic": msg.topic,
                "qos": msg.qos,
                "reason": str(reason),
                "payload": _payload_b64(msg.payload),
            },
        )

    def attach(self, hooks) -> None:
        hooks.add("client.connected", self.on_client_connected, tag="event_message")
        hooks.add("client.disconnected", self.on_client_disconnected,
                  tag="event_message")
        hooks.add("session.subscribed", self.on_session_subscribed,
                  tag="event_message")
        hooks.add("session.unsubscribed", self.on_session_unsubscribed,
                  tag="event_message")
        hooks.add("message.delivered", self.on_message_delivered,
                  tag="event_message")
        hooks.add("message.acked", self.on_message_acked, tag="event_message")
        hooks.add("message.dropped", self.on_message_dropped, tag="event_message")
