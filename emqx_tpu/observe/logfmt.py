"""Structured log formatters, switchable from config at runtime.

Parity: apps/emqx/src/emqx_logger_jsonfmt.erl + emqx_logger_textfmt.erl —
the reference configures OTP logger handlers with a json or text
formatter from the ``log`` config root, changeable at runtime. Here the
same pair of formatters attaches to the root ``emqx_tpu`` logger, and
``set_formatter``/``set_level`` re-point the live handler (the runtime
config pipeline's ``log`` subtree calls them).
"""

from __future__ import annotations

import json
import logging
import time
from typing import Optional

_LOGGER_NAME = "emqx_tpu"


def _iso_utc(record: logging.LogRecord) -> str:
    """``2026-07-30T12:00:00.123+00:00`` — UTC with an explicit offset,
    shared by both formatters (timestamps stay comparable across hosts
    and DST changes)."""
    t = time.gmtime(record.created)
    ms = int(record.msecs)
    return time.strftime("%Y-%m-%dT%H:%M:%S", t) + f".{ms:03d}+00:00"


class TextFormatter(logging.Formatter):
    """``2026-07-30T12:00:00.123+00:00 [info] module: message`` — the
    reference's default single-line text format."""

    def format(self, record: logging.LogRecord) -> str:
        ts = _iso_utc(record)
        msg = record.getMessage()
        out = f"{ts} [{record.levelname.lower()}] {record.name}: {msg}"
        if record.exc_info:
            out += "\n" + self.formatException(record.exc_info)
        return out


class JsonFormatter(logging.Formatter):
    """One JSON object per line (emqx_logger_jsonfmt best_effort_json):
    time/level/msg plus logger metadata; unserializable values fall back
    to their repr rather than failing the log call."""

    def format(self, record: logging.LogRecord) -> str:
        obj = {
            "time": _iso_utc(record),
            "level": record.levelname.lower(),
            "msg": record.getMessage(),
            "logger": record.name,
        }
        if record.exc_info:
            obj["exception"] = self.formatException(record.exc_info)
        for k, v in getattr(record, "__dict__", {}).items():
            if k.startswith("ctx_"):  # structured context fields
                try:
                    json.dumps(v)
                    obj[k[4:]] = v
                except (TypeError, ValueError):
                    obj[k[4:]] = repr(v)
        return json.dumps(obj, ensure_ascii=False)


_FORMATTERS = {"text": TextFormatter, "json": JsonFormatter}
_handler: Optional[logging.Handler] = None


def setup_logging(
    level: str = "info",
    formatter: str = "text",
    to_file: str = "",
) -> logging.Handler:
    """Install (or replace) the emqx_tpu log handler. Returns it."""
    global _handler
    logger = logging.getLogger(_LOGGER_NAME)
    if _handler is not None:
        logger.removeHandler(_handler)
        _handler.close()
    _handler = (
        logging.FileHandler(to_file) if to_file else logging.StreamHandler()
    )
    _handler.setFormatter(_FORMATTERS.get(formatter, TextFormatter)())
    logger.addHandler(_handler)
    logger.setLevel(getattr(logging, level.upper(), logging.INFO))
    logger.propagate = False
    return _handler


def set_formatter(kind: str) -> None:
    """Runtime switch text <-> json on the live handler."""
    if kind not in _FORMATTERS:
        raise ValueError(f"unknown log formatter {kind!r} (text|json)")
    if _handler is not None:
        _handler.setFormatter(_FORMATTERS[kind]())


def set_level(level: str) -> None:
    lv = getattr(logging, level.upper(), None)
    if lv is None:
        raise ValueError(f"unknown log level {level!r}")
    logging.getLogger(_LOGGER_NAME).setLevel(lv)
