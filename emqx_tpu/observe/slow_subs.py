"""Slow-subscriber detection: top-K delivery latency.

Parity with apps/emqx_slow_subs (SURVEY.md §2.2): measures per-delivery
latency on the 'delivery.completed' hook, keeps a bounded top-K table of
(clientid, topic) -> max latency over a sliding window, entries expire after
`expire_interval`. Stats modes of the reference (whole/internal/response)
collapse to whole-delivery latency here: publish timestamp -> ack (QoS1/2)
or send (QoS0).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple


@dataclass
class SlowEntry:
    client_id: str
    topic: str
    latency_ms: float
    last_update: float


class SlowSubs:
    def __init__(
        self,
        threshold_ms: float = 500.0,
        top_k: int = 10,
        expire_interval: float = 300.0,
    ):
        self.threshold_ms = threshold_ms
        self.top_k = top_k
        self.expire_interval = expire_interval
        self._table: Dict[Tuple[str, str], SlowEntry] = {}
        self.enabled = True

    # hook: delivery.completed(client_info, msg, latency_s)
    def on_delivery_completed(self, client_info, msg, latency_s) -> None:
        if not self.enabled:
            return
        ms = latency_s * 1000.0
        if ms < self.threshold_ms:
            return
        key = (client_info.get("client_id", ""), msg.topic)
        now = time.time()
        e = self._table.get(key)
        if e is None:
            self._table[key] = SlowEntry(key[0], key[1], ms, now)
            self._shrink()
        else:
            e.latency_ms = max(e.latency_ms, ms)
            e.last_update = now

    def _shrink(self) -> None:
        if len(self._table) <= self.top_k:
            return
        # evict the fastest entries so only the top-K slowest remain
        ranked = sorted(
            self._table.items(), key=lambda kv: -kv[1].latency_ms
        )
        self._table = dict(ranked[: self.top_k])

    def sweep(self, now: Optional[float] = None) -> None:
        now = now or time.time()
        self._table = {
            k: e
            for k, e in self._table.items()
            if now - e.last_update < self.expire_interval
        }

    def clear(self) -> None:
        self._table.clear()

    def topk(self) -> List[Dict]:
        ranked = sorted(self._table.values(), key=lambda e: -e.latency_ms)
        return [
            {
                "clientid": e.client_id,
                "topic": e.topic,
                "timespan": round(e.latency_ms, 3),
                "last_update_time": e.last_update,
            }
            for e in ranked
        ]

    def attach(self, hooks) -> None:
        hooks.add("delivery.completed", self.on_delivery_completed, tag="slow_subs")
