"""Segmented device tables: ONE manager under every table owner.

Before this module each device-table owner (the route/shape indexes, the
NFA residual engine, the subscriber/group bitmaps, the retained-topic
chunks) carried its own upload path, its own epoch bookkeeping, and its
own readback-site hygiene — three slightly different copies of the same
delta-overlay machinery (ROADMAP item 3). `DeviceSegmentManager` is that
machinery written once:

- **full uploads** on the source's `epoch` changing (structural events:
  growth, rehash, salt bump), with the `free_retired` one-epoch grace
  for in-flight executor batches still holding the previous snapshot;
- **O(delta) updates**: the op-log suffix since the last sync replays as
  ONE fused device launch (`segment_scatter_insert`, a registered
  `@device_contract` kernel) covering every touched array — not one
  dispatch per array, which on a tunneled chip multiplies the fixed
  per-launch RTT into the subscribe-visibility window;
- **per-array resync markers**: a source that rebuilt ONE small array
  (the shape index growing its hot segment, the retained index appending
  a chunk) logs `("!resync", name, 0)` and only that array re-uploads —
  the multi-GB packed tables never ride along;
- **offered buffers**: background compaction (`SegmentCompactor`) builds
  the merged packed table on an executor thread, `jax.device_put`s it
  there, and `offer()`s the device buffer tagged with the post-apply
  epoch — the next serving `prepare()` adopts it instead of paying the
  full upload on the critical path;
- **snapshot/restore**: the host tables a manager mirrors are plain
  numpy + dicts; `SegmentStateSnapshot` checkpoints them through
  `DurableState` so a rolling upgrade restores million-entry tables
  without replaying every subscribe.

Op-log protocol (sources: NfaBuilder, ShapeIndex, SubscriberTable,
GroupTable, DeviceRetainedIndex): `epoch` int, `version` int (total
mutation counter), `oplog` list of `(array_name, flat_index, value)`
scalar writes in program order — plus the `("!resync", array_name, 0)`
marker — and `device_snapshot() -> {name: np.ndarray}`. An epoch bump
clears the log (consumers that far behind resync fully).

Replay soundness of the `!resync` marker: the re-upload reads the LIVE
host array, which reflects every write up to the sync point, i.e. a
superset of every logged write in the suffix — so suffix writes for a
resync'd array are dropped, and writes logged after the marker are
already in the uploaded bytes.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from emqx_tpu.ops.contract import device_contract

RESYNC = "!resync"  # op-log marker: (RESYNC, array_name, 0)


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


@device_contract(
    "segment_scatter_insert",
    # host->device delta replay is device-local by construction: on a
    # mesh the placed sharding propagates through the scatter, no
    # collective may appear
    collectives=(),
)
def segment_scatter_impl(flats: Dict, idxs: Dict, vals: Dict) -> Dict:
    """The O(delta) update kernel: `flats[k][idxs[k]] = vals[k]` for every
    touched array, in ONE jitted program. Padded index vectors repeat one
    write (idempotent), so the program is keyed on pow2 delta buckets,
    not exact delta lengths. Outputs are fresh buffers — the inputs are
    deliberately NOT donated: in-flight executor batches may still hold
    the previous mirror generation (the same grace contract free_retired
    encodes for full uploads)."""
    return {k: flats[k].at[idxs[k]].set(vals[k]) for k in flats}


_scatter_jit = None


def _segment_scatter(flats, idxs, vals):
    global _scatter_jit
    if _scatter_jit is None:
        import jax

        _scatter_jit = jax.jit(segment_scatter_impl)
    return _scatter_jit(flats, idxs, vals)


class DeviceSegmentManager:
    """Device-resident mirror of one incrementally-mutated host source.

    `sync(src)` returns `{name: device_array}` matching
    `src.device_snapshot()`. All internal state is mutated under `_lock`
    (the retained flush path syncs from the dispatch executor while the
    loop thread inserts); callers receive a fresh shallow-copied dict, so
    a snapshot held across a later sync never tears.
    """

    def __init__(
        self,
        placement=None,
        free_retired: bool = False,
        name: str = "",
        metrics=None,
    ) -> None:
        """`placement`: optional fn(name, np_or_dev_array) -> device array
        applied to full uploads AND re-pinned after delta scatters — e.g.
        a NamedSharding device_put for SPMD serving, so churn stays
        O(delta) scatters on a mesh too (per-shard hot segments ride the
        same replicated placement as the packed tables).

        `free_retired`: explicitly `.delete()` the device buffers a full
        re-upload replaces, with ONE epoch of grace (the generation
        retired by rebuild N is freed at rebuild N+1) — in-flight
        executor batches still holding the previous snapshot stay valid.
        """
        self.name = name
        # per-kernel attribution sink (observe/profiler.py); None keeps
        # the manager usable as a bare library object
        self.metrics = metrics
        self._lock = threading.Lock()
        self._arrays: Optional[Dict] = None  # guarded-by: _lock
        self._epoch = -1  # guarded-by: _lock
        self._pos = 0  # guarded-by: _lock
        self._torn = False  # guarded-by: _lock
        self._placement = placement
        self._free_retired = free_retired
        self._retired: Optional[list] = None  # guarded-by: _lock
        self._offer: Optional[Tuple] = None  # guarded-by: _lock
        # observability counters, read by DeviceRouter.segment_status()
        self.full_resyncs = 0  # guarded-by: _lock
        self.delta_launches = 0  # guarded-by: _lock
        self.array_resyncs = 0  # guarded-by: _lock

    # -- background-compaction handoff ------------------------------------
    def offer(self, epoch: int, arrays: Dict, pos: int = 0) -> None:
        """Pre-built device buffers for the NEXT full resync, tagged with
        the source epoch they represent at op-log position `pos`. Adopted
        only when the epochs still match at sync time (a later structural
        event invalidates the offer); the op-log suffix past `pos`
        replays on top as usual."""
        with self._lock:
            self._offer = (epoch, dict(arrays), pos)

    def has_mirror(self) -> bool:
        with self._lock:
            return self._arrays is not None

    # -- fused-launch rider handoff ---------------------------------------
    def peek_delta(self, src):
        """Rider support (broker/session_store.py): the current mirror +
        the op-log suffix as per-array last-write-wins vectors, WITHOUT
        applying anything — the caller fuses the scatter into a serving
        launch (`session_ack_step` riding `session_route_step`) and
        hands the produced device arrays back via `adopt`. Returns
        ``(arrays, per_name_writes, pos, epoch)``, or None when the
        mirror needs a full resync / the suffix carries resync markers —
        those (rare, structural) paths go through `sync()` instead."""
        with self._lock:
            if (
                self._arrays is None
                or self._epoch != src.epoch
                or self._torn
            ):
                return None
            ops = src.oplog[self._pos :]
            per: Dict[str, Dict[int, int]] = {}
            for name, idx, val in ops:
                if name == RESYNC or name not in self._arrays:
                    return None
                per.setdefault(name, {})[idx] = val
            return dict(self._arrays), per, len(src.oplog), self._epoch

    def adopt(self, arrays: Dict, pos: int, epoch: int) -> bool:
        """Install rider-produced device arrays as the mirror at op-log
        position ``pos``. Refused (False) when a structural event moved
        the mirror past the rider's epoch/position — the host arrays are
        authoritative, so the refused rider's writes are already covered
        by the full re-upload that superseded it."""
        with self._lock:
            if (
                self._arrays is None
                or self._epoch != epoch
                or self._torn
                or pos < self._pos
            ):
                return False
            self._arrays = dict(arrays)
            self._pos = pos
            return True

    # -- sync --------------------------------------------------------------
    def sync(self, src) -> Dict:
        with self._lock:
            v0 = getattr(src, "version", None)
            out = self._sync_locked(src)
            if v0 is not None and getattr(src, "version", None) != v0:
                # torn read: an off-thread sync raced the mutator. The
                # snapshot is a usable superset for THIS call (consumers
                # re-verify matches on host), but it must never be
                # cached as clean — the next sync re-uploads.
                self._torn = True
            return out

    def _sync_locked(self, src) -> Dict:  # holds-lock: _lock
        if self._arrays is None or self._epoch != src.epoch or self._torn:
            self._torn = False
            return self._full_resync(src)
        return self._delta_sync(src)

    def _put(self, name: str, arr):
        if self._placement is not None:
            return self._placement(name, arr)
        import jax.numpy as jnp

        return jnp.asarray(arr)

    def _full_resync(self, src) -> Dict:  # holds-lock: _lock
        if self._free_retired:
            old = self._retired
            self._retired = (
                list(self._arrays.values()) if self._arrays else None
            )
            for arr in old or ():
                try:
                    arr.delete()
                except Exception:  # noqa: BLE001 — free is advisory
                    pass
        offer = self._offer
        self._offer = None
        if offer is not None and offer[0] != src.epoch:
            offer = None  # stale: a later structural event superseded it
        offered = offer[1] if offer is not None else {}
        self._arrays = {}
        for k, v in src.device_snapshot().items():
            if k in offered:
                self._arrays[k] = offered[k]
            else:
                self._arrays[k] = self._put(k, v.copy())
        self._epoch = src.epoch
        self.full_resyncs += 1
        if offer is not None:
            # adopted buffers represent op-log position `pos`; the
            # suffix (e.g. compaction-journal replay) scatters on top
            self._pos = offer[2]
            return self._delta_sync(src)
        self._pos = len(src.oplog)
        return dict(self._arrays)

    def _delta_sync(self, src) -> Dict:  # holds-lock: _lock
        import jax.numpy as jnp

        ops = src.oplog[self._pos :]
        snap = None
        if not ops:
            return dict(self._arrays)
        resync_names = {a for name, a, _v in ops if name == RESYNC}
        per: Dict[str, Dict[int, int]] = {}
        for name, idx, val in ops:
            if name == RESYNC or name in resync_names:
                continue  # the live re-upload supersedes these writes
            per.setdefault(name, {})[idx] = val  # last write per slot wins
        if resync_names:
            snap = src.device_snapshot()
            for name in resync_names:
                if name in snap:
                    self._arrays[name] = self._put(name, snap[name].copy())
                else:
                    self._arrays.pop(name, None)
                self.array_resyncs += 1
        # arrays that appeared without a marker (defensive: a source
        # growing its snapshot dict) upload too
        for name in list(per):
            if name not in self._arrays:
                if snap is None:
                    snap = src.device_snapshot()
                self._arrays[name] = self._put(name, snap[name].copy())
                self.array_resyncs += 1
                del per[name]
        if per:
            flats, idxs, vals, shapes = {}, {}, {}, {}
            for name, writes in per.items():
                arr = self._arrays[name]
                shapes[name] = arr.shape
                flats[name] = arr.reshape(-1)
                ix = np.fromiter(
                    writes.keys(), dtype=np.int32, count=len(writes)
                )
                vv = np.array(list(writes.values()), dtype=arr.dtype)
                # pad to a pow2 bucket (repeating one write is a no-op)
                # so the fused program recompiles per (touched-array-set,
                # size-bucket) combination, not per delta length
                n = len(ix)
                npad = max(16, _next_pow2(n))
                if npad != n:
                    ix = np.pad(ix, (0, npad - n), mode="edge")
                    vv = np.pad(vv, (0, npad - n), mode="edge")
                idxs[name] = jnp.asarray(ix)
                vals[name] = jnp.asarray(vv)
            # every touched array updates in ONE device launch
            t0 = time.perf_counter()
            out = _segment_scatter(flats, idxs, vals)
            if self.metrics is not None:
                # launch attribution (observe/profiler.py): the update
                # path's one fused kernel, keyed by its contract name
                from emqx_tpu.observe.profiler import (
                    record_kernel_launch,
                )

                record_kernel_launch(
                    self.metrics,
                    ("segment_scatter_insert",),
                    time.perf_counter() - t0,
                )
            self.delta_launches += 1
            for name in flats:
                new = out[name].reshape(shapes[name])
                if self._placement is not None:
                    # the scatter's jit may drop the placed sharding;
                    # re-pin (device-side reshard — no host re-upload)
                    new = self._placement(name, new)
                self._arrays[name] = new
        self._pos = len(src.oplog)
        # shallow copy: callers may hold the snapshot across a later sync
        return dict(self._arrays)


# -- background compaction ---------------------------------------------------

_compact_pool = None
_compact_pool_lock = threading.Lock()


def compact_pool():
    """Process-wide single-worker executor for segment compaction builds.
    One worker: compaction is a throughput background chore, and two
    concurrent multi-GB table builds would double peak host memory."""
    global _compact_pool
    with _compact_pool_lock:
        if _compact_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            _compact_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="segment-compact"
            )
        return _compact_pool


class SegmentCompactor:
    """Housekeeping-driven merge of hot segments into the packed tables.

    The loop thread owns every host table; the `segment-compact` executor
    thread only ever touches the immutable capture/built artifacts and
    `jax.device_put` (thread-safe). Per owner, one cycle is:

      loop:    cap   = owner.begin()          (array memcpys + journal on)
      thread:  built = owner.build(cap)       (pure numpy merge)
      thread:  bufs  = device_put(built)      (upload OFF the serving path)
      loop:    epoch = owner.apply(built)     (swap + journal replay)
      loop:    owner.manager.offer(epoch, bufs)

    so the next serving `prepare()` adopts the pre-uploaded buffers and
    the subscribe path never pays an O(table) rebuild or upload.
    """

    def __init__(self, metrics=None, interval_s: float = 5.0):
        self.metrics = metrics
        self.interval_s = interval_s
        self._busy = False  # single-writer: loop
        self._last: Dict[str, float] = {}  # single-writer: loop
        self._need_since: Dict[str, float] = {}  # single-writer: loop
        self.runs = 0  # single-writer: loop
        self.aborted = 0  # single-writer: loop

    def lag_s(self, key: str, now: Optional[float] = None) -> float:
        t0 = self._need_since.get(key)
        if t0 is None:
            return 0.0
        return (time.monotonic() if now is None else now) - t0

    def tick(self, owners) -> bool:
        """One housekeeping tick (loop thread): update gauges, and start
        at most one background compaction cycle. Returns True when a
        cycle was started."""
        import asyncio

        now = time.monotonic()
        started = False
        for owner in owners:
            key = owner.key
            need = owner.needs_compact()
            if need and key not in self._need_since:
                self._need_since[key] = now
            elif not need:
                self._need_since.pop(key, None)
            if self.metrics is not None and key == "shapes":
                self.metrics.gauge_set(
                    "router.compact.lag.seconds", self.lag_s(key, now)
                )
            if started or self._busy or not need:
                continue
            if now - self._last.get(key, 0.0) < self.interval_s:
                continue
            self._busy = True
            started = True
            asyncio.ensure_future(self._run(owner))
        return started

    async def _run(self, owner) -> None:
        import asyncio

        t0 = time.perf_counter()
        key = owner.key
        try:
            cap = owner.begin()
            loop = asyncio.get_running_loop()
            built = await loop.run_in_executor(
                compact_pool(), owner.build, cap
            )
            # back on the loop: swap host arrays + replay the journal,
            # then hand the pre-uploaded device buffers to the manager
            applied = owner.apply(built)
            if applied is None:
                self.aborted += 1
                if self.metrics is not None:
                    self.metrics.inc("router.compact.aborted")
            else:
                epoch, bufs, pos, merged = applied
                owner.manager.offer(epoch, bufs, pos)
                self.runs += 1
                if self.metrics is not None:
                    self.metrics.inc("router.compact.runs")
                    self.metrics.inc("router.compact.merged", merged)
                    if getattr(owner, "_placement", None) is not None:
                        # the rebuilt table pre-uploaded straight into
                        # the sharded mesh layout — no host gather, no
                        # serving-path re-placement (docs/scale_out.md)
                        self.metrics.inc("mesh.shard.compact.runs")
        except Exception:  # noqa: BLE001 — one bad cycle must not stop
            self.aborted += 1
            if self.metrics is not None:
                self.metrics.inc("router.compact.aborted")
            import logging

            logging.getLogger("emqx_tpu.segments").exception(
                "segment compaction cycle failed (%s)", key
            )
        finally:
            self._busy = False
            self._last[key] = time.monotonic()
            self._need_since.pop(key, None)
            if self.metrics is not None:
                self.metrics.observe(
                    "router.compact.seconds", time.perf_counter() - t0
                )

    def compact_now(self, owner) -> bool:
        """Synchronous cycle (tests / bench): begin+build+apply+offer on
        the calling thread. Returns False when the cycle aborted."""
        cap = owner.begin()
        built = owner.build(cap)
        applied = owner.apply(built)
        if applied is None:
            self.aborted += 1
            return False
        epoch, bufs, pos, merged = applied
        owner.manager.offer(epoch, bufs, pos)
        self.runs += 1
        if self.metrics is not None:
            self.metrics.inc("router.compact.runs")
            self.metrics.inc("router.compact.merged", merged)
            if getattr(owner, "_placement", None) is not None:
                self.metrics.inc("mesh.shard.compact.runs")
        return True


class ShapeSegmentOwner:
    """Compaction adapter for a `ShapeIndex` + its manager: merges the
    hot segment into the packed table and purges tombstones."""

    key = "shapes"

    def __init__(self, shapes, manager, placement=None,
                 hot_entries: int = 1024, tombstone_frac: float = 0.25):
        self.shapes = shapes
        self.manager = manager
        self._placement = placement
        self.hot_entries = hot_entries
        self.tombstone_frac = tombstone_frac

    def needs_compact(self) -> bool:
        s = self.shapes
        if s.hot_live >= self.hot_entries:
            return True
        return s.packed_tombstones > 0 and (
            s.packed_tombstones >= self.tombstone_frac * s._Tcap
        )

    def begin(self):
        return self.shapes.begin_compact()

    def build(self, cap):
        built = type(self.shapes).build_compact(cap)
        # upload on THIS (executor) thread: the built table is immutable,
        # so the device_put is race-free and the serving path never pays it
        arr = built["tab"].reshape(-1)
        if self._placement is not None:
            built["dev"] = self._placement("shape_tab", arr)
        else:
            import jax

            built["dev"] = jax.device_put(arr)
        return built

    def apply(self, built):
        merged = self.shapes.hot_live
        epoch = self.shapes.apply_compact(built)
        if epoch is None:
            return None
        return epoch, {"shape_tab": built["dev"]}, 0, merged


class BitmapGrowthOwner:
    """Compaction adapter for the subscriber bitmap matrix: PROACTIVE
    growth. `SubscriberTable` growth is an epoch bump (full re-upload of
    the biggest array in the process); growing at 3/4 occupancy from
    housekeeping — and pre-uploading the grown matrix off-thread — keeps
    the bump off the subscribe path entirely."""

    key = "bitmaps"

    def __init__(self, subtab, index, manager, placement=None,
                 headroom: float = 0.75):
        self.subtab = subtab
        self.index = index
        self.manager = manager
        self._placement = placement
        self.headroom = headroom

    def needs_compact(self) -> bool:
        if getattr(self.subtab, "sparse", False):
            return False  # the CSR representation has its own owner
        return (
            self.index.num_filters_capacity
            > self.headroom * self.subtab._fcap
        )

    def begin(self):
        # grow NOW on the loop (one memcpy; the expensive half — the
        # device upload — happens on the executor below), then capture
        # a consistent copy + the op-log position it represents
        tab = self.subtab
        tab.pack(_next_pow2(int(tab._fcap * 2)))
        return {
            "epoch": tab.epoch,
            "pos": len(tab.oplog),
            "arr": tab.arr.copy(),
        }

    def build(self, cap):
        if self._placement is not None:
            cap["dev"] = self._placement("sub_bitmaps", cap["arr"])
        else:
            import jax

            cap["dev"] = jax.device_put(cap["arr"])
        return cap

    def apply(self, built):
        if self.subtab.epoch != built["epoch"]:
            return None  # another structural event superseded the copy
        return built["epoch"], {"sub_bitmaps": built["dev"]}, built["pos"], 0


# -- durable snapshot/restore ------------------------------------------------


class SegmentStateSnapshot:
    """Rolling-upgrade story for the segment tables: pickle the host
    sources (numpy arrays + registries — mnesia disc_copies analog) to a
    sidecar file; `DurableState` carries the pointer + generation in its
    kv so a replacement process restores million-entry tables instead of
    replaying every subscribe.

    `capture()` must run on the thread that owns the tables (the loop).
    """

    def __init__(self, path: str, capture: Callable[[], Dict],
                 install: Optional[Callable[[Dict], None]] = None):
        self.path = path
        self._capture = capture
        self._install = install

    def save(self) -> Dict:
        import os
        import pickle

        state = self._capture()
        tmp = f"{self.path}.{os.getpid()}.tmp"
        with open(tmp, "wb") as f:
            pickle.dump(state, f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, self.path)
        return {
            "path": self.path,
            "at": time.time(),
            "keys": sorted(state),
        }

    def load(self, meta: Optional[Dict]) -> Optional[Dict]:
        import os
        import pickle

        path = (meta or {}).get("path", self.path)
        if not path or not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            state = pickle.load(f)
        if self._install is not None:
            self._install(state)
        return state
