"""TPU routing ops: topic algebra, NFA table compiler, batch matchers."""
