"""Semantic routing tables: embedding-filter subscriptions on the
segment machinery + the similarity kernel fused into the serving step.

This is the plane that makes the TPU broker do something the Erlang
reference *cannot* (ROADMAP item 3; "Neural Router: Semantic Content
Matching for Agentic AI", PAPERS.md): route by payload MEANING. A
subscription may carry an embedding filter — a unit vector plus a
cosine-similarity threshold — and the serving step answers it with one
batched matmul riding the same launch, program, and compact readback
the topic fan-out already pays for:

  ``sims [B, E] = q_vecs [B, D]  @  sem_vec.T [D, E]``

followed by a threshold mask, an optional topic-scope (fid-membership)
mask, and a per-message top-k pick whose winner slots UNION into the
existing ``slots / slot_count / overflow`` compact contract BEFORE
readback (`union_semantic_slots`). Dispatch then treats semantic hits
as ordinary slot recipients — zero new host fan-out machinery.

`SemanticTable` is the fifth `DeviceSegmentManager` owner, in the
PR 9/11/13 idiom (docs/update_path.md):

- **packed segment** (written only by rebuilds/compaction):
  ``sem_vec [S, P, D]`` (f32 or bf16-quantized at upload) plus the
  int/float lanes ``sem_fid / sem_slot / sem_thresh [S, P]``;
- **hot segment** (append-only between compactions): the ``sem_hot_*``
  twins — an insert is D+3 op-logged scalar writes riding the next
  fused segment scatter, never an O(table) rebuild;
- **tombstone lane**: an unsubscribe writes ``sem_slot = -1`` (ONE
  op-logged write) — dead entries mask out of the kernel;
- **compaction** (`SemanticSegmentOwner` on the ONE `SegmentCompactor`):
  merges ``packed - tombstones + hot`` into a fresh exact-size table on
  the compact executor, pre-uploads it, and replays racing mutations
  from a journal — the ShapeIndex cycle verbatim;
- **placement** (`parallel.mesh.semantic_placement`): every array's
  leading axis is the shard-owner axis (entry owned by
  ``slot % shards``), sharded over 'tp' — the same slot-ownership
  regime as the CSR subscriber table, so per-shard semantic hits emit
  GLOBAL slot ids and concatenate over 'tp' with no lane rebase.

Scope semantics (``sem_fid``): ``fid >= 0`` binds the entry to a topic
filter — the entry only fires when that fid appears in the row's
matched set (topic AND similarity); ``fid == -1`` is an unscoped
filter — similarity alone routes it (any topic). Liveness is the slot
lane: ``sem_slot >= 0``.

The entry registry is a plain ``{slot: position}`` dict — deliberately
NOT the PR 9 open-addressing idiom: one entry exists per EXPLICIT
embedding filter (a per-subscription opt-in), orders of magnitude below
the 10M-row fan-out tables that forced the numpy registries.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from emqx_tpu.ops.contract import device_contract
from emqx_tpu.ops.nfa import _next_pow2

# registry position flag: entry lives in the hot segment
HOT_POS = 1 << 30

# device-snapshot array names (the segment-manager sync set)
SEM_KEYS = (
    "sem_vec", "sem_fid", "sem_slot", "sem_thresh",
    "sem_hot_vec", "sem_hot_fid", "sem_hot_slot", "sem_hot_thresh",
)


def normalize(vec, dim: int) -> np.ndarray:
    """Embedding intake: f32, exactly ``dim`` wide, unit-norm (cosine
    similarity is then one dot product). Zero vectors stay zero — they
    match nothing at any positive threshold."""
    v = np.asarray(vec, np.float32).reshape(-1)
    if v.shape[0] != dim:
        raise ValueError(
            f"embedding has dim {v.shape[0]}, table expects {dim}"
        )
    n = float(np.linalg.norm(v))
    if n > 1e-12:
        v = v / np.float32(n)
    return v.astype(np.float32)


# -- device kernel -----------------------------------------------------------


@device_contract(
    "semantic_match_step",
    # device-local by construction: the mesh builders psum the per-shard
    # qualifying counts OUTSIDE the kernel, exactly like the fan-out
    # compaction stages
    collectives=(),
    out_bounds={
        # semantic fan-out is bounded by the top-k pick BY DESIGN:
        # outputs scale with B * topk (and [B]), never with the entry
        # capacity E or the embedding dim D
        "sem_slots": lambda cfg: cfg["B"] * cfg["kslot"] * 4,
        "sem_count": lambda cfg: cfg["B"] * 4,
    },
)
def semantic_match_step(sem: Dict, q_vecs, matched, topk: int):
    """ONE batched similarity matmul + threshold/top-k mask.

    sem: the LOCAL shard's arrays ([1, ...] leading axis — inside
    shard_map each device sees its own 'tp' slice; single-device tables
    are shard 0 of 1). q_vecs: f32 [B, D] per-message embeddings.
    matched: int32 [B, K] sparse fids (-1 holes) from the topic match —
    the scope mask joins against it with the same scanned-membership
    overlay the CSR hot segment uses.

    Returns ``(sem_slots [B, topk], sem_count [B])``: the top-k
    qualifying entries' subscriber slots (score-ordered, -1 holes) and
    the UNCAPPED qualifying count (drives the `semantic.*` series and
    the truncation stat). Unlike Kslot overflow there is no dense
    fallback: top-k IS the delivery semantic ("route to the k most
    similar subscribers"), so truncation is a feature, not a degraded
    mode.
    """
    import jax
    import jax.numpy as jnp

    if topk <= 0:
        raise ValueError("semantic matching requires topk > 0")
    vecs = jnp.concatenate(
        [sem["sem_vec"][0], sem["sem_hot_vec"][0]], axis=0
    )  # [E, D]
    fids = jnp.concatenate([sem["sem_fid"][0], sem["sem_hot_fid"][0]])
    slots = jnp.concatenate([sem["sem_slot"][0], sem["sem_hot_slot"][0]])
    ths = jnp.concatenate(
        [sem["sem_thresh"][0], sem["sem_hot_thresh"][0]]
    )
    B, K = matched.shape
    E = vecs.shape[0]
    q = q_vecs
    if q.dtype != vecs.dtype:
        # bf16-quantized tables: the query casts down, the MXU
        # accumulates f32 (preferred_element_type pins it)
        q = q.astype(vecs.dtype)
    sims = jnp.matmul(
        q, vecs.T, preferred_element_type=jnp.float32
    )  # [B, E] f32
    live = slots >= 0
    scoped = fids >= 0
    # scope membership: entry fid in this row's matched set. lax.scan
    # over the K matched columns keeps peak memory at one [B, E] mask
    # instead of materializing [B, K, E] (the CSR hot-overlay idiom).

    def _memb(acc, mcol):  # mcol: [B] one matched column
        return acc | (mcol[:, None] == fids[None, :]), None

    memb, _ = jax.lax.scan(
        _memb, jnp.zeros((B, E), bool), jnp.swapaxes(matched, 0, 1)
    )
    ok = (
        live[None, :]
        & (sims >= ths[None, :])
        & (~scoped[None, :] | memb)
    )
    count = jnp.sum(ok.astype(jnp.int32), axis=1)
    score = jnp.where(ok, sims, -jnp.inf)
    k = min(topk, E)
    top_v, top_i = jax.lax.top_k(score, k)
    sem_slots = jnp.where(
        top_v > -jnp.inf, slots[top_i], jnp.int32(-1)
    ).astype(jnp.int32)
    if k < topk:  # tiny tables: pad to the static contract width
        sem_slots = jnp.pad(
            sem_slots, ((0, 0), (0, topk - k)), constant_values=-1
        )
    return sem_slots, count


def union_semantic_slots(slots, sem_slots):
    """Union the semantic winners into the topic fan-out's compact slot
    rows BEFORE readback: ``[B, kslot] ++ [B, topk] -> [B, kslot+topk]``.

    Semantic entries already present in the topic part null out (a
    subscriber holding both a plain and a semantic subscription must
    not be delivered twice), and the TOPIC part is left byte-identical —
    `slot_count`/`overflow` keep their topic-only semantics, so the
    host's `slot_count > kslot` overflow derivation and the dense
    fallback contract are untouched. -1 holes are legal anywhere in a
    compact row (RouteResult contract), so no re-compaction is needed.
    """
    import jax.numpy as jnp

    dup = jnp.any(
        (sem_slots[:, :, None] == slots[:, None, :])
        & (sem_slots >= 0)[:, :, None],
        axis=2,
    )
    sem_clean = jnp.where(dup, jnp.int32(-1), sem_slots)
    return jnp.concatenate([slots, sem_clean], axis=1)


# -- host table --------------------------------------------------------------


class SemanticTable:
    """Host-side embedding-filter registry + its device mirror source
    (epoch/oplog/version protocol, docs/update_path.md).

    One entry per subscriber slot: ``slot`` is the broker's fan-out
    slot (`Broker._slot_subs`), so a semantic hit IS an ordinary slot
    recipient. ``fid`` scopes the entry to a topic filter (-1 =
    unscoped). Vectors normalize at intake.
    """

    HOT_MIN = 64  # minimum hot-segment capacity per shard (pow2)
    # hot population past this forces an inline rebuild instead of
    # another growth (the kernel concatenates hot into the matmul, so
    # hot size is a FLOP knob, not just memory)
    HOT_ABSORB_MAX = 1 << 14

    def __init__(self, dim: int = 64, topk: int = 16, shards: int = 1,
                 dtype: str = "float32"):
        if dim < 1:
            raise ValueError("semantic dim must be >= 1")
        if topk < 1:
            raise ValueError("semantic topk must be >= 1")
        if dtype not in ("float32", "bfloat16"):
            raise ValueError(f"semantic dtype {dtype!r}")
        self.dim = dim
        self.topk = topk
        self.dtype = dtype
        self.shards = S = max(1, int(shards))
        self._pcap = 64  # packed capacity PER SHARD
        self.sem_vec = np.zeros((S, self._pcap, dim), np.float32)
        self.sem_fid = np.full((S, self._pcap), -1, np.int32)
        self.sem_slot = np.full((S, self._pcap), -1, np.int32)
        self.sem_thresh = np.ones((S, self._pcap), np.float32)
        self._hcap = self.HOT_MIN
        self.sem_hot_vec = np.zeros((S, self._hcap, dim), np.float32)
        self.sem_hot_fid = np.full((S, self._hcap), -1, np.int32)
        self.sem_hot_slot = np.full((S, self._hcap), -1, np.int32)
        self.sem_hot_thresh = np.ones((S, self._hcap), np.float32)
        self._hot_tail = [0] * S
        self.live = 0
        self.packed_tombs = 0
        self.hot_tombs = 0
        # slot -> packed position | (HOT_POS | hot index), shard implied
        # by slot % shards (see module docstring for why a dict is fine)
        self._reg: Dict[int, int] = {}
        self.epoch = 0
        self.oplog: list = []
        self.version = 0
        self.OPLOG_MAX = 65536
        # compaction bookkeeping (the ShapeIndex/CsrTable cycle)
        self._structure_gen = 0
        self._journal: Optional[list] = None  # single-writer: loop

    # -- op-log plumbing ----------------------------------------------------
    def _bump(self) -> None:
        self.epoch += 1
        self.oplog.clear()
        self.version += 1

    def _log(self, name: str, flat_idx: int, val) -> None:
        # values stay python floats for the f32 lanes (the segment
        # scatter casts to the array dtype; int() here would truncate)
        self.version += 1
        if len(self.oplog) >= self.OPLOG_MAX:
            self._bump()
            return
        self.oplog.append((name, int(flat_idx), val))

    def _log_resync(self, name: str) -> None:
        from emqx_tpu.ops.segments import RESYNC

        self.version += 1
        if len(self.oplog) >= self.OPLOG_MAX:
            self._bump()
            return
        self.oplog.append((RESYNC, name, 0))

    # -- mutation -----------------------------------------------------------
    def add(self, slot: int, vec, threshold: float, fid: int = -1) -> bool:
        """Install (or replace) the embedding filter bound to a
        subscriber slot. Returns True when a NEW entry was created."""
        v = normalize(vec, self.dim)
        fid = -1 if fid is None or fid < 0 else int(fid)
        th = float(threshold)
        pos = self._reg.get(slot)
        if pos is not None:
            self._write_entry(slot, pos, v, th, fid)
            if self._journal is not None:
                self._journal.append(("add", slot, v, th, fid))
            return False
        s = slot % self.shards
        if self._hot_tail[s] >= self._hcap:
            if self.hot_fill >= self.HOT_ABSORB_MAX:
                # no compactor is draining hot: fold inline (epoch bump)
                self._rebuild([(slot, v, th, fid)])
                return True
            self._grow_hot()
        h = self._hot_tail[s]
        self._hot_tail[s] = h + 1
        self.sem_hot_vec[s, h] = v
        base = (s * self._hcap + h) * self.dim
        for d in range(self.dim):
            self._log("sem_hot_vec", base + d, float(v[d]))
        self.sem_hot_fid[s, h] = fid
        self._log("sem_hot_fid", s * self._hcap + h, fid)
        self.sem_hot_thresh[s, h] = th
        self._log("sem_hot_thresh", s * self._hcap + h, th)
        # slot lane LAST: liveness flips on only once the row is whole
        self.sem_hot_slot[s, h] = slot
        self._log("sem_hot_slot", s * self._hcap + h, slot)
        self._reg[slot] = h | HOT_POS
        self.live += 1
        if self._journal is not None:
            self._journal.append(("add", slot, v, th, fid))
        return True

    def _write_entry(self, slot: int, pos: int, v, th: float,
                     fid: int) -> None:
        """In-place filter replacement (same slot re-subscribes with a
        new embedding): scalar op-logged writes, no structural event."""
        s = slot % self.shards
        if pos & HOT_POS:
            h = pos & ~HOT_POS
            self.sem_hot_vec[s, h] = v
            base = (s * self._hcap + h) * self.dim
            for d in range(self.dim):
                self._log("sem_hot_vec", base + d, float(v[d]))
            self.sem_hot_fid[s, h] = fid
            self._log("sem_hot_fid", s * self._hcap + h, fid)
            self.sem_hot_thresh[s, h] = th
            self._log("sem_hot_thresh", s * self._hcap + h, th)
        else:
            self.sem_vec[s, pos] = v
            base = (s * self._pcap + pos) * self.dim
            for d in range(self.dim):
                self._log("sem_vec", base + d, float(v[d]))
            self.sem_fid[s, pos] = fid
            self._log("sem_fid", s * self._pcap + pos, fid)
            self.sem_thresh[s, pos] = th
            self._log("sem_thresh", s * self._pcap + pos, th)

    def remove(self, slot: int) -> bool:
        """Tombstone the entry bound to a slot: ONE op-logged write."""
        pos = self._reg.pop(slot, None)
        if pos is None:
            return False
        s = slot % self.shards
        if pos & HOT_POS:
            h = pos & ~HOT_POS
            self.sem_hot_slot[s, h] = -1
            self._log("sem_hot_slot", s * self._hcap + h, -1)
            self.hot_tombs += 1
        else:
            self.sem_slot[s, pos] = -1
            self._log("sem_slot", s * self._pcap + pos, -1)
            self.packed_tombs += 1
        self.live -= 1
        if self._journal is not None:
            self._journal.append(("remove", slot, None, 0.0, -1))
        return True

    def bulk_add(self, slots, vecs, thresholds, fids=None) -> None:
        """Vectorized cold load: one rebuild + one epoch bump."""
        slots = np.asarray(slots, np.int64)
        vecs = np.asarray(vecs, np.float32)
        ths = np.asarray(thresholds, np.float32)
        if fids is None:
            fids = np.full(len(slots), -1, np.int64)
        else:
            fids = np.asarray(fids, np.int64)
        n = np.linalg.norm(vecs, axis=1, keepdims=True)
        vecs = (vecs / np.maximum(n, 1e-12)).astype(np.float32)
        extra = [
            (int(slots[i]), vecs[i], float(ths[i]), int(fids[i]))
            for i in range(len(slots))
        ]
        self._rebuild(extra)

    def reshard(self, shards: int) -> None:
        """Re-partition over a new shard count (mesh attach after
        filters already landed). Epoch-bump rebuild."""
        shards = max(1, int(shards))
        if shards == self.shards:
            return
        # gather the live entries from the OLD layout before the shard
        # count (and every array's leading axis) changes
        ent = self._live_tuples()
        self.shards = shards
        self._structure_gen += 1
        self._journal = None
        built = self._build(ent, shards, self.dim)
        self._install(built)
        self._bump()

    # -- structure ----------------------------------------------------------
    def _grow_hot(self) -> None:
        nh = self._hcap * 2
        S = self.shards
        for name, fill in (
            ("sem_hot_fid", -1), ("sem_hot_slot", -1),
            ("sem_hot_thresh", 1.0),
        ):
            old = getattr(self, name)
            new = np.full((S, nh), fill, old.dtype)
            new[:, : self._hcap] = old  # append-only: indices preserved
            setattr(self, name, new)
            self._log_resync(name)
        old = self.sem_hot_vec
        new = np.zeros((S, nh, self.dim), np.float32)
        new[:, : self._hcap] = old
        self.sem_hot_vec = new
        self._log_resync("sem_hot_vec")
        self._hcap = nh

    @property
    def hot_fill(self) -> int:
        return sum(self._hot_tail) - self.hot_tombs

    @property
    def nbytes(self) -> int:
        """Device-table footprint: the eight mirrored arrays (bf16
        halves the vec arrays at upload; this reports the host f32)."""
        return sum(
            getattr(self, k).nbytes for k in SEM_KEYS
        )

    def __len__(self) -> int:
        return self.live

    def entries(self) -> List[Tuple[int, int, float]]:
        """(slot, fid, threshold) of every live entry (REST listing)."""
        out = []
        for slot, pos in self._reg.items():
            s = slot % self.shards
            if pos & HOT_POS:
                h = pos & ~HOT_POS
                out.append((
                    slot, int(self.sem_hot_fid[s, h]),
                    float(self.sem_hot_thresh[s, h]),
                ))
            else:
                out.append((
                    slot, int(self.sem_fid[s, pos]),
                    float(self.sem_thresh[s, pos]),
                ))
        return sorted(out)

    def live_arrays(self):
        """(vecs [E, D] f32, slots [E], fids [E], ths [E]) of every live
        entry — the host fallback / reference evaluator's view (loop
        thread; vectorized scans, no per-entry Python objects)."""
        vs, sl, fi, th = [], [], [], []
        for s in range(self.shards):
            m = self.sem_slot[s] >= 0
            if m.any():
                vs.append(self.sem_vec[s][m])
                sl.append(self.sem_slot[s][m])
                fi.append(self.sem_fid[s][m])
                th.append(self.sem_thresh[s][m])
            hm = self.sem_hot_slot[s] >= 0
            if hm.any():
                vs.append(self.sem_hot_vec[s][hm])
                sl.append(self.sem_hot_slot[s][hm])
                fi.append(self.sem_hot_fid[s][hm])
                th.append(self.sem_hot_thresh[s][hm])
        if not vs:
            z = np.empty(0, np.int32)
            return (np.empty((0, self.dim), np.float32), z, z,
                    np.empty(0, np.float32))
        return (
            np.concatenate(vs), np.concatenate(sl),
            np.concatenate(fi), np.concatenate(th),
        )

    def device_snapshot(self) -> Dict[str, np.ndarray]:
        out = {k: getattr(self, k) for k in SEM_KEYS}
        if self.dtype == "bfloat16":
            import ml_dtypes

            out = dict(out)
            for k in ("sem_vec", "sem_hot_vec"):
                out[k] = out[k].astype(ml_dtypes.bfloat16)
        return out

    def status(self) -> Dict:
        """Hotpath-REST / gauge block."""
        return {
            "filters": self.live,
            "dim": self.dim,
            "topk": self.topk,
            "dtype": self.dtype,
            "shards": self.shards,
            "packed_capacity": self._pcap * self.shards,
            "hot_fill": self.hot_fill,
            "tombstones": self.packed_tombs + self.hot_tombs,
            "bytes": self.nbytes,
        }

    # -- rebuild / compaction ----------------------------------------------
    def _live_tuples(self) -> List[Tuple[int, np.ndarray, float, int]]:
        vecs, slots, fids, ths = self.live_arrays()
        return [
            (int(slots[i]), vecs[i].copy(), float(ths[i]), int(fids[i]))
            for i in range(len(slots))
        ]

    def _rebuild(self, extra=()) -> None:
        ent = self._live_tuples()
        seen = {e[0] for e in extra}
        ent = [e for e in ent if e[0] not in seen] + list(extra)
        self._structure_gen += 1
        self._journal = None
        built = self._build(ent, self.shards, self.dim)
        self._install(built)
        self._bump()

    @staticmethod
    def _build(entries, shards: int, dim: int) -> Dict:
        """Pure-numpy exact-size packed build from (slot, vec, th, fid)
        tuples — safe on any thread (the compaction executor runs it)."""
        S = shards
        per: List[list] = [[] for _ in range(S)]
        for slot, v, th, fid in entries:
            per[slot % S].append((slot, v, th, fid))
        pcap = max(64, _next_pow2(max((len(p) for p in per), default=1)))
        vec = np.zeros((S, pcap, dim), np.float32)
        fidl = np.full((S, pcap), -1, np.int32)
        slotl = np.full((S, pcap), -1, np.int32)
        thl = np.ones((S, pcap), np.float32)
        reg: Dict[int, int] = {}
        n = 0
        for s in range(S):
            for i, (slot, v, th, fid) in enumerate(sorted(per[s])):
                vec[s, i] = v
                fidl[s, i] = fid
                slotl[s, i] = slot
                thl[s, i] = th
                reg[slot] = i
                n += 1
        return {
            "pcap": pcap, "sem_vec": vec, "sem_fid": fidl,
            "sem_slot": slotl, "sem_thresh": thl, "reg": reg, "n": n,
        }

    # oplog-covered-by: every caller bumps the epoch after install
    def _install(self, built: Dict) -> None:
        S = self.shards
        self._pcap = built["pcap"]
        self.sem_vec = built["sem_vec"]
        self.sem_fid = built["sem_fid"]
        self.sem_slot = built["sem_slot"]
        self.sem_thresh = built["sem_thresh"]
        self._hcap = self.HOT_MIN
        self.sem_hot_vec = np.zeros((S, self._hcap, self.dim), np.float32)
        self.sem_hot_fid = np.full((S, self._hcap), -1, np.int32)
        self.sem_hot_slot = np.full((S, self._hcap), -1, np.int32)
        self.sem_hot_thresh = np.ones((S, self._hcap), np.float32)
        self._hot_tail = [0] * S
        self.hot_tombs = 0
        self.packed_tombs = 0
        self.live = built["n"]
        self._reg = dict(built["reg"])

    def begin_compact(self) -> Dict:
        cap = {
            "entries": self._live_tuples(),
            "shards": self.shards,
            "dim": self.dim,
            "gen": self._structure_gen,
        }
        self._journal = []
        return cap

    @staticmethod
    def build_compact(cap: Dict) -> Dict:
        built = SemanticTable._build(
            cap["entries"], cap["shards"], cap["dim"]
        )
        built["gen"] = cap["gen"]
        return built

    def apply_compact(self, built: Dict) -> bool:
        """Install a built table (loop thread) + replay the journal of
        mutations that raced the build. False = capture invalidated by
        a structural rebuild (the cycle aborts cleanly)."""
        if self._journal is None or built["gen"] != self._structure_gen:
            self._journal = None
            return False
        journal, self._journal = self._journal, None
        self._structure_gen += 1
        self._install(built)
        self._bump()
        for op, slot, v, th, fid in journal:
            if op == "add":
                self.add(slot, v, th, fid)
            else:
                self.remove(slot)
        return True


class SemanticSegmentOwner:
    """Compaction adapter for a `SemanticTable` + its segment manager:
    merge ``packed - tombstones + hot`` into a fresh exact-size table
    off the subscribe path, pre-uploading the packed arrays on the
    compact executor (`SegmentCompactor` drives the cycle)."""

    key = "semantic"

    def __init__(self, semtab: SemanticTable, manager, placement=None,
                 hot_entries: int = 1024, tombstone_frac: float = 0.25):
        self.semtab = semtab
        self.manager = manager
        self._placement = placement
        self.hot_entries = hot_entries
        self.tombstone_frac = tombstone_frac

    def needs_compact(self) -> bool:
        t = self.semtab
        if t.hot_fill >= self.hot_entries:
            return True
        tombs = t.packed_tombs + t.hot_tombs
        return tombs > 0 and tombs >= self.tombstone_frac * max(1, t.live)

    def begin(self):
        return self.semtab.begin_compact()

    def build(self, cap):
        built = SemanticTable.build_compact(cap)
        # pre-upload the packed arrays on THIS (executor) thread: the
        # built table is immutable, so the device_put is race-free
        import jax

        dtype = self.semtab.dtype
        dev = {}
        for name in ("sem_vec", "sem_fid", "sem_slot", "sem_thresh"):
            arr = built[name]
            if name == "sem_vec" and dtype == "bfloat16":
                import ml_dtypes

                arr = arr.astype(ml_dtypes.bfloat16)
            if self._placement is not None:
                dev[name] = self._placement(name, arr)
            else:
                dev[name] = jax.device_put(arr)
        built["dev"] = dev
        return built

    def apply(self, built):
        merged = self.semtab.hot_fill
        if not self.semtab.apply_compact(built):
            return None
        return self.semtab.epoch, built["dev"], 0, merged
