"""Subscription-trie -> dense NFA table compiler (incrementally maintained).

The reference walks a prefix trie in ETS per published message
(apps/emqx/src/emqx_trie.erl:271-333). That design is pointer-chasing and
per-message — exactly wrong for a TPU. Here the same trie is compiled into a
set of flat arrays ("NFA tables") that a jitted JAX kernel
(`emqx_tpu.ops.matcher`) walks for a whole *batch* of topics at once, one
`lax.scan` step per topic level, with all lookups as vectorized gathers:

- ``plus_child[node]``   -> node id of the ``+`` child, or -1
- ``hash_filter[node]``  -> filter id of the ``#`` child, or -1 (``#`` is
  always a terminal leaf, so it needs no node of its own; matching ``a/#``
  against ``a`` — emqx_trie.erl 'match_#' at end of words — falls out of
  collecting this field both when consuming a word *and* at end-of-topic)
- ``term_filter[node]``  -> filter id ending exactly at this node, or -1
- literal edges: open-addressing hash table ``(node, sym) -> child`` with a
  fixed probe bound, so the device probe loop is a fixed-length unrolled
  gather (no data-dependent control flow under jit)
- vocab: open-addressing table ``(h1, h2) -> sym`` mapping *word hash pairs*
  to dense symbol ids, so topic tokenization is hash-based and runs entirely
  on device (`emqx_tpu.ops.tokenizer`)

Word hashing uses a 2x32-bit polynomial hash (see `word_hash_pair`) chosen so
the device tokenizer can compute it with prefix sums instead of a per-byte
scan. Hash-pair collisions between distinct words are detected at insert
time and resolved by bumping a salt and rebuilding the vocab (a ~2^-64
event).

Updates are the delta-overlay scheme (SURVEY.md §7 hard part (a)): the flat
arrays are the PRIMARY storage, mutated in place per subscribe/unsubscribe
(mirroring emqx_trie insert/delete:66-119 refcount semantics), and every
write is appended to an op-log. A device consumer (`DeviceDeltaSync`)
replays the log as one scatter per touched array — so subscription churn
costs O(delta) on both host and device, not O(table). Structural events
(array growth, hash-table rehash, salt change) bump `epoch`, forcing the
rare full re-upload. Deletions leave tombstones in the open-addressing
tables (edge_node = -2, vocab_sym = -3); the device probe loops are
tombstone-oblivious because they always scan the full probe window and
match on live keys only.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from emqx_tpu.ops import topics as T

# Polynomial-hash parameters; must match emqx_tpu.ops.tokenizer exactly.
P1 = np.uint32(0x01000193)  # FNV prime, odd => invertible mod 2^32
P2 = np.uint32(0x00BC8F6B)  # odd
_SALT1 = np.uint32(0x9E3779B9)
_SALT2 = np.uint32(0x85EBCA6B)

MAX_PROBES = 8

# Slot-hash constants shared bit-for-bit by the host packers below and the
# device probe loops (matcher._probe_edges, tokenizer.vocab_lookup_device).
EDGE_H_MUL_NODE = 0x9E3779B1
EDGE_H_MUL_SYM = 0x85EBCA77
EDGE_H_SHIFT = 15
VOCAB_H_MUL = 0xC2B2AE3D
VOCAB_H_SHIFT = 13

PLUS_SYM = -2  # sentinel syms (never produced by vocab lookup)
HASH_SYM = -3

EDGE_TOMB = -2  # tombstoned edge slot (edge_node value)
VOCAB_TOMB = -3  # tombstoned vocab slot (vocab_sym value)


_M32 = 0xFFFFFFFF


def _mix32(x: int) -> int:
    """Murmur3-style finalizer (32-bit). Pure-int: this runs per-word on the
    subscribe path and numpy scalar math is ~10x slower."""
    x &= _M32
    x ^= x >> 16
    x = (x * 0x7FEB352D) & _M32
    x ^= x >> 15
    x = (x * 0x846CA68B) & _M32
    x ^= x >> 16
    return x


def _poly_raw(word: bytes, P: int) -> int:
    h = 1  # == P^0; encodes length so "" hashes distinctly
    for c in word:
        h = (h * P + c) & _M32
    return h


def word_hash_pair(word: str, salt: int) -> Tuple[int, int]:
    """(h1, h2) for one word; the device tokenizer computes the same pair."""
    b = word.encode("utf-8", "surrogatepass")
    s1 = (salt * int(_SALT1) + 1) & _M32
    s2 = (salt * int(_SALT2) + 7) & _M32
    h1 = _mix32(_poly_raw(b, int(P1)) ^ s1)
    h2 = _mix32(_poly_raw(b, int(P2)) ^ s2)
    return h1, h2


def edge_slot_hash(node: int, sym: int) -> int:
    """Initial probe slot hash for the literal-edge table (pre-mask)."""
    h = (node * EDGE_H_MUL_NODE + sym * EDGE_H_MUL_SYM) & _M32
    h ^= h >> EDGE_H_SHIFT
    return h


def vocab_slot_hash(h1: int) -> int:
    h = (h1 * VOCAB_H_MUL) & _M32
    h ^= h >> VOCAB_H_SHIFT
    return h


@dataclass
class NfaTables:
    """Flat match tables; everything the device kernel needs.

    Arrays are VIEWS of the builder's live storage — valid until the next
    builder mutation. Consumers that need isolation across mutations copy
    (DeviceDeltaSync keeps its own device-side mirror)."""

    plus_child: np.ndarray  # int32 [N]
    hash_filter: np.ndarray  # int32 [N]
    term_filter: np.ndarray  # int32 [N]
    edge_node: np.ndarray  # int32 [E]
    edge_sym: np.ndarray  # int32 [E]
    edge_child: np.ndarray  # int32 [E]
    vocab_h1: np.ndarray  # uint32 [V]
    vocab_h2: np.ndarray  # uint32 [V]
    vocab_sym: np.ndarray  # int32 [V]
    salt: int
    num_nodes: int
    num_filters: int
    version: int

    def device_arrays(self):
        import jax.numpy as jnp

        return {
            "plus_child": jnp.asarray(self.plus_child.copy()),
            "hash_filter": jnp.asarray(self.hash_filter.copy()),
            "term_filter": jnp.asarray(self.term_filter.copy()),
            "edge_node": jnp.asarray(self.edge_node.copy()),
            "edge_sym": jnp.asarray(self.edge_sym.copy()),
            "edge_child": jnp.asarray(self.edge_child.copy()),
            "vocab_h1": jnp.asarray(self.vocab_h1.copy()),
            "vocab_h2": jnp.asarray(self.vocab_h2.copy()),
            "vocab_sym": jnp.asarray(self.vocab_sym.copy()),
        }


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


# The device consumer of the delta-overlay protocol now lives in
# emqx_tpu/ops/segments.py as the ONE segment-table manager under every
# index (router/shape/retained — ROADMAP item 3). The historical name is
# kept importable here: the manager is a strict superset (coalesced
# one-launch scatter replay, per-array resync markers, offered buffers
# from background compaction).
from emqx_tpu.ops.segments import (  # noqa: E402  (re-export)
    DeviceSegmentManager as DeviceDeltaSync,
)


class NfaBuilder:
    """Incrementally maintained subscription automaton.

    add/remove mirror emqx_trie:insert/delete refcount semantics
    (emqx_trie.erl:170-199), mutating the flat device tables in place and
    op-logging every write (see module docstring). `pack()` is O(1): it
    hands out views of the live arrays.
    """

    ROOT = 0
    OPLOG_MAX = 65536
    _MIN_CAP = 1024

    def __init__(self) -> None:
        cap = self._MIN_CAP
        # node tables
        self._cap_nodes = cap
        self.arr_plus = np.full(cap, -1, np.int32)
        self.arr_hashf = np.full(cap, -1, np.int32)
        self.arr_term = np.full(cap, -1, np.int32)
        self._n_nodes = 1  # high-water node count (root pre-allocated)
        self._refs: List[int] = [0]  # filters at-or-below node
        self._free_nodes: List[int] = []
        # literal edges: authoritative dict + open-addressing device table
        self._edges: Dict[Tuple[int, int], int] = {}
        self._E = cap
        self.arr_edge_node = np.full(cap, -1, np.int32)
        self.arr_edge_sym = np.full(cap, -1, np.int32)
        self.arr_edge_child = np.full(cap, -1, np.int32)
        self._edge_fill = 0  # non-empty slots (live + tombstones)
        # vocab: word -> [sym, refcount]; device table keyed by hash pair
        self._vocab: Dict[str, List[int]] = {}
        self._hash_pairs: Dict[Tuple[int, int], str] = {}
        self._V = cap
        self.arr_vocab_h1 = np.zeros(cap, np.uint32)
        self.arr_vocab_h2 = np.zeros(cap, np.uint32)
        self.arr_vocab_sym = np.full(cap, -1, np.int32)
        self._vocab_fill = 0
        self._sym_words: List[Optional[str]] = []
        self._free_syms: List[int] = []
        # filters
        self._filter_ids: Dict[str, int] = {}
        self._id_filters: List[Optional[str]] = []
        self._free_filters: List[int] = []
        self._filter_refs: List[int] = []
        self.salt = 0
        self.epoch = 0  # full-device-resync marker
        self.oplog: List[Tuple[str, int, int]] = []
        self.version = 0

    # -- op-logged writes --------------------------------------------------
    def _log(self, name: str, idx: int, val: int) -> None:
        self.version += 1
        if len(self.oplog) >= self.OPLOG_MAX:
            # cap the log: consumers that fell this far behind resync fully
            self._bump_epoch()
            return
        self.oplog.append((name, int(idx), int(val)))

    def _bump_epoch(self) -> None:
        self.epoch += 1
        self.oplog.clear()
        self.version += 1

    def _set_plus(self, node: int, val: int) -> None:
        self.arr_plus[node] = val
        self._log("plus_child", node, val)

    def _set_hashf(self, node: int, val: int) -> None:
        self.arr_hashf[node] = val
        self._log("hash_filter", node, val)

    def _set_term(self, node: int, val: int) -> None:
        self.arr_term[node] = val
        self._log("term_filter", node, val)

    # -- vocab -------------------------------------------------------------
    def _vocab_place(self, h1: int, h2: int, sym: int) -> bool:
        """Probe-insert into the device vocab table; False if window full."""
        slot = vocab_slot_hash(h1) & (self._V - 1)
        for p in range(MAX_PROBES):
            idx = (slot + p) & (self._V - 1)
            s = self.arr_vocab_sym[idx]
            if s == -1 or s == VOCAB_TOMB:
                if s == -1:
                    self._vocab_fill += 1
                self.arr_vocab_h1[idx] = h1
                self._log("vocab_h1", idx, h1)
                self.arr_vocab_h2[idx] = h2
                self._log("vocab_h2", idx, h2)
                self.arr_vocab_sym[idx] = sym
                self._log("vocab_sym", idx, sym)
                return True
        return False

    def _vocab_rehash(self, newV: int) -> None:
        while True:
            h1a = np.zeros(newV, np.uint32)
            h2a = np.zeros(newV, np.uint32)
            syma = np.full(newV, -1, np.int32)
            ok = True
            for w, ent in self._vocab.items():
                sym, h1, h2 = ent[0], ent[2], ent[3]
                slot = vocab_slot_hash(h1) & (newV - 1)
                placed = False
                for p in range(MAX_PROBES):
                    idx = (slot + p) & (newV - 1)
                    if syma[idx] < 0:
                        h1a[idx], h2a[idx], syma[idx] = h1, h2, sym
                        placed = True
                        break
                if not placed:
                    ok = False
                    break
            if ok:
                break
            newV *= 2
        self._V = newV
        self.arr_vocab_h1, self.arr_vocab_h2, self.arr_vocab_sym = h1a, h2a, syma
        self._vocab_fill = len(self._vocab)
        self._bump_epoch()

    def _salt_rebuild(self) -> None:
        """Hash-pair collision between distinct words: bump salt, rebuild."""
        for _ in range(16):
            self.salt += 1
            pairs: Dict[Tuple[int, int], str] = {}
            ok = True
            for w in self._vocab:
                p = word_hash_pair(w, self.salt)
                if p in pairs:
                    ok = False
                    break
                pairs[p] = w
            if ok:
                self._hash_pairs = pairs
                for w, ent in self._vocab.items():
                    ent[2], ent[3] = word_hash_pair(w, self.salt)
                self._vocab_rehash(self._V)
                return
        raise RuntimeError("vocab hash collisions persisted across 16 salts")

    def _sym_for(self, word: str, create: bool) -> int:
        ent = self._vocab.get(word)
        if ent is not None:
            if create:
                ent[1] += 1
            return ent[0]
        if not create:
            return -1
        if self._free_syms:
            sym = self._free_syms.pop()
            self._sym_words[sym] = word
        else:
            sym = len(self._sym_words)
            self._sym_words.append(word)
        h1, h2 = word_hash_pair(word, self.salt)
        self._vocab[word] = [sym, 1, h1, h2]
        other = self._hash_pairs.get((h1, h2))
        if other is not None and other != word:
            self._salt_rebuild()  # rehashes every word incl. this one
            return sym
        self._hash_pairs[(h1, h2)] = word
        if (self._vocab_fill + 1) * 2 > self._V:
            self._vocab_rehash(self._V * 2)
        elif not self._vocab_place(h1, h2, sym):
            self._vocab_rehash(self._V * 2)
        return sym

    def _sym_release(self, word: str) -> None:
        ent = self._vocab[word]
        ent[1] -= 1
        if ent[1] == 0:
            del self._vocab[word]
            self._sym_words[ent[0]] = None
            self._free_syms.append(ent[0])
            h1, h2 = ent[2], ent[3]
            self._hash_pairs.pop((h1, h2), None)
            slot = vocab_slot_hash(h1) & (self._V - 1)
            for p in range(MAX_PROBES):
                idx = (slot + p) & (self._V - 1)
                if (
                    self.arr_vocab_sym[idx] >= 0
                    and self.arr_vocab_h1[idx] == np.uint32(h1)
                    and self.arr_vocab_h2[idx] == np.uint32(h2)
                ):
                    self.arr_vocab_sym[idx] = VOCAB_TOMB
                    self._log("vocab_sym", idx, VOCAB_TOMB)
                    break
            # tombstone-heavy table: compact at the SAME size (without this,
            # churn of unique words ratchets fill up and doubles V forever)
            if (self._vocab_fill - len(self._vocab)) * 4 > self._V:
                self._vocab_rehash(self._V)

    # -- edges -------------------------------------------------------------
    def _edge_rehash(self, newE: int) -> None:
        while True:
            ena = np.full(newE, -1, np.int32)
            esa = np.full(newE, -1, np.int32)
            eca = np.full(newE, -1, np.int32)
            ok = True
            for (node, sym), child in self._edges.items():
                slot = edge_slot_hash(node, sym) & (newE - 1)
                placed = False
                for p in range(MAX_PROBES):
                    idx = (slot + p) & (newE - 1)
                    if ena[idx] == -1:
                        ena[idx], esa[idx], eca[idx] = node, sym, child
                        placed = True
                        break
                if not placed:
                    ok = False
                    break
            if ok:
                break
            newE *= 2
        self._E = newE
        self.arr_edge_node, self.arr_edge_sym, self.arr_edge_child = (
            ena,
            esa,
            eca,
        )
        self._edge_fill = len(self._edges)
        self._bump_epoch()

    def _edge_insert(self, node: int, sym: int, child: int) -> None:
        self._edges[(node, sym)] = child
        if (self._edge_fill + 1) * 2 > self._E:
            self._edge_rehash(self._E * 2)  # places the new edge too
            return
        slot = edge_slot_hash(node, sym) & (self._E - 1)
        for p in range(MAX_PROBES):
            idx = (slot + p) & (self._E - 1)
            n = self.arr_edge_node[idx]
            if n == -1 or n == EDGE_TOMB:
                if n == -1:
                    self._edge_fill += 1
                self.arr_edge_node[idx] = node
                self._log("edge_node", idx, node)
                self.arr_edge_sym[idx] = sym
                self._log("edge_sym", idx, sym)
                self.arr_edge_child[idx] = child
                self._log("edge_child", idx, child)
                return
        self._edge_rehash(self._E * 2)

    def _edge_delete(self, node: int, sym: int) -> None:
        del self._edges[(node, sym)]
        slot = edge_slot_hash(node, sym) & (self._E - 1)
        for p in range(MAX_PROBES):
            idx = (slot + p) & (self._E - 1)
            if (
                self.arr_edge_node[idx] == node
                and self.arr_edge_sym[idx] == sym
            ):
                self.arr_edge_node[idx] = EDGE_TOMB
                self._log("edge_node", idx, EDGE_TOMB)
                break
        # tombstone-heavy table: compact in place (drops tombstones)
        if (self._edge_fill - len(self._edges)) * 4 > self._E:
            self._edge_rehash(self._E)

    # -- nodes -------------------------------------------------------------
    def _grow_nodes(self) -> None:
        cap = self._cap_nodes * 2
        for name in ("arr_plus", "arr_hashf", "arr_term"):
            old = getattr(self, name)
            new = np.full(cap, -1, np.int32)
            new[: len(old)] = old
            setattr(self, name, new)
        self._cap_nodes = cap
        self._bump_epoch()

    def _new_node(self) -> int:
        if self._free_nodes:
            n = self._free_nodes.pop()
            if self.arr_plus[n] != -1:
                self._set_plus(n, -1)
            if self.arr_hashf[n] != -1:
                self._set_hashf(n, -1)
            if self.arr_term[n] != -1:
                self._set_term(n, -1)
            self._refs[n] = 0
            return n
        n = self._n_nodes
        self._n_nodes += 1
        if n >= self._cap_nodes:
            self._grow_nodes()
        self._refs.append(0)
        return n

    # -- filters -----------------------------------------------------------
    def _filter_id(self, filter_: str) -> int:
        fid = self._filter_ids.get(filter_)
        if fid is not None:
            return fid
        if self._free_filters:
            fid = self._free_filters.pop()
            self._id_filters[fid] = filter_
            self._filter_refs[fid] = 0
        else:
            fid = len(self._id_filters)
            self._id_filters.append(filter_)
            self._filter_refs.append(0)
        self._filter_ids[filter_] = fid
        return fid

    def filter_name(self, fid: int) -> Optional[str]:
        return self._id_filters[fid] if 0 <= fid < len(self._id_filters) else None

    def filter_id(self, filter_: str) -> Optional[int]:
        """Stable id of a live filter (None if not present)."""
        return self._filter_ids.get(filter_)

    def __len__(self) -> int:
        return len(self._filter_ids)

    @property
    def num_filters_capacity(self) -> int:
        return len(self._id_filters)

    # -- public mutation ---------------------------------------------------
    def _adopt_fid(self, filter_: str, fid: int) -> None:
        """Register an externally-allocated filter id (RouteIndex shares one
        fid space between the shape index and this residual engine)."""
        while len(self._id_filters) <= fid:
            self._id_filters.append(None)
            self._filter_refs.append(0)
        self._filter_ids[filter_] = fid
        self._id_filters[fid] = filter_

    def add(self, filter_: str, fid: Optional[int] = None) -> int:
        """Insert a topic filter; returns its stable filter id (refcounted).

        O(words) — array writes + op-log appends; never a table rebuild
        except amortized growth/rehash.
        """
        T.validate(filter_)  # before any mutation: invalid input must not corrupt state
        if fid is None:
            fid = self._filter_id(filter_)
        else:
            self._adopt_fid(filter_, fid)
        if self._filter_refs[fid] > 0:
            self._filter_refs[fid] += 1
            return fid
        self._filter_refs[fid] = 1
        ws = T.words(filter_)
        node = self.ROOT
        path = [node]
        for i, w in enumerate(ws):
            last = i == len(ws) - 1
            if w == "#":
                self._set_hashf(node, fid)
                break
            if w == "+":
                child = int(self.arr_plus[node])
                if child < 0:
                    child = self._new_node()
                    self._set_plus(node, child)
            else:
                sym = self._sym_for(w, create=True)
                key = (node, sym)
                child = self._edges.get(key, -1)
                if child < 0:
                    child = self._new_node()
                    self._edge_insert(node, sym, child)
            node = child
            path.append(node)
            if last:
                self._set_term(node, fid)
        for n in path:
            self._refs[n] += 1
        return fid

    def remove(self, filter_: str) -> bool:
        """Delete one reference to a filter; True when fully removed."""
        fid = self._filter_ids.get(filter_)
        if fid is None or self._filter_refs[fid] == 0:
            return False
        self._filter_refs[fid] -= 1
        if self._filter_refs[fid] > 0:
            return False
        del self._filter_ids[filter_]
        self._id_filters[fid] = None
        self._free_filters.append(fid)
        ws = T.words(filter_)
        node = self.ROOT
        steps: List[Tuple[int, str, int]] = []  # (parent, word, child)
        for i, w in enumerate(ws):
            if w == "#":
                self._set_hashf(node, -1)
                break
            child = (
                int(self.arr_plus[node])
                if w == "+"
                else self._edges.get((node, self._sym_for(w, create=False)), -1)
            )
            steps.append((node, w, child))
            node = child
            if i == len(ws) - 1:
                self._set_term(node, -1)
        self._refs[self.ROOT] -= 1
        for parent, w, child in steps:
            self._refs[child] -= 1
            if self._refs[child] == 0:
                if w == "+":
                    self._set_plus(parent, -1)
                else:
                    sym = self._vocab[w][0]
                    self._edge_delete(parent, sym)
                self._free_nodes.append(child)
            if w not in ("+", "#"):
                self._sym_release(w)
        return True

    # -- packing (O(1): views over live storage) ---------------------------
    def pack(self) -> NfaTables:
        return NfaTables(
            plus_child=self.arr_plus,
            hash_filter=self.arr_hashf,
            term_filter=self.arr_term,
            edge_node=self.arr_edge_node,
            edge_sym=self.arr_edge_sym,
            edge_child=self.arr_edge_child,
            vocab_h1=self.arr_vocab_h1,
            vocab_h2=self.arr_vocab_h2,
            vocab_sym=self.arr_vocab_sym,
            salt=self.salt,
            num_nodes=self._n_nodes,
            num_filters=len(self._id_filters),
            version=self.version,
        )

    def device_snapshot(self) -> Dict[str, np.ndarray]:
        """Host arrays for a full device upload (DeviceDeltaSync protocol)."""
        return {
            "plus_child": self.arr_plus,
            "hash_filter": self.arr_hashf,
            "term_filter": self.arr_term,
            "edge_node": self.arr_edge_node,
            "edge_sym": self.arr_edge_sym,
            "edge_child": self.arr_edge_child,
            "vocab_h1": self.arr_vocab_h1,
            "vocab_h2": self.arr_vocab_h2,
            "vocab_sym": self.arr_vocab_sym,
        }

    # -- host-side tokenization (exact; used by tests and CPU fallback) ----
    def tokenize_host(self, topic: str, max_levels: int):
        """-> (syms int32[max_levels], nwords, is_dollar, too_deep)."""
        ws = T.words(topic)
        syms = np.full(max_levels, -1, dtype=np.int32)
        for i, w in enumerate(ws[:max_levels]):
            ent = self._vocab.get(w)
            syms[i] = ent[0] if ent is not None else -1
        return syms, len(ws), topic.startswith("$"), len(ws) > max_levels
