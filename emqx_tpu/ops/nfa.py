"""Subscription-trie -> dense NFA table compiler.

The reference walks a prefix trie in ETS per published message
(apps/emqx/src/emqx_trie.erl:271-333). That design is pointer-chasing and
per-message — exactly wrong for a TPU. Here the same trie is compiled into a
set of flat arrays ("NFA tables") that a jitted JAX kernel
(`emqx_tpu.ops.matcher`) walks for a whole *batch* of topics at once, one
`lax.scan` step per topic level, with all lookups as vectorized gathers:

- ``plus_child[node]``   -> node id of the ``+`` child, or -1
- ``hash_filter[node]``  -> filter id of the ``#`` child, or -1 (``#`` is
  always a terminal leaf, so it needs no node of its own; matching ``a/#``
  against ``a`` — emqx_trie.erl 'match_#' at end of words — falls out of
  collecting this field both when consuming a word *and* at end-of-topic)
- ``term_filter[node]``  -> filter id ending exactly at this node, or -1
- literal edges: open-addressing hash table ``(node, sym) -> child`` with a
  build-time-verified probe bound, so the device probe loop is a fixed-length
  unrolled gather (no data-dependent control flow under jit)
- vocab: open-addressing table ``(h1, h2) -> sym`` mapping *word hash pairs*
  to dense symbol ids, so topic tokenization is hash-based and runs entirely
  on device (`emqx_tpu.ops.tokenizer`)

Word hashing uses a 2x32-bit polynomial hash (see `word_hash_pair`) chosen so
the device tokenizer can compute it with prefix sums instead of a per-byte
scan. Hash-pair collisions between distinct words are detected at build time
and resolved by bumping a salt and rebuilding (they are a ~2^-64 event).

Updates: the builder mutates small Python-side structures per
subscribe/unsubscribe (mirroring emqx_trie insert/delete:66-119 semantics,
including refcounted nodes) and re-packs flat arrays lazily on the next
`pack()` call. Packing is O(edges) in NumPy and amortized across batches;
a delta-overlay scheme is the planned next step (SURVEY.md §7 stage 2).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from emqx_tpu.ops import topics as T

# Polynomial-hash parameters; must match emqx_tpu.ops.tokenizer exactly.
P1 = np.uint32(0x01000193)  # FNV prime, odd => invertible mod 2^32
P2 = np.uint32(0x00BC8F6B)  # odd
_SALT1 = np.uint32(0x9E3779B9)
_SALT2 = np.uint32(0x85EBCA6B)

MAX_PROBES = 8

# Slot-hash constants shared bit-for-bit by the host packers below and the
# device probe loops (matcher._probe_edges, tokenizer.vocab_lookup_device).
EDGE_H_MUL_NODE = 0x9E3779B1
EDGE_H_MUL_SYM = 0x85EBCA77
EDGE_H_SHIFT = 15
VOCAB_H_MUL = 0xC2B2AE3D
VOCAB_H_SHIFT = 13

PLUS_SYM = -2  # sentinel syms (never produced by vocab lookup)
HASH_SYM = -3


def _mix32(x: np.uint32) -> np.uint32:
    """Murmur3-style finalizer (32-bit)."""
    x = np.uint32(x)
    x ^= x >> np.uint32(16)
    x = np.uint32(x * np.uint32(0x7FEB352D))
    x ^= x >> np.uint32(15)
    x = np.uint32(x * np.uint32(0x846CA68B))
    x ^= x >> np.uint32(16)
    return x


def _poly_raw(word: bytes, P: np.uint32) -> np.uint32:
    h = np.uint32(1)  # == P^0; encodes length so "" hashes distinctly
    with np.errstate(over="ignore"):
        for c in word:
            h = np.uint32(h * P + np.uint32(c))
    return h


def word_hash_pair(word: str, salt: int) -> Tuple[int, int]:
    """(h1, h2) for one word; the device tokenizer computes the same pair."""
    b = word.encode("utf-8", "surrogatepass")
    with np.errstate(over="ignore"):
        s1 = np.uint32(np.uint32(salt) * _SALT1 + np.uint32(1))
        s2 = np.uint32(np.uint32(salt) * _SALT2 + np.uint32(7))
        h1 = _mix32(_poly_raw(b, P1) ^ s1)
        h2 = _mix32(_poly_raw(b, P2) ^ s2)
    return int(h1), int(h2)


def edge_slot_hash(node: np.ndarray, sym: np.ndarray) -> np.ndarray:
    """Initial probe slot hash for the literal-edge table (pre-mask)."""
    with np.errstate(over="ignore"):
        h = np.uint32(node).astype(np.uint32) * np.uint32(EDGE_H_MUL_NODE)
        h = h + np.uint32(sym).astype(np.uint32) * np.uint32(EDGE_H_MUL_SYM)
        h ^= h >> np.uint32(EDGE_H_SHIFT)
    return h


def vocab_slot_hash(h1: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        h = np.uint32(h1).astype(np.uint32) * np.uint32(VOCAB_H_MUL)
        h ^= h >> np.uint32(VOCAB_H_SHIFT)
    return h


@dataclass
class NfaTables:
    """Flat match tables; everything the device kernel needs."""

    plus_child: np.ndarray  # int32 [N]
    hash_filter: np.ndarray  # int32 [N]
    term_filter: np.ndarray  # int32 [N]
    edge_node: np.ndarray  # int32 [E]
    edge_sym: np.ndarray  # int32 [E]
    edge_child: np.ndarray  # int32 [E]
    vocab_h1: np.ndarray  # uint32 [V]
    vocab_h2: np.ndarray  # uint32 [V]
    vocab_sym: np.ndarray  # int32 [V]
    salt: int
    num_nodes: int
    num_filters: int
    version: int

    def device_arrays(self):
        import jax.numpy as jnp

        return {
            "plus_child": jnp.asarray(self.plus_child),
            "hash_filter": jnp.asarray(self.hash_filter),
            "term_filter": jnp.asarray(self.term_filter),
            "edge_node": jnp.asarray(self.edge_node),
            "edge_sym": jnp.asarray(self.edge_sym),
            "edge_child": jnp.asarray(self.edge_child),
            "vocab_h1": jnp.asarray(self.vocab_h1),
            "vocab_h2": jnp.asarray(self.vocab_h2),
            "vocab_sym": jnp.asarray(self.vocab_sym),
        }


class _HashCollision(Exception):
    pass


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class NfaBuilder:
    """Incrementally maintained subscription automaton.

    add/remove mirror emqx_trie:insert/delete refcount semantics
    (emqx_trie.erl:170-199); `pack()` emits `NfaTables`.
    """

    ROOT = 0

    def __init__(self) -> None:
        # node arrays (python lists; index = node id)
        self._plus: List[int] = [-1]
        self._hashf: List[int] = [-1]
        self._term: List[int] = [-1]
        self._refs: List[int] = [0]  # filters at-or-below node
        self._free_nodes: List[int] = []
        # literal edges: (node, sym) -> child
        self._edges: Dict[Tuple[int, int], int] = {}
        # vocab: word -> (sym, refcount)
        self._vocab: Dict[str, List[int]] = {}
        self._sym_words: List[Optional[str]] = []
        self._free_syms: List[int] = []
        # filters
        self._filter_ids: Dict[str, int] = {}
        self._id_filters: List[Optional[str]] = []
        self._free_filters: List[int] = []
        self._filter_refs: List[int] = []
        self.salt = 0
        self.version = 0
        self._packed: Optional[NfaTables] = None

    # -- vocab -------------------------------------------------------------
    def _sym_for(self, word: str, create: bool) -> int:
        ent = self._vocab.get(word)
        if ent is not None:
            if create:
                ent[1] += 1
            return ent[0]
        if not create:
            return -1
        if self._free_syms:
            sym = self._free_syms.pop()
            self._sym_words[sym] = word
        else:
            sym = len(self._sym_words)
            self._sym_words.append(word)
        self._vocab[word] = [sym, 1]
        return sym

    def _sym_release(self, word: str) -> None:
        ent = self._vocab[word]
        ent[1] -= 1
        if ent[1] == 0:
            del self._vocab[word]
            self._sym_words[ent[0]] = None
            self._free_syms.append(ent[0])

    # -- nodes -------------------------------------------------------------
    def _new_node(self) -> int:
        if self._free_nodes:
            n = self._free_nodes.pop()
            self._plus[n] = -1
            self._hashf[n] = -1
            self._term[n] = -1
            self._refs[n] = 0
            return n
        self._plus.append(-1)
        self._hashf.append(-1)
        self._term.append(-1)
        self._refs.append(0)
        return len(self._plus) - 1

    # -- filters -----------------------------------------------------------
    def _filter_id(self, filter_: str) -> int:
        fid = self._filter_ids.get(filter_)
        if fid is not None:
            return fid
        if self._free_filters:
            fid = self._free_filters.pop()
            self._id_filters[fid] = filter_
            self._filter_refs[fid] = 0
        else:
            fid = len(self._id_filters)
            self._id_filters.append(filter_)
            self._filter_refs.append(0)
        self._filter_ids[filter_] = fid
        return fid

    def filter_name(self, fid: int) -> Optional[str]:
        return self._id_filters[fid] if 0 <= fid < len(self._id_filters) else None

    def filter_id(self, filter_: str) -> Optional[int]:
        """Stable id of a live filter (None if not present)."""
        return self._filter_ids.get(filter_)

    def __len__(self) -> int:
        return len(self._filter_ids)

    @property
    def num_filters_capacity(self) -> int:
        return len(self._id_filters)

    # -- public mutation ---------------------------------------------------
    def add(self, filter_: str) -> int:
        """Insert a topic filter; returns its stable filter id (refcounted)."""
        T.validate(filter_)  # before any mutation: invalid input must not corrupt state
        fid = self._filter_id(filter_)
        if self._filter_refs[fid] > 0:
            self._filter_refs[fid] += 1
            return fid
        self._filter_refs[fid] = 1
        ws = T.words(filter_)
        node = self.ROOT
        path = [node]
        for i, w in enumerate(ws):
            last = i == len(ws) - 1
            if w == "#":
                self._hashf[node] = fid
                break
            if w == "+":
                child = self._plus[node]
                if child < 0:
                    child = self._new_node()
                    self._plus[node] = child
            else:
                sym = self._sym_for(w, create=True)
                key = (node, sym)
                child = self._edges.get(key, -1)
                if child < 0:
                    child = self._new_node()
                    self._edges[key] = child
            node = child
            path.append(node)
            if last:
                self._term[node] = fid
        for n in path:
            self._refs[n] += 1
        self._dirty()
        return fid

    def remove(self, filter_: str) -> bool:
        """Delete one reference to a filter; True when fully removed."""
        fid = self._filter_ids.get(filter_)
        if fid is None or self._filter_refs[fid] == 0:
            return False
        self._filter_refs[fid] -= 1
        if self._filter_refs[fid] > 0:
            return False
        del self._filter_ids[filter_]
        self._id_filters[fid] = None
        self._free_filters.append(fid)
        ws = T.words(filter_)
        node = self.ROOT
        steps: List[Tuple[int, str, int]] = []  # (parent, word, child)
        for i, w in enumerate(ws):
            if w == "#":
                self._hashf[node] = -1
                break
            child = (
                self._plus[node]
                if w == "+"
                else self._edges.get((node, self._sym_for(w, create=False)), -1)
            )
            steps.append((node, w, child))
            node = child
            if i == len(ws) - 1:
                self._term[node] = -1
        self._refs[self.ROOT] -= 1
        for parent, w, child in steps:
            self._refs[child] -= 1
            if self._refs[child] == 0:
                if w == "+":
                    self._plus[parent] = -1
                else:
                    sym = self._vocab[w][0]
                    del self._edges[(parent, sym)]
                self._free_nodes.append(child)
            if w not in ("+", "#"):
                self._sym_release(w)
        self._dirty()
        return True

    def _dirty(self) -> None:
        self.version += 1
        self._packed = None

    # -- packing -----------------------------------------------------------
    def pack(self) -> NfaTables:
        if self._packed is not None:
            return self._packed
        for _ in range(16):
            try:
                self._packed = self._pack_with_salt(self.salt)
                return self._packed
            except _HashCollision:
                self.salt += 1
        raise RuntimeError("vocab hash collisions persisted across 16 salts")

    def _pack_with_salt(self, salt: int) -> NfaTables:
        n_nodes = len(self._plus)
        plus = np.asarray(self._plus, dtype=np.int32)
        hashf = np.asarray(self._hashf, dtype=np.int32)
        term = np.asarray(self._term, dtype=np.int32)

        # vocab table keyed by hash pair
        vocab_words = [(w, ent[0]) for w, ent in self._vocab.items()]
        V = _next_pow2(max(16, 2 * len(vocab_words)))
        for _ in range(4):
            vh1 = np.zeros(V, dtype=np.uint32)
            vh2 = np.zeros(V, dtype=np.uint32)
            vsym = np.full(V, -1, dtype=np.int32)
            seen: Dict[Tuple[int, int], str] = {}
            ok = True
            for w, sym in vocab_words:
                h1, h2 = word_hash_pair(w, salt)
                if (h1, h2) in seen:  # true 64-bit collision
                    raise _HashCollision()
                seen[(h1, h2)] = w
                slot = int(vocab_slot_hash(np.uint32(h1))) & (V - 1)
                placed = False
                for p in range(MAX_PROBES):
                    idx = (slot + p) & (V - 1)
                    if vsym[idx] < 0:
                        vh1[idx], vh2[idx], vsym[idx] = h1, h2, sym
                        placed = True
                        break
                if not placed:
                    ok = False
                    break
            if ok:
                break
            V *= 2
        else:
            raise RuntimeError("vocab table probe bound not satisfiable")

        # literal edge table
        E = _next_pow2(max(16, 2 * len(self._edges)))
        for _ in range(6):
            en = np.full(E, -1, dtype=np.int32)
            es = np.full(E, -1, dtype=np.int32)
            ec = np.full(E, -1, dtype=np.int32)
            ok = True
            for (node, sym), child in self._edges.items():
                slot = int(edge_slot_hash(np.int64(node), np.int64(sym))) & (E - 1)
                placed = False
                for p in range(MAX_PROBES):
                    idx = (slot + p) & (E - 1)
                    if en[idx] < 0:
                        en[idx], es[idx], ec[idx] = node, sym, child
                        placed = True
                        break
                if not placed:
                    ok = False
                    break
            if ok:
                break
            E *= 2
        else:
            raise RuntimeError("edge table probe bound not satisfiable")

        return NfaTables(
            plus_child=plus,
            hash_filter=hashf,
            term_filter=term,
            edge_node=en,
            edge_sym=es,
            edge_child=ec,
            vocab_h1=vh1,
            vocab_h2=vh2,
            vocab_sym=vsym,
            salt=salt,
            num_nodes=n_nodes,
            num_filters=len(self._id_filters),
            version=self.version,
        )

    # -- host-side tokenization (exact; used by tests and CPU fallback) ----
    def tokenize_host(self, topic: str, max_levels: int):
        """-> (syms int32[max_levels], nwords, is_dollar, too_deep)."""
        ws = T.words(topic)
        syms = np.full(max_levels, -1, dtype=np.int32)
        for i, w in enumerate(ws[:max_levels]):
            ent = self._vocab.get(w)
            syms[i] = ent[0] if ent is not None else -1
        return syms, len(ws), topic.startswith("$"), len(ws) > max_levels
