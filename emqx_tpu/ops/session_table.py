"""Device-resident session & QoS state: the (session, packet-id) table.

Sessions, QoS1/2 inflight windows, and offline-queue bookkeeping used to
live as per-client Python objects (`broker/session.py` dicts) — the next
10M-entry shadow-dict problem after PR 9 cured subscriptions (ROADMAP
item 2). This module is the table those objects collapse into:

- **host side**: `SessionTable`, a vectorized open-addressing
  (slot, packet-id) -> row table in the PR 9 fid-table style (EMOMA's
  one-memory-access exact match, PAPERS.md): every probe round is one
  numpy gather over the whole batch, inserts bid for empty/tombstone
  slots in bulk, and there is NO per-entry Python object anywhere. The
  host arrays are AUTHORITATIVE — acks and inserts mutate them first,
  so the dict-era session semantics are always answerable locally.
- **device side**: the same arrays mirror onto the accelerator through
  `DeviceSegmentManager` (epoch/oplog/device_snapshot protocol — the
  fourth table owner after shapes/bitmaps/retained). The hot mutation
  stream (delivery inserts + PUBACK/PUBREC/PUBCOMP clears) does NOT pay
  its own scatter launch: `broker/session_store.py` packages the op-log
  suffix as a *rider* that fuses into the next serving launch via
  `session_ack_step` below, and QoS1 retry / session-expiry scans come
  back as a device-side sweep riding the same coalesced readback.

Row lanes (all int32 — the device contract forbids 64-bit widening):
  ``sess_slot``  owning session slot (-1 empty, -2 tombstone)
  ``sess_pid``   packet id (1..65535)
  ``sess_state`` 0 free | 1 publish phase (awaiting PUBACK/PUBREC)
                 | 2 rel phase (awaiting PUBCOMP) | 3 incoming QoS2
                 (awaiting PUBREL)
  ``sess_ts``    last (re)transmit stamp, deciseconds on the store's
                 monotonic clock (int32 covers ~6.8 years)
  ``sess_mid``   message-slab id for redelivery (-1 when the payload is
                 gone, e.g. the rel phase)
Session lanes (indexed by slot; grown alone via the `!resync` marker):
  ``slot_expiry`` session-expiry deadline in deciseconds (0 = none)
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from emqx_tpu.ops.contract import device_contract
from emqx_tpu.ops.nfa import _next_pow2

# states
FREE = 0
ST_PUBLISH = 1  # QoS1/2 publish sent, awaiting PUBACK / PUBREC
ST_PUBREL = 2  # QoS2 rel phase, awaiting PUBCOMP
ST_AWAIT_REL = 3  # incoming QoS2 publish, awaiting PUBREL

# sess_slot occupancy markers
EMPTY = -1
TOMB = -2

SESSION_PROBES = 16
ROW_LANES = ("sess_slot", "sess_pid", "sess_state", "sess_ts", "sess_mid")
SLOT_LANES = ("slot_expiry",)
RESYNC = "!resync"


@device_contract(
    "session_ack_step",
    # host->device ack/insert replay is device-local (placed shardings
    # propagate through the scatter); the sweep outputs are O(sweep_k),
    # never O(cap) — reusing the compact_fanout_slots discipline
    collectives=(),
    out_bounds={
        "due": lambda cfg: max(cfg["kslot"], 1) * 4,
        "expired": lambda cfg: max(cfg["kslot"], 1) * 4,
        "due_count": lambda cfg: 4,
        "expired_count": lambda cfg: 4,
    },
)
def session_ack_impl(tables: Dict, idxs: Dict, vals: Dict, clock,
                     *, sweep_k: int = 0) -> Dict:
    """The fused session stage: apply one rider's row/slot writes as
    scatters — `tables[k][idxs[k]] = vals[k]` — and (``sweep_k > 0``)
    sweep the WHOLE table for QoS1 retransmits and expired sessions in
    the same program.

    This is what rides the serving launch (`session_route_step` in
    models/router_model.py): ack batches become scatter clears in the
    same program as routing, and the retry scan is a device bitmap sweep
    instead of a per-client dict walk. Padded index vectors repeat one
    write (identical values — idempotent), so programs key on pow2 delta
    buckets. ``clock`` is an int32 ``[2]`` array ``(now_ds, retry_ds)``
    — an array, not a static, so the tick never recompiles.

    Sweep outputs (compact, -1 padded; counts are UNCAPPED so the host
    knows when a flood overflowed ``sweep_k`` and sweeps again):
      ``due [sweep_k]``      row ids in publish phase older than retry
      ``expired [sweep_k]``  session slots past their expiry deadline
    """
    import jax.numpy as jnp

    from emqx_tpu.ops.matcher import _compact

    out = {}
    for k, arr in tables.items():
        if k in idxs:
            out[k] = arr.at[idxs[k]].set(vals[k])
        else:
            out[k] = arr
    res = {"tables": out}
    if sweep_k > 0:
        now = clock[0]
        retry = clock[1]
        st = out["sess_state"]
        ts = out["sess_ts"]
        occ = out["sess_slot"] >= 0
        due_mask = (
            occ
            & ((st == ST_PUBLISH) | (st == ST_PUBREL))
            & ((now - ts) >= retry)
        )
        rows = jnp.arange(st.shape[0], dtype=jnp.int32)
        due, _ = _compact(
            jnp.where(due_mask, rows, -1)[None, :], sweep_k
        )
        res["due"] = due[0]
        res["due_count"] = jnp.sum(due_mask.astype(jnp.int32))
        ex = out["slot_expiry"]
        ex_mask = (ex > 0) & (now >= ex)
        slots = jnp.arange(ex.shape[0], dtype=jnp.int32)
        exp, _ = _compact(
            jnp.where(ex_mask, slots, -1)[None, :], sweep_k
        )
        res["expired"] = exp[0]
        res["expired_count"] = jnp.sum(ex_mask.astype(jnp.int32))
    return res


def _mix(slot, pid):
    """Row hash of (slot, pid) — vectorized 32-bit mixing in uint64
    lanes (masked, so numpy never warns on scalar overflow), the same
    independent-multiplier shape as the PR 9 fid table."""
    m32 = np.uint64(0xFFFFFFFF)
    a = (
        (np.asarray(slot, np.uint64) * np.uint64(0x9E3779B1))
        ^ (np.asarray(pid, np.uint64) * np.uint64(0x85EBCA77))
    ) & m32
    a ^= a >> np.uint64(15)
    return (a * np.uint64(0xC2B2AE35)) & m32


def _step(slot, pid):
    """Odd probe stride (full cycle over any pow2 capacity): decouples
    probe paths that share a starting row, so clustering never walls a
    bulk load the way a linear stride does."""
    return (
        (np.asarray(pid, np.uint64) << np.uint64(1))
        ^ np.asarray(slot, np.uint64)
    ) | np.uint64(1)


class SessionTable:
    """Host-authoritative open-addressing (slot, pid) -> row store.

    Implements the segment-manager source protocol (`epoch`, `version`,
    `oplog`, `device_snapshot`) so `DeviceSegmentManager` mirrors it like
    every other table owner; the hot mutation stream additionally rides
    serving launches via `SessionStore.take_rider`. Growth of the row
    table doubles capacity and bumps the epoch (full re-upload); growth
    of the per-slot lanes re-uploads those arrays ALONE via the
    per-array `!resync` marker.
    """

    def __init__(self, capacity: int = 1024, slots: int = 256):
        cap = _next_pow2(max(64, capacity))
        scap = _next_pow2(max(64, slots))
        self._cap = cap
        self._scap = scap
        self.sess_slot = np.full(cap, EMPTY, np.int32)
        self.sess_pid = np.zeros(cap, np.int32)
        self.sess_state = np.zeros(cap, np.int32)
        self.sess_ts = np.zeros(cap, np.int32)
        self.sess_mid = np.full(cap, -1, np.int32)
        self.slot_expiry = np.zeros(scap, np.int32)
        self.live = 0
        self.tombstones = 0
        self.epoch = 0
        self.version = 0
        self.oplog: list = []
        self.OPLOG_MAX = 262144
        # compaction journal (loop-thread): semantic (slot,pid) upserts/
        # clears that raced a background rebuild — row ids relocate, so
        # raw lane writes cannot replay
        self._journal: Optional[list] = None
        self._structure_gen = 0

    # -- op-log plumbing ---------------------------------------------------
    def _bump(self) -> None:
        self.epoch += 1
        self.oplog.clear()
        self.version += 1
        self._structure_gen += 1

    def _log(self, name: str, idx: int, val: int) -> None:
        self.version += 1
        if len(self.oplog) >= self.OPLOG_MAX:
            self._bump()
            return
        self.oplog.append((name, int(idx), int(val)))

    def _log_resync(self, name: str) -> None:
        """Per-array re-upload marker. Appending through `_log` and
        rewriting `oplog[-1]` is NOT equivalent: at OPLOG_MAX `_log`
        bumps the epoch and clears the log, so the rewrite would blow
        up on an empty list (and the bump already covers the grow)."""
        self.version += 1
        if len(self.oplog) >= self.OPLOG_MAX:
            self._bump()
            return
        self.oplog.append((RESYNC, name, 0))

    def device_snapshot(self) -> Dict[str, np.ndarray]:
        return {
            "sess_slot": self.sess_slot,
            "sess_pid": self.sess_pid,
            "sess_state": self.sess_state,
            "sess_ts": self.sess_ts,
            "sess_mid": self.sess_mid,
            "slot_expiry": self.slot_expiry,
        }

    # -- probing -----------------------------------------------------------
    def _find(self, slot: int, pid: int) -> int:
        """Row of a live (slot, pid) entry, or -1."""
        mask = self._cap - 1
        h = int(_mix(slot, pid))
        st = int(_step(slot, pid))
        for r in range(SESSION_PROBES):
            row = (h + r * st) & mask
            if self.sess_slot[row] == EMPTY:
                return -1
            if (
                self.sess_slot[row] == slot
                and self.sess_pid[row] == pid
            ):
                return row
        return -1

    def _find_free(self, slot: int, pid: int) -> int:
        """First empty/tombstone row on the probe path, or -1 (full)."""
        mask = self._cap - 1
        h = int(_mix(slot, pid))
        st = int(_step(slot, pid))
        for r in range(SESSION_PROBES):
            row = (h + r * st) & mask
            if self.sess_slot[row] < 0:
                return row
        return -1

    def lookup_batch(self, slots, pids) -> np.ndarray:
        """Vectorized (slot, pid) -> row (-1 miss): one gather per probe
        round over the whole batch — the EMOMA exact-match idiom."""
        slots = np.asarray(slots, np.int64)
        pids = np.asarray(pids, np.int64)
        n = len(slots)
        mask = self._cap - 1
        h = _mix(slots, pids).astype(np.int64)
        st = _step(slots, pids).astype(np.int64)
        found = np.full(n, -1, np.int64)
        dead = np.zeros(n, bool)  # hit a hard EMPTY: stop probing
        for r in range(SESSION_PROBES):
            rows = (h + r * st) & mask
            open_ = (found < 0) & ~dead
            ent_slot = self.sess_slot[rows]
            hit = open_ & (ent_slot == slots) & (self.sess_pid[rows] == pids)
            found[hit] = rows[hit]
            dead |= open_ & (ent_slot == EMPTY)
            if not open_.any():
                break
        return found.astype(np.int64)

    # -- mutation ----------------------------------------------------------
    def _write_row(self, row: int, slot: int, pid: int, state: int,
                   ts: int, mid: int) -> None:
        self.sess_slot[row] = slot
        self.sess_pid[row] = pid
        self.sess_state[row] = state
        self.sess_ts[row] = ts
        self.sess_mid[row] = mid
        self._log("sess_slot", row, slot)
        self._log("sess_pid", row, pid)
        self._log("sess_state", row, state)
        self._log("sess_ts", row, ts)
        self._log("sess_mid", row, mid)

    def insert(self, slot: int, pid: int, state: int, ts: int,
               mid: int = -1) -> int:
        """Upsert one (slot, pid) row; returns its row id. Grows (epoch
        bump) when the probe path is saturated or load passes 3/4."""
        if self._journal is not None:
            self._journal.append(("set", slot, pid, state, ts, mid))
        row = self._find(slot, pid)
        if row < 0:
            if self.live + self.tombstones >= (self._cap * 3) // 4:
                self._grow(self._cap * 2)
            row = self._find_free(slot, pid)
            while row < 0:
                self._grow(self._cap * 2)
                row = self._find_free(slot, pid)
            if self.sess_slot[row] == TOMB:
                self.tombstones -= 1
            self.live += 1
        self._write_row(row, slot, pid, state, ts, mid)
        return row

    def set_state(self, row: int, state: int, ts: int,
                  mid: Optional[int] = None) -> None:
        if self._journal is not None:
            self._journal.append(
                ("set", int(self.sess_slot[row]), int(self.sess_pid[row]),
                 state, ts, self.sess_mid[row] if mid is None else mid)
            )
        self.sess_state[row] = state
        self.sess_ts[row] = ts
        self._log("sess_state", row, state)
        self._log("sess_ts", row, ts)
        if mid is not None:
            self.sess_mid[row] = mid
            self._log("sess_mid", row, mid)

    def touch(self, row: int, ts: int) -> None:
        """Refresh the retransmit stamp after a resend."""
        self.sess_ts[row] = ts
        self._log("sess_ts", row, ts)

    def touch_many(self, rows, ts: int) -> None:
        """Vectorized stamp refresh for a whole sweep's retransmits:
        one scatter store + one op-log extend (the redelivery flood
        used to pay `touch`'s per-row `_log` a million times)."""
        rows = np.asarray(rows, np.int64)
        if not rows.size:
            return
        self.sess_ts[rows] = ts
        if len(self.oplog) + rows.size > self.OPLOG_MAX:
            self._bump()  # overflow: next sync is a full re-upload
            return
        self.version += int(rows.size)
        t = int(ts)
        self.oplog.extend(("sess_ts", int(r), t) for r in rows)

    def clear(self, row: int) -> int:
        """Tombstone one row; returns the message id it carried.

        Idempotent: clearing an EMPTY/TOMB row is a no-op returning -1.
        Without the guard a duplicate clear (e.g. a redundant ack path
        holding a stale row handle) double-decrements `live` AND — when
        a compaction capture is open — journals the tombstone sentinel
        as the slot, which a later `apply_compact` replay feeds to
        `_find`/`_mix` where the negative value overflows uint64. The
        crash fires an arbitrary number of mutations after the actual
        bug, so it is stopped here at the source."""
        if self.sess_slot[row] < 0:
            return -1
        if self._journal is not None:
            self._journal.append(
                ("clear", int(self.sess_slot[row]),
                 int(self.sess_pid[row]), 0, 0, -1)
            )
        mid = int(self.sess_mid[row])
        self.sess_slot[row] = TOMB
        self.sess_state[row] = FREE
        self.sess_mid[row] = -1
        self._log("sess_slot", row, TOMB)
        self._log("sess_state", row, FREE)
        self._log("sess_mid", row, -1)
        self.live -= 1
        self.tombstones += 1
        return mid

    def set_expiry(self, slot: int, deadline_ds: int) -> None:
        if slot >= self._scap:
            self._grow_slots(_next_pow2(slot + 1))
        if self._journal is not None:
            self._journal.append(("expiry", slot, 0, 0, deadline_ds, -1))
        self.slot_expiry[slot] = deadline_ds
        self._log("slot_expiry", slot, deadline_ds)

    def bulk_insert(self, slots, pids, states, tss, mids) -> np.ndarray:
        """Vectorized cold/storm load of UNIQUE (slot, pid) keys: place
        everything with round-robin probe bidding (the `_bulk_place_hot`
        idiom) and ONE epoch bump. Returns the placed row ids (-1 = lost
        after growth retries — callers treat that as table-full)."""
        slots = np.asarray(slots, np.int64)
        pids = np.asarray(pids, np.int64)
        states = np.asarray(states, np.int64)
        tss = np.asarray(tss, np.int64)
        mids = np.asarray(mids, np.int64)
        n = len(slots)
        while self.live + self.tombstones + n > (self._cap * 3) // 4:
            self._grow(self._cap * 2)
        rows = self._bulk_place(slots, pids, states, tss, mids)
        for _ in range(4):
            lost = rows < 0
            if not lost.any():
                break
            # saturated probe paths: double (relocating every placed
            # entry), place ONLY the losers, then re-resolve all row ids
            # against the grown table — never re-place a placed key
            self._grow(self._cap * 2)
            self._bulk_place(
                slots[lost], pids[lost], states[lost], tss[lost],
                mids[lost],
            )
            rows = self.lookup_batch(slots, pids)
        self._bump()
        return rows

    # oplog-covered-by: callers (_grow / bulk_insert) bump the epoch
    def _bulk_place(self, slots, pids, states, tss, mids) -> np.ndarray:
        mask = self._cap - 1
        n = len(slots)
        h = _mix(slots, pids).astype(np.int64)
        stp = _step(slots, pids).astype(np.int64)
        rows = np.full(n, -1, np.int64)
        pending = np.arange(n)
        for r in range(SESSION_PROBES):
            if not len(pending):
                break
            cand = (h[pending] + r * stp[pending]) & mask
            free = self.sess_slot[cand] < 0
            bid = pending[free]
            brow = cand[free]
            # first bidder per row wins this round; losers re-probe
            uniq, first = np.unique(brow, return_index=True)
            win = bid[first]
            wrow = brow[first]
            tomb = self.sess_slot[wrow] == TOMB
            self.tombstones -= int(np.count_nonzero(tomb))
            self.sess_slot[wrow] = slots[win]
            self.sess_pid[wrow] = pids[win]
            self.sess_state[wrow] = states[win]
            self.sess_ts[wrow] = tss[win]
            self.sess_mid[wrow] = mids[win]
            rows[win] = wrow
            self.live += len(win)
            pending = pending[rows[pending] < 0]
        return rows

    # -- growth ------------------------------------------------------------
    def _grow(self, new_cap: int) -> None:
        """Double the row table and re-place every live entry (epoch
        bump: full re-upload, one recompile of the table-shaped jits)."""
        old = (
            self.sess_slot, self.sess_pid, self.sess_state,
            self.sess_ts, self.sess_mid,
        )
        live = np.nonzero(old[0] >= 0)[0]
        self._cap = new_cap
        self.sess_slot = np.full(new_cap, EMPTY, np.int32)
        self.sess_pid = np.zeros(new_cap, np.int32)
        self.sess_state = np.zeros(new_cap, np.int32)
        self.sess_ts = np.zeros(new_cap, np.int32)
        self.sess_mid = np.full(new_cap, -1, np.int32)
        self.live = 0
        self.tombstones = 0
        if len(live):
            self._bulk_place(
                old[0][live].astype(np.int64),
                old[1][live].astype(np.int64),
                old[2][live].astype(np.int64),
                old[3][live].astype(np.int64),
                old[4][live].astype(np.int64),
            )
        self._bump()

    def _grow_slots(self, new_scap: int) -> None:
        new = np.zeros(new_scap, np.int32)
        new[: self._scap] = self.slot_expiry
        self.slot_expiry = new
        self._scap = new_scap
        # small lane: re-upload ALONE (never the row table) — the
        # per-array resync marker exists for exactly this
        self._log_resync("slot_expiry")

    # -- host sweeps (authoritative; the device sweep mirrors these) -------
    def due_rows(self, now_ds: int, retry_ds: int) -> np.ndarray:
        """QoS retransmit scan (publish phase -> dup PUBLISH, rel phase
        -> PUBREL) — one vectorized pass, no dict walk."""
        return np.nonzero(
            (self.sess_slot >= 0)
            & (
                (self.sess_state == ST_PUBLISH)
                | (self.sess_state == ST_PUBREL)
            )
            & ((now_ds - self.sess_ts) >= retry_ds)
        )[0]

    def expired_slots(self, now_ds: int) -> np.ndarray:
        return np.nonzero(
            (self.slot_expiry > 0) & (self.slot_expiry <= now_ds)
        )[0]

    def rows_of_slot(self, slot: int) -> np.ndarray:
        """Every live row owned by one session (resume/drop path)."""
        return np.nonzero(self.sess_slot == slot)[0]

    # -- compaction (SegmentCompactor owner protocol) ----------------------
    def begin_compact(self) -> Dict:
        self._journal = []
        return {
            "arrays": {k: v.copy() for k, v in self.device_snapshot().items()},
            "cap": self._cap,
            "gen": self._structure_gen,
        }

    @staticmethod
    def build_compact(cap: Dict) -> Dict:
        """Re-place every live row into a fresh table (tombstones
        purged). Pure numpy over the capture — any thread."""
        arrs = cap["arrays"]
        live = np.nonzero(arrs["sess_slot"] >= 0)[0]
        built = SessionTable(capacity=cap["cap"], slots=1)
        built.slot_expiry = arrs["slot_expiry"].copy()
        built._scap = len(built.slot_expiry)
        if len(live):
            built._bulk_place(
                arrs["sess_slot"][live].astype(np.int64),
                arrs["sess_pid"][live].astype(np.int64),
                arrs["sess_state"][live].astype(np.int64),
                arrs["sess_ts"][live].astype(np.int64),
                arrs["sess_mid"][live].astype(np.int64),
            )
        return {"table": built, "gen": cap["gen"]}

    def apply_compact(self, built: Dict) -> Optional[int]:
        """Swap in the rebuilt table + replay the journal of racing
        mutations (semantic (slot, pid) upserts — row ids relocated).
        Returns the new epoch, or None when a structural event
        invalidated the capture."""
        journal = self._journal
        self._journal = None
        if journal is None or built["gen"] != self._structure_gen:
            return None
        t = built["table"]
        self._cap = t._cap
        self._scap = t._scap
        self.sess_slot = t.sess_slot
        self.sess_pid = t.sess_pid
        self.sess_state = t.sess_state
        self.sess_ts = t.sess_ts
        self.sess_mid = t.sess_mid
        self.slot_expiry = t.slot_expiry
        self.live = t.live
        self.tombstones = t.tombstones
        self._bump()
        for op, slot, pid, state, ts, mid in journal:
            if op == "set":
                self.insert(slot, pid, state, ts, mid)
            elif op == "clear":
                row = self._find(slot, pid)
                if row >= 0:
                    self.clear(row)
            elif op == "expiry":
                self.set_expiry(slot, ts)
        return self.epoch


class SessionSegmentOwner:
    """Compaction adapter for a `SessionTable` + its manager: purge
    tombstoned (acked) rows off the critical path, pre-uploading the
    rebuilt table on the compaction executor — the `ShapeSegmentOwner`
    contract, fourth owner on the one `SegmentCompactor`."""

    key = "sessions"

    def __init__(self, table: SessionTable, manager, placement=None,
                 tombstone_frac: float = 0.25):
        self.table = table
        self.manager = manager
        self._placement = placement
        self.tombstone_frac = tombstone_frac

    def needs_compact(self) -> bool:
        t = self.table
        return t.tombstones > 0 and (
            t.tombstones >= self.tombstone_frac * t._cap
        )

    def begin(self):
        return self.table.begin_compact()

    def build(self, cap):
        built = SessionTable.build_compact(cap)
        devs = {}
        for k, v in built["table"].device_snapshot().items():
            if self._placement is not None:
                devs[k] = self._placement(k, v.copy())
            else:
                import jax

                devs[k] = jax.device_put(v.copy())
        built["devs"] = devs
        return built

    def apply(self, built):
        merged = self.table.tombstones
        epoch = self.table.apply_compact(built)
        if epoch is None:
            return None
        return epoch, built["devs"], 0, merged
