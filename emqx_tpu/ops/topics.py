"""MQTT topic algebra.

Capability parity with the reference's `emqx_topic` module
(reference: apps/emqx/src/emqx_topic.erl:17-110): word split/join, wildcard
test, single-pair name-vs-filter match (including the `$`-prefix exclusion
rules), validation of names and filters, and `$share/<group>/<topic>` parsing.

Topics are plain Python strings here; the hot path never touches this module —
batch matching happens on padded byte tensors in `emqx_tpu.ops.matcher`.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

MAX_TOPIC_LEN = 65535  # bytes (reference: emqx_topic.erl ?MAX_TOPIC_LEN)

SHARE_PREFIX = "$share"
SYS_PREFIX = "$SYS"


def words(topic: str) -> List[str]:
    """Split a topic into its level words. ``a//b`` -> ``['a', '', 'b']``."""
    return topic.split("/")


def join(ws: List[str]) -> str:
    return "/".join(ws)


def levels(topic: str) -> int:
    return len(words(topic))


def wildcard(topic_or_words) -> bool:
    """True if the filter contains ``+`` or ``#`` at any level."""
    ws = words(topic_or_words) if isinstance(topic_or_words, str) else topic_or_words
    return any(w in ("+", "#") for w in ws)


def is_dollar(topic: str) -> bool:
    """Topics beginning with ``$`` are excluded from root-level wildcards."""
    return topic.startswith("$")


def match(name: str, filter_: str) -> bool:
    """Does topic `name` match topic `filter_`?

    Implements MQTT matching semantics, including:
    - ``+`` matches exactly one level, ``#`` matches any suffix *including the
      empty suffix* (so ``a/#`` matches ``a``).
    - A ``$``-prefixed name never matches a filter starting with ``+`` or ``#``
      (reference: emqx_topic.erl match/2 clauses on ``<<$$, ...>>``).
    """
    if name.startswith("$") and (filter_.startswith("+") or filter_.startswith("#")):
        return False
    return match_words(words(name), words(filter_))


def match_words(nw: List[str], fw: List[str]) -> bool:
    i = 0
    nn, nf = len(nw), len(fw)
    while True:
        if i == nf:
            return i == nn
        f = fw[i]
        if f == "#":
            # '#' must be last; matches any remaining suffix incl. empty
            return True
        if i == nn:
            return False
        if f != "+" and f != nw[i]:
            return False
        i += 1


class TopicValidationError(ValueError):
    pass


def validate(topic: str, kind: str = "filter") -> None:
    """Validate a topic name or filter; raises TopicValidationError.

    Rules (reference: emqx_topic.erl validate/2, validate2/1, validate3/1):
    empty topic invalid; > 65535 bytes invalid; ``#`` only as last level;
    ``+``/``#`` must occupy a whole level; names must contain no wildcards;
    no NUL characters.
    """
    if topic == "":
        raise TopicValidationError("empty_topic")
    if len(topic.encode("utf-8", "surrogatepass")) > MAX_TOPIC_LEN:
        raise TopicValidationError("topic_too_long")
    if "\x00" in topic:
        raise TopicValidationError("topic_invalid_char")
    ws = words(topic)
    for i, w in enumerate(ws):
        if w == "#":
            if i != len(ws) - 1:
                raise TopicValidationError("'#' must be the last level")
        elif "#" in w or "+" in w:
            if w not in ("+", "#"):
                raise TopicValidationError(
                    "'+' and '#' must occupy an entire level: %r" % w
                )
    if kind == "name" and wildcard(ws):
        raise TopicValidationError("topic_name_error: wildcards not allowed in names")


def parse_share(topic: str) -> Tuple[Optional[str], str]:
    """Parse ``$share/<group>/<real topic>`` -> (group, real_topic).

    Returns (None, topic) for non-shared subscriptions.
    (reference: emqx_topic.erl parse/2)
    """
    if not topic.startswith(SHARE_PREFIX + "/"):
        return None, topic
    rest = topic[len(SHARE_PREFIX) + 1 :]
    group, sep, real = rest.partition("/")
    if not sep or group == "" or real == "":
        raise TopicValidationError("invalid_share_subscription: %r" % topic)
    if "+" in group or "#" in group:
        raise TopicValidationError("invalid_share_group: %r" % group)
    return group, real


def feed_var(var: str, value: str, topic: str) -> str:
    """Substitute a ``%c``/``%u``-style or ``${var}`` placeholder level."""
    return join([value if w == var else w for w in words(topic)])


def systop(name: str) -> str:
    """``$SYS/brokers/<node>/<name>`` system topic (reference: emqx_topic.erl systop/1)."""
    from emqx_tpu.utils.node import node_name

    return f"$SYS/brokers/{node_name()}/{name}"
