"""Device-contract registry: declares what a kernel's compiled artifact
is ALLOWED to look like, next to the kernel itself.

PR 3 made the serving hot path's cost profile a contract — O(matches)
readback, a fixed collective set over the ('dp', 'tp') mesh, no dtype
widening — and the `@device_contract` decorator is where that contract
is *written down*. The decorator only registers; it never wraps, so jit
caching, `lru_cache`d builders and call signatures are untouched. The
semantic auditor (`tools/analysis/device_contract`, run via
`python -m tools.analysis --contracts` and the tier-1 suite) traces
every registered kernel with `jax.make_jaxpr` over a small config
matrix — abstract tracing only, nothing executes — and checks the
jaxpr against the declaration + a golden snapshot under
`tests/fixtures/analysis/jaxprs/`.

This module is import-light on purpose (stdlib only): product modules
pay nothing for declaring a contract.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, FrozenSet, Optional

# kernel name -> contract (module-level: populated at import of the
# decorated modules; the auditor imports them explicitly)
REGISTRY: Dict[str, "DeviceContract"] = {}


@dataclass(frozen=True)
class DeviceContract:
    """What the compiled artifact of one kernel may contain.

    name         registry key (also the snapshot file name)
    fn           the registered callable (jit-wrapped fn, plain
                 traceable fn, or a builder returning a jitted fn)
    kind         'jit'     — trace `fn` directly
                 'builder' — call `fn(...)` first (mesh step builders),
                             then trace what it returns
    collectives  EXACT set of collective primitives the kernel's traces
                 may contain, matrix-wide: every traced config must stay
                 a subset, and the union over the matrix must equal the
                 declaration (so it can neither grow nor rot silently)
    forbid_dtypes  dtype names that may appear NOWHERE in the jaxpr —
                 not as a convert_element_type target, not in any
                 intermediate or output aval (default: the f64/i64
                 widenings that double readback and HBM for free)
    out_bounds   per-output byte bounds: output name -> fn(cfg) -> max
                 bytes (`cfg` is the audit config dict). This is how
                 "compact outputs are O(B*Kslot), not O(B*W)" is pinned.
    """

    name: str
    fn: Callable = None  # type: ignore[assignment]
    kind: str = "jit"
    collectives: FrozenSet[str] = frozenset()
    forbid_dtypes: tuple = ("float64", "int64", "uint64")
    out_bounds: Dict[str, Callable[[dict], int]] = field(
        default_factory=dict
    )


def device_contract(
    name: str,
    *,
    kind: str = "jit",
    collectives=(),
    forbid_dtypes=("float64", "int64", "uint64"),
    out_bounds: Optional[Dict[str, Callable[[dict], int]]] = None,
    registry: Optional[Dict[str, DeviceContract]] = None,
):
    """Register a kernel's device contract; returns the fn unchanged."""
    reg = REGISTRY if registry is None else registry

    def register(fn):
        reg[name] = DeviceContract(
            name=name,
            fn=fn,
            kind=kind,
            collectives=frozenset(collectives),
            forbid_dtypes=tuple(forbid_dtypes),
            out_bounds=dict(out_bounds or {}),
        )
        return fn

    return register
