"""Batched NFA topic matching on TPU.

Replaces the per-message ETS trie walk of the reference
(apps/emqx/src/emqx_trie.erl:271-333 `match_no_compact`, driven from
emqx_router:match_routes emqx_router.erl:128-141) with one jitted SPMD kernel
over a *batch* of topics:

- state: a fixed-width frontier of NFA node ids per topic (a trie has no
  converging paths, so the frontier never contains duplicates);
- one `lax.scan` step per topic level: gather `#`-terminals (they match any
  non-empty suffix), probe the literal-edge hash table, gather `+` children,
  then compact the doubled frontier with a cumsum+scatter;
- end-of-scan: collect exact terminals and `#`-terminals of the surviving
  frontier (``a/#`` matches ``a`` — 'match_#' at emqx_trie.erl:288-291);
- `$`-topics skip root-level ``+``/``#`` (emqx_trie.erl:271-278).

Everything is static-shape, data-independent control flow; matched filter ids
accumulate into a fixed [B, K] buffer via cumsum+scatter with an overflow
flag. Rows that overflow (frontier or matches) or exceed the level budget are
flagged so the host can fall back to the authoritative CPU trie
(`emqx_tpu.broker.trie.TopicTrie`) — correctness never depends on the caps.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from emqx_tpu.ops import tokenizer as tok
from emqx_tpu.ops.nfa import (
    EDGE_H_MUL_NODE,
    EDGE_H_MUL_SYM,
    EDGE_H_SHIFT,
    MAX_PROBES,
    NfaBuilder,
    NfaTables,
    _next_pow2,
)


@dataclass(frozen=True)
class MatcherConfig:
    max_levels: int = 16  # topic depth budget (scan length)
    frontier: int = 32  # max simultaneous NFA states per topic
    max_matches: int = 64  # max matched filters per topic
    # open-addressing probe bound; must cover the build-time bound
    # (nfa.MAX_PROBES) or lookups would silently miss — TpuMatcher clamps.
    probes: int = MAX_PROBES
    max_bytes: int = 256  # topic byte budget for the device tokenizer
    # sparse fan-out compaction (router_model.compact_fanout_slots):
    # read back O(matches) slot lists instead of dense [B, W] bitmaps;
    # overflow rows fall back to a masked dense transfer, so the cap is
    # a bandwidth knob, never a correctness one
    fanout_compact: bool = True
    # per-row compact-slot cap: 0 = auto-size from the dispatch.fanout
    # histogram p99 (grow-only, pow2-padded); > 0 pins it (pow2-padded)
    fanout_slots: int = 0
    # subscriber-table representation policy (router.sub_table,
    # docs/serving_pipeline.md "subscriber-table memory budget"):
    # "dense" pins the [Fcap, W] bitmap matrix (the degrade fallback),
    # "sparse" pins the CSR slot lists (O(total subscriptions) memory),
    # "auto" starts dense and flips ONCE when occupancy x width says
    # the matrix is mostly zeros
    sub_table: str = "auto"
    # CSR gather-window bound per row (sparse mode): rows whose matched
    # regions exceed it rebuild on host like Kslot overflow. 0 = auto
    # (2 x Kslot, tracking the fanout p99)
    sparse_gather: int = 0
    # donate the per-batch input buffers (token bytes, lengths) to the
    # serving-path jit so steady-state batches reuse them for outputs
    # instead of allocating fresh device buffers every launch
    donate_buffers: bool = True
    # bound on cached compiled programs per serving-path jit entry: table
    # growth / config transitions each compile a fresh program, and a
    # long-lived process must not accumulate every shape it ever served.
    # 0 disables trimming.
    jit_cache_max: int = 64


def _probe_edges(tables, node, sym, probes: int):
    """Vectorized open-addressing lookup of literal edges (node, sym)->child."""
    import jax.numpy as jnp

    E = tables["edge_node"].shape[0]
    mask = jnp.uint32(E - 1)
    valid = (node >= 0) & (sym >= 0)
    h = node.astype(jnp.uint32) * jnp.uint32(EDGE_H_MUL_NODE) + sym.astype(
        jnp.uint32
    ) * jnp.uint32(EDGE_H_MUL_SYM)
    h ^= h >> EDGE_H_SHIFT
    child = jnp.full(node.shape, -1, dtype=jnp.int32)
    found = jnp.zeros(node.shape, dtype=bool)
    for p in range(probes):
        idx = ((h + jnp.uint32(p)) & mask).astype(jnp.int32)
        hit = (
            (tables["edge_node"][idx] == node)
            & (tables["edge_sym"][idx] == sym)
            & valid
            & ~found
        )
        child = jnp.where(hit, tables["edge_child"][idx], child)
        found |= hit
    return child


def _compact(cand, width: int):
    """Left-pack the >=0 entries of cand [B, W] into [B, width]; flag overflow."""
    import jax.numpy as jnp

    B = cand.shape[0]
    valid = cand >= 0
    pos = jnp.cumsum(valid.astype(jnp.int32), axis=1) - 1
    idx = jnp.where(valid & (pos < width), pos, width)
    rows = jnp.arange(B, dtype=jnp.int32)[:, None]
    out = jnp.full((B, width), -1, dtype=jnp.int32)
    out = out.at[rows, idx].set(cand, mode="drop")
    over = jnp.sum(valid, axis=1) > width
    return out, over


def _append(matched, mcount, hits, cap: int):
    """Append the >=0 entries of hits [B, H] to matched [B, cap] at mcount."""
    import jax.numpy as jnp

    B = matched.shape[0]
    valid = hits >= 0
    pos = mcount[:, None] + jnp.cumsum(valid.astype(jnp.int32), axis=1) - 1
    idx = jnp.where(valid & (pos < cap), pos, cap)
    rows = jnp.arange(B, dtype=jnp.int32)[:, None]
    matched = matched.at[rows, idx].set(hits, mode="drop")
    return matched, mcount + jnp.sum(valid, axis=1, dtype=jnp.int32)


@partial(jax.jit, static_argnames=("frontier", "max_matches", "probes"))
def batch_match_syms(
    tables,
    syms,
    nwords,
    dollar,
    *,
    frontier: int = 32,
    max_matches: int = 64,
    probes: int = 8,
):
    """Match pre-tokenized topics against the NFA tables.

    syms: int32 [B, L] dense word symbols (-1 = OOV/absent)
    nwords: int32 [B]; dollar: bool [B]
    -> matched int32 [B, K] filter ids (-1 padded), mcount int32 [B],
       flags bool [B] (overflow or too-deep => host must fall back),
       causes {too_deep, frontier_overflow, match_overflow} bool [B]
       (per-cause breakdown of flags — the flight recorder counts WHY
       the fast path missed, not just that it did)
    """
    import jax
    import jax.numpy as jnp

    B, L = syms.shape
    F, K = frontier, max_matches

    # derive carry inits from the inputs so they carry the same device-varying
    # type as the loop body under shard_map (see shard_map scan-vma docs)
    z = jnp.zeros_like(nwords)  # [B] int32
    frontier0 = jnp.full((B, F), -1, dtype=jnp.int32) + z[:, None]
    frontier0 = frontier0.at[:, 0].set(z)  # root
    matched0 = jnp.full((B, K), -1, dtype=jnp.int32) + z[:, None]
    mcount0 = z
    fover0 = z < 0  # all-False, device-varying

    def step(carry, xs):
        fr, matched, mcount, fover = carry
        wsym, lvl = xs
        active_row = lvl < nwords
        act = (fr >= 0) & active_row[:, None]
        fr_safe = jnp.maximum(fr, 0)
        allow_wild = act & ~((lvl == 0) & dollar)[:, None]
        # '#' children match any non-empty remaining suffix
        hf = jnp.where(allow_wild, tables["hash_filter"][fr_safe], -1)
        matched, mcount = _append(matched, mcount, hf, K)
        lit = _probe_edges(
            tables,
            jnp.where(act, fr, -1),
            jnp.broadcast_to(wsym[:, None], (B, F)),
            probes,
        )
        plus = jnp.where(allow_wild, tables["plus_child"][fr_safe], -1)
        newf, over = _compact(jnp.concatenate([lit, plus], axis=1), F)
        fr = jnp.where(active_row[:, None], newf, fr)
        fover = fover | (over & active_row)
        return (fr, matched, mcount, fover), None

    (fr, matched, mcount, fover), _ = jax.lax.scan(
        step,
        (frontier0, matched0, mcount0, fover0),
        (syms.T, jnp.arange(L, dtype=jnp.int32)),
    )

    done = nwords <= L
    fin = (fr >= 0) & done[:, None]
    fr_safe = jnp.maximum(fr, 0)
    term = jnp.where(fin, tables["term_filter"][fr_safe], -1)
    matched, mcount = _append(matched, mcount, term, K)
    endhash = jnp.where(fin, tables["hash_filter"][fr_safe], -1)
    matched, mcount = _append(matched, mcount, endhash, K)

    too_deep = ~done
    mover = mcount > K
    flags = fover | mover | too_deep
    causes = {
        "too_deep": too_deep,
        "frontier_overflow": fover,
        "match_overflow": mover,
    }
    return matched, jnp.minimum(mcount, K), flags, causes


def batch_match_bytes_impl(
    tables,
    bytes_mat,
    lengths,
    *,
    salt: int,
    max_levels: int = 16,
    frontier: int = 32,
    max_matches: int = 64,
    probes: int = 8,
):
    """Fused full-device pipeline: tokenize + vocab lookup + NFA match."""
    h1, h2, nwords, dollar = tok.tokenize_device(
        bytes_mat, lengths, salt, max_levels
    )
    syms = tok.vocab_lookup_device(tables, h1, h2, probes)
    return batch_match_syms(
        tables,
        syms,
        nwords,
        dollar,
        frontier=frontier,
        max_matches=max_matches,
        probes=probes,
    )


batch_match_bytes = partial(
    jax.jit,
    static_argnames=("salt", "max_levels", "frontier", "max_matches", "probes"),
)(batch_match_bytes_impl)


def _pad_pow2(n: int, lo: int = 256) -> int:
    return max(lo, _next_pow2(n))


class MatchError(RuntimeError):
    """Per-row match failure marker (returned, never raised mid-batch).

    With ``fallback=None`` a device-flagged row (too deep / overflow /
    too long) used to raise AFTER the whole batch's device work was
    done — one oversized topic poisoned every other row's result. Now
    each flagged row yields a `MatchError` in its slot and the rest of
    the batch returns normally; callers either pass a fallback (the CPU
    trie) or filter/inspect the error rows themselves."""

    def __init__(self, topic: str, cause: str = "overflow"):
        super().__init__(
            f"device match overflow for topic {topic!r}; no fallback "
            "provided"
        )
        self.topic = topic
        self.cause = cause


class TpuMatcher:
    """Host-facing wrapper: owns packed tables on device, pads batches,
    decodes matches back to filter names, and falls back to a caller-provided
    exact matcher for flagged rows.

    Records the hot-path flight-recorder series (`matcher.*`, see
    docs/observability.md): device match wall time, batch size, delta-sync
    upload time, and fallback-flagged row counts broken down by cause."""

    def __init__(
        self,
        builder: NfaBuilder,
        config: MatcherConfig = MatcherConfig(),
        metrics=None,
        mesh=None,
    ):
        """`mesh`: a ('dp','tp') jax Mesh — the NFA table mirror then
        uploads through the segment manager with the canonical
        replicated NamedSharding (parallel/mesh.table_placement), the
        same placement-hook path every other table owner uses, so churn
        stays O(delta) scatters on the mesh too."""
        from emqx_tpu.broker.metrics import default_metrics
        from emqx_tpu.ops.nfa import DeviceDeltaSync

        self.builder = builder
        if config.probes < MAX_PROBES:
            import dataclasses

            config = dataclasses.replace(config, probes=MAX_PROBES)
        self.config = config
        self.metrics = metrics if metrics is not None else default_metrics
        if mesh is not None:
            from emqx_tpu.parallel.mesh import table_placement

            self._sync = DeviceDeltaSync(
                placement=table_placement(mesh), name="nfa"
            )
        else:
            self._sync = DeviceDeltaSync()
        self._salt = 0

    def _tables(self):
        # delta-overlay sync: subscription churn reaches the device as
        # scatters, not full re-uploads (see nfa.DeviceDeltaSync)
        import time

        self._salt = self.builder.salt
        t0 = time.perf_counter()
        tables = self._sync.sync(self.builder)
        self.metrics.observe(
            "matcher.sync.seconds", time.perf_counter() - t0
        )
        return tables

    def match_batch(  # readback-site
        self, topics: Sequence[str], fallback=None
    ) -> List[List[str]]:
        """Match a batch of topic strings -> list of matched filter names.

        `fallback(topic) -> list[str]` handles rows the device flags
        (too deep / overflow). With no fallback a flagged row yields a
        `MatchError` IN ITS SLOT (per-row error contract) — the rest of
        the batch still returns; one pathological topic cannot poison
        the device work already done for its batchmates.
        """
        import jax
        import time

        cfg = self.config
        tables = self._tables()
        B = len(topics)
        Bp = _pad_pow2(B, 64)
        mat, lens, too_long = tok.encode_topics(list(topics), cfg.max_bytes)
        if Bp != B:
            mat = np.pad(mat, ((0, Bp - B), (0, 0)))
            lens = np.pad(lens, (0, Bp - B))
        t0 = time.perf_counter()
        matched, mcount, flags, causes = batch_match_bytes(
            tables,
            mat,
            lens,
            salt=self._salt,
            max_levels=cfg.max_levels,
            frontier=cfg.frontier,
            max_matches=cfg.max_matches,
            probes=cfg.probes,
        )
        # ONE coalesced device->host transfer for everything the batch
        # and its flight recorder need; per-array `asarray` pulls each
        # paid their own sync + RTT (8 transfers on a flagged batch)
        host = jax.device_get({
            "matched": matched[:B],
            "mcount": mcount[:B],
            "flags": flags[:B],
            "causes": {k: v[:B] for k, v in causes.items()},
        })
        matched, mcount = host["matched"], host["mcount"]
        flags = host["flags"] | too_long
        # cumulative link-bandwidth accounting (observe/device_watch.py)
        self.metrics.inc(
            "device.transfer.bytes",
            sum(v.nbytes for v in (matched, mcount, host["flags"]))
            + sum(v.nbytes for v in host["causes"].values()),
        )
        self._record(
            B, time.perf_counter() - t0, flags, host["causes"], too_long
        )
        out: List[List[str]] = []
        for i in range(B):
            if flags[i]:
                if fallback is None:
                    out.append(MatchError(topics[i]))
                else:
                    out.append(fallback(topics[i]))
            else:
                names = []
                for fid in matched[i, : mcount[i]]:
                    name = self.builder.filter_name(int(fid))
                    if name is not None:
                        names.append(name)
                out.append(names)
        return out

    def _record(self, B, wall_s, flags, causes, too_long) -> None:
        """Flight-recorder write-back for one matched batch. `causes`
        arrives as HOST arrays (already row-sliced) — the single
        coalesced readback in `match_batch` covers them."""
        m = self.metrics
        m.observe("matcher.device.seconds", wall_s)
        m.observe("matcher.batch.size", B)
        m.inc("matcher.rows", B)
        # per-kernel attribution: the match program is the runtime
        # analog of the audited `route_step` contract's match half
        from emqx_tpu.observe.profiler import record_kernel_launch

        record_kernel_launch(m, ("route_step",), wall_s)
        fell = int(np.count_nonzero(flags))
        if not fell:
            return
        m.inc("matcher.fallback.rows", fell)
        # causes are independent bits: one row can be both too deep and
        # frontier-overflowed; the per-cause counters count each bit
        for cause, arr in causes.items():
            n = int(np.count_nonzero(arr))
            if n:
                m.inc(f"matcher.fallback.rows.{cause}", n)
        n_long = int(np.count_nonzero(too_long))
        if n_long:
            m.inc("matcher.fallback.rows.too_long", n_long)
