"""Top-level incremental route compiler: shape fast path + residual NFA.

One filter-id space shared by two device engines:

- `ShapeIndex` (ops/shape_index.py) — O(#shapes) hash probes per topic;
  takes every filter whose wildcard shape fits. This is where ~all real
  subscription tables land.
- `NfaBuilder` (ops/nfa.py) — the general trie-walk kernel; holds only the
  RESIDUAL filters the shape index rejected (shape overflow past
  MAX_SHAPES, or a 2^-64 combined-hash collision).

The device route step runs the shape kernel always and the NFA kernel only
when residuals exist (models/router_model.shape_route_step). Both engines
speak the delta-overlay protocol, so churn reaches the device as scatters.

Reference analog: this pair replaces emqx_router's match path
(emqx_router.erl:128-141) the way the trie's compaction replaces
level-by-level walking (emqx_trie.erl:201-232) — except compiled all the
way down to fixed-shape batch kernels.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

import numpy as np

from emqx_tpu.ops import topics as T
from emqx_tpu.ops.nfa import NfaBuilder
from emqx_tpu.ops.shape_index import (
    MAX_MASK_LEVELS,
    MAX_SHAPES,
    ShapeIndex,
    level_mul,
)

_PLUS = ord("+")
_HASH = ord("#")
_SLASH = ord("/")


class _ColdFallback(Exception):
    """Input needs the per-filter path (non-ASCII, exotic dtypes, ...)."""


def _encode_ascii(filters: List[str]):
    """list[str] -> (mat uint8 [n,W], lens int32 [n]) via numpy's C-level
    ASCII encode. Raises _ColdFallback for non-ASCII / embedded NULs
    (the 'S' dtype cannot represent trailing NULs faithfully)."""
    try:
        arr = np.asarray(filters, dtype="S")
    except (UnicodeEncodeError, TypeError) as e:
        raise _ColdFallback from e
    width = arr.dtype.itemsize
    if width == 0:
        raise _ColdFallback  # all-empty: let validate raise properly
    lens = np.char.str_len(arr).astype(np.int32)
    if int(lens.sum()) != sum(map(len, filters)):
        raise _ColdFallback  # NUL bytes somewhere: disagreement w/ S-dtype
    mat = np.ascontiguousarray(arr).view(np.uint8).reshape(len(arr), width)
    return mat, lens


def _validate_rows(filters: List[str], mat, lens) -> None:
    """Vectorized emqx_topic validate over the whole batch; raises the
    slow-path TopicValidationError for the first offending filter.
    Processed in row blocks so the working set stays cache-resident."""
    n, width = mat.shape
    cols = np.arange(width, dtype=np.int32)[None, :]
    BLOCK = 1 << 17
    for lo in range(0, n, BLOCK):
        hi = min(lo + BLOCK, n)
        mb, lb = mat[lo:hi], lens[lo:hi]
        inb = cols < lb[:, None]
        nul = inb & (mb == 0)  # embedded NUL: invalid (trailing NULs are
        # padding and sit beyond lens, so inb excludes them)
        is_p = inb & (mb == _PLUS)
        is_h = inb & (mb == _HASH)
        w = is_p | is_h
        if (
            not w.any()
            and not nul.any()
            and not (lb == 0).any()
            and width <= T.MAX_TOPIC_LEN
        ):
            continue  # pure-literal block: nothing left to check
        left_ok = np.empty(mb.shape, dtype=bool)
        left_ok[:, 0] = True
        left_ok[:, 1:] = mb[:, :-1] == _SLASH
        at_end = cols == (lb[:, None] - 1)
        right_ok = np.empty(mb.shape, dtype=bool)
        right_ok[:, :-1] = mb[:, 1:] == _SLASH
        right_ok[:, -1] = False
        right_ok |= at_end
        standalone = left_ok & right_ok
        bad = (w & ~standalone) | (is_h & standalone & ~at_end) | nul
        bad_rows = bad.any(axis=1) | (lb == 0)
        if width > T.MAX_TOPIC_LEN:
            bad_rows |= lb > T.MAX_TOPIC_LEN
        if bad_rows.any():
            i = lo + int(np.argmax(bad_rows))
            T.validate(filters[i])  # raises with the precise reason
            raise T.TopicValidationError("topic_invalid: %r" % filters[i])


def _dedup_rows(mat, lens):
    """Group identical rows without a full string sort: 64-bit row hashes
    + stable argsort + exact adjacent-row compare. Returns
    (first_pos, inv_fid, counts) with distinct rows numbered in
    FIRST-OCCURRENCE order, or None when a hash collision makes the
    grouping ambiguous (caller falls back to the dict path)."""
    n, width = mat.shape
    rng = np.random.default_rng(0x5EED)
    R = rng.integers(1, 1 << 63, size=width, dtype=np.uint64) | np.uint64(1)
    with np.errstate(over="ignore"):
        key = mat.astype(np.uint64) @ R + lens.astype(np.uint64) * np.uint64(
            0x9E3779B97F4A7C15
        )
    srt = np.argsort(key, kind="stable")
    ks = key[srt]
    ms = mat[srt]
    same_key = np.empty(n, dtype=bool)
    same_key[0] = False
    same_key[1:] = ks[1:] == ks[:-1]
    same_row = np.empty(n, dtype=bool)
    same_row[0] = False
    same_row[1:] = (
        same_key[1:] & (ms[1:] == ms[:-1]).all(axis=1)
    )
    # hash-equal but content-different adjacency could interleave two
    # distinct strings' duplicates => ambiguous grouping; bail out
    if (same_key & ~same_row).any():
        return None
    group_sorted = np.cumsum(~same_row) - 1  # group id along sorted order
    n_groups = int(group_sorted[-1]) + 1
    starts = np.nonzero(~same_row)[0]
    counts_sorted = np.diff(np.append(starts, n))
    first_pos_sorted = np.minimum.reduceat(srt, starts)
    # renumber groups by first occurrence (== repeated-add fid order)
    order = np.argsort(first_pos_sorted, kind="stable")
    rank = np.empty(n_groups, dtype=np.int64)
    rank[order] = np.arange(n_groups, dtype=np.int64)
    inv = np.empty(n, dtype=np.int64)
    inv[srt] = rank[group_sorted]
    return first_pos_sorted[order], inv, counts_sorted[order]


class RouteIndex:
    def __init__(self, max_shapes: int = MAX_SHAPES):
        # filter -> fid; after a cold bulk load this dict materializes
        # LAZILY from `_ids` on first access (10M dict inserts cost ~7s
        # a pure serving process never pays)
        self._names_d: Dict[str, int] = {}
        self._names_lazy = False
        self._ids: List[Optional[str]] = []
        self._refs: List[int] = []
        self._free: List[int] = []
        self.nfa = NfaBuilder()
        self.shapes = ShapeIndex(max_shapes=max_shapes)
        self._residual: Set[str] = set()

    @property
    def _names(self) -> Dict[str, int]:
        if self._names_lazy:
            self._names_lazy = False
            self._names_d = dict(zip(self._ids, range(len(self._ids))))
        return self._names_d

    # -- mutation ----------------------------------------------------------
    def add(self, filter_: str) -> int:
        T.validate(filter_)
        fid = self._names.get(filter_)
        if fid is not None:
            self._refs[fid] += 1
            return fid
        if self._free:
            fid = self._free.pop()
            self._ids[fid] = filter_
            self._refs[fid] = 1
        else:
            fid = len(self._ids)
            self._ids.append(filter_)
            self._refs.append(1)
        self._names[filter_] = fid
        if not self.shapes.add(filter_, fid):
            self._residual.add(filter_)
            self.nfa.add(filter_, fid=fid)
            # vocab collision bumped the tokenizer salt: every combined
            # hash in the shape index is now stale. Filters whose NEW
            # hashes collide are evicted and re-homed in the NFA — which
            # can itself bump the salt again, hence the loop (converges:
            # each iteration needs a fresh 64-bit hash collision).
            while self.nfa.salt != self.shapes.salt:
                for ef, efid in self.shapes.rebuild(self.nfa.salt):
                    self._residual.add(ef)
                    self.nfa.add(ef, fid=efid)
        return fid

    def bulk_add(self, filters) -> List[int]:
        """Vectorized insert (cold start / session restore). Returns fids,
        parallel to `filters`. Matches repeated `add` bit-for-bit (tests
        enforce).

        Two tiers: on an EMPTY index with ASCII filters the whole load —
        encode, validate, dedup, tokenize, shape compile, hash-table
        placement, host mirror — runs as numpy passes with no per-filter
        Python (`_bulk_add_cold`); anything else takes the per-filter
        dict path (`_bulk_add_warm`), which still vectorizes hashing and
        placement but walks dicts for dedup against live state.
        """
        filters = list(filters)
        if not filters:
            return []
        if not self._ids and not self._free:
            try:
                return self._bulk_add_cold(filters)
            except _ColdFallback:
                pass
        return self._bulk_add_warm(filters)

    def _bulk_add_cold(self, filters: List[str]) -> List[int]:
        """Cold-start load: every step a numpy pass over the batch.

        Replaces the reference's per-route mnesia writes on session
        restore (emqx_trie.erl:66-119 insert per filter) with one
        vectorized table compile; at 10M filters this is the difference
        between minutes and seconds.
        """
        mat, lens = _encode_ascii(filters)
        _validate_rows(filters, mat, lens)
        dd = _dedup_rows(mat, lens)
        if dd is None:
            raise _ColdFallback  # pathological 64-bit row-hash collision
        first_pos, inv, counts = dd
        n = len(first_pos)
        first_l = first_pos.tolist()
        names = [filters[i] for i in first_l]
        mat_d = mat[first_pos]
        lens_d = lens[first_pos]
        del mat, lens
        # -- tokenize + shape-compile the distinct rows, in blocks -------
        from emqx_tpu.ops.tokenizer import tokenize_host_np

        cols = np.arange(mat_d.shape[1], dtype=np.int32)[None, :]
        nsep_all = (
            (mat_d == _SLASH) & (cols < lens_d[:, None])
        ).sum(axis=1)
        # levels needed: literal mask positions (<= 32) + the last word
        # for the trailing-'#' test; deeper rows are residual regardless
        L = int(min(int(nsep_all.max()) + 1, MAX_MASK_LEVELS + 2))
        Lc = min(L, MAX_MASK_LEVELS)
        k1 = np.array([level_mul(l, 1) for l in range(Lc)], dtype=np.uint32)
        k2 = np.array([level_mul(l, 2) for l in range(Lc)], dtype=np.uint32)
        lvls = np.arange(Lc, dtype=np.int64)[None, :]
        masks = np.empty(n, np.uint32)
        plens = np.empty(n, np.int64)
        hhs = np.empty(n, bool)
        s1 = np.empty(n, np.uint32)
        s2 = np.empty(n, np.uint32)
        unfit = np.zeros(n, bool)
        BLOCK = 1 << 18
        salt = self.shapes.salt
        W = mat_d.shape[1]
        with np.errstate(over="ignore"):
            for lo in range(0, n, BLOCK):
                hi = min(lo + BLOCK, n)
                mb, lb = mat_d[lo:hi], lens_d[lo:hi]
                h1, h2, nw, _dol, ws, wl = tokenize_host_np(mb, lb, salt, L)
                first_b = np.take_along_axis(
                    mb, np.clip(ws, 0, W - 1), axis=1
                )
                one = wl == 1
                isp = one & (first_b == _PLUS)
                ish = one & (first_b == _HASH)
                nwb = nw.astype(np.int64)
                deep = nwb > L
                last = np.clip(nwb - 1, 0, L - 1)[:, None]
                hh = (
                    np.take_along_axis(ish, last, axis=1)[:, 0] & ~deep
                )
                pl = nwb - hh
                bad = deep | (pl > MAX_MASK_LEVELS)
                lit = (~isp[:, :Lc]) & (lvls < pl[:, None])
                mk = (
                    lit.astype(np.uint64) << lvls.astype(np.uint64)
                ).sum(axis=1).astype(np.uint32)
                lb32 = lit.astype(np.uint32)
                s1[lo:hi] = np.sum(
                    h1[:, :Lc] * k1[None, :] * lb32, axis=1, dtype=np.uint32
                )
                s2[lo:hi] = np.sum(
                    h2[:, :Lc] * k2[None, :] * lb32, axis=1, dtype=np.uint32
                )
                masks[lo:hi] = mk
                plens[lo:hi] = pl
                hhs[lo:hi] = hh
                unfit[lo:hi] = bad
        fids = np.arange(n, dtype=np.int64)
        rejected = self.shapes.bulk_add_cold(
            names, fids, masks, plens, hhs, s1, s2, unfit
        )
        # -- host registry (name->fid dict materializes lazily; COPY the
        # list — `names` is also stashed in shapes._cold and `add` appends
        # to `_ids`) --------------------------------------------------------
        self._ids = list(names)
        self._refs = counts.tolist()
        self._names_lazy = True
        for ef, efid in rejected:
            self._residual.add(ef)
            self.nfa.add(ef, fid=efid)
        while self.nfa.salt != self.shapes.salt:
            for ef, efid in self.shapes.rebuild(self.nfa.salt):
                self._residual.add(ef)
                self.nfa.add(ef, fid=efid)
        return inv.tolist()

    def _bulk_add_warm(self, filters) -> List[int]:
        """Per-filter dict path: correct against any live index state."""
        # validate EVERYTHING before any mutation: an invalid filter must
        # not leave earlier batch entries half-registered (named but not
        # indexed => silently unroutable)
        for f in filters:
            if f not in self._names:
                T.validate(f)
        fids: List[int] = []
        fresh: List[tuple] = []
        for f in filters:
            fid = self._names.get(f)
            if fid is not None:
                self._refs[fid] += 1
                fids.append(fid)
                continue
            if self._free:
                fid = self._free.pop()
                self._ids[fid] = f
                self._refs[fid] = 1
            else:
                fid = len(self._ids)
                self._ids.append(f)
                self._refs.append(1)
            self._names[f] = fid
            fids.append(fid)
            fresh.append((f, fid))
        if fresh:
            for ef, efid in self.shapes.bulk_add(fresh):
                self._residual.add(ef)
                self.nfa.add(ef, fid=efid)
            while self.nfa.salt != self.shapes.salt:
                for ef, efid in self.shapes.rebuild(self.nfa.salt):
                    self._residual.add(ef)
                    self.nfa.add(ef, fid=efid)
        return fids

    def remove(self, filter_: str) -> bool:
        fid = self._names.get(filter_)
        if fid is None:
            return False
        self._refs[fid] -= 1
        if self._refs[fid] > 0:
            return False
        del self._names[filter_]
        self._ids[fid] = None
        self._free.append(fid)
        if filter_ in self._residual:
            self._residual.discard(filter_)
            self.nfa.remove(filter_)
        else:
            self.shapes.remove(filter_)
        return True

    # -- lookups -----------------------------------------------------------
    def filter_name(self, fid: int) -> Optional[str]:
        return self._ids[fid] if 0 <= fid < len(self._ids) else None

    def filter_id(self, filter_: str) -> Optional[int]:
        return self._names.get(filter_)

    def __len__(self) -> int:
        if self._names_lazy:
            return len(self._ids)  # cold load: no removals yet
        return len(self._names_d)

    @property
    def num_filters_capacity(self) -> int:
        return len(self._ids)

    @property
    def residual_count(self) -> int:
        return len(self._residual)

    @property
    def salt(self) -> int:
        return self.shapes.salt

    @property
    def version(self) -> int:
        return self.shapes.version + self.nfa.version
