"""Top-level incremental route compiler: shape fast path + residual NFA.

One filter-id space shared by two device engines:

- `ShapeIndex` (ops/shape_index.py) — O(#shapes) hash probes per topic;
  takes every filter whose wildcard shape fits. This is where ~all real
  subscription tables land.
- `NfaBuilder` (ops/nfa.py) — the general trie-walk kernel; holds only the
  RESIDUAL filters the shape index rejected (shape overflow past
  MAX_SHAPES, or a 2^-64 combined-hash collision).

The device route step runs the shape kernel always and the NFA kernel only
when residuals exist (models/router_model.shape_route_step). Both engines
speak the delta-overlay protocol, so churn reaches the device as scatters.

Reference analog: this pair replaces emqx_router's match path
(emqx_router.erl:128-141) the way the trie's compaction replaces
level-by-level walking (emqx_trie.erl:201-232) — except compiled all the
way down to fixed-shape batch kernels.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

from emqx_tpu.ops import topics as T
from emqx_tpu.ops.nfa import NfaBuilder
from emqx_tpu.ops.shape_index import MAX_SHAPES, ShapeIndex


class RouteIndex:
    def __init__(self, max_shapes: int = MAX_SHAPES):
        self._names: Dict[str, int] = {}
        self._ids: List[Optional[str]] = []
        self._refs: List[int] = []
        self._free: List[int] = []
        self.nfa = NfaBuilder()
        self.shapes = ShapeIndex(max_shapes=max_shapes)
        self._residual: Set[str] = set()

    # -- mutation ----------------------------------------------------------
    def add(self, filter_: str) -> int:
        T.validate(filter_)
        fid = self._names.get(filter_)
        if fid is not None:
            self._refs[fid] += 1
            return fid
        if self._free:
            fid = self._free.pop()
            self._ids[fid] = filter_
            self._refs[fid] = 1
        else:
            fid = len(self._ids)
            self._ids.append(filter_)
            self._refs.append(1)
        self._names[filter_] = fid
        if not self.shapes.add(filter_, fid):
            self._residual.add(filter_)
            self.nfa.add(filter_, fid=fid)
            # vocab collision bumped the tokenizer salt: every combined
            # hash in the shape index is now stale. Filters whose NEW
            # hashes collide are evicted and re-homed in the NFA — which
            # can itself bump the salt again, hence the loop (converges:
            # each iteration needs a fresh 64-bit hash collision).
            while self.nfa.salt != self.shapes.salt:
                for ef, efid in self.shapes.rebuild(self.nfa.salt):
                    self._residual.add(ef)
                    self.nfa.add(ef, fid=efid)
        return fid

    def bulk_add(self, filters) -> List[int]:
        """Vectorized insert (cold start / session restore): one numpy
        tokenizer pass + vectorized table build instead of per-filter
        hashing. Returns fids, parallel to `filters`. Matches repeated
        `add` bit-for-bit (tests enforce)."""
        # validate EVERYTHING before any mutation: an invalid filter must
        # not leave earlier batch entries half-registered (named but not
        # indexed => silently unroutable)
        for f in filters:
            if f not in self._names:
                T.validate(f)
        fids: List[int] = []
        fresh: List[tuple] = []
        for f in filters:
            fid = self._names.get(f)
            if fid is not None:
                self._refs[fid] += 1
                fids.append(fid)
                continue
            if self._free:
                fid = self._free.pop()
                self._ids[fid] = f
                self._refs[fid] = 1
            else:
                fid = len(self._ids)
                self._ids.append(f)
                self._refs.append(1)
            self._names[f] = fid
            fids.append(fid)
            fresh.append((f, fid))
        if fresh:
            for ef, efid in self.shapes.bulk_add(fresh):
                self._residual.add(ef)
                self.nfa.add(ef, fid=efid)
            while self.nfa.salt != self.shapes.salt:
                for ef, efid in self.shapes.rebuild(self.nfa.salt):
                    self._residual.add(ef)
                    self.nfa.add(ef, fid=efid)
        return fids

    def remove(self, filter_: str) -> bool:
        fid = self._names.get(filter_)
        if fid is None:
            return False
        self._refs[fid] -= 1
        if self._refs[fid] > 0:
            return False
        del self._names[filter_]
        self._ids[fid] = None
        self._free.append(fid)
        if filter_ in self._residual:
            self._residual.discard(filter_)
            self.nfa.remove(filter_)
        else:
            self.shapes.remove(filter_)
        return True

    # -- lookups -----------------------------------------------------------
    def filter_name(self, fid: int) -> Optional[str]:
        return self._ids[fid] if 0 <= fid < len(self._ids) else None

    def filter_id(self, filter_: str) -> Optional[int]:
        return self._names.get(filter_)

    def __len__(self) -> int:
        return len(self._names)

    @property
    def num_filters_capacity(self) -> int:
        return len(self._ids)

    @property
    def residual_count(self) -> int:
        return len(self._residual)

    @property
    def salt(self) -> int:
        return self.shapes.salt

    @property
    def version(self) -> int:
        return self.shapes.version + self.nfa.version
