"""Top-level incremental route compiler: shape fast path + residual NFA.

One filter-id space shared by two device engines:

- `ShapeIndex` (ops/shape_index.py) — O(#shapes) hash probes per topic;
  takes every filter whose wildcard shape fits. This is where ~all real
  subscription tables land.
- `NfaBuilder` (ops/nfa.py) — the general trie-walk kernel; holds only the
  RESIDUAL filters the shape index rejected (shape overflow past
  MAX_SHAPES, or a 2^-64 combined-hash collision).

The device route step runs the shape kernel always and the NFA kernel only
when residuals exist (models/router_model.shape_route_step). Both engines
speak the delta-overlay protocol, so churn reaches the device as scatters.

Reference analog: this pair replaces emqx_router's match path
(emqx_router.erl:128-141) the way the trie's compaction replaces
level-by-level walking (emqx_trie.erl:201-232) — except compiled all the
way down to fixed-shape batch kernels.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

import numpy as np

from emqx_tpu.ops import topics as T
from emqx_tpu.ops.nfa import NfaBuilder, _next_pow2
from emqx_tpu.ops.shape_index import (
    MAX_MASK_LEVELS,
    MAX_SHAPES,
    ShapeIndex,
    level_mul,
)

_PLUS = ord("+")
_HASH = ord("#")
_SLASH = ord("/")


class _ColdFallback(Exception):
    """Input needs the per-filter path (non-ASCII, exotic dtypes, ...)."""


def _encode_ascii(filters: List[str]):
    """list[str] -> (mat uint8 [n,W], lens int32 [n]) via numpy's C-level
    ASCII encode. Raises _ColdFallback for non-ASCII / embedded NULs
    (the 'S' dtype cannot represent trailing NULs faithfully)."""
    try:
        arr = np.asarray(filters, dtype="S")
    except (UnicodeEncodeError, TypeError) as e:
        raise _ColdFallback from e
    width = arr.dtype.itemsize
    if width == 0:
        raise _ColdFallback  # all-empty: let validate raise properly
    lens = np.char.str_len(arr).astype(np.int32)
    if int(lens.sum()) != sum(map(len, filters)):
        raise _ColdFallback  # NUL bytes somewhere: disagreement w/ S-dtype
    mat = np.ascontiguousarray(arr).view(np.uint8).reshape(len(arr), width)
    return mat, lens


def _validate_rows(filters: List[str], mat, lens) -> None:
    """Vectorized emqx_topic validate over the whole batch; raises the
    slow-path TopicValidationError for the first offending filter.
    Processed in row blocks so the working set stays cache-resident."""
    n, width = mat.shape
    cols = np.arange(width, dtype=np.int32)[None, :]
    BLOCK = 1 << 17
    for lo in range(0, n, BLOCK):
        hi = min(lo + BLOCK, n)
        mb, lb = mat[lo:hi], lens[lo:hi]
        inb = cols < lb[:, None]
        nul = inb & (mb == 0)  # embedded NUL: invalid (trailing NULs are
        # padding and sit beyond lens, so inb excludes them)
        is_p = inb & (mb == _PLUS)
        is_h = inb & (mb == _HASH)
        w = is_p | is_h
        if (
            not w.any()
            and not nul.any()
            and not (lb == 0).any()
            and width <= T.MAX_TOPIC_LEN
        ):
            continue  # pure-literal block: nothing left to check
        left_ok = np.empty(mb.shape, dtype=bool)
        left_ok[:, 0] = True
        left_ok[:, 1:] = mb[:, :-1] == _SLASH
        at_end = cols == (lb[:, None] - 1)
        right_ok = np.empty(mb.shape, dtype=bool)
        right_ok[:, :-1] = mb[:, 1:] == _SLASH
        right_ok[:, -1] = False
        right_ok |= at_end
        standalone = left_ok & right_ok
        bad = (w & ~standalone) | (is_h & standalone & ~at_end) | nul
        bad_rows = bad.any(axis=1) | (lb == 0)
        if width > T.MAX_TOPIC_LEN:
            bad_rows |= lb > T.MAX_TOPIC_LEN
        if bad_rows.any():
            i = lo + int(np.argmax(bad_rows))
            T.validate(filters[i])  # raises with the precise reason
            raise T.TopicValidationError("topic_invalid: %r" % filters[i])


_ROW_C = np.uint64(0x9E3779B97F4A7C15)
_ROW_C2 = np.uint64(0xC2B2AE3D27D4EB4F)
_row_R_cache: Optional[np.ndarray] = None
_row_R2_cache: Optional[np.ndarray] = None


def _row_R(width: int) -> np.ndarray:
    """Per-column multipliers for the primary 64-bit row hash. One fixed
    stream sliced to `width`: zero-padding beyond a row's length
    contributes nothing, so the key of a string is independent of the
    batch's padded matrix width."""
    global _row_R_cache
    if _row_R_cache is None or len(_row_R_cache) < width:
        rng = np.random.default_rng(0x5EED)
        # 4x: utf-8 bytes per char upper bound (scalar keys hash the
        # encoded bytes) — the stream must never regrow once keys exist
        n = max(4 * (T.MAX_TOPIC_LEN + 1), width)
        _row_R_cache = rng.integers(
            1, 1 << 63, size=n, dtype=np.uint64
        ) | np.uint64(1)
    return _row_R_cache[:width]


def _row_R2(width: int) -> np.ndarray:
    """Independent multiplier stream for the 32-bit confirm hash (96
    bits of key material total — see RouteIndex registry notes)."""
    global _row_R2_cache
    if _row_R2_cache is None or len(_row_R2_cache) < width:
        rng = np.random.default_rng(0xBEEF)
        n = max(4 * (T.MAX_TOPIC_LEN + 1), width)
        _row_R2_cache = rng.integers(
            1, 1 << 63, size=n, dtype=np.uint64
        ) | np.uint64(1)
    return _row_R2_cache[:width]


def _row_keys(mat, lens) -> np.ndarray:
    """Primary 64-bit row hashes for an encoded batch (shared by dedup
    and the registry hash table, so cold-load keys are reusable
    verbatim)."""
    with np.errstate(over="ignore"):
        return mat.astype(np.uint64) @ _row_R(mat.shape[1]) + lens.astype(
            np.uint64
        ) * _ROW_C


def _fold32(k: np.ndarray) -> np.ndarray:
    return (k ^ (k >> np.uint64(32))).astype(np.uint32)


def _row_keys2(mat, lens) -> np.ndarray:
    """Confirm hashes (uint32) from the independent stream."""
    with np.errstate(over="ignore"):
        k = mat.astype(np.uint64) @ _row_R2(
            mat.shape[1]
        ) + lens.astype(np.uint64) * _ROW_C2
    return _fold32(k)


def _row_key_str(f: str):
    """Scalar (primary, confirm) key pair for one (possibly non-ASCII)
    filter string — bit-identical to the vectorized batch keys."""
    b = np.frombuffer(f.encode("utf-8"), np.uint8)
    n = len(b)
    with np.errstate(over="ignore"):
        b64 = b.astype(np.uint64)
        k1 = (b64 * _row_R(n)).sum(dtype=np.uint64) + np.uint64(n) * _ROW_C
        k2 = (b64 * _row_R2(n)).sum(dtype=np.uint64) + np.uint64(n) * _ROW_C2
    return k1, _fold32(k2)


def _dedup_rows(mat, lens, key=None):
    """Group identical rows without a full string sort: 64-bit row hashes
    + stable argsort + exact adjacent-row compare. Returns
    (first_pos, inv_fid, counts) with distinct rows numbered in
    FIRST-OCCURRENCE order, or None when a hash collision makes the
    grouping ambiguous (caller falls back to the per-filter path)."""
    n, width = mat.shape
    if key is None:
        key = _row_keys(mat, lens)
    srt = np.argsort(key, kind="stable")
    ks = key[srt]
    ms = mat[srt]
    same_key = np.empty(n, dtype=bool)
    same_key[0] = False
    same_key[1:] = ks[1:] == ks[:-1]
    same_row = np.empty(n, dtype=bool)
    same_row[0] = False
    same_row[1:] = (
        same_key[1:] & (ms[1:] == ms[:-1]).all(axis=1)
    )
    # hash-equal but content-different adjacency could interleave two
    # distinct strings' duplicates => ambiguous grouping; bail out
    if (same_key & ~same_row).any():
        return None
    group_sorted = np.cumsum(~same_row) - 1  # group id along sorted order
    n_groups = int(group_sorted[-1]) + 1
    starts = np.nonzero(~same_row)[0]
    counts_sorted = np.diff(np.append(starts, n))
    first_pos_sorted = np.minimum.reduceat(srt, starts)
    # renumber groups by first occurrence (== repeated-add fid order)
    order = np.argsort(first_pos_sorted, kind="stable")
    rank = np.empty(n_groups, dtype=np.int64)
    rank[order] = np.arange(n_groups, dtype=np.int64)
    inv = np.empty(n, dtype=np.int64)
    inv[srt] = rank[group_sorted]
    return first_pos_sorted[order], inv, counts_sorted[order]


# registry hash-table fid-lane sentinels
_H_EMPTY = -1
_H_TOMB = -2


class RouteIndex:
    def __init__(self, max_shapes: int = MAX_SHAPES):
        # filter -> fid registry as an open-addressing numpy table:
        # `_hkey` (primary 64-bit row hash), `_hkey2` (independent
        # 32-bit confirm hash), `_hfid` fids. A Python dict at 10M
        # entries costs ~700MB and a ~30s one-shot materialization the
        # first post-restore subscribe would stall on; the table is
        # built vectorized inside the cold bulk load and batch lookups
        # are numpy probe rounds — the mass-reconnect path never walks
        # a 10M dict. Exactness: scalar paths (add/remove/filter_id)
        # confirm every hit by exact string compare; BULK lookups
        # confirm by the 96-bit key pair only — re-encoding ~131k
        # candidate strings per churn wave costs ~70ms (measured), vs
        # a 2^-96 false-accept bound, orders below memory-error rates.
        self._hkey: np.ndarray = np.zeros(16, np.uint64)
        self._hkey2: np.ndarray = np.zeros(16, np.uint32)
        self._hfid: np.ndarray = np.full(16, _H_EMPTY, np.int64)
        self._hfill = 0  # occupied slots (live + tombstones)
        self._live = 0  # distinct live filters
        self._ids: List[Optional[str]] = []
        # refcounts as a capacity-doubled numpy array: churn-storm waves
        # bump thousands of refs per batch as ONE np.add.at scatter
        self._refs: np.ndarray = np.zeros(16, np.int64)
        self._free: List[int] = []
        self.nfa = NfaBuilder()
        self.shapes = ShapeIndex(max_shapes=max_shapes)
        # fid -> name recovery for the shape engine's salt rebuilds
        # (bound method: picklable, follows `_ids` mutations)
        self.shapes.resolve_name = self.filter_name
        self._residual: Set[str] = set()

    def _refs_ensure(self, n: int) -> None:
        if n > len(self._refs):
            new = np.zeros(max(16, _next_pow2(n)), np.int64)
            new[: len(self._refs)] = self._refs
            self._refs = new

    # -- filter->fid registry (open-addressing, two-key confirmed) --------
    def _hash_get(self, filter_: str, _keys=None) -> Optional[int]:
        """Probe for `filter_`; every key hit is confirmed by exact
        string compare, so a key collision degrades to one extra probe,
        never a wrong fid. `_keys` lets add() reuse one key computation
        across its get+set pair (subscribe-storm hot path)."""
        key, key2 = _keys if _keys is not None else _row_key_str(filter_)
        cap = len(self._hkey)
        mask = cap - 1
        slot = int(key) & mask
        step = ((int(key) >> 32) & mask) | 1
        hfid, hkey, hkey2, ids = (
            self._hfid, self._hkey, self._hkey2, self._ids
        )
        for _ in range(cap):
            fid = int(hfid[slot])
            if fid == _H_EMPTY:
                return None
            if (
                fid >= 0
                and hkey[slot] == key
                and hkey2[slot] == key2
                and ids[fid] == filter_
            ):
                return fid
            slot = (slot + step) & mask
        return None

    def _hash_set(self, filter_: str, fid: int, _keys=None) -> None:
        """Insert (caller has established absence). Reuses the first
        tombstone on the probe path; grows at 2/3 occupancy."""
        if (self._hfill + 1) * 3 > 2 * len(self._hkey):
            self._hash_rehash(self._live + 1)
        key, key2 = _keys if _keys is not None else _row_key_str(filter_)
        cap = len(self._hkey)
        mask = cap - 1
        slot = int(key) & mask
        step = ((int(key) >> 32) & mask) | 1
        tomb = -1
        for _ in range(cap):
            fid0 = int(self._hfid[slot])
            if fid0 == _H_EMPTY:
                if tomb >= 0:
                    slot = tomb
                else:
                    self._hfill += 1
                self._hkey[slot] = key
                self._hkey2[slot] = key2
                self._hfid[slot] = fid
                return
            if fid0 == _H_TOMB and tomb < 0:
                tomb = slot
            slot = (slot + step) & mask
        raise RuntimeError("registry hash table full")  # unreachable

    def _hash_del(self, filter_: str) -> None:
        key, key2 = _row_key_str(filter_)
        cap = len(self._hkey)
        mask = cap - 1
        slot = int(key) & mask
        step = ((int(key) >> 32) & mask) | 1
        ids = self._ids
        for _ in range(cap):
            fid = int(self._hfid[slot])
            if fid == _H_EMPTY:
                return
            if (
                fid >= 0
                and self._hkey[slot] == key
                and self._hkey2[slot] == key2
                and ids[fid] == filter_
            ):
                self._hfid[slot] = _H_TOMB  # slot stays occupied for probes
                return
            slot = (slot + step) & mask

    def _hash_alloc(self, cap: int) -> None:
        self._hkey = np.zeros(cap, np.uint64)
        self._hkey2 = np.zeros(cap, np.uint32)
        self._hfid = np.full(cap, _H_EMPTY, np.int64)
        self._hfill = 0

    def _hash_build(
        self,
        keys: np.ndarray,
        keys2: np.ndarray,
        fids: np.ndarray,
        cap: int,
    ) -> None:
        """Vectorized table build from per-row keys: each probe round
        gathers the pending rows' slots, the first pending row per free
        slot claims it (stable sort), losers and occupied-slot rows
        advance by their stride. ~2 rounds resolve a fresh table."""
        self._hash_alloc(cap)
        mask = np.int64(cap - 1)
        slot = (keys & np.uint64(cap - 1)).astype(np.int64)
        step = (
            ((keys >> np.uint64(32)).astype(np.int64) & mask) | np.int64(1)
        )
        pending = np.arange(len(keys))
        while pending.size:
            s = slot[pending]
            free = self._hfid[s] == _H_EMPTY
            if free.any():
                cand, scand = pending[free], s[free]
                order = np.argsort(scand, kind="stable")
                scand, cand = scand[order], cand[order]
                first = np.empty(len(scand), bool)
                first[0] = True
                first[1:] = scand[1:] != scand[:-1]
                win, wslot = cand[first], scand[first]
                self._hkey[wslot] = keys[win]
                self._hkey2[wslot] = keys2[win]
                self._hfid[wslot] = fids[win]
                placed = np.zeros(len(keys), bool)
                placed[win] = True
                pending = pending[~placed[pending]]
                if pending.size == 0:
                    break
            slot[pending] = (slot[pending] + step[pending]) & mask
        self._hfill = len(keys)

    def _hash_insert_batch(
        self, keys: np.ndarray, keys2: np.ndarray, fids: np.ndarray
    ) -> None:
        """Vectorized insert of fresh rows into the LIVE table (caller
        has established absence): probe rounds claim empty OR tombstone
        slots, first bidder per slot wins. O(batch), not O(table)."""
        n = len(keys)
        if n == 0:
            return
        if (self._hfill + n) * 3 > 2 * len(self._hkey):
            self._hash_rehash(self._live + n)
        cap = len(self._hkey)
        mask = np.int64(cap - 1)
        slot = (keys & np.uint64(cap - 1)).astype(np.int64)
        step = (
            ((keys >> np.uint64(32)).astype(np.int64) & mask) | np.int64(1)
        )
        pending = np.arange(n)
        while pending.size:
            s = slot[pending]
            free = self._hfid[s] < 0  # EMPTY or TOMB: both claimable
            if free.any():
                cand, scand = pending[free], s[free]
                order = np.argsort(scand, kind="stable")
                scand, cand = scand[order], cand[order]
                first = np.empty(len(scand), bool)
                first[0] = True
                first[1:] = scand[1:] != scand[:-1]
                win, wslot = cand[first], scand[first]
                # count EMPTY claims before overwriting the lane
                self._hfill += int(
                    (self._hfid[wslot] == _H_EMPTY).sum()
                )
                self._hkey[wslot] = keys[win]
                self._hkey2[wslot] = keys2[win]
                self._hfid[wslot] = fids[win]
                placed = np.zeros(n, bool)
                placed[win] = True
                pending = pending[~placed[pending]]
                if pending.size == 0:
                    break
            slot[pending] = (slot[pending] + step[pending]) & mask

    def _hash_rehash(self, need: int) -> None:
        """Grow + drop tombstones: vectorized rebuild from `_ids` (the
        per-filter fallback covers non-ASCII registries)."""
        cap = _next_pow2(max(16, 2 * max(need, self._live)))
        ids = self._ids
        live = [
            (f, fid) for fid, f in enumerate(ids) if f is not None
        ]
        if not live:
            self._hash_alloc(cap)
            return
        try:
            mat, lens = _encode_ascii([f for f, _ in live])
        except _ColdFallback:
            self._hash_alloc(cap)
            for f, fid in live:
                self._hash_set(f, fid)
            return
        self._hash_build(
            _row_keys(mat, lens),
            _row_keys2(mat, lens),
            np.array([fid for _, fid in live], np.int64),
            cap,
        )

    def _hash_lookup_batch(self, filters: List[str]):
        """Vectorized membership for a warm batch: returns
        (fids int64 — -1 for miss, mat, lens, keys, keys2). Hits are
        confirmed by BOTH independent keys (96 bits; see __init__
        notes); unconfirmed key-matches keep probing (a same-key
        different-string chain is legal). Raises _ColdFallback for
        non-ASCII input."""
        mat, lens = _encode_ascii(filters)
        keys = _row_keys(mat, lens)
        keys2 = _row_keys2(mat, lens)
        n = len(filters)
        cap = len(self._hkey)
        mask = np.int64(cap - 1)
        res = np.full(n, -1, np.int64)
        slot = (keys & np.uint64(cap - 1)).astype(np.int64)
        step = (
            ((keys >> np.uint64(32)).astype(np.int64) & mask) | np.int64(1)
        )
        pending = np.arange(n)
        for _ in range(cap):
            s = slot[pending]
            fidv = self._hfid[s]
            empty = fidv == _H_EMPTY
            hit = (
                (fidv >= 0)
                & (self._hkey[s] == keys[pending])
                & (self._hkey2[s] == keys2[pending])
            )
            res[pending[hit]] = fidv[hit]
            pending = pending[~(empty | hit)]
            if pending.size == 0:
                break
            slot[pending] = (slot[pending] + step[pending]) & mask
        return res, mat, lens, keys, keys2

    # -- mutation ----------------------------------------------------------
    def add(self, filter_: str) -> int:
        T.validate(filter_)
        keys = _row_key_str(filter_)
        fid = self._hash_get(filter_, keys)
        if fid is not None:
            self._refs[fid] += 1
            return fid
        if self._free:
            fid = self._free.pop()
            self._ids[fid] = filter_
            self._refs[fid] = 1
        else:
            fid = len(self._ids)
            self._ids.append(filter_)
            self._refs_ensure(fid + 1)
            self._refs[fid] = 1
        self._hash_set(filter_, fid, keys)
        self._live += 1
        if not self.shapes.add(filter_, fid):
            self._residual.add(filter_)
            self.nfa.add(filter_, fid=fid)
            # vocab collision bumped the tokenizer salt: every combined
            # hash in the shape index is now stale. Filters whose NEW
            # hashes collide are evicted and re-homed in the NFA — which
            # can itself bump the salt again, hence the loop (converges:
            # each iteration needs a fresh 64-bit hash collision).
            while self.nfa.salt != self.shapes.salt:
                for ef, efid in self.shapes.rebuild(self.nfa.salt):
                    self._residual.add(ef)
                    self.nfa.add(ef, fid=efid)
        return fid

    def bulk_add(self, filters) -> List[int]:
        """Vectorized insert (cold start / session restore). Returns fids,
        parallel to `filters`. Matches repeated `add` bit-for-bit (tests
        enforce).

        Two tiers: on an EMPTY index with ASCII filters the whole load —
        encode, validate, dedup, tokenize, shape compile, hash-table
        placement, host mirror — runs as numpy passes with no per-filter
        Python (`_bulk_add_cold`); anything else takes the per-filter
        dict path (`_bulk_add_warm`), which still vectorizes hashing and
        placement but walks dicts for dedup against live state.
        """
        filters = list(filters)
        if not filters:
            return []
        if not self._ids and not self._free:
            try:
                return self._bulk_add_cold(filters)
            except _ColdFallback:
                pass
        return self._bulk_add_warm(filters)

    def _bulk_add_cold(self, filters: List[str]) -> List[int]:
        """Cold-start load: every step a numpy pass over the batch.

        Replaces the reference's per-route mnesia writes on session
        restore (emqx_trie.erl:66-119 insert per filter) with one
        vectorized table compile; at 10M filters this is the difference
        between minutes and seconds.
        """
        mat, lens = _encode_ascii(filters)
        _validate_rows(filters, mat, lens)
        key = _row_keys(mat, lens)
        dd = _dedup_rows(mat, lens, key)
        if dd is None:
            raise _ColdFallback  # pathological 64-bit row-hash collision
        first_pos, inv, counts = dd
        n = len(first_pos)
        # registry keys for the distinct rows (both streams, pre-del)
        keys_d = key[first_pos]
        keys2_d = _row_keys2(mat, lens)[first_pos]
        del key
        first_l = first_pos.tolist()
        names = [filters[i] for i in first_l]
        mat_d = mat[first_pos]
        lens_d = lens[first_pos]
        del mat, lens
        # -- tokenize + shape-compile the distinct rows, in blocks -------
        from emqx_tpu.ops.tokenizer import tokenize_host_np

        cols = np.arange(mat_d.shape[1], dtype=np.int32)[None, :]
        nsep_all = (
            (mat_d == _SLASH) & (cols < lens_d[:, None])
        ).sum(axis=1)
        # levels needed: literal mask positions (<= 32) + the last word
        # for the trailing-'#' test; deeper rows are residual regardless
        L = int(min(int(nsep_all.max()) + 1, MAX_MASK_LEVELS + 2))
        Lc = min(L, MAX_MASK_LEVELS)
        k1 = np.array([level_mul(l, 1) for l in range(Lc)], dtype=np.uint32)
        k2 = np.array([level_mul(l, 2) for l in range(Lc)], dtype=np.uint32)
        lvls = np.arange(Lc, dtype=np.int64)[None, :]
        masks = np.empty(n, np.uint32)
        plens = np.empty(n, np.int64)
        hhs = np.empty(n, bool)
        s1 = np.empty(n, np.uint32)
        s2 = np.empty(n, np.uint32)
        unfit = np.zeros(n, bool)
        BLOCK = 1 << 18
        salt = self.shapes.salt
        W = mat_d.shape[1]
        with np.errstate(over="ignore"):
            for lo in range(0, n, BLOCK):
                hi = min(lo + BLOCK, n)
                mb, lb = mat_d[lo:hi], lens_d[lo:hi]
                h1, h2, nw, _dol, ws, wl = tokenize_host_np(mb, lb, salt, L)
                first_b = np.take_along_axis(
                    mb, np.clip(ws, 0, W - 1), axis=1
                )
                one = wl == 1
                isp = one & (first_b == _PLUS)
                ish = one & (first_b == _HASH)
                nwb = nw.astype(np.int64)
                deep = nwb > L
                last = np.clip(nwb - 1, 0, L - 1)[:, None]
                hh = (
                    np.take_along_axis(ish, last, axis=1)[:, 0] & ~deep
                )
                pl = nwb - hh
                bad = deep | (pl > MAX_MASK_LEVELS)
                lit = (~isp[:, :Lc]) & (lvls < pl[:, None])
                mk = (
                    lit.astype(np.uint64) << lvls.astype(np.uint64)
                ).sum(axis=1).astype(np.uint32)
                lb32 = lit.astype(np.uint32)
                s1[lo:hi] = np.sum(
                    h1[:, :Lc] * k1[None, :] * lb32, axis=1, dtype=np.uint32
                )
                s2[lo:hi] = np.sum(
                    h2[:, :Lc] * k2[None, :] * lb32, axis=1, dtype=np.uint32
                )
                masks[lo:hi] = mk
                plens[lo:hi] = pl
                hhs[lo:hi] = hh
                unfit[lo:hi] = bad
        fids = np.arange(n, dtype=np.int64)
        rejected = self.shapes.bulk_add_cold(
            names, fids, masks, plens, hhs, s1, s2, unfit
        )
        # -- host registry (COPY the list — `names` is also stashed in
        # shapes._cold and `add` appends to `_ids`). The hash table builds
        # HERE, vectorized from the dedup keys: ~2s at 10M vs the ~30s
        # first-subscribe stall a lazily-materialized dict would cost ----
        self._ids = list(names)
        self._refs = np.zeros(max(16, _next_pow2(len(names))), np.int64)
        self._refs[: len(names)] = counts
        self._hash_build(
            keys_d,
            keys2_d,
            np.arange(n, dtype=np.int64),
            _next_pow2(max(16, 2 * n)),
        )
        self._live = n
        for ef, efid in rejected:
            self._residual.add(ef)
            self.nfa.add(ef, fid=efid)
        while self.nfa.salt != self.shapes.salt:
            for ef, efid in self.shapes.rebuild(self.nfa.salt):
                self._residual.add(ef)
                self.nfa.add(ef, fid=efid)
        return inv.tolist()

    def _bulk_add_warm(self, filters) -> List[int]:
        """Warm-state batch path, churn-storm shaped: resubscribes (the
        mass-reconnect common case — the filter already exists) resolve
        as vectorized hash-table probe rounds plus ONE refcount scatter
        — no per-filter Python and no 10M-entry dict; fresh filters
        validate first (an invalid filter must not leave earlier batch
        entries half-registered => silently unroutable), then flow to
        the shape engine's hot segment in one batch."""
        try:
            got_a, _mat, _lens, keys, keys2 = self._hash_lookup_batch(
                filters
            )
        except _ColdFallback:
            # non-ASCII somewhere: per-filter path, identical semantics
            return [self.add(f) for f in filters]
        if (got_a < 0).any():
            fresh_pos = np.nonzero(got_a < 0)[0].tolist()
            seen: Dict[str, int] = {}
            uniq_i: List[int] = []
            for i in fresh_pos:
                f = filters[i]
                if f not in seen:
                    seen[f] = -1
                    uniq_i.append(i)
            # validate EVERYTHING before any mutation: an invalid filter
            # must not leave earlier batch entries half-registered
            # (named but not indexed => silently unroutable)
            for i in uniq_i:
                T.validate(filters[i])
            fresh: List[tuple] = []
            ids = self._ids
            free = self._free
            ufids = np.empty(len(uniq_i), np.int64)
            for j, i in enumerate(uniq_i):
                f = filters[i]
                if free:
                    fid = free.pop()
                    ids[fid] = f
                else:
                    fid = len(ids)
                    ids.append(f)
                ufids[j] = fid
                seen[f] = fid
                fresh.append((f, fid))
            self._refs_ensure(int(ufids.max()) + 1)
            self._refs[ufids] = 0  # counted with the batch below
            ui = np.array(uniq_i, np.int64)
            self._hash_insert_batch(keys[ui], keys2[ui], ufids)
            self._live += len(uniq_i)
            for ef, efid in self.shapes.bulk_add(fresh):
                self._residual.add(ef)
                self.nfa.add(ef, fid=efid)
            while self.nfa.salt != self.shapes.salt:
                for ef, efid in self.shapes.rebuild(self.nfa.salt):
                    self._residual.add(ef)
                    self.nfa.add(ef, fid=efid)
            for i in fresh_pos:
                got_a[i] = seen[filters[i]]
        np.add.at(self._refs, got_a, 1)
        return got_a.tolist()

    def remove(self, filter_: str) -> bool:
        fid = self._hash_get(filter_)
        if fid is None:
            return False
        self._refs[fid] -= 1
        if self._refs[fid] > 0:
            return False
        self._hash_del(filter_)
        self._live -= 1
        self._ids[fid] = None
        self._free.append(fid)
        if filter_ in self._residual:
            self._residual.discard(filter_)
            self.nfa.remove(filter_)
        else:
            self.shapes.remove(filter_)
        return True

    # -- lookups -----------------------------------------------------------
    def filter_name(self, fid: int) -> Optional[str]:
        return self._ids[fid] if 0 <= fid < len(self._ids) else None

    def filter_id(self, filter_: str) -> Optional[int]:
        return self._hash_get(filter_)

    def __len__(self) -> int:
        return self._live

    @property
    def num_filters_capacity(self) -> int:
        return len(self._ids)

    @property
    def residual_count(self) -> int:
        return len(self._residual)

    @property
    def salt(self) -> int:
        return self.shapes.salt

    @property
    def version(self) -> int:
        return self.shapes.version + self.nfa.version
