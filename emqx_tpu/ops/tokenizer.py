"""On-device topic tokenization.

Splits a batch of topic byte-strings into level words and hashes each word —
entirely on the TPU, with no per-byte recurrence. The trick: a polynomial
word hash ``raw = sum_j c_j * P^(m-1-j) + P^m  (mod 2^32)`` can be computed
from *prefix sums* over the whole padded byte matrix:

    u_i  = c_i * P^(-i)          (P odd => invertible mod 2^32)
    U    = cumsum(u)             per row
    word [s..e]:  raw = P^e * (U[e] - U[s-1]) + P^(e-s+1)

so tokenization is a handful of vectorized elementwise ops, one cumsum, and
two gather/scatters — VPU-friendly and fully fusable by XLA. The reference
has no analog (it splits binaries per message on the BEAM,
apps/emqx/src/emqx_topic.erl words/1); this is the TPU-first replacement.

The hash pair (two independent P's + murmur finalizer) must match
`emqx_tpu.ops.nfa.word_hash_pair` bit-for-bit; build-time salt handling and
collision detection live there.
"""

from __future__ import annotations

from functools import lru_cache
from typing import List, NamedTuple, Tuple

import numpy as np

from emqx_tpu.ops.nfa import (
    P1,
    P2,
    VOCAB_H_MUL,
    VOCAB_H_SHIFT,
    _SALT1,
    _SALT2,
)

SLASH = np.uint8(ord("/"))
DOLLAR = np.uint8(ord("$"))


def _inv_mod_2_32(p: int) -> int:
    """Modular inverse of odd p mod 2^32 via Newton iteration."""
    x = p  # 3-bit correct
    for _ in range(5):
        x = (x * (2 - p * x)) & 0xFFFFFFFF
    assert (x * p) & 0xFFFFFFFF == 1
    return x


@lru_cache(maxsize=8)
def _pow_tables(max_bytes: int) -> Tuple[np.ndarray, ...]:
    """P^i and P^-i tables, i in [0, max_bytes], for both primes."""
    out = []
    for P in (int(P1), int(P2)):
        inv = _inv_mod_2_32(P)
        pw = np.empty(max_bytes + 1, dtype=np.uint32)
        ipw = np.empty(max_bytes + 1, dtype=np.uint32)
        a = b = 1
        for i in range(max_bytes + 1):
            pw[i] = a
            ipw[i] = b
            a = (a * P) & 0xFFFFFFFF
            b = (b * inv) & 0xFFFFFFFF
        out += [pw, ipw]
    return tuple(out)


class TopicRef(NamedTuple):
    """A topic's bytes IN PLACE inside a shared read slab (the fabric
    frame body): `buf` is the flat uint8 view of the whole slab, the
    topic is buf[off:off+ln]. `encode_topics` gathers every ref sharing
    a slab into the topic matrix with ONE vectorized pass — the
    zero-copy seam between transport/fabric.py and the device tokenizer
    (no str decode, no per-row copy)."""

    buf: np.ndarray
    off: int
    ln: int

    def tobytes(self) -> bytes:
        return self.buf[self.off : self.off + self.ln].tobytes()

    def __str__(self) -> str:
        return self.tobytes().decode("utf-8", "surrogatepass")


def _fill_from_slab(mat, lens, too_long, buf, rows, offs, lns, max_bytes):
    """One gather fills every row backed by the same slab buffer."""
    rows = np.asarray(rows, np.int64)
    offs = np.asarray(offs, np.int64)
    lns = np.asarray(lns, np.int64)
    if buf.size == 0:
        return  # degenerate slab: rows keep their zero fill
    tl = lns > max_bytes
    eff = np.minimum(lns, max_bytes)
    cols = np.arange(max_bytes, dtype=np.int64)
    idx = offs[:, None] + cols[None, :]
    valid = cols[None, :] < eff[:, None]
    np.clip(idx, 0, max(buf.size - 1, 0), out=idx)
    mat[rows] = buf[idx] * valid
    lens[rows] = eff
    too_long[rows] = tl


def encode_topics(
    topics: List[bytes | str], max_bytes: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pack topics into a zero-padded uint8 matrix.

    -> (bytes_mat uint8 [B, max_bytes], lengths int32 [B], too_long bool [B]).
    Too-long topics are truncated and flagged (host falls back to the CPU
    trie for those rows; cf. 64KB cap at emqx_topic.erl ?MAX_TOPIC_LEN).

    `TopicRef` entries (zero-copy ingest: topic bytes still sitting in a
    fabric read slab) are grouped per backing buffer and gathered into
    the matrix with one vectorized indexed read per slab — the common
    serving batch (one PUBB frame) fills in a single pass.
    """
    B = len(topics)
    mat = np.zeros((B, max_bytes), dtype=np.uint8)
    lens = np.zeros(B, dtype=np.int32)
    too_long = np.zeros(B, dtype=bool)
    slabs: dict = {}
    for i, t in enumerate(topics):
        if isinstance(t, TopicRef):
            g = slabs.get(id(t.buf))
            if g is None:
                g = slabs[id(t.buf)] = (t.buf, [], [], [])
            g[1].append(i)
            g[2].append(t.off)
            g[3].append(t.ln)
            continue
        b = t.encode("utf-8", "surrogatepass") if isinstance(t, str) else t
        n = len(b)
        if n > max_bytes:
            too_long[i] = True
            n = max_bytes
        mat[i, :n] = np.frombuffer(b[:n], dtype=np.uint8)
        lens[i] = n
    for buf, rows, offs, lns in slabs.values():
        _fill_from_slab(mat, lens, too_long, buf, rows, offs, lns,
                        max_bytes)
    return mat, lens, too_long


def tokenize_device(bytes_mat, lengths, salt: int, max_levels: int):
    """jnp: (bytes [B,MB] uint8, lengths [B]) -> word hash pairs per level.

    Returns (h1 [B,L] uint32, h2 [B,L] uint32, nwords [B] int32,
    is_dollar [B] bool). Rows deeper than `max_levels` report their true
    nwords; the matcher flags them too_deep.
    """
    import jax.numpy as jnp

    B, MB = bytes_mat.shape
    L = max_levels
    pw1, ipw1, pw2, ipw2 = (jnp.asarray(t) for t in _pow_tables(MB))
    cols = jnp.arange(MB, dtype=jnp.int32)
    inb = cols[None, :] < lengths[:, None]
    c = bytes_mat.astype(jnp.uint32)
    issep = inb & (bytes_mat == SLASH)
    ischar = inb & ~issep
    # word index per column (separators carry the index of the word they end)
    segex = jnp.cumsum(issep.astype(jnp.int32), axis=1) - issep.astype(jnp.int32)
    rows = jnp.arange(B, dtype=jnp.int32)[:, None]

    # prefix sums of c_i * P^-i  (uint32, wraps mod 2^32 by construction)
    u1 = jnp.where(ischar, c * ipw1[cols][None, :], jnp.uint32(0))
    u2 = jnp.where(ischar, c * ipw2[cols][None, :], jnp.uint32(0))
    U1 = jnp.cumsum(u1, axis=1, dtype=jnp.uint32)
    U2 = jnp.cumsum(u2, axis=1, dtype=jnp.uint32)

    # per-word boundaries: scatter separator columns into word slots
    sep_slot = jnp.where(issep, segex, L)  # L => dropped
    sepcol = jnp.full((B, L), -1, dtype=jnp.int32)
    sepcol = sepcol.at[rows, sep_slot].set(
        jnp.broadcast_to(cols[None, :], (B, MB)), mode="drop"
    )
    k = jnp.arange(L, dtype=jnp.int32)[None, :]
    nsep = jnp.sum(issep, axis=1).astype(jnp.int32)
    # "" splits to [''] (one empty word), matching emqx_topic:words/1 on host
    nwords = nsep + 1
    has_sep = sepcol >= 0
    wend = jnp.where(has_sep, sepcol - 1, lengths[:, None] - 1)  # [B,L]
    prev_sep = jnp.concatenate(
        [jnp.full((B, 1), -1, dtype=jnp.int32), sepcol[:, : L - 1]], axis=1
    )
    wstart = prev_sep + 1
    wlen = wend - wstart + 1  # 0 for empty words

    def word_hash(U, pw, salt_mul, salt_add):
        e = jnp.clip(wend, 0, MB - 1)
        s0 = jnp.clip(wstart - 1, 0, MB - 1)
        Ue = jnp.take_along_axis(U, e, axis=1)
        Us = jnp.where(
            wstart > 0, jnp.take_along_axis(U, s0, axis=1), jnp.uint32(0)
        )
        raw = (Ue - Us) * pw[e] + pw[jnp.clip(wlen, 0, MB)]
        seed = jnp.uint32(salt) * salt_mul + salt_add
        x = raw ^ seed
        x ^= x >> 16
        x = x * jnp.uint32(0x7FEB352D)
        x ^= x >> 15
        x = x * jnp.uint32(0x846CA68B)
        x ^= x >> 16
        return x

    h1 = word_hash(U1, pw1, _SALT1, jnp.uint32(1))
    h2 = word_hash(U2, pw2, _SALT2, jnp.uint32(7))
    valid_word = k < jnp.minimum(nwords, L)[:, None]
    h1 = jnp.where(valid_word, h1, jnp.uint32(0))
    h2 = jnp.where(valid_word, h2, jnp.uint32(0))
    is_dollar = (lengths > 0) & (bytes_mat[:, 0] == DOLLAR)
    return h1, h2, nwords, is_dollar


def tokenize_host_np(bytes_mat, lengths, salt: int, max_levels: int):
    """Numpy mirror of `tokenize_device`, bit-for-bit.

    The vectorized host half of bulk subscription loads: computing a
    million filters' word hashes one Python call at a time
    (nfa.word_hash_pair) is the cold-start bottleneck; this produces the
    same (h1, h2, nwords, is_dollar) — plus the word extents the shape
    compiler needs — with a handful of numpy passes.

    Returns (h1, h2, nwords, is_dollar, wstart, wlen); all uint32/int32
    arrays shaped like the device variant's.
    """
    B, MB = bytes_mat.shape
    L = max_levels
    pw1, ipw1, pw2, ipw2 = _pow_tables(MB)
    cols = np.arange(MB, dtype=np.int32)
    inb = cols[None, :] < lengths[:, None]
    c = bytes_mat.astype(np.uint32)
    issep = inb & (bytes_mat == SLASH)
    ischar = inb & ~issep
    segex = np.cumsum(issep, axis=1, dtype=np.int32) - issep.astype(np.int32)
    rows = np.arange(B, dtype=np.int32)[:, None]

    with np.errstate(over="ignore"):
        u1 = np.where(ischar, c * ipw1[cols][None, :], np.uint32(0))
        u2 = np.where(ischar, c * ipw2[cols][None, :], np.uint32(0))
        U1 = np.cumsum(u1, axis=1, dtype=np.uint32)
        U2 = np.cumsum(u2, axis=1, dtype=np.uint32)

        # slot L is the discard bucket (device uses scatter mode="drop");
        # separators past L words clip into it
        sep_slot = np.minimum(np.where(issep, segex, L), L)
        sepcol = np.full((B, L + 1), -1, dtype=np.int32)
        sepcol[rows, sep_slot] = np.broadcast_to(cols[None, :], (B, MB))
        sepcol = sepcol[:, :L]
        k = np.arange(L, dtype=np.int32)[None, :]
        nsep = np.sum(issep, axis=1).astype(np.int32)
        nwords = nsep + 1
        has_sep = sepcol >= 0
        wend = np.where(has_sep, sepcol - 1, lengths[:, None] - 1)
        prev_sep = np.concatenate(
            [np.full((B, 1), -1, dtype=np.int32), sepcol[:, : L - 1]], axis=1
        )
        wstart = prev_sep + 1
        wlen = wend - wstart + 1

        def word_hash(U, pw, salt_mul, salt_add):
            e = np.clip(wend, 0, MB - 1)
            s0 = np.clip(wstart - 1, 0, MB - 1)
            Ue = np.take_along_axis(U, e, axis=1)
            Us = np.where(
                wstart > 0,
                np.take_along_axis(U, s0, axis=1),
                np.uint32(0),
            )
            raw = (Ue - Us) * pw[e] + pw[np.clip(wlen, 0, MB)]
            seed = np.uint32(
                (int(salt) * int(salt_mul) + salt_add) & 0xFFFFFFFF
            )
            x = raw ^ seed
            x ^= x >> np.uint32(16)
            x = x * np.uint32(0x7FEB352D)
            x ^= x >> np.uint32(15)
            x = x * np.uint32(0x846CA68B)
            x ^= x >> np.uint32(16)
            return x

        h1 = word_hash(U1, pw1, int(_SALT1), 1)
        h2 = word_hash(U2, pw2, int(_SALT2), 7)
    valid_word = k < np.minimum(nwords, L)[:, None]
    h1 = np.where(valid_word, h1, np.uint32(0))
    h2 = np.where(valid_word, h2, np.uint32(0))
    is_dollar = (lengths > 0) & (bytes_mat[:, 0] == DOLLAR)
    return h1, h2, nwords, is_dollar, wstart, wlen


def vocab_lookup_device(tables, h1, h2, probes: int = 8):
    """jnp: word hash pairs -> dense symbol ids (-1 = out-of-vocabulary)."""
    import jax.numpy as jnp

    V = tables["vocab_h1"].shape[0]
    mask = jnp.uint32(V - 1)
    h = h1 * jnp.uint32(VOCAB_H_MUL)
    h ^= h >> VOCAB_H_SHIFT
    sym = jnp.full(h1.shape, -1, dtype=jnp.int32)
    found = jnp.zeros(h1.shape, dtype=bool)
    for p in range(probes):
        idx = ((h + jnp.uint32(p)) & mask).astype(jnp.int32)
        th1 = tables["vocab_h1"][idx]
        th2 = tables["vocab_h2"][idx]
        tsym = tables["vocab_sym"][idx]
        hit = (th1 == h1) & (th2 == h2) & (tsym >= 0) & ~found
        sym = jnp.where(hit, tsym, sym)
        found |= hit
    return sym
