"""Filter-shape hash index: the large-table fast path of the route matcher.

The NFA kernel (ops/matcher.py) walks the subscription trie level-by-level
with `frontier x probes` random gathers per topic level. On small tables
that's fast (everything sits in cache), but at 100k+ filters the tables
spill to HBM and TPU random gather throughput becomes the wall (measured:
12k topics/s at 1M filters vs 108M at 1k).

This module exploits the structure of real subscription tables: filters
cluster into a handful of *shapes* — patterns of (literal | +) positions
with an optional trailing '#'. The reference's trie compaction leans on the
same observation (literal runs between wildcards, emqx_trie.erl:201-232);
taken to its TPU-native conclusion, matching becomes:

    for each shape m:  one combined hash over the topic's words at m's
                       literal positions  ->  one table probe

i.e. O(#shapes) hashes + probes per topic, independent of filter count and
topic depth. The per-level word hashes already come out of the device
tokenizer as prefix sums (ops/tokenizer.py); the combined hash is a masked
sum-product over levels — pure VPU work. Only the final table probe touches
HBM, gathering ONE fused 16-byte row per (topic, shape, probe):
~B x M x P rows, vs the NFA's B x L x F x P x 3 scattered words.

Filters whose shape doesn't fit (more than MAX_SHAPES distinct shapes, or
a 2^-64 combined-hash collision) fall back to the residual NFA engine —
correctness never depends on the shape heuristic.

Host-side updates follow the same delta-overlay protocol as NfaBuilder
(epoch / oplog / device_snapshot; see ops/nfa.py) so subscribe/unsubscribe
churn reaches the device as scatters.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from emqx_tpu.ops import topics as T
from emqx_tpu.ops.nfa import MAX_PROBES, _next_pow2, word_hash_pair

_M32 = 0xFFFFFFFF

MAX_SHAPES = 64
MAX_MASK_LEVELS = 32  # literal mask is one int32
# open-addressing probe bound. The DEVICE kernel must probe at least this
# far or host-placed entries at the cluster tail become invisible to it —
# shape_match_device and ShapeIndex._place share this constant.
SHAPE_PROBES = MAX_PROBES

# per-level combining multipliers (odd => bijective mod 2^32) and the
# shape-id fold constants; the device kernel computes the same values
K1_MUL = 0x9E3779B1
K2_MUL = 0x85EBCA77
FOLD1 = 0xC2B2AE35
FOLD2 = 0x27D4EB2F
SLOT_MUL = 0x165667B1
SLOT_SHIFT = 14

TOMB_FID = -2  # tombstoned table slot (fid lane)


def _mix32_np(x):
    """Vectorized `_mix32` (numpy uint32, wraps mod 2^32)."""
    x = x.astype(np.uint32)
    x ^= x >> np.uint32(16)
    x = x * np.uint32(0x7FEB352D)
    x ^= x >> np.uint32(15)
    x = x * np.uint32(0x846CA68B)
    x ^= x >> np.uint32(16)
    return x


def _mix32(x: int) -> int:
    x &= _M32
    x ^= x >> 16
    x = (x * 0x7FEB352D) & _M32
    x ^= x >> 15
    x = (x * 0x846CA68B) & _M32
    x ^= x >> 16
    return x


def level_mul(l: int, which: int) -> int:
    base = K1_MUL if which == 1 else K2_MUL
    return (base * (l + 1) * 2 + 1) & _M32


def combined_pair(words: List[str], mask: int, shape_id: int, salt: int) -> Tuple[int, int]:
    """(c1, c2) for a filter's literal words / a topic probed under a shape."""
    s1 = 0
    s2 = 0
    for l, w in enumerate(words):
        if mask >> l & 1:
            h1, h2 = word_hash_pair(w, salt)
            s1 = (s1 + h1 * level_mul(l, 1)) & _M32
            s2 = (s2 + h2 * level_mul(l, 2)) & _M32
    c1 = _mix32(s1 ^ ((shape_id * FOLD1) & _M32))
    c2 = _mix32(s2 ^ ((shape_id * FOLD2) & _M32))
    return c1, c2


def slot_hash(c1: int) -> int:
    h = (c1 * SLOT_MUL) & _M32
    h ^= h >> SLOT_SHIFT
    return h


def probe_step(c2: int) -> int:
    """Double-hashing probe stride (odd => full cycle mod pow2 capacity).

    Linear probing's clustering makes an 8-probe bound fail thousands of
    placements at 10M entries even at 30% load (forcing capacity
    doublings into the GBs); with a c2-derived stride the probe sequence
    is uniform and P(8 occupied) ~ load^8."""
    return (c2 | 1) & _M32


class ShapeIndex:
    """Incrementally-maintained shape hash index (host side).

    Accepts filters whose (wildcard-shape, combined-hash) fit; `add`
    returns False when the filter must go to the residual NFA engine.
    """

    OPLOG_MAX = 65536

    def __init__(self, salt: int = 0, max_shapes: int = MAX_SHAPES):
        self.salt = salt
        self.max_shapes = max_shapes
        # shape registry: key -> shape id
        self._shape_ids: Dict[Tuple[int, int, bool], int] = {}
        self._shape_refs: List[int] = []
        self._free_shapes: List[int] = []
        # shape meta (fixed capacity; device slices [0:M_active])
        self.arr_shape_mask = np.zeros(max_shapes, np.int32)
        self.arr_shape_len = np.full(max_shapes, -1, np.int32)  # -1 = dead
        self.arr_shape_flags = np.zeros(max_shapes, np.int32)  # 1=#, 2=rootwild
        # filter table: fused [T, 4] int32 (c1, c2, fid, shape_id)
        self._Tcap = 1024
        self.arr_table = np.zeros((self._Tcap, 4), np.int32)
        self.arr_table[:, 2] = -1  # fid lane: -1 empty
        self._fill = 0  # non-empty slots (live + tombstones)
        # filter -> (shape_id, c1, c2, fid); key -> filter for collisions.
        # After a cold bulk load these dicts are materialized LAZILY from
        # the stashed arrays (`_cold`) on first incremental access — dict
        # construction for 10M filters costs ~1min the serving path may
        # never need.
        self._entries_d: Dict[str, Tuple[int, int, int, int]] = {}
        self._by_key_d: Dict[Tuple[int, int], str] = {}
        self._cold = None  # (names, sid_arr, c1_arr, c2_arr, fid_arr)
        self.epoch = 0
        self.oplog: list = []
        self.version = 0

    # -- lazy host mirror --------------------------------------------------
    def _materialize(self) -> None:
        if self._cold is None:
            return
        names, sid, c1, c2, fid = self._cold
        self._cold = None
        sid_l = sid.tolist()
        c1_l = c1.tolist()
        c2_l = c2.tolist()
        fid_l = fid.tolist()
        self._entries_d = dict(zip(names, zip(sid_l, c1_l, c2_l, fid_l)))
        self._by_key_d = dict(zip(zip(c1_l, c2_l), names))
        if len(self._entries_d) != len(names):
            raise RuntimeError("cold bulk load lost entries (dup names?)")

    @property
    def _entries(self) -> Dict[str, Tuple[int, int, int, int]]:
        self._materialize()
        return self._entries_d

    @property
    def _by_key(self) -> Dict[Tuple[int, int], str]:
        self._materialize()
        return self._by_key_d

    # -- delta protocol ----------------------------------------------------
    def _log(self, name: str, idx: int, val: int) -> None:
        self.version += 1
        if len(self.oplog) >= self.OPLOG_MAX:
            self._bump_epoch()
            return
        self.oplog.append((name, int(idx), int(val)))

    def _bump_epoch(self) -> None:
        self.epoch += 1
        self.oplog.clear()
        self.version += 1

    def device_snapshot(self) -> Dict[str, np.ndarray]:
        return {
            # flat view: row-major [T,4] -> [T*4], matching the oplog's
            # flat indices AND avoiding the TPU [_,4] tile-padding blowup
            "shape_tab": self.arr_table.reshape(-1),
            "shape_mask": self.arr_shape_mask,
            "shape_len": self.arr_shape_len,
            "shape_flags": self.arr_shape_flags,
        }

    # -- shape parsing -----------------------------------------------------
    @staticmethod
    def parse_shape(filter_: str) -> Optional[Tuple[int, int, bool, List[str]]]:
        """-> (literal_mask, prefix_len, has_hash, words) or None if unfit."""
        ws = T.words(filter_)
        has_hash = bool(ws) and ws[-1] == "#"
        prefix = ws[:-1] if has_hash else ws
        if len(prefix) > MAX_MASK_LEVELS:
            return None
        mask = 0
        for l, w in enumerate(prefix):
            if w == "#":
                return None  # invalid anyway ('# only last'), but be safe
            if w != "+":
                mask |= 1 << l
        return mask, len(prefix), has_hash, prefix

    # -- mutation ----------------------------------------------------------
    def _shape_for(self, mask: int, plen: int, has_hash: bool) -> Optional[int]:
        key = (mask, plen, has_hash)
        sid = self._shape_ids.get(key)
        if sid is not None:
            self._shape_refs[sid] += 1
            return sid
        if self._free_shapes:
            sid = self._free_shapes.pop()
        elif len(self._shape_refs) < self.max_shapes:
            sid = len(self._shape_refs)
            self._shape_refs.append(0)
        else:
            return None  # shape overflow -> residual
        self._shape_ids[key] = sid
        self._shape_refs[sid] = 1
        rootwild = (plen == 0 and has_hash) or (plen > 0 and not (mask & 1))
        flags = (1 if has_hash else 0) | (2 if rootwild else 0)
        # int32 wrap: a 32-literal-level mask sets bit 31; the device's
        # arithmetic shift + &1 reads bits identically either way
        mask_i32 = int(np.int32(np.uint32(mask)))
        self.arr_shape_mask[sid] = mask_i32
        self._log("shape_mask", sid, mask_i32)
        self.arr_shape_flags[sid] = flags
        self._log("shape_flags", sid, flags)
        self.arr_shape_len[sid] = plen
        self._log("shape_len", sid, plen)
        return sid

    def _shape_release(self, sid: int, key: Tuple[int, int, bool]) -> None:
        self._shape_refs[sid] -= 1
        if self._shape_refs[sid] == 0:
            del self._shape_ids[key]
            self._free_shapes.append(sid)
            self.arr_shape_len[sid] = -1  # dead: never matches
            self._log("shape_len", sid, -1)

    def num_active_shapes(self) -> int:
        """High-water shape id + 1 (device meta slice length)."""
        return len(self._shape_refs)

    def m_active(self, floor: int = 4) -> int:
        """Device meta slice length, pow2-bucketed so the jitted step
        recompiles only on shape-count doublings, clamped to capacity
        (max_shapes need not be a power of two). The single source for
        every shape_route_step caller."""
        return min(
            _next_pow2(max(floor, self.num_active_shapes())),
            self.max_shapes,
        )

    def _place(self, c1: int, c2: int, fid: int, sid: int) -> None:
        # NOTE: the caller has already put the entry in self._entries, so a
        # rehash (which rebuilds from _entries) places it — just return.
        if (self._fill + 1) * 2 > self._Tcap:
            self._rehash(self._Tcap * 2)
            return
        res = self._cuckoo_walk(self.arr_table, self._Tcap, (c1, c2, fid, sid))
        if res is None:
            self._rehash(self._Tcap * 2)
            return
        writes, was_empty = res
        if was_empty:
            # _fill counts non-empty slots; a walk converts exactly ONE
            # slot from empty/tombstone to live (displacements only move
            # live entries between live slots)
            self._fill += 1
        for idx, row in writes:
            base = idx * 4
            for lane in range(4):
                self._log("shape_tab", base + lane, int(row[lane]))

    @staticmethod
    def _probe_positions(c1: int, c2: int, Tcap: int):
        home = slot_hash(c1)
        step = probe_step(c2)
        return [(home + p * step) & (Tcap - 1) for p in range(MAX_PROBES)]

    @staticmethod
    def _cuckoo_walk(tab, Tcap: int, entry, max_kicks: int = 512):
        """Place `entry` = (c1u32, c2u32, fid, sid) into `tab` [T,4] i32,
        displacing resident entries among THEIR OWN probe positions when
        every position of the current entry is full (random-walk cuckoo
        with MAX_PROBES choices). Lookup correctness only needs each
        entry to sit at one of its probe positions, so displacement is
        invisible to readers. Returns (writes, terminal_was_empty) where
        `writes` is the list of (slot, row4) applied — or None when the
        walk exceeds max_kicks (caller doubles the table).
        """
        writes = []
        c1, c2, fid, sid = entry
        seed = c1
        for _kick in range(max_kicks):
            pos = ShapeIndex._probe_positions(
                int(np.uint32(c1)), int(np.uint32(c2)), Tcap
            )
            row = np.array(
                [np.int32(np.uint32(c1)), np.int32(np.uint32(c2)), fid, sid],
                np.int32,
            )
            for idx in pos:
                f = tab[idx, 2]
                if f == -1 or f == TOMB_FID:
                    tab[idx] = row
                    writes.append((idx, row))
                    return writes, f == -1
            # all positions full: evict a deterministic pseudo-random one
            seed = _mix32(seed ^ (_kick * 0x9E3779B1))
            vidx = pos[seed % MAX_PROBES]
            victim = tab[vidx].copy()
            tab[vidx] = row
            writes.append((vidx, row))
            c1 = int(np.uint32(victim[0]))
            c2 = int(np.uint32(victim[1]))
            fid = int(victim[2])
            sid = int(victim[3])
        return None

    @staticmethod
    def _build_table(sid, c1, c2, fid, newT: int):
        """Vectorized double-hash placement -> (tab [T,4] i32, T).

        Any placement within MAX_PROBES along an entry's (home, stride)
        probe sequence is valid for lookup (host and device walk the same
        sequence), so placement runs in probe ROUNDS: in round p every
        still-unplaced entry bids for home + p*stride, first bidder per
        empty slot wins. The tail left after MAX_PROBES rounds (~load^8
        of the batch) is placed by cuckoo displacement; only if a walk
        fails does the table double.
        """
        n = len(sid)
        with np.errstate(over="ignore"):
            home = c1 * np.uint32(SLOT_MUL)
            home = home ^ (home >> np.uint32(SLOT_SHIFT))
            step = c2 | np.uint32(1)
        while True:
            tab = np.zeros((newT, 4), np.int32)
            tab[:, 2] = -1
            unplaced = np.arange(n)
            for p in range(MAX_PROBES):
                if not len(unplaced):
                    break
                with np.errstate(over="ignore"):
                    idx = (
                        home[unplaced] + np.uint32(p) * step[unplaced]
                    ) & np.uint32(newT - 1)
                idx = idx.astype(np.int64)
                free = tab[idx, 2] == -1
                cand = unplaced[free]
                cidx = idx[free]
                # first bidder per distinct empty slot wins this round
                _, first = np.unique(cidx, return_index=True)
                win, widx = cand[first], cidx[first]
                tab[widx, 0] = c1[win].view(np.int32)
                tab[widx, 1] = c2[win].view(np.int32)
                tab[widx, 2] = fid[win]
                tab[widx, 3] = sid[win]
                placed_mask = np.zeros(n, bool)
                placed_mask[win] = True
                unplaced = unplaced[~placed_mask[unplaced]]
            ok = True
            for i in unplaced.tolist():
                if (
                    ShapeIndex._cuckoo_walk(
                        tab,
                        newT,
                        (int(c1[i]), int(c2[i]), int(fid[i]), int(sid[i])),
                    )
                    is None
                ):
                    ok = False
                    break
            if ok:
                return tab, newT
            newT *= 2

    def _rehash(self, newT: int) -> None:
        """Rebuild the table from `_entries` (vectorized placement)."""
        ents = list(self._entries.values())
        n = len(ents)
        if n == 0:
            tab = np.zeros((newT, 4), np.int32)
            tab[:, 2] = -1
            self._Tcap = newT
            self.arr_table = tab
            self._fill = 0
            self._bump_epoch()
            return
        sid = np.array([e[0] for e in ents], np.int64)
        c1 = np.array([e[1] & 0xFFFFFFFF for e in ents], np.uint32)
        c2 = np.array([e[2] & 0xFFFFFFFF for e in ents], np.uint32)
        fid = np.array([e[3] for e in ents], np.int64)
        tab, newT = self._build_table(sid, c1, c2, fid, newT)
        self._Tcap = newT
        self.arr_table = tab
        self._fill = n
        self._bump_epoch()

    def add(self, filter_: str, fid: int) -> bool:
        """Index this filter under `fid`. False => caller routes it to the
        residual NFA engine (shape overflow or hash collision)."""
        parsed = self.parse_shape(filter_)
        if parsed is None:
            return False
        mask, plen, has_hash, prefix = parsed
        sid = self._shape_for(mask, plen, has_hash)
        if sid is None:
            return False
        c1, c2 = combined_pair(prefix, mask, sid, self.salt)
        other = self._by_key.get((c1, c2))
        if other is not None and other != filter_:
            # true 64-bit collision between distinct filters: residual
            self._shape_release(sid, (mask, plen, has_hash))
            return False
        self._by_key[(c1, c2)] = filter_
        self._entries[filter_] = (sid, c1, c2, fid)
        self._place(c1, c2, fid, sid)
        return True

    def bulk_add_cold(
        self,
        names: List[str],
        fids: np.ndarray,
        masks: np.ndarray,
        plens: np.ndarray,
        hhs: np.ndarray,
        s1: np.ndarray,
        s2: np.ndarray,
        unfit: np.ndarray,
    ) -> List[Tuple[str, int]]:
        """Fully-vectorized cold-start insert (empty index only).

        The caller (RouteIndex._bulk_add_cold) has already tokenized the
        DISTINCT filters and reduced each to its shape signature
        (masks/plens/hhs) and pre-fold combined sums (s1/s2 — the masked
        sum-products WITHOUT the shape-id fold, which is applied here once
        shape ids are assigned). `unfit` marks rows parse_shape would
        reject. Returns the rejected (filter, fid) pairs, in input order,
        for the residual engine. Bit-identical to repeated `add`.
        """
        assert not self._entries, "bulk_add_cold requires an empty index"
        n = len(names)
        rej = np.zeros(n, dtype=bool)
        rej |= unfit
        # -- shape registration (first-occurrence order, like add) -------
        key = (
            (masks.astype(np.uint64) << np.uint64(8))
            | (plens.astype(np.uint64) << np.uint64(1))
            | hhs.astype(np.uint64)
        )
        key[unfit] = np.uint64(0xFFFFFFFFFFFFFFFF)
        uq_key, first_idx, inv = np.unique(
            key, return_index=True, return_inverse=True
        )
        order = np.argsort(first_idx, kind="stable")
        sid_of_group = np.full(len(uq_key), -1, dtype=np.int64)
        group_counts = np.bincount(inv, minlength=len(uq_key))
        for g in order.tolist():
            i = int(first_idx[g])
            if unfit[i]:
                continue
            sid = self._shape_for(int(masks[i]), int(plens[i]), bool(hhs[i]))
            if sid is None:
                continue  # shape overflow -> whole family is residual
            sid_of_group[g] = sid
            self._shape_refs[sid] += int(group_counts[g]) - 1
        sids = sid_of_group[inv]
        rej |= sids < 0
        # -- combined hashes (sid fold applied post-registration) --------
        with np.errstate(over="ignore"):
            su = sids.astype(np.uint32)
            c1 = _mix32_np(s1 ^ (su * np.uint32(FOLD1)))
            c2 = _mix32_np(s2 ^ (su * np.uint32(FOLD2)))
        # -- 64-bit key collisions: first (by input order) wins ----------
        fit_idx = np.nonzero(~rej)[0]
        ckey = (c1[fit_idx].astype(np.uint64) << np.uint64(32)) | c2[
            fit_idx
        ].astype(np.uint64)
        srt = np.argsort(ckey, kind="stable")  # stable => input order
        dup = np.zeros(len(ckey), dtype=bool)
        dup[srt[1:]] = ckey[srt[1:]] == ckey[srt[:-1]]
        for i in fit_idx[dup].tolist():
            # true 64-bit collision between distinct filters: residual
            self._shape_release(
                int(sids[i]),
                (int(masks[i]), int(plens[i]), bool(hhs[i])),
            )
            rej[i] = True
        # -- vectorized placement ----------------------------------------
        keep = np.nonzero(~rej)[0]
        newT = self._Tcap
        while (len(keep) + 1) * 2 > newT:
            newT *= 2
        tab, newT = self._build_table(
            sids[keep], c1[keep], c2[keep], fids[keep], newT
        )
        self._Tcap = newT
        self.arr_table = tab
        self._fill = len(keep)
        # -- host mirror (lazy: arrays stashed, dicts on first access) ----
        if rej.any():
            keep_names = [names[i] for i in keep.tolist()]
            self._cold = (
                keep_names, sids[keep], c1[keep], c2[keep], fids[keep]
            )
            rej_idx = np.nonzero(rej)[0].tolist()
            out = [(names[i], int(fids[i])) for i in rej_idx]
        else:
            self._cold = (names, sids, c1, c2, fids)
            out = []
        self._bump_epoch()
        return out

    def bulk_add(self, entries: List[Tuple[str, int]]) -> List[Tuple[str, int]]:
        """Vectorized insert of many (filter, fid) pairs; returns the
        REJECTED pairs (shape overflow / hash collision / unparseable) the
        caller must route to the residual engine.

        The cold-start path (restore 10M subscriptions): per-level word
        hashes come from the numpy mirror of the device tokenizer in one
        pass, combined hashes and table placement are vectorized; results
        are bit-identical to repeated `add` calls. Ends with an epoch bump
        (one full device upload) instead of millions of op-log entries.
        """
        from emqx_tpu.ops.tokenizer import encode_topics, tokenize_host_np

        rejected: List[Tuple[str, int]] = []
        metas = []  # (filter, fid, sid, key=(mask, plen, has_hash))
        raw: List[str] = []
        for f, fid in entries:
            parsed = self.parse_shape(f)
            if parsed is None:
                rejected.append((f, fid))
                continue
            mask, plen, has_hash, _prefix = parsed
            sid = self._shape_for(mask, plen, has_hash)
            if sid is None:
                rejected.append((f, fid))
                continue
            metas.append((f, fid, sid, (mask, plen, has_hash)))
            raw.append(f)
        if not metas:
            return rejected
        L = MAX_MASK_LEVELS
        # row width sized to the actual data (so every row fits by
        # construction) and rows processed in blocks: a fixed 8*L width at
        # 1M+ filters costs GBs of cumsum intermediates
        maxlen = max(16, max(len(f.encode()) for f in raw))
        width = 1 << (maxlen - 1).bit_length()
        masks = np.array([m[3][0] for m in metas], dtype=np.int64)
        sids = np.array([m[2] for m in metas], dtype=np.uint32)
        k1 = np.array([level_mul(l, 1) for l in range(L)], dtype=np.uint32)
        k2 = np.array([level_mul(l, 2) for l in range(L)], dtype=np.uint32)
        lvls = np.arange(L)[None, :]
        n = len(raw)
        c1s = np.empty(n, np.uint32)
        c2s = np.empty(n, np.uint32)
        BLOCK = 1 << 18
        with np.errstate(over="ignore"):
            for lo in range(0, n, BLOCK):
                hi = min(lo + BLOCK, n)
                mat, lens, _tl = encode_topics(raw[lo:hi], width)
                h1, h2, _nw, _dl, _ws, _wl = tokenize_host_np(
                    mat, lens, self.salt, L
                )
                lb = ((masks[lo:hi, None] >> lvls) & 1).astype(np.uint32)
                s1 = np.sum(h1 * k1[None, :] * lb, axis=1, dtype=np.uint32)
                s2 = np.sum(h2 * k2[None, :] * lb, axis=1, dtype=np.uint32)
                c1s[lo:hi] = _mix32_np(s1 ^ (sids[lo:hi] * np.uint32(FOLD1)))
                c2s[lo:hi] = _mix32_np(s2 ^ (sids[lo:hi] * np.uint32(FOLD2)))
        # grow once to the final load factor
        need = len(self._entries) + len(metas)
        newT = self._Tcap
        while (need + 1) * 2 > newT:
            newT *= 2
        for i, (f, fid, sid, key) in enumerate(metas):
            c1, c2 = int(c1s[i]), int(c2s[i])
            other = self._by_key.get((c1, c2))
            if other is not None and other != f:
                self._shape_release(sid, key)
                rejected.append((f, fid))
                continue
            self._by_key[(c1, c2)] = f
            self._entries[f] = (sid, c1, c2, fid)
        self._rehash(newT)  # places everything; bumps epoch once
        return rejected

    def remove(self, filter_: str) -> bool:
        ent = self._entries.pop(filter_, None)
        if ent is None:
            return False
        sid, c1, c2, _fid = ent
        self._by_key.pop((c1, c2), None)
        slot = slot_hash(c1)
        step = probe_step(c2)
        cc1, cc2 = np.int32(np.uint32(c1)), np.int32(np.uint32(c2))
        for p in range(MAX_PROBES):
            idx = (slot + p * step) & (self._Tcap - 1)
            if (
                self.arr_table[idx, 2] >= 0
                and self.arr_table[idx, 0] == cc1
                and self.arr_table[idx, 1] == cc2
            ):
                self.arr_table[idx, 2] = TOMB_FID
                self._log("shape_tab", idx * 4 + 2, TOMB_FID)
                break
        parsed = self.parse_shape(filter_)
        if parsed is not None:
            mask, plen, has_hash, _ = parsed
            self._shape_release(sid, (mask, plen, has_hash))
        if (self._fill - len(self._entries)) * 4 > self._Tcap:
            self._rehash(self._Tcap)  # compact tombstones in place
        return True

    def rebuild(self, salt: int) -> List[Tuple[str, int]]:
        """Salt changed (vocab collision in the residual engine): recompute
        every combined hash and rebuild the table. Rare by construction.

        Returns [(filter, fid)] EVICTED because their new combined hash
        collides with another filter's — `add` enforces key uniqueness, so
        rebuild must too or the first-probe-wins device lookup would
        silently drop one of the pair. The caller (RouteIndex) re-homes
        evictees in the residual NFA engine.
        """
        self.salt = salt
        entries = list(self._entries.items())
        self._by_key.clear()
        evicted: List[Tuple[str, int]] = []
        for f, (sid, _c1, _c2, fid) in entries:
            parsed = self.parse_shape(f)
            mask, plen, has_hash, prefix = parsed
            c1, c2 = combined_pair(prefix, mask, sid, salt)
            if (c1, c2) in self._by_key:
                del self._entries[f]
                self._shape_release(sid, (mask, plen, has_hash))
                evicted.append((f, fid))
                continue
            self._entries[f] = (sid, c1, c2, fid)
            self._by_key[(c1, c2)] = f
        self._rehash(self._Tcap)
        return evicted

    def __len__(self) -> int:
        if self._cold is not None:
            return len(self._entries_d) + len(self._cold[0])
        return len(self._entries_d)


# -- device kernel ---------------------------------------------------------


def shape_match_device(
    tables, m_active: int, h1, h2, nwords, dollar, probes: int = SHAPE_PROBES
):
    """Match tokenized topics against the shape index. Jit-traceable.

    tables: device dict (shape_tab FLAT [T*4] i32 — kept one-dimensional
    because a [T, 4] s32 operand pads its minor dim 4 -> 128 under TPU
    tiling, a 32x HBM expansion that OOMs at 10M-filter scale;
    shape_mask/len/flags [Mcap])
    h1, h2: uint32 [B, L] per-level word hashes; nwords [B]; dollar [B]
    -> matched fid int32 [B, M] (-1 = no match; SPARSE, not compacted)
    """
    import jax
    import jax.numpy as jnp

    B, L = h1.shape
    M = m_active
    mask = tables["shape_mask"][:M]  # [M]
    plen = tables["shape_len"][:M]
    flags = tables["shape_flags"][:M]
    tab = tables["shape_tab"]  # [T*4] flat row-major
    Tcap = tab.shape[0] // 4

    lvl = jnp.arange(L, dtype=jnp.int32)
    lvl_bit = (mask[None, :] >> lvl[:, None]) & 1  # [L, M]
    k1 = jnp.asarray(
        [level_mul(int(l), 1) for l in range(L)], dtype=jnp.uint32
    )
    k2 = jnp.asarray(
        [level_mul(int(l), 2) for l in range(L)], dtype=jnp.uint32
    )
    w1 = k1[:, None] * lvl_bit.astype(jnp.uint32)  # [L, M]
    w2 = k2[:, None] * lvl_bit.astype(jnp.uint32)
    # masked sum-product over levels (uint32 wrap = mod 2^32)
    s1 = jnp.sum(h1[:, :, None] * w1[None, :, :], axis=1, dtype=jnp.uint32)
    s2 = jnp.sum(h2[:, :, None] * w2[None, :, :], axis=1, dtype=jnp.uint32)
    sid = jnp.arange(M, dtype=jnp.uint32)
    c1 = _mix32_dev(s1 ^ (sid[None, :] * jnp.uint32(FOLD1)))
    c2 = _mix32_dev(s2 ^ (sid[None, :] * jnp.uint32(FOLD2)))

    has_hash = (flags & 1) != 0
    rootwild = (flags & 2) != 0
    live = plen >= 0
    nw = nwords[:, None]
    ok_len = jnp.where(has_hash[None, :], nw >= plen[None, :], nw == plen[None, :])
    valid = ok_len & live[None, :] & ~(dollar[:, None] & rootwild[None, :])

    c1i = jax.lax.bitcast_convert_type(c1, jnp.int32)
    c2i = jax.lax.bitcast_convert_type(c2, jnp.int32)
    slot = c1 * jnp.uint32(SLOT_MUL)
    slot = slot ^ (slot >> SLOT_SHIFT)
    step = c2 | jnp.uint32(1)  # double-hash stride (see probe_step)
    fid = jnp.full((B, M), -1, dtype=jnp.int32)
    found = jnp.zeros((B, M), dtype=bool)
    tmask = jnp.uint32(Tcap - 1)
    for p in range(probes):
        idx = ((slot + jnp.uint32(p) * step) & tmask).astype(jnp.int32)
        base4 = idx * 4  # flat row offset (4 x 1D gathers: the 2D form
        # would force the 32x-padded [T,4] layout back into HBM)
        r_c1 = tab[base4]
        r_c2 = tab[base4 + 1]
        r_fid = tab[base4 + 2]
        r_sid = tab[base4 + 3]
        hit = (
            (r_c1 == c1i)
            & (r_c2 == c2i)
            & (r_sid == jnp.arange(M, dtype=jnp.int32)[None, :])
            & (r_fid >= 0)
            & valid
            & ~found
        )
        fid = jnp.where(hit, r_fid, fid)
        found |= hit
    return fid


def _mix32_dev(x):
    import jax.numpy as jnp

    x ^= x >> 16
    x = x * jnp.uint32(0x7FEB352D)
    x ^= x >> 15
    x = x * jnp.uint32(0x846CA68B)
    x ^= x >> 16
    return x
