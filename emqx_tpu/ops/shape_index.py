"""Filter-shape hash index: the large-table fast path of the route matcher.

The NFA kernel (ops/matcher.py) walks the subscription trie level-by-level
with `frontier x probes` random gathers per topic level. On small tables
that's fast (everything sits in cache), but at 100k+ filters the tables
spill to HBM and TPU random gather throughput becomes the wall (measured:
12k topics/s at 1M filters vs 108M at 1k).

This module exploits the structure of real subscription tables: filters
cluster into a handful of *shapes* — patterns of (literal | +) positions
with an optional trailing '#'. The reference's trie compaction leans on the
same observation (literal runs between wildcards, emqx_trie.erl:201-232);
taken to its TPU-native conclusion, matching becomes:

    for each shape m:  one combined hash over the topic's words at m's
                       literal positions  ->  one table probe

i.e. O(#shapes) hashes + probes per topic, independent of filter count and
topic depth. The per-level word hashes already come out of the device
tokenizer as prefix sums (ops/tokenizer.py); the combined hash is a masked
sum-product over levels — pure VPU work. Only the final table probe touches
HBM, gathering ONE fused 16-byte row per (topic, shape, probe):
~B x M x P rows, vs the NFA's B x L x F x P x 3 scattered words.

Filters whose shape doesn't fit (more than MAX_SHAPES distinct shapes, or
a 2^-64 combined-hash collision) fall back to the residual NFA engine —
correctness never depends on the shape heuristic.

Host-side updates follow the same delta-overlay protocol as NfaBuilder
(epoch / oplog / device_snapshot; see ops/nfa.py) so subscribe/unsubscribe
churn reaches the device as scatters.

Update-path segmentation (docs/update_path.md): the PACKED table
(`arr_table`) is written only by rebuilds — cold bulk loads and
compaction. Incremental subscribes land in a small append-only **hot
segment** (`arr_hot`, an open-addressing table probed with the same
slot_hash/probe_step sequence), so a subscribe is O(1) host writes plus
one device scatter, never an O(table) rehash; unsubscribes of packed
entries set a bit in a **tombstone mask** (`arr_tomb`) instead of
touching the row. The device kernel matches against
``packed ∪ hot − tombstones`` in the same single launch, and a
background compaction (`ops/segments.SegmentCompactor`) periodically
merges the hot segment into a rebuilt packed table off the critical
path, replaying the mutations that raced the build from a journal.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from emqx_tpu.ops import topics as T
from emqx_tpu.ops.nfa import MAX_PROBES, _next_pow2, word_hash_pair

_M32 = 0xFFFFFFFF

MAX_SHAPES = 64
MAX_MASK_LEVELS = 32  # literal mask is one int32
# open-addressing probe bound. The DEVICE kernel must probe at least this
# far or host-placed entries at the cluster tail become invisible to it —
# shape_match_device and ShapeIndex._place share this constant.
SHAPE_PROBES = MAX_PROBES

# per-level combining multipliers (odd => bijective mod 2^32) and the
# shape-id fold constants; the device kernel computes the same values
K1_MUL = 0x9E3779B1
K2_MUL = 0x85EBCA77
FOLD1 = 0xC2B2AE35
FOLD2 = 0x27D4EB2F
SLOT_MUL = 0x165667B1
SLOT_SHIFT = 14

TOMB_FID = -2  # tombstoned table slot (fid lane)


def _mix32_np(x):
    """Vectorized `_mix32` (numpy uint32, wraps mod 2^32)."""
    x = x.astype(np.uint32)
    x ^= x >> np.uint32(16)
    x = x * np.uint32(0x7FEB352D)
    x ^= x >> np.uint32(15)
    x = x * np.uint32(0x846CA68B)
    x ^= x >> np.uint32(16)
    return x


def _mix32(x: int) -> int:
    x &= _M32
    x ^= x >> 16
    x = (x * 0x7FEB352D) & _M32
    x ^= x >> 15
    x = (x * 0x846CA68B) & _M32
    x ^= x >> 16
    return x


def level_mul(l: int, which: int) -> int:
    base = K1_MUL if which == 1 else K2_MUL
    return (base * (l + 1) * 2 + 1) & _M32


def combined_pair(words: List[str], mask: int, shape_id: int, salt: int) -> Tuple[int, int]:
    """(c1, c2) for a filter's literal words / a topic probed under a shape."""
    s1 = 0
    s2 = 0
    for l, w in enumerate(words):
        if mask >> l & 1:
            h1, h2 = word_hash_pair(w, salt)
            s1 = (s1 + h1 * level_mul(l, 1)) & _M32
            s2 = (s2 + h2 * level_mul(l, 2)) & _M32
    c1 = _mix32(s1 ^ ((shape_id * FOLD1) & _M32))
    c2 = _mix32(s2 ^ ((shape_id * FOLD2) & _M32))
    return c1, c2


def slot_hash(c1: int) -> int:
    h = (c1 * SLOT_MUL) & _M32
    h ^= h >> SLOT_SHIFT
    return h


def probe_step(c2: int) -> int:
    """Double-hashing probe stride (odd => full cycle mod pow2 capacity).

    Linear probing's clustering makes an 8-probe bound fail thousands of
    placements at 10M entries even at 30% load (forcing capacity
    doublings into the GBs); with a c2-derived stride the probe sequence
    is uniform and P(8 occupied) ~ load^8."""
    return (c2 | 1) & _M32


class ShapeIndex:
    """Incrementally-maintained shape hash index (host side).

    Accepts filters whose (wildcard-shape, combined-hash) fit; `add`
    returns False when the filter must go to the residual NFA engine.
    """

    OPLOG_MAX = 65536
    HOT_MIN = 256  # initial/minimum hot-segment capacity (pow2)
    # largest hot-segment population a warm bulk_add may leave behind;
    # bigger loads take the classic packed rebuild (they are restore-
    # scale, already epoch-bump territory)
    HOT_ABSORB_MAX = 1 << 17

    def __init__(self, salt: int = 0, max_shapes: int = MAX_SHAPES):
        self.salt = salt
        self.max_shapes = max_shapes
        # shape registry: key -> shape id
        self._shape_ids: Dict[Tuple[int, int, bool], int] = {}
        self._shape_refs: List[int] = []
        self._free_shapes: List[int] = []
        # shape meta (fixed capacity; device slices [0:M_active])
        self.arr_shape_mask = np.zeros(max_shapes, np.int32)
        self.arr_shape_len = np.full(max_shapes, -1, np.int32)  # -1 = dead
        self.arr_shape_flags = np.zeros(max_shapes, np.int32)  # 1=#, 2=rootwild
        # PACKED filter table: fused [T, 4] int32 (c1, c2, fid, shape_id);
        # written only by rebuilds (cold bulk load / compaction)
        self._Tcap = 1024
        self.arr_table = np.zeros((self._Tcap, 4), np.int32)
        self.arr_table[:, 2] = -1  # fid lane: -1 empty
        self._fill = 0  # non-empty slots (live + tombstones)
        # packed-row tombstone mask: bit i set => packed slot i is dead.
        # Unsubscribe flips ONE bit (one device scatter word) instead of
        # rewriting the row; compaction purges the mask.
        self.arr_tomb = np.zeros(self._Tcap // 32, np.uint32)
        self._tombs = 0  # tombstoned packed slots
        # HOT segment: same fused [H, 4] layout + probe sequence as the
        # packed table, but small and append-only between compactions.
        # Every incremental add lands here — the packed table never
        # rehashes on the subscribe path.
        self._Hcap = self.HOT_MIN
        self.arr_hot = np.zeros((self._Hcap, 4), np.int32)
        self.arr_hot[:, 2] = -1
        self._hot_fill = 0  # non-empty hot slots (live + tombstones)
        self._hot_tombs = 0
        self._in_hot: set = set()  # filters currently living in hot
        # compaction bookkeeping: a capture is valid while no structural
        # rebuild (_rehash / cold load) happened; mutations racing an
        # outstanding build are journaled and replayed at apply
        self._structure_gen = 0
        self._journal: Optional[list] = None  # single-writer: loop
        # The packed/hot arrays ARE the host mirror: an entry's
        # (c1, c2) recomputes from its filter string (shape registry +
        # salt) and its row is found by the same probe walk the device
        # runs — no 10M-entry shadow dicts, so nothing materializes on
        # the first post-restore subscribe/unsubscribe (the dict version
        # cost a ~30s one-shot stall there). Name recovery for the rare
        # salt rebuild goes through `resolve_name` (fid -> filter; set
        # by RouteIndex to its registry lookup).
        self.resolve_name: Optional[Callable[[int], Optional[str]]] = None
        self.epoch = 0
        self.oplog: list = []
        self.version = 0

    # -- host probe mirror -------------------------------------------------
    def _find_live(self, c1: int, c2: int):
        """Locate the LIVE row holding (c1, c2): -> (in_hot, idx, fid,
        sid) or None. Walks the same (home, stride) probe sequence as
        the device kernel — hot segment first, then the packed table
        with its tombstone mask."""
        cc1 = np.int32(np.uint32(c1))
        cc2 = np.int32(np.uint32(c2))
        slot = slot_hash(c1)
        step = probe_step(c2)
        hot = self.arr_hot
        for p in range(MAX_PROBES):
            idx = (slot + p * step) & (self._Hcap - 1)
            if (
                hot[idx, 2] >= 0
                and hot[idx, 0] == cc1
                and hot[idx, 1] == cc2
            ):
                return True, idx, int(hot[idx, 2]), int(hot[idx, 3])
        tab = self.arr_table
        for p in range(MAX_PROBES):
            idx = (slot + p * step) & (self._Tcap - 1)
            if (
                tab[idx, 2] >= 0
                and tab[idx, 0] == cc1
                and tab[idx, 1] == cc2
                and not (self.arr_tomb[idx >> 5] >> (idx & 31)) & 1
            ):
                return False, idx, int(tab[idx, 2]), int(tab[idx, 3])
        return None

    def _find_live_batch(self, c1s: np.ndarray, c2s: np.ndarray):
        """Vectorized `_find_live` existence test for a batch of
        (c1, c2) pairs (uint32 arrays) -> bool [n]. One probe-round
        sweep over the hot segment and the packed table."""
        n = len(c1s)
        with np.errstate(over="ignore"):
            home = c1s * np.uint32(SLOT_MUL)
            home = home ^ (home >> np.uint32(SLOT_SHIFT))
            step = c2s | np.uint32(1)
        cc1 = c1s.view(np.int32)
        cc2 = c2s.view(np.int32)
        found = np.zeros(n, bool)
        hot, Hm = self.arr_hot, np.uint32(self._Hcap - 1)
        tab, Tm = self.arr_table, np.uint32(self._Tcap - 1)
        with np.errstate(over="ignore"):
            for p in range(MAX_PROBES):
                idx = ((home + np.uint32(p) * step) & Hm).astype(np.int64)
                row = hot[idx]
                found |= (
                    (row[:, 2] >= 0)
                    & (row[:, 0] == cc1)
                    & (row[:, 1] == cc2)
                )
            for p in range(MAX_PROBES):
                idx = ((home + np.uint32(p) * step) & Tm).astype(np.int64)
                row = tab[idx]
                alive = (row[:, 2] >= 0) & (
                    (
                        (self.arr_tomb[idx >> 5] >> (idx & 31).astype(
                            np.uint32
                        ))
                        & np.uint32(1)
                    )
                    == 0
                )
                found |= alive & (row[:, 0] == cc1) & (row[:, 1] == cc2)
        return found

    def _ent_of(self, filter_: str):
        """Recompute `filter_`'s entry from live state: -> (sid, c1, c2,
        fid) or None when absent. The shape registry lookup is read-only
        (no ref bump)."""
        parsed = self.parse_shape(filter_)
        if parsed is None:
            return None
        mask, plen, has_hash, prefix = parsed
        sid = self._shape_ids.get((mask, plen, has_hash))
        if sid is None:
            return None
        c1, c2 = combined_pair(prefix, mask, sid, self.salt)
        found = self._find_live(c1, c2)
        if found is None:
            return None
        _in_hot, _idx, fid, row_sid = found
        if row_sid != sid:
            return None  # foreign row (collision shadow): not ours
        if self.resolve_name is not None:
            owner = self.resolve_name(fid)
            if owner is not None and owner != filter_:
                return None  # 64-bit collision: the live row is another's
        return sid, c1, c2, fid

    def _live_rows(self, with_hot: bool = True) -> np.ndarray:
        """All live rows [(c1, c2, fid, sid)] as an int32 [n, 4] matrix:
        packed minus tombstones, plus (optionally) the hot segment."""
        idx = np.nonzero(self.arr_table[:, 2] >= 0)[0]
        tword = self.arr_tomb[idx >> 5]
        dead = (tword >> (idx & 31).astype(np.uint32)) & np.uint32(1)
        rows = [self.arr_table[idx[dead == 0]]]
        if with_hot:
            rows.append(self.arr_hot[self.arr_hot[:, 2] >= 0])
        return np.concatenate(rows, axis=0)

    # -- delta protocol ----------------------------------------------------
    def _log(self, name: str, idx: int, val: int) -> None:
        self.version += 1
        if len(self.oplog) >= self.OPLOG_MAX:
            self._bump_epoch()
            return
        self.oplog.append((name, int(idx), int(val)))

    def _bump_epoch(self) -> None:
        self.epoch += 1
        self.oplog.clear()
        self.version += 1

    def _log_resync(self, name: str) -> None:
        """Per-array resync marker: consumers re-upload ONLY `name`
        (DeviceSegmentManager) — the big packed table never rides along
        with a hot-segment rebuild."""
        self.version += 1
        if len(self.oplog) >= self.OPLOG_MAX:
            self._bump_epoch()
            return
        from emqx_tpu.ops.segments import RESYNC

        self.oplog.append((RESYNC, name, 0))

    def device_snapshot(self) -> Dict[str, np.ndarray]:
        return {
            # flat view: row-major [T,4] -> [T*4], matching the oplog's
            # flat indices AND avoiding the TPU [_,4] tile-padding blowup
            "shape_tab": self.arr_table.reshape(-1),
            "shape_hot": self.arr_hot.reshape(-1),
            "shape_tomb": self.arr_tomb,
            "shape_mask": self.arr_shape_mask,
            "shape_len": self.arr_shape_len,
            "shape_flags": self.arr_shape_flags,
        }

    # -- segment status (metrics / compaction triggers) --------------------
    @property
    def hot_live(self) -> int:
        return self._hot_fill - self._hot_tombs

    @property
    def hot_capacity(self) -> int:
        return self._Hcap

    @property
    def packed_tombstones(self) -> int:
        return self._tombs

    # -- shape parsing -----------------------------------------------------
    @staticmethod
    def parse_shape(filter_: str) -> Optional[Tuple[int, int, bool, List[str]]]:
        """-> (literal_mask, prefix_len, has_hash, words) or None if unfit."""
        ws = T.words(filter_)
        has_hash = bool(ws) and ws[-1] == "#"
        prefix = ws[:-1] if has_hash else ws
        if len(prefix) > MAX_MASK_LEVELS:
            return None
        mask = 0
        for l, w in enumerate(prefix):
            if w == "#":
                return None  # invalid anyway ('# only last'), but be safe
            if w != "+":
                mask |= 1 << l
        return mask, len(prefix), has_hash, prefix

    # -- mutation ----------------------------------------------------------
    def _shape_for(self, mask: int, plen: int, has_hash: bool) -> Optional[int]:
        key = (mask, plen, has_hash)
        sid = self._shape_ids.get(key)
        if sid is not None:
            self._shape_refs[sid] += 1
            return sid
        if self._free_shapes:
            sid = self._free_shapes.pop()
        elif len(self._shape_refs) < self.max_shapes:
            sid = len(self._shape_refs)
            self._shape_refs.append(0)
        else:
            return None  # shape overflow -> residual
        self._shape_ids[key] = sid
        self._shape_refs[sid] = 1
        rootwild = (plen == 0 and has_hash) or (plen > 0 and not (mask & 1))
        flags = (1 if has_hash else 0) | (2 if rootwild else 0)
        # int32 wrap: a 32-literal-level mask sets bit 31; the device's
        # arithmetic shift + &1 reads bits identically either way
        mask_i32 = int(np.int32(np.uint32(mask)))
        self.arr_shape_mask[sid] = mask_i32
        self._log("shape_mask", sid, mask_i32)
        self.arr_shape_flags[sid] = flags
        self._log("shape_flags", sid, flags)
        self.arr_shape_len[sid] = plen
        self._log("shape_len", sid, plen)
        return sid

    def _shape_release(self, sid: int, key: Tuple[int, int, bool]) -> None:
        self._shape_refs[sid] -= 1
        if self._shape_refs[sid] == 0:
            del self._shape_ids[key]
            self._free_shapes.append(sid)
            self.arr_shape_len[sid] = -1  # dead: never matches
            self._log("shape_len", sid, -1)

    def num_active_shapes(self) -> int:
        """High-water shape id + 1 (device meta slice length)."""
        return len(self._shape_refs)

    def m_active(self, floor: int = 4) -> int:
        """Device meta slice length, pow2-bucketed so the jitted step
        recompiles only on shape-count doublings, clamped to capacity
        (max_shapes need not be a power of two). The single source for
        every shape_route_step caller."""
        return min(
            _next_pow2(max(floor, self.num_active_shapes())),
            self.max_shapes,
        )

    def _place_hot(self, filter_: str, c1: int, c2: int, fid: int,
                   sid: int) -> None:
        """O(1) insert into the hot segment (probe placement + 4 logged
        writes = one device scatter). The caller has already registered
        key uniqueness against the live tables. Growth rebuilds ONLY the hot
        segment (small) and re-uploads only it (resync marker)."""
        if (self._hot_fill + 1) * 2 > self._Hcap:
            self._rebuild_hot(extra=[(filter_, c1, c2, fid, sid)])
            return
        slot = slot_hash(c1)
        step = probe_step(c2)
        for p in range(MAX_PROBES):
            idx = (slot + p * step) & (self._Hcap - 1)
            f = self.arr_hot[idx, 2]
            if f == -1 or f == TOMB_FID:
                if f == -1:
                    self._hot_fill += 1
                else:
                    self._hot_tombs -= 1
                row = (
                    int(np.int32(np.uint32(c1))),
                    int(np.int32(np.uint32(c2))),
                    fid,
                    sid,
                )
                self.arr_hot[idx] = row
                base = idx * 4
                for lane in range(4):
                    self._log("shape_hot", base + lane, row[lane])
                self._in_hot.add(filter_)
                return
        # probe window full (pathological cluster): grow + rebuild hot
        self._rebuild_hot(extra=[(filter_, c1, c2, fid, sid)])

    def _rebuild_hot(self, extra=(), min_cap: int = 0) -> None:
        """Rebuild the hot segment (vectorized placement, drops hot
        tombstones) sized for its live population plus `extra` fresh
        entries [(filter, c1, c2, fid, sid)]. O(hot) — the hot segment is
        small by construction; one `!resync` marker re-uploads it."""
        live = self.arr_hot[self.arr_hot[:, 2] >= 0]  # drops tombs
        n = len(live) + len(extra)
        if n > self.HOT_ABSORB_MAX:
            # no compactor drained the hot segment (standalone index):
            # fold everything into the packed table inline, `extra`
            # rides along explicitly (it is not in any array yet)
            self._rehash(
                self._Tcap,
                extra=[(a, b, f, s) for _name, a, b, f, s in extra],
            )
            return
        newH = max(
            self.HOT_MIN, min_cap, _next_pow2(2 * (n + 1))
        )
        sid = np.empty(n, np.int64)
        c1 = np.empty(n, np.uint32)
        c2 = np.empty(n, np.uint32)
        fid = np.empty(n, np.int64)
        k = len(live)
        sid[:k] = live[:, 3].astype(np.int64)
        c1[:k] = np.ascontiguousarray(live[:, 0]).view(np.uint32)
        c2[:k] = np.ascontiguousarray(live[:, 1]).view(np.uint32)
        fid[:k] = live[:, 2].astype(np.int64)
        for j, (name, a, b, f, s) in enumerate(extra):
            i = k + j
            sid[i], c1[i], c2[i], fid[i] = s, a & _M32, b & _M32, f
            self._in_hot.add(name)
        tab, newH = self._build_table(sid, c1, c2, fid, newH)
        self._Hcap = newH
        self.arr_hot = tab
        self._hot_fill = n
        self._hot_tombs = 0
        self._log_resync("shape_hot")

    def _bulk_place_hot(self, accepted) -> None:
        """Vectorized placement of a fresh batch [(filter, c1, c2, fid,
        sid)] into the LIVE hot table — probe-round bidding in the
        `_build_table` style, O(batch) not O(hot), with ONE `!resync`
        marker (re-uploading the small hot array beats logging 4 scalar
        writes per entry, and keeps the op-log flat under churn storms).
        This is what lets a mass-reconnect wave land at millions of
        subscribes/sec without ever touching the packed table."""
        n = len(accepted)
        if n == 0:
            return
        if self.hot_live + n > self.HOT_ABSORB_MAX:
            # restore-scale batch: classic full rebuild, one epoch bump
            # (the batch rows ride as extras — they are in no array yet)
            self._rehash(
                self._Tcap,
                extra=[(a, b, f, s) for _name, a, b, f, s in accepted],
            )
            return
        if (self._hot_fill + n + 1) * 2 > self._Hcap:
            self._rebuild_hot(extra=accepted)  # grows + places, 1 marker
            return
        c1 = np.fromiter((a[1] & _M32 for a in accepted), np.uint32, n)
        c2 = np.fromiter((a[2] & _M32 for a in accepted), np.uint32, n)
        fidv = np.fromiter((a[3] for a in accepted), np.int64, n)
        sidv = np.fromiter((a[4] for a in accepted), np.int64, n)
        with np.errstate(over="ignore"):
            home = c1 * np.uint32(SLOT_MUL)
            home = home ^ (home >> np.uint32(SLOT_SHIFT))
            step = c2 | np.uint32(1)
        H = self._Hcap
        tab = self.arr_hot
        unplaced = np.arange(n)
        placed_empty = 0
        for p in range(MAX_PROBES):
            if not len(unplaced):
                break
            with np.errstate(over="ignore"):
                idx = (
                    home[unplaced] + np.uint32(p) * step[unplaced]
                ) & np.uint32(H - 1)
            idx = idx.astype(np.int64)
            free = tab[idx, 2] == -1  # tombs stay occupied here; the
            # next rebuild drops them
            cand = unplaced[free]
            cidx = idx[free]
            _, first = np.unique(cidx, return_index=True)
            win, widx = cand[first], cidx[first]
            tab[widx, 0] = c1[win].view(np.int32)
            tab[widx, 1] = c2[win].view(np.int32)
            tab[widx, 2] = fidv[win]
            tab[widx, 3] = sidv[win]
            placed_empty += len(win)
            pm = np.zeros(n, bool)
            pm[win] = True
            unplaced = unplaced[~pm[unplaced]]
        self._hot_fill += placed_empty
        self._in_hot.update(a[0] for a in accepted)
        self._log_resync("shape_hot")
        for i in unplaced.tolist():
            # pathological-cluster tail (~load^8): per-entry placement,
            # which may grow/rebuild the hot segment
            f = accepted[i][0]
            self._in_hot.discard(f)  # _place_hot re-registers it
            self._place_hot(
                f, int(c1[i]), int(c2[i]), int(fidv[i]), int(sidv[i])
            )

    def _tomb_hot(self, c1: int, c2: int) -> None:
        """Tombstone a live hot entry (fid lane -> TOMB_FID: one logged
        write; the slot stays occupied so probe chains hold)."""
        slot = slot_hash(c1)
        step = probe_step(c2)
        cc1, cc2 = np.int32(np.uint32(c1)), np.int32(np.uint32(c2))
        for p in range(MAX_PROBES):
            idx = (slot + p * step) & (self._Hcap - 1)
            if (
                self.arr_hot[idx, 2] >= 0
                and self.arr_hot[idx, 0] == cc1
                and self.arr_hot[idx, 1] == cc2
            ):
                self.arr_hot[idx, 2] = TOMB_FID
                self._log("shape_hot", idx * 4 + 2, TOMB_FID)
                self._hot_tombs += 1
                break
        if self._hot_tombs * 4 > self._Hcap:
            self._rebuild_hot()  # cheap: hot is small

    def _tomb_packed(self, c1: int, c2: int) -> None:
        """Tombstone a packed entry by setting its mask bit — the row is
        untouched (probe chains hold), the device sees one scattered
        word, and compaction purges the bit later."""
        slot = slot_hash(c1)
        step = probe_step(c2)
        cc1, cc2 = np.int32(np.uint32(c1)), np.int32(np.uint32(c2))
        for p in range(MAX_PROBES):
            idx = (slot + p * step) & (self._Tcap - 1)
            if (
                self.arr_table[idx, 2] >= 0
                and self.arr_table[idx, 0] == cc1
                and self.arr_table[idx, 1] == cc2
                and not (self.arr_tomb[idx >> 5] >> (idx & 31)) & 1
            ):
                self.arr_tomb[idx >> 5] |= np.uint32(1 << (idx & 31))
                self._log(
                    "shape_tomb", idx >> 5, int(self.arr_tomb[idx >> 5])
                )
                self._tombs += 1
                break

    @staticmethod
    def _probe_positions(c1: int, c2: int, Tcap: int):
        home = slot_hash(c1)
        step = probe_step(c2)
        return [(home + p * step) & (Tcap - 1) for p in range(MAX_PROBES)]

    @staticmethod
    def _cuckoo_walk(tab, Tcap: int, entry, max_kicks: int = 512):
        """Place `entry` = (c1u32, c2u32, fid, sid) into `tab` [T,4] i32,
        displacing resident entries among THEIR OWN probe positions when
        every position of the current entry is full (random-walk cuckoo
        with MAX_PROBES choices). Lookup correctness only needs each
        entry to sit at one of its probe positions, so displacement is
        invisible to readers. Returns (writes, terminal_was_empty) where
        `writes` is the list of (slot, row4) applied — or None when the
        walk exceeds max_kicks (caller doubles the table).
        """
        writes = []
        c1, c2, fid, sid = entry
        seed = c1
        for _kick in range(max_kicks):
            pos = ShapeIndex._probe_positions(
                int(np.uint32(c1)), int(np.uint32(c2)), Tcap
            )
            row = np.array(
                [np.int32(np.uint32(c1)), np.int32(np.uint32(c2)), fid, sid],
                np.int32,
            )
            for idx in pos:
                f = tab[idx, 2]
                if f == -1 or f == TOMB_FID:
                    tab[idx] = row
                    writes.append((idx, row))
                    return writes, f == -1
            # all positions full: evict a deterministic pseudo-random one
            seed = _mix32(seed ^ (_kick * 0x9E3779B1))
            vidx = pos[seed % MAX_PROBES]
            victim = tab[vidx].copy()
            tab[vidx] = row
            writes.append((vidx, row))
            c1 = int(np.uint32(victim[0]))
            c2 = int(np.uint32(victim[1]))
            fid = int(victim[2])
            sid = int(victim[3])
        return None

    @staticmethod
    def _build_table(sid, c1, c2, fid, newT: int):
        """Vectorized double-hash placement -> (tab [T,4] i32, T).

        Any placement within MAX_PROBES along an entry's (home, stride)
        probe sequence is valid for lookup (host and device walk the same
        sequence), so placement runs in probe ROUNDS: in round p every
        still-unplaced entry bids for home + p*stride, first bidder per
        empty slot wins. The tail left after MAX_PROBES rounds (~load^8
        of the batch) is placed by cuckoo displacement; only if a walk
        fails does the table double.
        """
        n = len(sid)
        with np.errstate(over="ignore"):
            home = c1 * np.uint32(SLOT_MUL)
            home = home ^ (home >> np.uint32(SLOT_SHIFT))
            step = c2 | np.uint32(1)
        while True:
            tab = np.zeros((newT, 4), np.int32)
            tab[:, 2] = -1
            unplaced = np.arange(n)
            for p in range(MAX_PROBES):
                if not len(unplaced):
                    break
                with np.errstate(over="ignore"):
                    idx = (
                        home[unplaced] + np.uint32(p) * step[unplaced]
                    ) & np.uint32(newT - 1)
                idx = idx.astype(np.int64)
                free = tab[idx, 2] == -1
                cand = unplaced[free]
                cidx = idx[free]
                # first bidder per distinct empty slot wins this round
                _, first = np.unique(cidx, return_index=True)
                win, widx = cand[first], cidx[first]
                tab[widx, 0] = c1[win].view(np.int32)
                tab[widx, 1] = c2[win].view(np.int32)
                tab[widx, 2] = fid[win]
                tab[widx, 3] = sid[win]
                placed_mask = np.zeros(n, bool)
                placed_mask[win] = True
                unplaced = unplaced[~placed_mask[unplaced]]
            ok = True
            for i in unplaced.tolist():
                if (
                    ShapeIndex._cuckoo_walk(
                        tab,
                        newT,
                        (int(c1[i]), int(c2[i]), int(fid[i]), int(sid[i])),
                    )
                    is None
                ):
                    ok = False
                    break
            if ok:
                return tab, newT
            newT *= 2

    def _reset_segments(self) -> None:  # oplog-covered-by: caller bump
        """Fresh tombstone mask (sized to the packed table) + empty hot
        segment: the packed rebuild just absorbed everything live."""
        self.arr_tomb = np.zeros(max(1, self._Tcap // 32), np.uint32)
        self._tombs = 0
        self.arr_hot = np.zeros((self._Hcap, 4), np.int32)
        self.arr_hot[:, 2] = -1
        self._hot_fill = 0
        self._hot_tombs = 0
        self._in_hot = set()

    def _rehash(self, newT: int, extra=()) -> None:
        """Full rebuild from the LIVE rows (vectorized array scan — no
        dict walk) — the inline path for restore-scale bulk loads, salt
        rebuilds and the tombstone safety valve. `extra` rows
        [(c1, c2, fid, sid)] are not in any array yet (overflowing
        insert) and ride the same placement. Invalidates any outstanding
        compaction capture (`_structure_gen`) and absorbs the hot
        segment."""
        self._structure_gen += 1
        self._journal = None
        live = self._live_rows()
        n = len(live) + len(extra)
        while (n + 1) * 2 > newT:
            newT *= 2
        if n == 0:
            tab = np.zeros((newT, 4), np.int32)
            tab[:, 2] = -1
            self._Tcap = newT
            self.arr_table = tab
            self._fill = 0
            self._reset_segments()
            self._bump_epoch()
            return
        sid = np.empty(n, np.int64)
        c1 = np.empty(n, np.uint32)
        c2 = np.empty(n, np.uint32)
        fid = np.empty(n, np.int64)
        k = len(live)
        sid[:k] = live[:, 3].astype(np.int64)
        c1[:k] = np.ascontiguousarray(live[:, 0]).view(np.uint32)
        c2[:k] = np.ascontiguousarray(live[:, 1]).view(np.uint32)
        fid[:k] = live[:, 2].astype(np.int64)
        for j, (a, b, f, s) in enumerate(extra):
            i = k + j
            sid[i], c1[i], c2[i], fid[i] = s, a & _M32, b & _M32, f
        tab, newT = self._build_table(sid, c1, c2, fid, newT)
        self._Tcap = newT
        self.arr_table = tab
        self._fill = n
        self._reset_segments()
        self._bump_epoch()

    def add(self, filter_: str, fid: int) -> bool:
        """Index this filter under `fid`. False => caller routes it to the
        residual NFA engine (shape overflow or hash collision)."""
        parsed = self.parse_shape(filter_)
        if parsed is None:
            return False
        mask, plen, has_hash, prefix = parsed
        sid = self._shape_for(mask, plen, has_hash)
        if sid is None:
            return False
        c1, c2 = combined_pair(prefix, mask, sid, self.salt)
        if self._find_live(c1, c2) is not None:
            # (c1, c2) already live: a true 64-bit collision between
            # distinct filters (the caller only adds absent filters) —
            # first-probe-wins lookup cannot hold both, so residual
            self._shape_release(sid, (mask, plen, has_hash))
            return False
        if self._journal is not None:
            self._journal.append(("add", filter_, (sid, c1, c2, fid)))
        self._place_hot(filter_, c1, c2, fid, sid)
        return True

    def bulk_add_cold(
        self,
        names: List[str],
        fids: np.ndarray,
        masks: np.ndarray,
        plens: np.ndarray,
        hhs: np.ndarray,
        s1: np.ndarray,
        s2: np.ndarray,
        unfit: np.ndarray,
    ) -> List[Tuple[str, int]]:
        """Fully-vectorized cold-start insert (empty index only).

        The caller (RouteIndex._bulk_add_cold) has already tokenized the
        DISTINCT filters and reduced each to its shape signature
        (masks/plens/hhs) and pre-fold combined sums (s1/s2 — the masked
        sum-products WITHOUT the shape-id fold, which is applied here once
        shape ids are assigned). `unfit` marks rows parse_shape would
        reject. Returns the rejected (filter, fid) pairs, in input order,
        for the residual engine. Bit-identical to repeated `add`.
        """
        assert len(self) == 0, "bulk_add_cold requires an empty index"
        n = len(names)
        rej = np.zeros(n, dtype=bool)
        rej |= unfit
        # -- shape registration (first-occurrence order, like add) -------
        key = (
            (masks.astype(np.uint64) << np.uint64(8))
            | (plens.astype(np.uint64) << np.uint64(1))
            | hhs.astype(np.uint64)
        )
        key[unfit] = np.uint64(0xFFFFFFFFFFFFFFFF)
        uq_key, first_idx, inv = np.unique(
            key, return_index=True, return_inverse=True
        )
        order = np.argsort(first_idx, kind="stable")
        sid_of_group = np.full(len(uq_key), -1, dtype=np.int64)
        group_counts = np.bincount(inv, minlength=len(uq_key))
        for g in order.tolist():
            i = int(first_idx[g])
            if unfit[i]:
                continue
            sid = self._shape_for(int(masks[i]), int(plens[i]), bool(hhs[i]))
            if sid is None:
                continue  # shape overflow -> whole family is residual
            sid_of_group[g] = sid
            self._shape_refs[sid] += int(group_counts[g]) - 1
        sids = sid_of_group[inv]
        rej |= sids < 0
        # -- combined hashes (sid fold applied post-registration) --------
        with np.errstate(over="ignore"):
            su = sids.astype(np.uint32)
            c1 = _mix32_np(s1 ^ (su * np.uint32(FOLD1)))
            c2 = _mix32_np(s2 ^ (su * np.uint32(FOLD2)))
        # -- 64-bit key collisions: first (by input order) wins ----------
        fit_idx = np.nonzero(~rej)[0]
        ckey = (c1[fit_idx].astype(np.uint64) << np.uint64(32)) | c2[
            fit_idx
        ].astype(np.uint64)
        srt = np.argsort(ckey, kind="stable")  # stable => input order
        dup = np.zeros(len(ckey), dtype=bool)
        dup[srt[1:]] = ckey[srt[1:]] == ckey[srt[:-1]]
        for i in fit_idx[dup].tolist():
            # true 64-bit collision between distinct filters: residual
            self._shape_release(
                int(sids[i]),
                (int(masks[i]), int(plens[i]), bool(hhs[i])),
            )
            rej[i] = True
        # -- vectorized placement ----------------------------------------
        keep = np.nonzero(~rej)[0]
        newT = self._Tcap
        while (len(keep) + 1) * 2 > newT:
            newT *= 2
        tab, newT = self._build_table(
            sids[keep], c1[keep], c2[keep], fids[keep], newT
        )
        self._structure_gen += 1
        self._journal = None
        self._Tcap = newT
        self.arr_table = tab
        self._fill = len(keep)
        self._reset_segments()
        # -- no shadow mirror to build: the packed table IS the host
        # state (probe lookups + array scans serve every later need) ----
        if rej.any():
            rej_idx = np.nonzero(rej)[0].tolist()
            out = [(names[i], int(fids[i])) for i in rej_idx]
        else:
            out = []
        self._bump_epoch()
        return out

    def bulk_add(self, entries: List[Tuple[str, int]]) -> List[Tuple[str, int]]:
        """Vectorized insert of many (filter, fid) pairs; returns the
        REJECTED pairs (shape overflow / hash collision / unparseable) the
        caller must route to the residual engine.

        The cold-start path (restore 10M subscriptions): per-level word
        hashes come from the numpy mirror of the device tokenizer in one
        pass, combined hashes and table placement are vectorized; results
        are bit-identical to repeated `add` calls. Ends with an epoch bump
        (one full device upload) instead of millions of op-log entries.
        """
        from emqx_tpu.ops.tokenizer import encode_topics, tokenize_host_np

        rejected: List[Tuple[str, int]] = []
        metas = []  # (filter, fid, sid, key=(mask, plen, has_hash))
        raw: List[str] = []
        for f, fid in entries:
            parsed = self.parse_shape(f)
            if parsed is None:
                rejected.append((f, fid))
                continue
            mask, plen, has_hash, _prefix = parsed
            sid = self._shape_for(mask, plen, has_hash)
            if sid is None:
                rejected.append((f, fid))
                continue
            metas.append((f, fid, sid, (mask, plen, has_hash)))
            raw.append(f)
        if not metas:
            return rejected
        L = MAX_MASK_LEVELS
        # row width sized to the actual data (so every row fits by
        # construction) and rows processed in blocks: a fixed 8*L width at
        # 1M+ filters costs GBs of cumsum intermediates
        maxlen = max(16, max(len(f.encode()) for f in raw))
        width = 1 << (maxlen - 1).bit_length()
        masks = np.array([m[3][0] for m in metas], dtype=np.int64)
        sids = np.array([m[2] for m in metas], dtype=np.uint32)
        k1 = np.array([level_mul(l, 1) for l in range(L)], dtype=np.uint32)
        k2 = np.array([level_mul(l, 2) for l in range(L)], dtype=np.uint32)
        lvls = np.arange(L)[None, :]
        n = len(raw)
        c1s = np.empty(n, np.uint32)
        c2s = np.empty(n, np.uint32)
        BLOCK = 1 << 18
        with np.errstate(over="ignore"):
            for lo in range(0, n, BLOCK):
                hi = min(lo + BLOCK, n)
                mat, lens, _tl = encode_topics(raw[lo:hi], width)
                h1, h2, _nw, _dl, _ws, _wl = tokenize_host_np(
                    mat, lens, self.salt, L
                )
                lb = ((masks[lo:hi, None] >> lvls) & 1).astype(np.uint32)
                s1 = np.sum(h1 * k1[None, :] * lb, axis=1, dtype=np.uint32)
                s2 = np.sum(h2 * k2[None, :] * lb, axis=1, dtype=np.uint32)
                c1s[lo:hi] = _mix32_np(s1 ^ (sids[lo:hi] * np.uint32(FOLD1)))
                c2s[lo:hi] = _mix32_np(s2 ^ (sids[lo:hi] * np.uint32(FOLD2)))
        accepted = []  # (filter, c1, c2, fid, sid)
        journal = self._journal
        live_clash = self._find_live_batch(c1s, c2s)  # ONE vector sweep
        batch_keys: Dict[Tuple[int, int], bool] = {}  # in-batch dups
        for i, (f, fid, sid, key) in enumerate(metas):
            c1, c2 = int(c1s[i]), int(c2s[i])
            if live_clash[i] or (c1, c2) in batch_keys:
                # live (c1, c2) => a different filter (caller only adds
                # absent ones): 64-bit collision, route to residual
                self._shape_release(sid, key)
                rejected.append((f, fid))
                continue
            batch_keys[(c1, c2)] = True
            if journal is not None:
                journal.append(("add", f, (sid, c1, c2, fid)))
            accepted.append((f, c1, c2, fid, sid))
        # churn-scale batches land in the hot segment (one vectorized
        # placement + one small re-upload; the packed table is never
        # touched); restore-scale batches fall through to a full rebuild
        # inside _bulk_place_hot
        self._bulk_place_hot(accepted)
        return rejected

    def remove(self, filter_: str) -> bool:
        ent = self._ent_of(filter_)
        if ent is None:
            return False
        sid, c1, c2, fid = ent
        if self._journal is not None:
            self._journal.append(("remove", filter_, ent))
        if filter_ in self._in_hot:
            self._in_hot.discard(filter_)
            self._tomb_hot(c1, c2)
        else:
            self._tomb_packed(c1, c2)
        parsed = self.parse_shape(filter_)
        if parsed is not None:
            mask, plen, has_hash, _ = parsed
            self._shape_release(sid, (mask, plen, has_hash))
        if self._tombs * 2 > self._Tcap:
            # safety valve only: background compaction (SegmentCompactor)
            # normally purges tombstones long before half the table dies
            self._rehash(self._Tcap)
        return True

    # oplog-covered-by: _rehash ends the rebuild with an epoch bump
    def rebuild(self, salt: int) -> List[Tuple[str, int]]:
        """Salt changed (vocab collision in the residual engine): recompute
        every combined hash and rebuild the table. Rare by construction.

        Returns [(filter, fid)] EVICTED because their new combined hash
        collides with another filter's — `add` enforces key uniqueness, so
        rebuild must too or the first-probe-wins device lookup would
        silently drop one of the pair. The caller (RouteIndex) re-homes
        evictees in the residual NFA engine.
        """
        self.salt = salt
        if self.resolve_name is None:
            raise RuntimeError(
                "ShapeIndex.rebuild needs resolve_name (fid -> filter) "
                "to re-hash entries under the new salt"
            )
        live = self._live_rows()
        seen: Dict[Tuple[int, int], bool] = {}
        rows: List[Tuple[int, int, int, int]] = []
        evicted: List[Tuple[str, int]] = []
        for fid, sid in zip(
            live[:, 2].astype(np.int64).tolist(),
            live[:, 3].astype(np.int64).tolist(),
        ):
            f = self.resolve_name(int(fid))
            parsed = self.parse_shape(f)
            mask, plen, has_hash, prefix = parsed
            c1, c2 = combined_pair(prefix, mask, sid, salt)
            if (c1, c2) in seen:
                self._shape_release(sid, (mask, plen, has_hash))
                evicted.append((f, int(fid)))
                continue
            seen[(c1, c2)] = True
            rows.append((c1, c2, int(fid), int(sid)))
        # drop EVERYTHING live (the old-salt rows are all stale) and
        # rebuild from the re-hashed rows only
        self.arr_table[:, 2] = -1
        self._fill = 0
        self._reset_segments()
        self._rehash(self._Tcap, extra=rows)
        return evicted

    def __len__(self) -> int:
        return (
            self._fill
            - self._tombs
            + self._hot_fill
            - self._hot_tombs
        )

    # -- background compaction (ops/segments.SegmentCompactor) -------------
    # One cycle: begin() on the mutating thread (array memcpys + journal
    # on), build_compact() anywhere (pure numpy over the capture),
    # apply_compact() back on the mutating thread (swap + journal
    # replay). A structural rebuild racing the build (_rehash/cold load)
    # bumps `_structure_gen` and the apply aborts cleanly.

    def begin_compact(self) -> Dict:
        """Capture a consistent array snapshot (fast memcpys — never the
        10M-entry host dicts) and start journaling mutations."""
        cap = {
            "tab": self.arr_table.copy(),
            "tomb": self.arr_tomb.copy(),
            "hot": self.arr_hot.copy(),
            "Tcap": self._Tcap,
            "gen": self._structure_gen,
        }
        self._journal = []
        return cap

    @staticmethod
    def build_compact(cap: Dict) -> Dict:
        """Merge `packed − tombstones + hot` into a fresh packed table.
        Pure numpy over the capture — safe on any thread, off the
        subscribe path entirely."""
        tab, Tcap = cap["tab"], cap["Tcap"]
        idx = np.nonzero(tab[:, 2] >= 0)[0]
        tword = cap["tomb"][idx >> 5]
        dead = (tword >> (idx & 31).astype(np.uint32)) & np.uint32(1)
        rows = [tab[idx[dead == 0]]]
        hot = cap["hot"]
        rows.append(hot[hot[:, 2] >= 0])
        live = np.concatenate(rows, axis=0)
        n = len(live)
        newT = 1024
        while (n + 1) * 2 > newT:
            newT *= 2
        if n:
            tab2, newT = ShapeIndex._build_table(
                live[:, 3].astype(np.int64),
                np.ascontiguousarray(live[:, 0]).view(np.uint32),
                np.ascontiguousarray(live[:, 1]).view(np.uint32),
                live[:, 2].astype(np.int64),
                newT,
            )
        else:
            tab2 = np.zeros((newT, 4), np.int32)
            tab2[:, 2] = -1
        return {"tab": tab2, "Tcap": newT, "gen": cap["gen"], "n": n}

    def apply_compact(self, built: Dict) -> Optional[int]:
        """Install a built packed table (mutating thread). The journal of
        mutations that raced the build replays on top — adds re-place
        into the (fresh) hot segment, removes re-tombstone — so the
        result is bit-equivalent to having paused the world. Returns the
        new epoch (for `DeviceSegmentManager.offer`), or None when a
        structural rebuild invalidated the capture."""
        if self._journal is None or built["gen"] != self._structure_gen:
            self._journal = None
            return None
        journal, self._journal = self._journal, None
        self._structure_gen += 1
        self._Tcap = built["Tcap"]
        self.arr_table = built["tab"]
        self._fill = built["n"]
        self._reset_segments()
        self._bump_epoch()
        for op, f, (sid, c1, c2, fid) in journal:
            if op == "add":
                self._place_hot(f, c1, c2, fid, sid)
            elif f in self._in_hot:  # remove of a journal-replayed add
                self._in_hot.discard(f)
                self._tomb_hot(c1, c2)
            else:  # remove of an entry the build merged into packed
                self._tomb_packed(c1, c2)
        return self.epoch


# -- device kernel ---------------------------------------------------------


def shape_match_device(
    tables, m_active: int, h1, h2, nwords, dollar, probes: int = SHAPE_PROBES
):
    """Match tokenized topics against the shape index. Jit-traceable.

    tables: device dict (shape_tab FLAT [T*4] i32 — kept one-dimensional
    because a [T, 4] s32 operand pads its minor dim 4 -> 128 under TPU
    tiling, a 32x HBM expansion that OOMs at 10M-filter scale;
    shape_hot FLAT [H*4] i32 hot segment; shape_tomb u32 [T/32]
    packed-row tombstone mask; shape_mask/len/flags [Mcap])
    h1, h2: uint32 [B, L] per-level word hashes; nwords [B]; dollar [B]
    -> matched fid int32 [B, M] (-1 = no match; SPARSE, not compacted)

    The match is ``packed ∪ hot − tombstones`` in ONE program: the
    packed probe loop masks hits through the tombstone bitmask, then the
    same (c1, c2) pair probes the small hot segment — a subscribe is
    routable the moment its hot-segment scatter lands, with no repack
    and no program change (the hot table is always probed, so the
    compiled program is stable across empty/full hot states).
    """
    import jax
    import jax.numpy as jnp

    B, L = h1.shape
    M = m_active
    mask = tables["shape_mask"][:M]  # [M]
    plen = tables["shape_len"][:M]
    flags = tables["shape_flags"][:M]
    tab = tables["shape_tab"]  # [T*4] flat row-major
    Tcap = tab.shape[0] // 4
    hot = tables["shape_hot"]  # [H*4] flat row-major
    Hcap = hot.shape[0] // 4
    tomb = tables["shape_tomb"]  # uint32 [Tcap/32] packed tombstone bits

    lvl = jnp.arange(L, dtype=jnp.int32)
    lvl_bit = (mask[None, :] >> lvl[:, None]) & 1  # [L, M]
    k1 = jnp.asarray(
        [level_mul(int(l), 1) for l in range(L)], dtype=jnp.uint32
    )
    k2 = jnp.asarray(
        [level_mul(int(l), 2) for l in range(L)], dtype=jnp.uint32
    )
    w1 = k1[:, None] * lvl_bit.astype(jnp.uint32)  # [L, M]
    w2 = k2[:, None] * lvl_bit.astype(jnp.uint32)
    # masked sum-product over levels (uint32 wrap = mod 2^32)
    s1 = jnp.sum(h1[:, :, None] * w1[None, :, :], axis=1, dtype=jnp.uint32)
    s2 = jnp.sum(h2[:, :, None] * w2[None, :, :], axis=1, dtype=jnp.uint32)
    sid = jnp.arange(M, dtype=jnp.uint32)
    c1 = _mix32_dev(s1 ^ (sid[None, :] * jnp.uint32(FOLD1)))
    c2 = _mix32_dev(s2 ^ (sid[None, :] * jnp.uint32(FOLD2)))

    has_hash = (flags & 1) != 0
    rootwild = (flags & 2) != 0
    live = plen >= 0
    nw = nwords[:, None]
    ok_len = jnp.where(has_hash[None, :], nw >= plen[None, :], nw == plen[None, :])
    valid = ok_len & live[None, :] & ~(dollar[:, None] & rootwild[None, :])

    c1i = jax.lax.bitcast_convert_type(c1, jnp.int32)
    c2i = jax.lax.bitcast_convert_type(c2, jnp.int32)
    slot = c1 * jnp.uint32(SLOT_MUL)
    slot = slot ^ (slot >> SLOT_SHIFT)
    step = c2 | jnp.uint32(1)  # double-hash stride (see probe_step)
    fid = jnp.full((B, M), -1, dtype=jnp.int32)
    found = jnp.zeros((B, M), dtype=bool)
    tmask = jnp.uint32(Tcap - 1)
    sid_lane = jnp.arange(M, dtype=jnp.int32)[None, :]
    for p in range(probes):
        idx = ((slot + jnp.uint32(p) * step) & tmask).astype(jnp.int32)
        base4 = idx * 4  # flat row offset (4 x 1D gathers: the 2D form
        # would force the 32x-padded [T,4] layout back into HBM)
        r_c1 = tab[base4]
        r_c2 = tab[base4 + 1]
        r_fid = tab[base4 + 2]
        r_sid = tab[base4 + 3]
        # tombstone mask: an unsubscribed packed row stays in place (its
        # probe chain holds) but may not match
        tword = tomb[idx >> 5]
        t_dead = (
            (tword >> (idx & 31).astype(jnp.uint32)) & jnp.uint32(1)
        ) != 0
        hit = (
            (r_c1 == c1i)
            & (r_c2 == c2i)
            & (r_sid == sid_lane)
            & (r_fid >= 0)
            & ~t_dead
            & valid
            & ~found
        )
        fid = jnp.where(hit, r_fid, fid)
        found |= hit
    # hot segment: same probe sequence over the small overlay table
    # (entries subscribed since the last compaction). Host add keeps
    # (c1, c2) unique across packed-live and hot, so chaining on `found`
    # is dedup enough.
    hmask = jnp.uint32(Hcap - 1)
    for p in range(probes):
        idx = ((slot + jnp.uint32(p) * step) & hmask).astype(jnp.int32)
        base4 = idx * 4
        r_c1 = hot[base4]
        r_c2 = hot[base4 + 1]
        r_fid = hot[base4 + 2]
        r_sid = hot[base4 + 3]
        hit = (
            (r_c1 == c1i)
            & (r_c2 == c2i)
            & (r_sid == sid_lane)
            & (r_fid >= 0)
            & valid
            & ~found
        )
        fid = jnp.where(hit, r_fid, fid)
        found |= hit
    return fid


def _mix32_dev(x):
    import jax.numpy as jnp

    x ^= x >> 16
    x = x * jnp.uint32(0x7FEB352D)
    x ^= x >> 15
    x = x * jnp.uint32(0x846CA68B)
    x ^= x >> 16
    return x
