"""CSR subscriber tables: O(total subscriptions) device fan-out state.

The dense fan-out representation (`router_model.SubscriberTable`'s
``sub_bitmaps [Fcap, W]`` uint32 matrix) costs O(Fcap * Slots / 32)
regardless of how many subscriptions exist: one million DISTINCT
single-subscriber topics need a ~128GB matrix (the measured wall the
PR 12 `conn_scaling` sweep documented). This module is the sparse
representation that removes that wall: per-filter subscriber slot
LISTS, stored as segment arrays in the TrieJax shape — relational
gather over CSR adjacency — on the same `DeviceSegmentManager`
machinery every other table owner uses (docs/update_path.md):

- **packed CSR** (written only by rebuilds/compaction):
  ``csr_off [S, F]`` / ``csr_len [S, F]`` int32 region table plus the
  concatenated slot column ``csr_slots [S, P]`` (-1 = hole/tombstone).
  Regions are laid contiguously in fid order, exactly sized at build;
- **hot segment** (append-only between compactions):
  ``hot_fid / hot_slot [S, H]`` pairs — a subscribe is two op-logged
  scalar writes riding the next fused segment scatter, never an
  O(table) rebuild; an unsubscribe tombstones ONE lane (packed column
  slot or hot fid) the same way;
- **compaction** (`CsrSegmentOwner` on the ONE `SegmentCompactor`):
  merges ``packed - tombstones + hot`` into a fresh exact-size CSR on
  the compact executor, pre-uploads it, and replays the mutations that
  raced the build from a journal — the ShapeIndex cycle verbatim;
- **registry**: a vectorized open-addressing (fid, slot) -> position
  table (the PR 9 no-shadow-dicts idiom: int64 key lanes + int32
  position lanes, probe-round bulk build) makes unsubscribe O(1)
  without a 100M-entry Python dict.

``S`` is the shard axis: the mesh placement shards every array's
leading axis over 'tp' (the subscriber-lane axis the dense matrix
already sharded), with a subscription owned by shard ``slot % S``.
Slot ids are stored GLOBALLY, so per-shard compact lists concatenate
over 'tp' with no lane rebase. Single-device tables keep ``S = 1``.

The device half, `sparse_fanout_slots`, is the CSR twin of
`compact_fanout_slots`: a windowed gather-union of the matched fids'
slot lists (segment offsets via cumsum + a searchsorted-style
position->segment join), the hot overlay folded in by a scanned
membership test, deduped and left-packed into the SAME
``slots [B, Kslot] / slot_count [B] / overflow [B]`` compact readback
contract — so `Broker._dispatch_device_results` and the slab DLV path
run unchanged on either representation.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from emqx_tpu.ops.contract import device_contract
from emqx_tpu.ops.nfa import _next_pow2

_M64 = 0xFFFFFFFFFFFFFFFF

# registry position flag: the entry lives in the hot segment (low bits =
# hot index within its shard), not the packed slot column
HOT_POS = 1 << 30

# device-snapshot array names (the segment-manager sync set)
CSR_KEYS = ("csr_off", "csr_len", "csr_slots", "hot_fid", "hot_slot")


# -- device kernel -----------------------------------------------------------


@device_contract(
    "sparse_fanout_slots",
    # device-local by construction: the mesh builders psum the per-shard
    # counts/overflow OUTSIDE this kernel, exactly like the dense
    # compaction stage
    collectives=(),
    out_bounds={
        # the whole point: outputs scale with B * kslot (and the [B]
        # vectors), never with the slot-column capacity P
        "slots": lambda cfg: cfg["B"] * cfg["kslot"] * 4,
        "count": lambda cfg: cfg["B"] * 4,
        "overflow": lambda cfg: cfg["B"],
        "live": lambda cfg: cfg["B"] * 4,
    },
)
def sparse_fanout_slots(csr: Dict, matched, kslot: int, kg: int = 0):
    """Union the matched fids' CSR slot lists -> compact slot rows.

    csr: the LOCAL shard's arrays ([1, ...] leading axis — inside
    shard_map each device sees its own 'tp' slice; single-device tables
    are shard 0 of 1). matched: int32 [B, K] sparse fids (-1 holes).
    Returns (slots [B, kslot], count [B], overflow [B], live [B]).

    ``kg`` bounds the packed-gather window per row (0 = 2 * kslot):
    segment starts come from an exclusive cumsum of the matched fids'
    ALLOCATED region lengths, each window position joins to its segment
    with a searchsorted-style rank (sum of starts <= pos), and one
    gather pulls the slot column. Rows whose regions don't fit the
    window flag ``overflow`` (count is forced past kslot so the
    single-device host derivation agrees) and fall back to a host-built
    dense row — correctness never depends on the window, it is a
    bandwidth/FLOP knob exactly like Kslot itself.

    The hot segment folds in as a scanned membership overlay (one
    [B, H] mask OR'd per matched column), and the final rows are
    sorted + adjacent-deduped: the host keeps (fid, slot) unique, so
    dedup only guards double-delivery against invariant breakage —
    mirroring the dense path's OR semantics, where a duplicate is
    structurally impossible.
    """
    import jax
    import jax.numpy as jnp

    from emqx_tpu.ops.matcher import _compact

    if kslot <= 0:
        raise ValueError("sparse fan-out requires kslot > 0")
    if kg <= 0:
        kg = 2 * kslot
    off = csr["csr_off"][0]
    ln = csr["csr_len"][0]
    col = csr["csr_slots"][0]
    hfid = csr["hot_fid"][0]
    hslot = csr["hot_slot"][0]
    B, K = matched.shape
    has = matched >= 0
    safe = jnp.maximum(matched, 0)
    fl = jnp.where(has, ln[safe], 0)  # [B, K] allocated region lens
    fo = off[safe]  # [B, K]
    starts = jnp.cumsum(fl, axis=1) - fl  # exclusive cumsum
    total = starts[:, -1] + fl[:, -1]  # [B]
    pos = jnp.arange(kg, dtype=jnp.int32)
    # seg[b, p] = rank of the segment containing window position p:
    # (# of starts <= p) - 1. Zero-length segments tie their successor's
    # start; the last of a tie run is the one that can contain p, and
    # the count-of-starts form picks exactly it (searchsorted 'right').
    seg = (
        jnp.sum(
            (starts[:, :, None] <= pos[None, None, :]).astype(jnp.int32),
            axis=1,
        )
        - 1
    )
    seg = jnp.clip(seg, 0, K - 1)
    sg = jnp.take_along_axis(starts, seg, axis=1)  # [B, kg]
    lg = jnp.take_along_axis(fl, seg, axis=1)
    og = jnp.take_along_axis(fo, seg, axis=1)
    j = pos[None, :] - sg
    valid = (pos[None, :] < total[:, None]) & (j < lg)
    src = jnp.clip(og + j, 0, col.shape[0] - 1)
    cand_p = jnp.where(valid, col[src], jnp.int32(-1))  # [B, kg]
    # hot overlay: pairs whose fid appears in this row's matched set.
    # lax.scan over the K matched columns keeps peak memory at one
    # [B, H] mask instead of materializing [B, K, H].
    H = hfid.shape[0]

    def _memb(acc, mcol):  # mcol: [B] one matched column
        return acc | (mcol[:, None] == hfid[None, :]), None

    memb, _ = jax.lax.scan(
        _memb, jnp.zeros((B, H), bool), jnp.swapaxes(matched, 0, 1)
    )
    hlive = hfid >= 0  # masks holes AND tombstones (and -1 == -1 ties)
    cand_h = jnp.where(memb & hlive[None, :], hslot[None, :], jnp.int32(-1))
    cand = jnp.concatenate([cand_p, cand_h], axis=1)
    live = jnp.sum((cand >= 0).astype(jnp.int32), axis=1)  # exact unless
    # the window overflowed (then the host rebuilds the row anyway)
    slots, _ = _compact(cand, kslot)
    slots = jnp.sort(slots, axis=1)  # -1 pads sort to the front
    dup = jnp.concatenate(
        [
            jnp.zeros((B, 1), bool),
            (slots[:, 1:] == slots[:, :-1]) & (slots[:, 1:] >= 0),
        ],
        axis=1,
    )
    slots = jnp.where(dup, jnp.int32(-1), slots)
    gather_ovf = total > kg
    count = jnp.where(gather_ovf, jnp.maximum(total, kslot + 1), live)
    overflow = count > kslot
    return slots, count, overflow, live


# -- host registry: (fid, slot) -> position ----------------------------------


def _mix64_np(x):
    """splitmix64 finalizer, vectorized (uint64 wrap)."""
    x = x.astype(np.uint64)
    with np.errstate(over="ignore"):
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        x ^= x >> np.uint64(31)
    return x


def _mix64(x: int) -> int:
    x &= _M64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _M64
    return x ^ (x >> 31)


class CsrTable:
    """Host-side CSR subscriber state (one representation behind
    `router_model.SubscriberTable`). Mutations emit op-log writes
    through the owner-provided `log` / `log_resync` / `bump` callbacks
    (the owner holds the ONE epoch/version/oplog the segment manager
    syncs on, so a representation flip is just another epoch bump).
    """

    HOT_MIN = 256  # minimum hot-segment capacity per shard (pow2)
    # hot population past this forces an inline rebuild instead of
    # another growth: the kernel scans the full hot segment per batch,
    # so its size is a compute knob, not just memory
    HOT_ABSORB_MAX = 1 << 17
    # serve-time absorb bound (`maybe_absorb`, called from the dirty
    # prepare): a subscribe storm with no background compactor (bench
    # drivers, embedded brokers) must not hand the kernel a 100k-entry
    # hot scan — past this, the prepare folds hot into packed once
    # (epoch bump) before snapshotting. The background compactor keeps
    # hot far below this on a live broker.
    HOT_SERVE_MAX = 4096

    def __init__(self, shards: int = 1, log=None, log_resync=None,
                 bump=None):
        self.shards = S = max(1, int(shards))
        self._log = log or (lambda name, idx, val: None)
        self._log_resync = log_resync or (lambda name: None)
        self._bump = bump or (lambda: None)
        self._fcap = 64
        self._pcap = 256  # packed column capacity PER SHARD
        self.csr_off = np.zeros((S, self._fcap), np.int32)
        self.csr_len = np.zeros((S, self._fcap), np.int32)
        self.csr_slots = np.full((S, self._pcap), -1, np.int32)
        self._hcap = self.HOT_MIN
        self.hot_fid = np.full((S, self._hcap), -1, np.int32)
        self.hot_slot = np.full((S, self._hcap), -1, np.int32)
        self._hot_tail = [0] * S  # next append index per shard
        self.live = 0
        self.packed_tombs = 0
        self.hot_tombs = 0
        self.max_slot = -1
        # (fid, slot) -> position registry (no per-entry Python objects)
        self._reg_cap = 1024
        self._reg_key = np.full(self._reg_cap, -1, np.int64)
        self._reg_pos = np.zeros(self._reg_cap, np.int32)
        self._reg_live = 0
        self._reg_fill = 0  # live + tombstones
        # compaction bookkeeping (ShapeIndex cycle): a capture is valid
        # while no structural rebuild happened; racing mutations journal
        self._structure_gen = 0
        self._journal: Optional[list] = None  # single-writer: loop

    # -- registry ----------------------------------------------------------
    @staticmethod
    def _key(fid: int, slot: int) -> int:
        return (fid << 32) | slot

    def _reg_get(self, key: int) -> Optional[int]:
        cap = self._reg_cap
        h = _mix64(key)
        home = h & (cap - 1)
        step = ((h >> 32) | 1) & (cap - 1)
        rk = self._reg_key
        for p in range(cap):
            i = (home + p * step) & (cap - 1)
            k = rk[i]
            if k == key:
                return int(self._reg_pos[i])
            if k == -1:
                return None
        return None

    def _reg_set(self, key: int, pos: int) -> None:
        if (self._reg_fill + 1) * 2 > self._reg_cap:
            self._reg_rehash()
        cap = self._reg_cap
        h = _mix64(key)
        home = h & (cap - 1)
        step = ((h >> 32) | 1) & (cap - 1)
        rk = self._reg_key
        first_tomb = -1
        for p in range(cap):
            i = (home + p * step) & (cap - 1)
            k = rk[i]
            if k == key:
                self._reg_pos[i] = pos
                return
            if k == -2 and first_tomb < 0:
                first_tomb = i
            elif k == -1:
                if first_tomb >= 0:
                    i = first_tomb
                else:
                    self._reg_fill += 1
                rk[i] = key
                self._reg_pos[i] = pos
                self._reg_live += 1
                return
        raise RuntimeError("csr registry probe exhausted")  # unreachable

    def _reg_del(self, key: int) -> Optional[int]:
        cap = self._reg_cap
        h = _mix64(key)
        home = h & (cap - 1)
        step = ((h >> 32) | 1) & (cap - 1)
        rk = self._reg_key
        for p in range(cap):
            i = (home + p * step) & (cap - 1)
            k = rk[i]
            if k == key:
                rk[i] = -2
                self._reg_live -= 1
                return int(self._reg_pos[i])
            if k == -1:
                return None
        return None

    def _reg_rehash(self) -> None:
        live = self._reg_key >= 0
        keys = self._reg_key[live]
        poss = self._reg_pos[live]
        cap = self._reg_cap
        while (len(keys) + 1) * 2 > cap:
            cap *= 2
        rk, rp = self._reg_build_arrays(keys, poss, cap)
        self._reg_key, self._reg_pos = rk, rp
        self._reg_cap = cap
        self._reg_fill = self._reg_live = len(keys)

    @staticmethod
    def _reg_build_arrays(keys, poss, cap):
        """Vectorized probe-round build (the `_build_table` bidding
        idiom): round p, every unplaced key bids for home + p*step;
        first bidder per empty slot wins."""
        rk = np.full(cap, -1, np.int64)
        rp = np.zeros(cap, np.int32)
        n = len(keys)
        if not n:
            return rk, rp
        h = _mix64_np(keys.astype(np.uint64))
        home = (h & np.uint64(cap - 1)).astype(np.int64)
        step = (((h >> np.uint64(32)) | np.uint64(1)) & np.uint64(
            cap - 1
        )).astype(np.int64)
        unplaced = np.arange(n)
        for p in range(cap):
            if not len(unplaced):
                break
            idx = (home[unplaced] + p * step[unplaced]) & (cap - 1)
            free = rk[idx] == -1
            cand = unplaced[free]
            cidx = idx[free]
            _, first = np.unique(cidx, return_index=True)
            win, widx = cand[first], cidx[first]
            rk[widx] = keys[win]
            rp[widx] = poss[win]
            pm = np.zeros(n, bool)
            pm[win] = True
            unplaced = unplaced[~pm[unplaced]]
        assert not len(unplaced), "csr registry build did not converge"
        return rk, rp

    # -- structure ---------------------------------------------------------
    def _grow_fcap(self, need: int) -> None:
        nf = max(self._fcap, _next_pow2(need))
        if nf == self._fcap:
            return
        for name in ("csr_off", "csr_len"):
            old = getattr(self, name)
            new = np.zeros((self.shards, nf), np.int32)
            new[:, : self._fcap] = old
            setattr(self, name, new)
            # per-array resync: only the (small) region tables re-upload
            self._log_resync(name)
        self._fcap = nf

    def _grow_hot(self) -> None:
        nh = self._hcap * 2
        for name in ("hot_fid", "hot_slot"):
            old = getattr(self, name)
            new = np.full((self.shards, nh), -1, np.int32)
            new[:, : self._hcap] = old  # append-only: indices preserved
            setattr(self, name, new)
            self._log_resync(name)
        self._hcap = nh

    def pack(self, filter_capacity: int) -> None:
        """Grow the region tables to cover `filter_capacity` fids (the
        serving snapshot gathers a real region for every matched fid)."""
        if filter_capacity > self._fcap:
            self._grow_fcap(filter_capacity)

    def maybe_absorb(self) -> bool:
        """Serve-time hot bound: fold an oversized hot segment into the
        packed CSR before the next snapshot (see HOT_SERVE_MAX). Runs on
        the mutating thread (the dirty prepare); one epoch bump."""
        if self.hot_fill <= self.HOT_SERVE_MAX:
            return False
        self._rebuild()
        return True

    @property
    def max_region(self) -> int:
        """Largest allocated packed region (diagnostics; the kernel's
        gather window is sized from Kslot, not from this)."""
        return int(self.csr_len.max()) if self.csr_len.size else 0

    @property
    def hot_fill(self) -> int:
        return sum(self._hot_tail) - self.hot_tombs

    @property
    def nbytes(self) -> int:
        """Device-table footprint (the `sub_table_bytes` number): the
        five mirrored arrays, exactly what the segment manager uploads."""
        return (
            self.csr_off.nbytes
            + self.csr_len.nbytes
            + self.csr_slots.nbytes
            + self.hot_fid.nbytes
            + self.hot_slot.nbytes
        )

    # -- mutation ----------------------------------------------------------
    def add(self, fid: int, slot: int) -> bool:
        key = self._key(fid, slot)
        if self._reg_get(key) is not None:
            return False  # already live (idempotent, like a bitmap OR)
        self._grow_fcap(fid + 1)
        s = slot % self.shards
        if self._hot_tail[s] >= self._hcap:
            if sum(self._hot_tail) - self.hot_tombs >= self.HOT_ABSORB_MAX:
                # no compactor is draining hot: fold inline (epoch bump)
                self._rebuild([(fid, slot)])
                return True
            self._grow_hot()
        h = self._hot_tail[s]
        self._hot_tail[s] = h + 1
        self.hot_fid[s, h] = fid
        self._log("hot_fid", s * self._hcap + h, fid)
        self.hot_slot[s, h] = slot
        self._log("hot_slot", s * self._hcap + h, slot)
        self._reg_set(key, h | HOT_POS)
        self.live += 1
        if slot > self.max_slot:
            self.max_slot = slot
        if self._journal is not None:
            self._journal.append(("add", fid, slot))
        return True

    def remove(self, fid: int, slot: int) -> bool:
        pos = self._reg_del(self._key(fid, slot))
        if pos is None:
            return False
        s = slot % self.shards
        if pos & HOT_POS:
            h = pos & ~HOT_POS
            self.hot_fid[s, h] = -1
            self._log("hot_fid", s * self._hcap + h, -1)
            self.hot_tombs += 1
        else:
            self.csr_slots[s, pos] = -1
            self._log("csr_slots", s * self._pcap + pos, -1)
            self.packed_tombs += 1
        self.live -= 1
        if self._journal is not None:
            self._journal.append(("remove", fid, slot))
        return True

    def slots_of(self, fid: int, out=None) -> np.ndarray:
        """All live slots of one fid (vectorized scans; used by the
        overflow-row dense fallback and tests — NOT the batch path)."""
        parts = []
        if fid < self._fcap:
            for s in range(self.shards):
                o = int(self.csr_off[s, fid])
                n = int(self.csr_len[s, fid])
                seg = self.csr_slots[s, o : o + n]
                parts.append(seg[seg >= 0])
        m = self.hot_fid == fid
        if m.any():
            parts.append(self.hot_slot[m])
        if not parts:
            return np.empty(0, np.int32)
        return np.concatenate(parts)

    def live_pairs(self) -> Tuple[np.ndarray, np.ndarray]:
        """(fids, slots) of every live subscription — vectorized array
        scans (rebuilds, snapshots, representation flips)."""
        return self._pairs_from(
            self.csr_len, self.csr_slots, self.hot_fid, self.hot_slot
        )

    @staticmethod
    def _pairs_from(csr_len, csr_slots, hot_fid, hot_slot):
        fids, slots = [], []
        S = csr_len.shape[0]
        for s in range(S):
            total = int(csr_len[s].sum())
            if total:
                fid_of_pos = np.repeat(
                    np.arange(csr_len.shape[1], dtype=np.int64), csr_len[s]
                )
                seg = csr_slots[s, :total]
                m = seg >= 0
                fids.append(fid_of_pos[m])
                slots.append(seg[m].astype(np.int64))
        hm = hot_fid >= 0
        if hm.any():
            fids.append(hot_fid[hm].astype(np.int64))
            slots.append(hot_slot[hm].astype(np.int64))
        if not fids:
            return (np.empty(0, np.int64), np.empty(0, np.int64))
        return np.concatenate(fids), np.concatenate(slots)

    def _rebuild(self, extra: List[Tuple[int, int]] = ()) -> None:
        """Inline full rebuild (bulk loads, re-sharding, the hot safety
        valve): merge live + `extra` pairs into a fresh exact-size CSR.
        One epoch bump — the op-log path never sees O(table) writes."""
        fids, slots = self.live_pairs()
        if extra:
            fids = np.concatenate(
                [fids, np.array([e[0] for e in extra], np.int64)]
            )
            slots = np.concatenate(
                [slots, np.array([e[1] for e in extra], np.int64)]
            )
        self._structure_gen += 1
        self._journal = None
        built = self._build(
            fids, slots, self.shards, max(self._fcap, 64)
        )
        self._install(built)
        self._bump()

    @staticmethod
    def _build(fids, slots, shards: int, fcap: int) -> Dict:
        """Pure-numpy CSR build from (fid, slot) pairs (dedup'd): safe on
        any thread — this is what the compaction executor runs."""
        if len(fids):
            key = (fids.astype(np.int64) << 32) | slots.astype(np.int64)
            key = np.unique(key)  # dedup + sorted by (fid, slot)
            fids = (key >> 32).astype(np.int64)
            slots = (key & 0xFFFFFFFF).astype(np.int64)
            fcap = max(fcap, _next_pow2(int(fids.max()) + 1))
        S = shards
        shard = (slots % S).astype(np.int64) if len(slots) else slots
        counts = np.zeros((S, fcap), np.int64)
        if len(fids):
            np.add.at(counts, (shard, fids), 1)
        per_total = counts.sum(axis=1)
        pcap = max(256, _next_pow2(int(per_total.max()) if S else 0))
        csr_len = counts.astype(np.int32)
        csr_off = np.zeros((S, fcap), np.int32)
        csr_slots = np.full((S, pcap), -1, np.int32)
        poss = np.zeros(len(fids), np.int64)
        for s in range(S):
            off = np.cumsum(counts[s]) - counts[s]
            csr_off[s] = off.astype(np.int32)
            m = shard == s
            # key-sorted pairs are already grouped by fid (ascending):
            # position = region offset + rank within the fid run, where
            # rank = own index - index of the run's first element
            sf = fids[m]
            if len(sf):
                idx = np.arange(len(sf))
                rank = idx - np.searchsorted(sf, sf, side="left")
                pos = off[sf] + rank
                csr_slots[s, pos] = slots[m].astype(np.int32)
                poss[m] = pos
        keys = (
            (fids << 32) | slots
            if len(fids)
            else np.empty(0, np.int64)
        )
        cap = 1024
        while (len(keys) + 1) * 2 > cap:
            cap *= 2
        rk, rp = CsrTable._reg_build_arrays(
            keys, poss.astype(np.int32), cap
        )
        return {
            "fcap": fcap,
            "pcap": pcap,
            "csr_off": csr_off,
            "csr_len": csr_len,
            "csr_slots": csr_slots,
            "reg_key": rk,
            "reg_pos": rp,
            "reg_cap": cap,
            "n": len(fids),
            "max_slot": int(slots.max()) if len(slots) else -1,
        }

    # oplog-covered-by: every caller bumps the epoch after install
    def _install(self, built: Dict) -> None:
        S = self.shards
        self._fcap = built["fcap"]
        self._pcap = built["pcap"]
        self.csr_off = built["csr_off"]
        self.csr_len = built["csr_len"]
        self.csr_slots = built["csr_slots"]
        self._hcap = self.HOT_MIN
        self.hot_fid = np.full((S, self._hcap), -1, np.int32)
        self.hot_slot = np.full((S, self._hcap), -1, np.int32)
        self._hot_tail = [0] * S
        self.hot_tombs = 0
        self.packed_tombs = 0
        self.live = built["n"]
        self.max_slot = max(self.max_slot, built["max_slot"])
        self._reg_key = built["reg_key"]
        self._reg_pos = built["reg_pos"]
        self._reg_cap = built["reg_cap"]
        self._reg_fill = self._reg_live = built["n"]

    def bulk_add(self, fids, slots) -> None:
        """Vectorized bulk load: one rebuild + one epoch bump (the dense
        table's `bulk_add` contract)."""
        fids = np.asarray(fids, dtype=np.int64)
        slots = np.asarray(slots, dtype=np.int64)
        if not len(fids):
            return
        self._rebuild(list(zip(fids.tolist(), slots.tolist())))

    def reshard(self, shards: int) -> None:
        """Re-partition the table over a new shard count (mesh attach
        after subscriptions already landed). Epoch-bump rebuild."""
        if shards == self.shards:
            return
        fids, slots = self.live_pairs()
        self.shards = max(1, int(shards))
        self._structure_gen += 1
        self._journal = None
        built = self._build(fids, slots, self.shards, 64)
        self._install(built)
        self._bump()

    def device_snapshot(self) -> Dict[str, np.ndarray]:
        return {
            "csr_off": self.csr_off,
            "csr_len": self.csr_len,
            "csr_slots": self.csr_slots,
            "hot_fid": self.hot_fid,
            "hot_slot": self.hot_slot,
        }

    # -- background compaction (ops/segments.SegmentCompactor cycle) -------
    def begin_compact(self) -> Dict:
        cap = {
            "csr_len": self.csr_len.copy(),
            "csr_slots": self.csr_slots.copy(),
            "hot_fid": self.hot_fid.copy(),
            "hot_slot": self.hot_slot.copy(),
            "shards": self.shards,
            "fcap": self._fcap,
            "gen": self._structure_gen,
        }
        self._journal = []
        return cap

    @staticmethod
    def build_compact(cap: Dict) -> Dict:
        fids, slots = CsrTable._pairs_from(
            cap["csr_len"], cap["csr_slots"], cap["hot_fid"],
            cap["hot_slot"],
        )
        built = CsrTable._build(fids, slots, cap["shards"], cap["fcap"])
        built["gen"] = cap["gen"]
        return built

    def apply_compact(self, built: Dict) -> bool:
        """Install a built CSR (loop thread) + replay the journal of
        mutations that raced the build. False = capture invalidated by a
        structural rebuild (the cycle aborts cleanly)."""
        if self._journal is None or built["gen"] != self._structure_gen:
            self._journal = None
            return False
        journal, self._journal = self._journal, None
        self._structure_gen += 1
        self._install(built)
        self._bump()
        for op, fid, slot in journal:
            if op == "add":
                self.add(fid, slot)
            else:
                self.remove(fid, slot)
        return True


class CsrSegmentOwner:
    """Compaction adapter for a sparse `SubscriberTable` + its segment
    manager: merge ``packed - tombstones + hot`` into a fresh exact-size
    CSR off the subscribe path, pre-uploading the packed arrays on the
    compact executor (`SegmentCompactor` drives the cycle)."""

    key = "bitmaps"

    def __init__(self, subtab, manager, placement=None,
                 hot_entries: int = 1024, tombstone_frac: float = 0.25):
        self.subtab = subtab  # the facade; .csr is the live CsrTable
        self.manager = manager
        self._placement = placement
        self.hot_entries = hot_entries
        self.tombstone_frac = tombstone_frac

    def needs_compact(self) -> bool:
        sp = self.subtab.csr
        if sp is None:
            return False
        if sp.hot_fill >= self.hot_entries:
            return True
        tombs = sp.packed_tombs + sp.hot_tombs
        return tombs > 0 and tombs >= self.tombstone_frac * max(
            1, sp.live
        )

    def begin(self):
        return self.subtab.csr.begin_compact()

    def build(self, cap):
        built = CsrTable.build_compact(cap)
        # pre-upload the packed arrays on THIS (executor) thread: the
        # built table is immutable, so the device_put is race-free and
        # the serving path adopts instead of paying the upload
        import jax

        dev = {}
        for name in ("csr_off", "csr_len", "csr_slots"):
            if self._placement is not None:
                dev[name] = self._placement(name, built[name])
            else:
                dev[name] = jax.device_put(built[name])
        built["dev"] = dev
        return built

    def apply(self, built):
        sp = self.subtab.csr
        if sp is None:  # the representation flipped away mid-cycle
            return None
        merged = sp.hot_fill
        if not sp.apply_compact(built):
            return None
        return self.subtab.epoch, built["dev"], 0, merged
