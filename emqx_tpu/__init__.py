"""emqx_tpu — a TPU-native messaging framework with the capability surface of EMQX.

The reference (surveyed in /root/repo/SURVEY.md) is EMQX, a distributed MQTT
broker written in Erlang/OTP. This package is a ground-up redesign for TPU:

- The wildcard-topic routing hot path (reference: apps/emqx/src/emqx_trie.erl,
  emqx_router.erl, emqx_broker.erl dispatch) is a dense NFA transition table
  matched in SPMD batches on TPU via JAX/XLA (`emqx_tpu.ops`).
- The broker data plane (sessions, QoS, dispatch) is an asyncio host layer
  (`emqx_tpu.broker`, `emqx_tpu.transport`) with native C++ components for the
  codec hot path (`emqx_tpu.mqtt.codec_native`).
- Multi-chip scaling uses `jax.sharding.Mesh` + shard_map collectives
  (`emqx_tpu.parallel`), not per-node RPC as in the reference.
"""

__version__ = "0.1.0"
