"""Per-connection pump: socket bytes <-> frames <-> channel.

Parity with the reference connection process (apps/emqx/src/
emqx_connection.erl: recvloop :356-390, parse->handle :462-493, serialize +
send, keepalive enforcement). The MQTT spec's 1.5x keepalive grace is
enforced here; an idle pre-CONNECT socket is closed after idle_timeout
(emqx_channel idle timer parity).
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional

from emqx_tpu.broker.channel import Channel, ChannelConfig
from emqx_tpu.mqtt import packet as pkt
from emqx_tpu.mqtt.frame import FrameError, Parser, serialize


class Connection:
    """One connected socket; owns the parser, the channel, and timers."""

    def __init__(self, broker, cm, reader, writer, config: ChannelConfig, ctx=None):
        self.reader = reader
        self.writer = writer
        peer = writer.get_extra_info("peername") or ("?", 0)
        self.channel = Channel(
            broker,
            cm,
            sink=self,
            conninfo={"peerhost": peer[0], "peerport": peer[1]},
            config=config,
        )
        self.parser = Parser(max_size=config.caps.max_packet_size)
        self.last_rx = time.time()
        self._closing = False
        self._tasks: list = []
        # rate limiting / congestion / forced GC (TransportContext wiring)
        self.limiters = None
        self.congestion = None
        self.forced_gc = None
        if ctx is not None:
            if ctx.limiters is not None:
                # None when all types are unlimited -> zero hot-path cost
                self.limiters = ctx.limiters.container(
                    "bytes_in", "message_in"
                )
            if ctx.alarms is not None:
                from emqx_tpu.transport.congestion import Congestion

                self.congestion = Congestion(alarms=ctx.alarms)
            if ctx.make_forced_gc is not None:
                self.forced_gc = ctx.make_forced_gc()

    # -- sink interface used by the channel -------------------------------
    def send_packet(self, p) -> None:
        if self._closing:
            return
        try:
            self.writer.write(serialize(p, self.channel.version))
        except Exception:
            self.close("send_error")

    def send_bytes(self, b: bytes) -> None:
        """Pre-serialized frame (the channel's QoS0 fan-out cache:
        serialize once per message, write to every subscriber socket)."""
        if self._closing:
            return
        try:
            self.writer.write(b)
        except Exception:
            self.close("send_error")

    def send_segments(self, segs) -> None:
        """Pre-serialized frame segments (the batched slab serializer:
        writelines of memoryviews — shared heads/tails and slab frame
        views land on the socket without an intermediate join)."""
        if self._closing:
            return
        try:
            self.writer.writelines(segs)
        except Exception:
            self.close("send_error")

    def close(self, reason: str) -> None:
        if self._closing:
            return
        self._closing = True
        try:
            self.writer.close()
        except Exception:
            pass

    # -- pump --------------------------------------------------------------
    async def run(self) -> None:
        keeper = asyncio.ensure_future(self._keepalive_loop())
        ticker = asyncio.ensure_future(self._tick_loop())
        try:
            while not self._closing:
                data = await self.reader.read(65536)
                if not data:
                    break
                self.last_rx = time.time()
                if self.forced_gc is not None:
                    self.forced_gc.inc(0, len(data))
                if self.limiters is not None:
                    # bytes_in: pause the read loop until tokens accrue
                    # (emqx_connection rate-limit pause, :103-120)
                    await self._limited("bytes_in", len(data))
                try:
                    for p in self.parser.feed(data):
                        if (
                            self.limiters is not None
                            and p.type == pkt.PUBLISH
                        ):
                            await self._limited("message_in", 1)
                        if self.forced_gc is not None:
                            self.forced_gc.inc(1, 0)
                        await self.channel.handle_in(p)
                except FrameError as e:
                    self.channel.disconnect_reason = f"frame_error:{e.reason}"
                    if self.channel.version == pkt.MQTT_V5:
                        self.send_packet(
                            pkt.Disconnect(reason_code=pkt.RC_MALFORMED_PACKET)
                        )
                    break
                await self._drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            keeper.cancel()
            ticker.cancel()
            if self.congestion is not None:
                self.congestion.on_close(self.channel.client_id)
            self.close("sock_closed")
            try:
                await self.writer.wait_closed()
            except Exception:
                pass
            await self.channel.on_sock_closed()

    async def _limited(self, type_: str, n: float) -> None:
        """Charge the limiter and pause for the returned interval.

        The charge always lands (token debt), so sustained throughput
        converges on the configured rate for any chunk size. The pause is
        counted as liveness — the client IS sending, we are throttling it —
        so keepalive must not fire mid-throttle."""
        wait = self.limiters.consume(type_, n)
        # sleep in short slices, refreshing last_rx each one, so keepalive
        # never fires during a long throttle pause (waits reach 60s)
        while wait > 0 and not self._closing:
            step = min(wait, 5.0)
            self.last_rx = time.time()
            await asyncio.sleep(step)
            wait -= step
        self.last_rx = time.time()

    async def _drain(self) -> None:
        try:
            await self.writer.drain()
        except ConnectionError:
            self.close("sock_error")

    async def _keepalive_loop(self) -> None:
        # pre-CONNECT idle timeout (poll so keepalive arms right after CONNECT)
        start = time.time()
        while self.channel.state == "idle":
            if time.time() - start > self.channel.config.idle_timeout:
                self.close("idle_timeout")
                return
            await asyncio.sleep(0.2)
        while not self._closing:
            ka = self.channel.keepalive
            if ka <= 0:
                return
            await asyncio.sleep(ka / 2)
            if time.time() - self.last_rx > ka * 1.5:
                self.channel.disconnect_reason = "keepalive_timeout"
                self.close("keepalive_timeout")
                return

    async def _tick_loop(self) -> None:
        while not self._closing:
            await asyncio.sleep(
                max(1.0, self.channel.config.session.retry_interval / 2)
            )
            if self.channel.state == "connected":
                self.channel.tick()
                await self._drain()
            if self.congestion is not None:
                self.congestion.check(
                    getattr(self.writer, "transport", None),
                    self.channel.client_id,
                )
