"""DTLS 1.2 PSK endpoint for the UDP gateways (CoAP / LwM2M / MQTT-SN).

The reference offers every UDP gateway listener as ``udp | dtls``
(apps/emqx_gateway/src/emqx_gateway_schema.erl:361-371) with PSK
ciphersuites for constrained devices (emqx_psk). This module implements
the server (and a scripted test client) from scratch for exactly one
suite — TLS_PSK_WITH_AES_128_GCM_SHA256 (RFC 4279 + RFC 5487) over
DTLS 1.2 (RFC 6347):

- stateless HelloVerifyRequest cookie exchange (DoS guard: no state is
  allocated until the client echoes an HMAC cookie bound to its address)
- PSK key exchange: premaster = len||zeros||len||psk, master via the
  TLS 1.2 P_SHA256 PRF, AES-128-GCM record protection (AEAD nonce =
  4-byte write_IV salt + 8-byte explicit epoch+seq, RFC 5288)
- single-fragment handshake only (PSK flights are far below any
  realistic PMTU; fragmented handshake messages are rejected)
- anti-replay: strictly-increasing record sequence per epoch (reordered
  datagrams drop — the gateways' own retransmission recovers)

Identities come from the broker's PSK store (auth/psk.py — the same
store the reference's emqx_psk file feeds). AES-GCM itself comes from
the `cryptography` package; everything protocol-level is implemented
here.
"""

from __future__ import annotations

import asyncio
import hmac
import hashlib
import os
import struct
import time
from typing import Callable, Dict, Optional, Tuple

# `cryptography` is imported lazily: the module must stay importable on
# hosts without it (gateways default to plain UDP), and a DTLS listener
# should fail at START time with an actionable error, not at import.
try:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except ImportError:  # pragma: no cover - exercised on slim images
    AESGCM = None

HAVE_AESGCM = AESGCM is not None


def require_dtls_support() -> None:
    """Raise a clear error when the AEAD backend is unavailable; called
    when a `transport: dtls` listener actually starts."""
    if AESGCM is None:
        raise RuntimeError(
            "DTLS support requires the 'cryptography' package "
            "(AES-128-GCM AEAD); install it or switch the gateway "
            "listener back to `transport: udp`"
        )


# record content types
CT_CCS = 20
CT_ALERT = 21
CT_HANDSHAKE = 22
CT_APPDATA = 23
# handshake message types
HT_CLIENT_HELLO = 1
HT_SERVER_HELLO = 2
HT_HELLO_VERIFY = 3
HT_SERVER_HELLO_DONE = 14
HT_CLIENT_KEY_EXCHANGE = 16
HT_FINISHED = 20

DTLS12 = 0xFEFD  # {254, 253}
DTLS10 = 0xFEFF  # legal in ClientHello record headers
SUITE_PSK_AES128_GCM_SHA256 = 0x00A8

_REC = struct.Struct("!BHHHIH")  # type, ver, epoch, seq_hi16 ... manual


def _hmac256(key: bytes, msg: bytes) -> bytes:
    return hmac.new(key, msg, hashlib.sha256).digest()


def prf_sha256(secret: bytes, label: bytes, seed: bytes, n: int) -> bytes:
    """TLS 1.2 PRF (P_SHA256, RFC 5246 §5)."""
    seed = label + seed
    out = b""
    a = seed
    while len(out) < n:
        a = _hmac256(secret, a)
        out += _hmac256(secret, a + seed)
    return out[:n]


def psk_premaster(psk: bytes) -> bytes:
    """RFC 4279 §2: other_secret = N zero octets, N = len(psk)."""
    n = len(psk)
    return struct.pack("!H", n) + b"\x00" * n + struct.pack("!H", n) + psk


def pack_record(ctype: int, epoch: int, seq: int, frag: bytes,
                version: int = DTLS12) -> bytes:
    return (
        struct.pack("!BH", ctype, version)
        + struct.pack("!HIH", epoch, 0, 0)[:2]  # epoch
        + seq.to_bytes(6, "big")
        + struct.pack("!H", len(frag))
        + frag
    )


def parse_records(data: bytes):
    """-> [(ctype, version, epoch, seq, fragment)] (a datagram may carry
    several records — a whole handshake flight typically does)."""
    out = []
    off = 0
    while off + 13 <= len(data):
        ctype, version = struct.unpack_from("!BH", data, off)
        epoch = int.from_bytes(data[off + 3 : off + 5], "big")
        seq = int.from_bytes(data[off + 5 : off + 11], "big")
        (length,) = struct.unpack_from("!H", data, off + 11)
        off += 13
        if off + length > len(data):
            break
        out.append((ctype, version, epoch, seq, data[off : off + length]))
        off += length
    return out


def pack_handshake(msg_type: int, msg_seq: int, body: bytes) -> bytes:
    """DTLS handshake header: single-fragment form."""
    ln = len(body).to_bytes(3, "big")
    return (
        bytes([msg_type]) + ln + struct.pack("!H", msg_seq)
        + (0).to_bytes(3, "big") + ln + body
    )


def parse_handshake(frag: bytes):
    """-> (msg_type, msg_seq, body, raw_single_fragment) or None.
    Rejects fragmented messages (PSK flights never need them)."""
    if len(frag) < 12:
        return None
    msg_type = frag[0]
    length = int.from_bytes(frag[1:4], "big")
    (msg_seq,) = struct.unpack_from("!H", frag, 4)
    frag_off = int.from_bytes(frag[6:9], "big")
    frag_len = int.from_bytes(frag[9:12], "big")
    if frag_off != 0 or frag_len != length or len(frag) < 12 + length:
        return None
    body = frag[12 : 12 + length]
    return msg_type, msg_seq, body, frag[: 12 + length]


class _Cipher:
    """One direction of AES-128-GCM record protection (RFC 5288)."""

    def __init__(self, key: bytes, iv_salt: bytes):
        require_dtls_support()
        self.aead = AESGCM(key)
        self.salt = iv_salt

    def seal(self, epoch: int, seq: int, ctype: int, plain: bytes) -> bytes:
        explicit = struct.pack("!H", epoch) + seq.to_bytes(6, "big")
        nonce = self.salt + explicit
        aad = explicit + struct.pack("!BHH", ctype, DTLS12, len(plain))
        return explicit + self.aead.encrypt(nonce, plain, aad)

    def open(self, epoch: int, seq: int, ctype: int,
             frag: bytes) -> Optional[bytes]:
        if len(frag) < 8 + 16:
            return None
        explicit, ct = frag[:8], frag[8:]
        nonce = self.salt + explicit
        aad = (
            struct.pack("!H", epoch) + seq.to_bytes(6, "big")
            + struct.pack("!BHH", ctype, DTLS12, len(ct) - 16)
        )
        try:
            return self.aead.decrypt(nonce, ct, aad)
        except Exception:
            return None


class _Session:
    """Per-peer server-side state machine."""

    def __init__(self):
        self.state = "wait_hello"  # -> wait_cke -> wait_finished -> open
        self.client_random = b""
        self.server_random = b""
        self.handshake_hash = hashlib.sha256()
        self.master: bytes = b""
        self.read: Optional[_Cipher] = None
        self.write: Optional[_Cipher] = None
        self.psk_identity: str = ""
        self.next_rx_hs_seq = 1  # CH0 consumed statelessly
        self.tx_hs_seq = 1  # HVR was 0
        self.tx_epoch = 0
        self.tx_seq = 0
        self.rx_epoch = 0
        self.rx_last_seq = -1
        self.last_seen = time.monotonic()

    def next_record(self, ctype: int, frag: bytes) -> bytes:
        seq = self.tx_seq
        self.tx_seq += 1
        if self.tx_epoch > 0 and self.write is not None:
            frag = self.write.seal(self.tx_epoch, seq, ctype, frag)
        return pack_record(ctype, self.tx_epoch, seq, frag)


class DtlsEndpoint:
    """Server endpoint multiplexing DTLS sessions over one UDP socket.

    `psk_lookup(identity: str) -> Optional[bytes]` resolves identities
    (wire to auth/psk.PskStore.lookup). Decrypted application data goes
    to `recv_plain(plain, addr)`; `sendto(plain, addr)` encrypts to an
    established peer (silently dropped otherwise — the gateway layers
    all retransmit)."""

    COOKIE_LIFE_S = 60.0
    SESSION_IDLE_S = 600.0

    def __init__(self, psk_lookup: Callable[[str], Optional[bytes]],
                 recv_plain: Callable[[bytes, tuple], None]):
        self.psk_lookup = psk_lookup
        self.recv_plain = recv_plain
        self._transport = None
        self._sessions: Dict[tuple, _Session] = {}
        self._cookie_key = os.urandom(16)

    # -- plumbing ---------------------------------------------------------
    def attach(self, transport) -> None:
        self._transport = transport

    def _raw_send(self, data: bytes, addr) -> None:
        if self._transport is not None:
            self._transport.sendto(data, addr)

    def forget(self, addr) -> None:
        self._sessions.pop(addr, None)

    def sweep(self, now: Optional[float] = None) -> int:
        now = now or time.monotonic()
        gone = [
            a for a, s in self._sessions.items()
            if now - s.last_seen > self.SESSION_IDLE_S
        ]
        for a in gone:
            del self._sessions[a]
        return len(gone)

    def established(self, addr) -> bool:
        s = self._sessions.get(addr)
        return s is not None and s.state == "open"

    def identity(self, addr) -> Optional[str]:
        s = self._sessions.get(addr)
        return s.psk_identity if s is not None else None

    # -- outbound ---------------------------------------------------------
    def sendto(self, plain: bytes, addr) -> None:
        s = self._sessions.get(addr)
        if s is None or s.state != "open":
            return
        self._raw_send(s.next_record(CT_APPDATA, plain), addr)

    # -- inbound ----------------------------------------------------------
    def datagram_received(self, data: bytes, addr) -> None:
        for ctype, _ver, epoch, seq, frag in parse_records(data):
            try:
                self._record(ctype, epoch, seq, frag, addr)
            except Exception:
                self._fatal(addr, 80)  # internal_error

    def _fatal(self, addr, desc: int) -> None:
        s = self._sessions.pop(addr, None)
        frag = bytes([2, desc])
        if s is not None and s.state == "open" and s.write is not None:
            self._raw_send(s.next_record(CT_ALERT, frag), addr)
        else:
            self._raw_send(pack_record(CT_ALERT, 0, 0, frag), addr)

    def _record(self, ctype, epoch, seq, frag, addr) -> None:
        s = self._sessions.get(addr)
        if s is not None:
            s.last_seen = time.monotonic()
            if epoch == s.rx_epoch:
                if seq <= s.rx_last_seq:
                    return  # replay/reorder: drop
            elif epoch != s.rx_epoch + 1:
                return
            if epoch > 0 and s.read is not None:
                frag = s.read.open(epoch, seq, ctype, frag)
                if frag is None:
                    return  # bad MAC: drop silently (DTLS rule)
            if epoch == s.rx_epoch:
                s.rx_last_seq = seq
        if ctype == CT_HANDSHAKE:
            self._handshake(frag, addr, epoch, seq)
        elif ctype == CT_CCS:
            if s is not None and s.state == "wait_finished_ccs":
                s.rx_epoch += 1
                s.rx_last_seq = -1
                s.state = "wait_finished"
        elif ctype == CT_APPDATA:
            if s is not None and s.state == "open":
                self.recv_plain(frag, addr)
        elif ctype == CT_ALERT:
            self._sessions.pop(addr, None)

    # -- handshake --------------------------------------------------------
    def _cookie(self, addr, client_random: bytes) -> bytes:
        msg = repr(addr).encode() + client_random
        return _hmac256(self._cookie_key, msg)[:16]

    def _handshake(self, frag: bytes, addr, epoch: int, seq: int) -> None:
        p = parse_handshake(frag)
        if p is None:
            return
        msg_type, _msg_seq, body, raw = p
        if msg_type == HT_CLIENT_HELLO:
            self._client_hello(body, raw, addr)
            return
        s = self._sessions.get(addr)
        if s is None:
            return
        if msg_type == HT_CLIENT_KEY_EXCHANGE and s.state == "wait_cke":
            self._client_key_exchange(s, body, raw, addr)
        elif msg_type == HT_FINISHED and s.state == "wait_finished":
            self._client_finished(s, body, raw, addr)

    def _client_hello(self, body: bytes, raw: bytes, addr) -> None:
        # client_version(2) random(32) session_id cookie cipher_suites
        if len(body) < 35:
            return
        off = 2
        client_random = body[off : off + 32]
        off += 32
        sid_len = body[off]
        off += 1 + sid_len
        if off >= len(body):
            return
        cookie_len = body[off]
        cookie = body[off + 1 : off + 1 + cookie_len]
        off += 1 + cookie_len
        if off + 2 > len(body):
            return
        (cs_len,) = struct.unpack_from("!H", body, off)
        off += 2
        suites = {
            struct.unpack_from("!H", body, off + i)[0]
            for i in range(0, cs_len, 2)
            if off + i + 2 <= len(body)
        }
        want = self._cookie(addr, client_random)
        if not cookie or not hmac.compare_digest(cookie, want):
            # stateless verify flight (RFC 6347 §4.2.1)
            hvr = struct.pack("!H", DTLS12) + bytes([len(want)]) + want
            self._raw_send(
                pack_record(
                    CT_HANDSHAKE, 0, 0,
                    pack_handshake(HT_HELLO_VERIFY, 0, hvr),
                ),
                addr,
            )
            return
        if SUITE_PSK_AES128_GCM_SHA256 not in suites:
            self._fatal(addr, 40)  # handshake_failure
            return
        s = _Session()
        self._sessions[addr] = s
        s.client_random = client_random
        s.server_random = os.urandom(32)
        s.rx_last_seq = -1  # cookie CH consumed; handshake hash starts HERE
        s.handshake_hash.update(raw)  # CH with cookie (CH0/HVR excluded)
        sh = (
            struct.pack("!H", DTLS12)
            + s.server_random
            + b"\x00"  # empty session id
            + struct.pack("!H", SUITE_PSK_AES128_GCM_SHA256)
            + b"\x00"  # null compression
        )
        flight = b""
        for ht, hbody in (
            (HT_SERVER_HELLO, sh),
            (HT_SERVER_HELLO_DONE, b""),
        ):
            msg = pack_handshake(ht, s.tx_hs_seq, hbody)
            s.tx_hs_seq += 1
            s.handshake_hash.update(msg)
            flight += s.next_record(CT_HANDSHAKE, msg)
        # transition BEFORE the send: the peer's next flight may arrive
        # (or, on a loopback transport, re-enter) before send returns
        s.state = "wait_cke"
        self._raw_send(flight, addr)

    def _client_key_exchange(self, s: _Session, body: bytes, raw: bytes,
                             addr) -> None:
        if len(body) < 2:
            return self._fatal(addr, 47)  # illegal_parameter
        (id_len,) = struct.unpack_from("!H", body, 0)
        identity = body[2 : 2 + id_len].decode("utf-8", "replace")
        psk = self.psk_lookup(identity)
        if psk is None:
            return self._fatal(addr, 115)  # unknown_psk_identity
        s.psk_identity = identity
        s.handshake_hash.update(raw)
        s.master = prf_sha256(
            psk_premaster(psk), b"master secret",
            s.client_random + s.server_random, 48,
        )
        kb = prf_sha256(
            s.master, b"key expansion",
            s.server_random + s.client_random, 40,
        )
        # client_write_key(16) server_write_key(16) client_IV(4) server_IV(4)
        s.read = _Cipher(kb[0:16], kb[32:36])
        s.write = _Cipher(kb[16:32], kb[36:40])
        s.state = "wait_finished_ccs"

    def _client_finished(self, s: _Session, body: bytes, raw: bytes,
                         addr) -> None:
        want = prf_sha256(
            s.master, b"client finished",
            s.handshake_hash.digest(), 12,
        )
        if not hmac.compare_digest(body, want):
            return self._fatal(addr, 51)  # decrypt_error
        s.handshake_hash.update(raw)
        # server flight: CCS (epoch 0) + Finished (epoch 1)
        ccs = s.next_record(CT_CCS, b"\x01")
        s.tx_epoch += 1
        s.tx_seq = 0
        verify = prf_sha256(
            s.master, b"server finished",
            s.handshake_hash.digest(), 12,
        )
        fin = s.next_record(
            CT_HANDSHAKE, pack_handshake(HT_FINISHED, s.tx_hs_seq, verify)
        )
        s.tx_hs_seq += 1
        s.state = "open"  # before the send (see _client_hello)
        self._raw_send(ccs + fin, addr)


def build_endpoint_for_gateway(gw, recv_plain) -> DtlsEndpoint:
    """Wire a gateway's ``transport: dtls`` listener: identities resolve
    from the listener's own ``psk`` map (identity -> hex or utf-8
    secret) first, then the broker-wide PSK store (auth/psk.py — the
    emqx_psk analog), matching the reference's per-listener ssl_options
    + global PSK hook layering."""
    table: Dict[str, bytes] = {}
    for ident, secret in (gw.config.get("psk") or {}).items():
        if isinstance(secret, bytes):
            table[ident] = secret
            continue
        try:
            table[ident] = bytes.fromhex(secret)
        except ValueError:
            table[ident] = str(secret).encode()
    store = getattr(gw, "psk_store", None)

    def lookup(identity: str) -> Optional[bytes]:
        hit = table.get(identity)
        if hit is not None:
            return hit
        if store is not None:
            return store.lookup(identity)
        return None

    return DtlsEndpoint(lookup, recv_plain)


class DtlsUdpGatewayMixin:
    """Shared `transport: udp | dtls` plumbing for the UDP gateways
    (CoAP / LwM2M / MQTT-SN). Subclasses implement
    ``_plain_datagram(data, addr)`` (decode + channel dispatch) and keep
    peer channels in ``self._chans``; this mixin provides the
    dtls-aware send/forget and the datagram protocol factory so the
    demux logic lives in exactly one place."""

    _dtls = None
    _transport = None

    def _init_dtls(self) -> None:
        if self.config.get("transport") == "dtls":
            require_dtls_support()
            self._dtls = build_endpoint_for_gateway(
                self, self._plain_datagram
            )

    def _make_proto(self):
        gw = self

        class Proto(asyncio.DatagramProtocol):
            def connection_made(self, transport):
                gw._transport = transport
                if gw._dtls is not None:
                    gw._dtls.attach(transport)

            def datagram_received(self, data, addr):
                if gw._dtls is not None:
                    gw._dtls.datagram_received(data, addr)
                else:
                    gw._plain_datagram(data, addr)

        return Proto

    def sendto(self, data: bytes, peer) -> None:
        if self._dtls is not None:
            self._dtls.sendto(data, peer)
        elif self._transport is not None:
            self._transport.sendto(data, peer)

    def forget(self, peer) -> None:
        self._chans.pop(peer, None)
        if self._dtls is not None:
            self._dtls.forget(peer)


class DtlsClient:
    """Minimal scripted PSK client (tests + tooling): drives one
    handshake over a caller-supplied `send(bytes)` and consumes inbound
    datagrams via `datagram_received`. Plaintext callbacks mirror the
    server endpoint."""

    def __init__(self, identity: str, psk: bytes,
                 send: Callable[[bytes], None],
                 recv_plain: Callable[[bytes], None]):
        self.identity = identity
        self.psk = psk
        self._send = send
        self.recv_plain = recv_plain
        self.state = "start"
        self.client_random = os.urandom(32)
        self.server_random = b""
        self.handshake_hash = hashlib.sha256()
        self.master = b""
        self.read: Optional[_Cipher] = None
        self.write: Optional[_Cipher] = None
        self.tx_epoch = 0
        self.tx_seq = 0
        self.tx_hs_seq = 0
        self.rx_epoch = 0
        self.rx_last_seq = -1

    def _record(self, ctype: int, frag: bytes) -> bytes:
        seq = self.tx_seq
        self.tx_seq += 1
        if self.tx_epoch > 0 and self.write is not None:
            frag = self.write.seal(self.tx_epoch, seq, ctype, frag)
        return pack_record(ctype, self.tx_epoch, seq, frag)

    def _client_hello(self, cookie: bytes) -> bytes:
        body = (
            struct.pack("!H", DTLS12)
            + self.client_random
            + b"\x00"  # session id
            + bytes([len(cookie)]) + cookie
            + struct.pack("!HH", 2, SUITE_PSK_AES128_GCM_SHA256)
            + b"\x01\x00"  # compression: null
        )
        msg = pack_handshake(HT_CLIENT_HELLO, self.tx_hs_seq, body)
        self.tx_hs_seq += 1
        if cookie:
            self.handshake_hash.update(msg)
        return self._record(CT_HANDSHAKE, msg)

    def connect(self) -> None:
        self.state = "wait_hvr"
        self._send(self._client_hello(b""))

    def send(self, plain: bytes) -> None:
        if self.state == "open":
            self._send(self._record(CT_APPDATA, plain))

    def datagram_received(self, data: bytes) -> None:
        for ctype, _v, epoch, seq, frag in parse_records(data):
            if epoch > 0 and self.read is not None:
                frag = self.read.open(epoch, seq, ctype, frag)
                if frag is None:
                    continue
            if ctype == CT_HANDSHAKE:
                self._hs(frag)
            elif ctype == CT_CCS:
                self.rx_epoch += 1
                self.rx_last_seq = -1
            elif ctype == CT_APPDATA and self.state == "open":
                self.recv_plain(frag)

    def _hs(self, frag: bytes) -> None:
        p = parse_handshake(frag)
        if p is None:
            return
        msg_type, _seq, body, raw = p
        if msg_type == HT_HELLO_VERIFY and self.state == "wait_hvr":
            cookie_len = body[2]
            cookie = body[3 : 3 + cookie_len]
            self.state = "wait_sh"
            self._send(self._client_hello(cookie))
        elif msg_type == HT_SERVER_HELLO and self.state == "wait_sh":
            self.server_random = body[2:34]
            self.handshake_hash.update(raw)
            self.state = "wait_shd"
        elif msg_type == HT_SERVER_HELLO_DONE and self.state == "wait_shd":
            self.handshake_hash.update(raw)
            ident = self.identity.encode()
            cke_body = struct.pack("!H", len(ident)) + ident
            cke = pack_handshake(
                HT_CLIENT_KEY_EXCHANGE, self.tx_hs_seq, cke_body
            )
            self.tx_hs_seq += 1
            self.handshake_hash.update(cke)
            self.master = prf_sha256(
                psk_premaster(self.psk), b"master secret",
                self.client_random + self.server_random, 48,
            )
            kb = prf_sha256(
                self.master, b"key expansion",
                self.server_random + self.client_random, 40,
            )
            self.write = _Cipher(kb[0:16], kb[32:36])
            self.read = _Cipher(kb[16:32], kb[36:40])
            flight = self._record(CT_HANDSHAKE, cke)
            flight += self._record(CT_CCS, b"\x01")
            self.tx_epoch += 1
            self.tx_seq = 0
            verify = prf_sha256(
                self.master, b"client finished",
                self.handshake_hash.digest(), 12,
            )
            fin = pack_handshake(HT_FINISHED, self.tx_hs_seq, verify)
            self.tx_hs_seq += 1
            self.handshake_hash.update(fin)
            flight += self._record(CT_HANDSHAKE, fin)
            # transition BEFORE the send: the server's finished flight
            # may arrive synchronously on loopback transports
            self.state = "wait_server_finished"
            self._send(flight)
        elif msg_type == HT_FINISHED and self.state == "wait_server_finished":
            want = prf_sha256(
                self.master, b"server finished",
                self.handshake_hash.digest(), 12,
            )
            if hmac.compare_digest(body, want):
                self.state = "open"
