"""Transports: asyncio TCP/TLS listeners and per-connection pumps.

The reference runs one Erlang process per client over esockd/cowboy/quicer
(apps/emqx/src/emqx_connection.erl, emqx_listeners.erl). Here each client is
an asyncio task on the broker loop; the protocol state machine
(emqx_tpu.broker.channel) is sans-IO, so transports stay thin.
"""
