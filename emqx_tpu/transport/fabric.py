"""Worker-fabric wire protocol: connection workers <-> router process.

The reference scales its connection layer with one BEAM process per
connection inside a single node (emqx_connection.erl:173-176 — the
scheduler spreads them over cores). A Python host gets the same effect
with OS processes: N connection WORKERS own the client sockets (accepting
on a shared SO_REUSEPORT port, one asyncio loop + full Channel/Session
stack each), while the ROUTER process owns the single DeviceRouter and
the subscription tables. This module is the seam between them: a
length-prefixed binary protocol over a unix-domain socket, batched in
both directions so the device batch window keeps its shape.

Frames (all little-endian, u32 length prefix EXCLUDES the 5-byte header):

  [u32 len][u8 type][body]

  HELLO (w->r): u16 worker_id
  SUB   (w->r): json {h, sid, cid, f, qos, nl, rap, rh}
  UNSUB (w->r): json {sid, f}
  PUBB  (w->r): u32 seq, u32 n, n * pub_record
  DLV   (r->w): u32 n, n * dlv_record
  PUBB_ACK (r->w): u32 seq, u32 n, n * i32 delivery_count

A PUBB is acked AFTER the router dispatched (or banked) every message
in it, with per-message delivery counts — the worker-side channel
holds each QoS1/2 client ack on that confirmation, so the at-least-once
boundary sits at the router, not at the worker's socket buffer.

  pub_record: u16 tlen, topic, u32 plen, payload,
              u8 flags (qos | retain<<2 | dup<<3 | has_props<<4),
              u16 clen, from_client,
              [u32 pblen, props_block]           (iff has_props)
  dlv_record: u16 tlen, topic, u32 plen, payload,
              u8 flags (pub qos | retain<<2 | retained<<3 |
                        has_props<<4),
              u16 clen, from_client,
              [u32 pblen, props_block],          (iff has_props)
              u16 ntargets, ntargets * u32 handle

props_block is the MQTT5 encoded property block (frame.encode_properties
output) — v5 publish properties survive the worker fabric end to end.

A delivery record carries the message ONCE per worker; per-subscription
QoS downgrade happens worker-side in the Session (same code path as the
in-process broker), so the router serializes each matched message once
per worker, not once per subscriber.
"""

from __future__ import annotations

import json
import os
import struct
from typing import Iterable, List, Tuple

import numpy as np

T_HELLO = 0
T_SUB = 1
T_UNSUB = 2
T_PUBB = 3
T_DLV = 4
T_PUBB_ACK = 5
# SUB confirm (router -> worker, body = json {h}): sent after the
# router registered the subscription + enqueued retained replay. The
# worker holds the client's SUBACK on it, so SUBACK keeps the
# reference's meaning — the subscription is ROUTABLE, broker-wide
# (emqx_broker.erl:127-160 is synchronous for the same reason).
T_SUB_ACK = 6
# RAW delivery (r->w): pre-serialized MQTT PUBLISH frames for the QoS0
# fast lane — the router serializes once per (message, version, retain)
# and the worker writes the bytes straight to subscriber sockets,
# bypassing the per-delivery Channel/Session work (eligibility is
# negotiated per subscription via the SUB json's "fl" field: qos 0, no
# mountpoint, empty delivered/completed hook chains worker-side).
#   body: u32 n, n * (u32 blen, frame_bytes, u16 nh, nh * u32 handle)
T_RAW = 8
# Slab twins of PUBB/DLV (see "slab codec" below): same record fields,
# but all fixed headers land in ONE contiguous table followed by the
# variable regions (topics, payloads, clients, props[, handles]) each
# concatenated — so the receiver recovers every record offset/length
# with a handful of vectorized numpy passes and hands out memoryviews
# into the ONE read buffer instead of materializing per-record tuples.
T_PUBB_S = 9
T_DLV_S = 10
# Session ops (json, both directions): the router brokers emqx_cm
# semantics ACROSS workers — open (w->r: resolve takeover/resume at
# CONNECT), take/discard (r->w: hand over / kill a live channel),
# state (w->r: serialized session after take), open_ack (r->w),
# park (w->r: disconnect with expiry>0 -> router-side detached store,
# WAL-backed when persistence is on), resume_done (w->r: new channel
# installed; router flushes handoff-banked messages), closed (w->r).
T_SESS = 7

_HDR = struct.Struct("<IB")
_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")

MAX_FRAME = 64 * 1024 * 1024
# soft per-frame body cap for senders: batches above this split into
# multiple frames so a large tick (pipelined max-size publishes, a huge
# fan-out delivery flush) can never hit the receiver's MAX_FRAME reject,
# which would tear down the whole fabric link
MAX_BODY = 8 * 1024 * 1024


def pack_frame(ftype: int, body: bytes) -> bytes:
    return _HDR.pack(len(body), ftype) + body


def pub_record_size(m) -> int:
    """Serialized size of one pub_record (sender-side chunking). Props
    count too: a batch of props-carrying max-size publishes sized only
    by topic+payload could exceed the receiver's MAX_FRAME and tear the
    fabric link."""
    props = getattr(m, "properties", None)
    return (
        9
        + len(m.topic.encode())
        + len(m.payload or b"")
        + len((m.from_client or "").encode())
        + ((4 + len(_encode_props(props))) if props else 0)
    )


def pack_json(ftype: int, obj) -> bytes:
    return pack_frame(ftype, json.dumps(obj).encode())


def _encode_props(props) -> bytes:
    from emqx_tpu.mqtt.frame import encode_properties

    return encode_properties(props)


def _decode_props(blob: bytes):
    from emqx_tpu.mqtt.frame import decode_properties

    props, _off = decode_properties(blob, 0)
    return props


def pack_pub_batch(msgs, seq: int = 0) -> bytes:
    """msgs: iterable of Message."""
    parts = [b""]
    n = 0
    for m in msgs:
        t = m.topic.encode()
        p = m.payload or b""
        c = (m.from_client or "").encode()
        props = getattr(m, "properties", None)
        flags = (m.qos & 3) | (4 if m.retain else 0) | (
            8 if getattr(m, "dup", False) else 0
        ) | (0x10 if props else 0)
        rec = (
            _U16.pack(len(t)) + t + _U32.pack(len(p)) + p
            + bytes([flags]) + _U16.pack(len(c)) + c
        )
        if props:
            pb = _encode_props(props)
            rec += _U32.pack(len(pb)) + pb
        parts.append(rec)
        n += 1
    parts[0] = _U32.pack(seq) + _U32.pack(n)
    return pack_frame(T_PUBB, b"".join(parts))


def unpack_pub_batch(body: bytes):
    """-> (seq, [(topic, payload, qos, retain, dup, from_client,
    props | None)])"""
    (seq,) = _U32.unpack_from(body, 0)
    (n,) = _U32.unpack_from(body, 4)
    off = 8
    out = []
    for _ in range(n):
        (tl,) = _U16.unpack_from(body, off)
        off += 2
        topic = body[off : off + tl].decode()
        off += tl
        (pl,) = _U32.unpack_from(body, off)
        off += 4
        payload = body[off : off + pl]
        off += pl
        flags = body[off]
        off += 1
        (cl,) = _U16.unpack_from(body, off)
        off += 2
        client = body[off : off + cl].decode()
        off += cl
        props = None
        if flags & 0x10:
            (pbl,) = _U32.unpack_from(body, off)
            off += 4
            props = _decode_props(body[off : off + pbl])
            off += pbl
        out.append(
            (topic, payload, flags & 3, bool(flags & 4), bool(flags & 8),
             client, props)
        )
    return seq, out


def pack_pub_ack(seq: int, counts) -> bytes:
    return pack_frame(
        T_PUBB_ACK,
        _U32.pack(seq) + _U32.pack(len(counts))
        + struct.pack(f"<{len(counts)}i", *counts),
    )


def unpack_pub_ack(body: bytes):
    (seq,) = _U32.unpack_from(body, 0)
    (n,) = _U32.unpack_from(body, 4)
    return seq, list(struct.unpack_from(f"<{n}i", body, 8))


def pack_dlv_batches(records, max_body: float = MAX_BODY):
    """records: [(msg, [handle, ...])] -> yields one or more DLV frames,
    each body bounded by ~max_body (always at least one record per
    frame), so a huge delivery tick can't exceed the receiver's
    MAX_FRAME and tear the fabric link."""
    out = bytearray(9)  # frame header (5) + count (4), patched below
    n = 0
    for m, handles in records:
        t = m.topic.encode()
        p = m.payload or b""
        c = (m.from_client or "").encode()
        props = getattr(m, "properties", None)
        flags = (m.qos & 3) | (4 if m.retain else 0) | (
            8 if m.headers.get("retained") else 0
        ) | (0x10 if props else 0)
        head = (
            _U16.pack(len(t)) + t + _U32.pack(len(p)) + p
            + bytes([flags]) + _U16.pack(len(c)) + c
        )
        if props:
            pb = _encode_props(props)
            head += _U32.pack(len(pb)) + pb
        # ntargets is u16: split monster fan-outs across records rather
        # than raise mid-flush (a 10M-sub broker CAN put >65535 matching
        # subscriptions on one worker)
        for lo in range(0, len(handles), 0xFFFF):
            chunk = handles[lo : lo + 0xFFFF]
            rec_len = len(head) + 2 + 4 * len(chunk)
            if n and len(out) + rec_len > max_body:
                out[0:5] = _HDR.pack(len(out) - 5, T_DLV)
                out[5:9] = _U32.pack(n)
                yield bytes(out)
                out = bytearray(9)
                n = 0
            out += head
            out += _U16.pack(len(chunk))
            out += struct.pack(f"<{len(chunk)}I", *chunk)
            n += 1
    if n:
        out[0:5] = _HDR.pack(len(out) - 5, T_DLV)
        out[5:9] = _U32.pack(n)
        yield bytes(out)


def pack_dlv_batch(records) -> bytes:
    """Single-frame variant (tests / small ticks)."""
    frames = list(pack_dlv_batches(records, max_body=float("inf")))
    return frames[0] if frames else pack_frame(T_DLV, _U32.pack(0))


def unpack_dlv_batch(body: bytes):
    """-> [(topic, payload, qos, retain, retained, from_client,
    props | None, [handles])]"""
    (n,) = _U32.unpack_from(body, 0)
    off = 4
    out = []
    for _ in range(n):
        (tl,) = _U16.unpack_from(body, off)
        off += 2
        topic = body[off : off + tl].decode()
        off += tl
        (pl,) = _U32.unpack_from(body, off)
        off += 4
        payload = body[off : off + pl]
        off += pl
        flags = body[off]
        off += 1
        (cl,) = _U16.unpack_from(body, off)
        off += 2
        client = body[off : off + cl].decode()
        off += cl
        props = None
        if flags & 0x10:
            (pbl,) = _U32.unpack_from(body, off)
            off += 4
            props = _decode_props(body[off : off + pbl])
            off += pbl
        (nh,) = _U16.unpack_from(body, off)
        off += 2
        handles = list(struct.unpack_from(f"<{nh}I", body, off))
        off += 4 * nh
        out.append(
            (topic, payload, flags & 3, bool(flags & 4), bool(flags & 8),
             client, props, handles)
        )
    return out


# -- native acceleration ------------------------------------------------
# The C codec (mqtt/_codec.c) implements the same wire format; the pure-
# Python functions above stay the semantic reference and differentially
# test it (tests/test_codec_native.py). Packing DLV batches in Python
# was the largest router-process cost in the serving profile.
from emqx_tpu.mqtt import codec_native as _nc  # noqa: E402

_py_pack_dlv_batches = pack_dlv_batches
_py_pack_pub_batch = pack_pub_batch
_py_unpack_pub_batch = unpack_pub_batch
_py_unpack_dlv_batch = unpack_dlv_batch

if _nc.pack_dlv_frames is not None:

    def pack_dlv_batches(records, max_body: float = MAX_BODY):  # noqa: F811
        if max_body == float("inf"):
            max_body = 1 << 62
        if not isinstance(records, list):
            records = list(records)
        if any(getattr(m, "properties", None) for m, _h in records):
            # props-carrying batches take the (rarer) Python packer;
            # the C packer handles the propless hot path
            return _py_pack_dlv_batches(records, max_body)
        return _nc.pack_dlv_frames(records, int(max_body))

    def pack_pub_batch(msgs, seq: int = 0) -> bytes:  # noqa: F811
        if not isinstance(msgs, list):
            msgs = list(msgs)
        if any(getattr(m, "properties", None) for m in msgs):
            return _py_pack_pub_batch(msgs, seq)
        return _nc.pack_pub_batch(msgs, seq)

    def unpack_pub_batch(body: bytes):  # noqa: F811
        seq, recs = _nc.unpack_pub_batch(body)
        # the C layer returns the raw props block (or None); decode here
        return seq, [
            r if r[6] is None else r[:6] + (_decode_props(r[6]),)
            for r in recs
        ]

    def unpack_dlv_batch(body: bytes):  # noqa: F811
        return [
            r if r[6] is None else r[:6] + (_decode_props(r[6]), r[7])
            for r in _nc.unpack_dlv_batch(body)
        ]


def pack_raw_batches(records, max_body: float = MAX_BODY):
    """records: [(frame_bytes, [handle, ...])] -> one or more T_RAW
    frames, each body bounded by ~max_body."""
    out = bytearray(9)
    n = 0
    for buf, handles in records:
        # nh is u16: split monster fan-outs across records (same rule
        # as pack_dlv_batches — a 10M-sub broker CAN put >65535
        # matching subscriptions on one worker)
        for lo in range(0, len(handles), 0xFFFF):
            chunk = handles[lo : lo + 0xFFFF]
            rec_len = 4 + len(buf) + 2 + 4 * len(chunk)
            if n and len(out) + rec_len > max_body:
                out[0:5] = _HDR.pack(len(out) - 5, T_RAW)
                out[5:9] = _U32.pack(n)
                yield bytes(out)
                out = bytearray(9)
                n = 0
            out += _U32.pack(len(buf))
            out += buf
            out += _U16.pack(len(chunk))
            out += struct.pack(f"<{len(chunk)}I", *chunk)
            n += 1
    if n:
        out[0:5] = _HDR.pack(len(out) - 5, T_RAW)
        out[5:9] = _U32.pack(n)
        yield bytes(out)


def unpack_raw_batch(body: bytes):
    """-> [(frame_bytes, [handles])]"""
    (n,) = _U32.unpack_from(body, 0)
    off = 4
    out = []
    for _ in range(n):
        (bl,) = _U32.unpack_from(body, off)
        off += 4
        buf = body[off : off + bl]
        off += bl
        (nh,) = _U16.unpack_from(body, off)
        off += 2
        handles = list(struct.unpack_from(f"<{nh}I", body, off))
        off += 4 * nh
        out.append((buf, handles))
    return out


# -- slab codec ---------------------------------------------------------
# The slab wire format is the protocol-plane fast path (ROADMAP item 1,
# docs/protocol_plane.md): one fixed-size header TABLE up front, then
# each variable field concatenated into its own contiguous REGION:
#
#   PUBB_S body: u32 seq, u32 n, n * pub_hdr(13B),
#                topics | payloads | clients | props
#   DLV_S  body: u32 n, n * dlv_hdr(17B),
#                topics | payloads | clients | props | handles(u32 LE)
#
#   pub_hdr: u16 tlen, u32 plen, u16 clen, u32 pblen, u8 flags
#   dlv_hdr: pub_hdr + u32 nh          (flags bits as the legacy records)
#
# Unpacking is a vectorized fixed-header scan: ONE np.frombuffer over
# the header table, four/five cumsums for the region offsets — no
# per-record struct.unpack, no per-record tuple. Accessors hand out
# memoryview/ndarray slices into the ONE read buffer; str decode and
# payload copies happen lazily at the consumer (broker/message.py
# SlabMessage), which is the zero-copy ingest contract. Packing builds
# the header table with vectorized numpy writes into a preallocated
# slab and joins each region once; DLV frame splitting slices the
# once-built regions, so a record straddling MAX_BODY is NEVER
# re-serialized for the next frame.

PUB_HDR_DT = np.dtype(
    [("tlen", "<u2"), ("plen", "<u4"), ("clen", "<u2"),
     ("pblen", "<u4"), ("flags", "u1")]
)  # itemsize 13
DLV_HDR_DT = np.dtype(
    [("tlen", "<u2"), ("plen", "<u4"), ("clen", "<u2"),
     ("pblen", "<u4"), ("flags", "u1"), ("nh", "<u4")]
)  # itemsize 17

# senders emit slab frames by default; the env kill-switch drops the
# whole fabric back to the per-record wire (both receivers always
# accept both — the differential tests and codec microbench rely on it)
SLAB_WIRE = os.environ.get("EMQX_TPU_NO_SLAB_FABRIC") != "1"
# slab DLV records chunk monster fan-outs so one record stays far below
# MAX_FRAME (the legacy u16 ntargets cap is gone — nh is u32)
SLAB_HANDLE_CHUNK = 1 << 20


def _region_offsets(base: int, lens: np.ndarray) -> np.ndarray:
    """-> int64 [n+1] absolute offsets: base + exclusive cumsum(lens)."""
    off = np.empty(len(lens) + 1, np.int64)
    off[0] = base
    np.cumsum(lens, out=off[1:])
    off[1:] += base
    return off


class _Slab:
    """Shared accessor base over one contiguous frame body."""

    __slots__ = (
        "n", "buf", "flat", "flags", "t_off", "t_len", "p_off", "p_len",
        "c_off", "c_len", "pb_off", "pb_len", "_ll",
    )

    def _init_regions(self, body, hdr, base: int) -> None:
        # the slab accessor IS the buffer's holder, not a borrower:
        # ownership transfers downstream via SlabMessage.own_buffers()
        # at the annotated escape sinks
        self.buf = memoryview(body)  # lint: disable=BV001
        self.flat = np.frombuffer(body, np.uint8)
        self.flags = hdr["flags"]
        self.t_len = hdr["tlen"].astype(np.int64)
        self.p_len = hdr["plen"].astype(np.int64)
        self.c_len = hdr["clen"].astype(np.int64)
        self.pb_len = hdr["pblen"].astype(np.int64)
        self.t_off = _region_offsets(base, self.t_len)
        self.p_off = _region_offsets(int(self.t_off[-1]), self.p_len)
        self.c_off = _region_offsets(int(self.p_off[-1]), self.c_len)
        self.pb_off = _region_offsets(int(self.c_off[-1]), self.pb_len)
        self._ll = None  # lazy plain-int offset lists (accessor path)

    def _lists(self):
        """Plain-int twins of the offset/length arrays, built ONCE on
        first per-record access (numpy scalar indexing costs ~5x a list
        index on the accessor path; the pure-scan consumers never pay
        this)."""
        ll = self._ll
        if ll is None:
            ll = self._ll = (
                self.t_off.tolist(), self.t_len.tolist(),
                self.p_off.tolist(), self.p_len.tolist(),
                self.c_off.tolist(), self.c_len.tolist(),
                self.pb_off.tolist(), self.pb_len.tolist(),
            )
        return ll

    def topic_bytes(self, i: int) -> memoryview:
        ll = self._lists()
        o = ll[0][i]
        return self.buf[o : o + ll[1][i]]

    def topic(self, i: int) -> str:
        return str(self.topic_bytes(i), "utf-8")

    def payload_view(self, i: int) -> memoryview:
        ll = self._lists()
        o = ll[2][i]
        return self.buf[o : o + ll[3][i]]

    def client(self, i: int) -> str:
        ll = self._lists()
        o = ll[4][i]
        return str(self.buf[o : o + ll[5][i]], "utf-8")

    def props(self, i: int):
        if not (int(self.flags[i]) & 0x10):
            return None
        ll = self._lists()
        o = ll[6][i]
        return _decode_props(bytes(self.buf[o : o + ll[7][i]]))

    def topic_refs(self):
        """-> (flat uint8 [body], t_off int64 [n], t_len int64 [n]) —
        the tokenizer's bulk-gather inputs (ops/tokenizer.encode_topics
        slab fast path)."""
        return self.flat, self.t_off[:-1], self.t_len


class PubSlab(_Slab):
    """Vectorized view over one T_PUBB_S body."""

    __slots__ = ("seq",)

    def __init__(self, body):
        (seq,) = _U32.unpack_from(body, 0)
        (n,) = _U32.unpack_from(body, 4)
        self.seq = seq
        self.n = n
        hdr = np.frombuffer(body, PUB_HDR_DT, count=n, offset=8)
        self._init_regions(body, hdr, 8 + PUB_HDR_DT.itemsize * n)
        if int(self.pb_off[-1]) != len(body):
            raise ValueError("slab pub frame length mismatch")

    def record(self, i: int):
        """Legacy per-record tuple (differential tests / compat)."""
        f = int(self.flags[i])
        return (
            self.topic(i), bytes(self.payload_view(i)), f & 3,
            bool(f & 4), bool(f & 8), self.client(i), self.props(i),
        )

    def records(self) -> List:
        return [self.record(i) for i in range(self.n)]


class DlvSlab(_Slab):
    """Vectorized view over one T_DLV_S body."""

    __slots__ = ("h_off", "h_len", "_handles")

    def __init__(self, body):
        (n,) = _U32.unpack_from(body, 0)
        self.n = n
        hdr = np.frombuffer(body, DLV_HDR_DT, count=n, offset=4)
        self._init_regions(body, hdr, 4 + DLV_HDR_DT.itemsize * n)
        self.h_len = hdr["nh"].astype(np.int64)
        self.h_off = _region_offsets(0, self.h_len)  # element offsets
        hbase = int(self.pb_off[-1])
        nh_total = int(self.h_off[-1])
        if hbase + 4 * nh_total != len(body):
            raise ValueError("slab dlv frame length mismatch")
        self._handles = np.frombuffer(
            body, "<u4", count=nh_total, offset=hbase
        )

    def handles(self, i: int) -> np.ndarray:
        return self._handles[int(self.h_off[i]) : int(self.h_off[i + 1])]

    def record(self, i: int):
        f = int(self.flags[i])
        return (
            self.topic(i), bytes(self.payload_view(i)), f & 3,
            bool(f & 4), bool(f & 8), self.client(i), self.props(i),
            self.handles(i).tolist(),
        )

    def records(self) -> List:
        return [self.record(i) for i in range(self.n)]


def unpack_pub_slab(body) -> PubSlab:
    return PubSlab(body)


def unpack_dlv_slab(body) -> DlvSlab:
    return DlvSlab(body)


def _msg_fields(m, dlv: bool):
    """One record's serialized pieces (shared by both slab packers)."""
    tb = getattr(m, "topic_bytes", None)
    t = tb() if tb is not None else m.topic.encode()
    pv = getattr(m, "payload_view", None)
    p = pv() if pv is not None else (m.payload or b"")
    c = (m.from_client or "").encode()
    props = getattr(m, "properties", None)
    flags = (m.qos & 3) | (4 if m.retain else 0) | (0x10 if props else 0)
    if dlv:
        flags |= 8 if m.headers.get("retained") else 0
    else:
        flags |= 8 if getattr(m, "dup", False) else 0
    pb = _encode_props(props) if props else b""
    return t, p, c, pb, flags


def pack_pub_slab(msgs, seq: int = 0) -> bytes:
    """Slab twin of pack_pub_batch: ONE T_PUBB_S frame, header table
    written vectorized, each region joined once."""
    if not isinstance(msgs, list):
        msgs = list(msgs)
    n = len(msgs)
    ts: List = []
    ps: List = []
    cs: List = []
    pbs: List = []
    flags = bytearray(n)
    for i, m in enumerate(msgs):
        t, p, c, pb, f = _msg_fields(m, dlv=False)
        ts.append(t)
        ps.append(p)
        cs.append(c)
        pbs.append(pb)
        flags[i] = f
    tl = np.fromiter(map(len, ts), np.int64, n)
    pl = np.fromiter(map(len, ps), np.int64, n)
    cl = np.fromiter(map(len, cs), np.int64, n)
    pbl = np.fromiter(map(len, pbs), np.int64, n)
    body_len = 8 + PUB_HDR_DT.itemsize * n + int(tl.sum() + pl.sum()
                                                 + cl.sum() + pbl.sum())
    out = bytearray(5 + body_len)
    _HDR.pack_into(out, 0, body_len, T_PUBB_S)
    _U32.pack_into(out, 5, seq)
    _U32.pack_into(out, 9, n)
    hdr = np.frombuffer(out, PUB_HDR_DT, count=n, offset=13)
    hdr["tlen"] = tl
    hdr["plen"] = pl
    hdr["clen"] = cl
    hdr["pblen"] = pbl
    hdr["flags"] = np.frombuffer(flags, np.uint8)
    pos = 13 + PUB_HDR_DT.itemsize * n
    for region in (ts, ps, cs, pbs):
        blob = b"".join(region)
        out[pos : pos + len(blob)] = blob
        pos += len(blob)
    return bytes(out)


def pack_dlv_slabs(records, max_body: float = MAX_BODY):
    """Slab twin of pack_dlv_batches: every record's pieces are
    serialized ONCE into shared region buffers; MAX_BODY splitting then
    slices those regions per frame — a record straddling the cap moves
    to the next frame as slices, never re-serialized (the legacy
    packer's retry-path property, now structural)."""
    ts: List = []
    ps: List = []
    cs: List = []
    pbs: List = []
    flags_l: List[int] = []
    hl: List = []
    for m, handles in records:
        if not len(handles):
            continue  # no targets: nothing on the wire (legacy parity)
        t, p, c, pb, f = _msg_fields(m, dlv=True)
        ha = np.asarray(handles, "<u4")
        # split monster fan-outs so one record can never approach
        # MAX_FRAME (nh is u32; the chunk bound replaces the u16 cap)
        for lo in range(0, len(ha), SLAB_HANDLE_CHUNK):
            ts.append(t)
            ps.append(p)
            cs.append(c)
            pbs.append(pb)
            flags_l.append(f)
            hl.append(ha[lo : lo + SLAB_HANDLE_CHUNK])
    n = len(ts)
    if not n:
        return
    tl = np.fromiter(map(len, ts), np.int64, n)
    pl = np.fromiter(map(len, ps), np.int64, n)
    cl = np.fromiter(map(len, cs), np.int64, n)
    pbl = np.fromiter(map(len, pbs), np.int64, n)
    nh = np.fromiter(map(len, hl), np.int64, n)
    hdr_all = np.zeros(n, DLV_HDR_DT)
    hdr_all["tlen"] = tl
    hdr_all["plen"] = pl
    hdr_all["clen"] = cl
    hdr_all["pblen"] = pbl
    hdr_all["flags"] = np.asarray(flags_l, np.uint8)
    hdr_all["nh"] = nh
    hdr_bytes = hdr_all.tobytes()
    regions = [b"".join(r) for r in (ts, ps, cs, pbs)]
    handles_bytes = (
        np.concatenate(hl).tobytes() if hl else b""
    )
    # region element offsets (per record), for per-frame slicing
    tco = _region_offsets(0, tl)
    pco = _region_offsets(0, pl)
    cco = _region_offsets(0, cl)
    pbco = _region_offsets(0, pbl)
    hco = _region_offsets(0, nh)
    rec_size = (DLV_HDR_DT.itemsize + tl + pl + cl + pbl + 4 * nh)
    csum = _region_offsets(0, rec_size)
    if max_body == float("inf"):
        max_body = 1 << 62
    i = 0
    while i < n:
        j = int(
            np.searchsorted(csum, csum[i] + int(max_body) - 9, side="right")
        ) - 1
        j = min(max(j, i + 1), n)
        parts = [
            b"",  # frame header patched below
            _U32.pack(j - i),
            hdr_bytes[DLV_HDR_DT.itemsize * i : DLV_HDR_DT.itemsize * j],
            regions[0][int(tco[i]) : int(tco[j])],
            regions[1][int(pco[i]) : int(pco[j])],
            regions[2][int(cco[i]) : int(cco[j])],
            regions[3][int(pbco[i]) : int(pbco[j])],
            handles_bytes[4 * int(hco[i]) : 4 * int(hco[j])],
        ]
        body_len = sum(len(x) for x in parts)
        parts[0] = _HDR.pack(body_len, T_DLV_S)
        yield b"".join(parts)
        i = j


def unpack_pub_frame(frame: bytes):
    """Whole-frame helper (tests/bench): -> (seq, legacy record list)
    for either pub wire format."""
    body = frame[5:]
    if frame[4] == T_PUBB_S:
        s = unpack_pub_slab(body)
        return s.seq, s.records()
    return unpack_pub_batch(body)


def unpack_dlv_frame(frame: bytes):
    """Whole-frame helper (tests/bench): -> legacy record list for
    either dlv wire format."""
    body = frame[5:]
    if frame[4] == T_DLV_S:
        return unpack_dlv_slab(body).records()
    return unpack_dlv_batch(body)


async def read_frame(reader) -> Tuple[int, bytes]:
    hdr = await reader.readexactly(5)
    length, ftype = _HDR.unpack(hdr)
    if length > MAX_FRAME:
        raise ValueError(f"fabric frame too large: {length}")
    body = await reader.readexactly(length) if length else b""
    return ftype, body
